"""Node: the application container.

Reference: src/ripple_app/main/Application.cpp — ApplicationImp owns ~35
subsystems wired in constructor order (:257-365) with setup() (:659-917)
and run(); here the container is small because the TPU build splits into
a host protocol machine + a device crypto plane, but the wiring order
(storage → crypto plane → executor → ledger chain → brain → API doors)
mirrors the reference.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ..nodestore.core import make_database
from ..protocol.keys import KeyPair, decode_seed
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger
from .config import DEFAULT_KERNEL_TUNING, Config
from .hashrouter import HashRouter
from .jobqueue import JobQueue
from .ledgermaster import LedgerMaster
from .networkops import NetworkOPs, TxStatus
from .txdb import TxDatabase
from .verifyplane import VerifyPlane

__all__ = ["Node"]

# reference: the well-known test genesis passphrase ("masterpassphrase")
MASTER_PASSPHRASE = "masterpassphrase"


def _parse_host_port(entry: str, default_port: int) -> Optional[tuple[str, int]]:
    """One "host port" / "host:port" / bare-host entry -> (host, port).
    A colon is only a separator when it appears exactly once — an IPv6
    literal like ::1 stays a bare host (reference Config.cpp IPS rules).
    Returns None for malformed entries (callers skip, never crash)."""
    entry = entry.strip()
    if not entry:
        return None
    if " " in entry:
        host, _, port = entry.partition(" ")
    elif entry.count(":") == 1:
        host, _, port = entry.partition(":")
    else:
        host, port = entry, ""
    try:
        return (host.strip(), int(port) if port else default_port)
    except ValueError:
        return None


def _parse_peer_addrs(ips: list[str]) -> list[tuple[str, int]]:
    """[ips] entries -> (host, port) dial pairs."""
    out = []
    for entry in ips:
        pair = _parse_host_port(entry, 51235)
        if pair is not None:
            out.append(pair)
    return out


def _results_from_meta(ledger: Ledger) -> dict:
    """{txid: TER} recovered from each committed tx's sfTransactionResult
    metadata byte — for ledgers adopted from the net (never applied
    locally, so no local results exist)."""
    from ..protocol.sfields import sfTransactionResult
    from ..protocol.stobject import STObject

    out = {}
    for txid, _blob, meta in ledger.tx_entries():
        if not meta:
            continue
        try:
            code = STObject.from_bytes(meta).get(sfTransactionResult)
            if code is not None:
                out[txid] = TER(code)
        except Exception:  # noqa: BLE001 — unparseable meta: skip this tx
            continue
    return out


def build_tx_rows(ledger: Ledger, results: dict) -> list[tuple]:
    """Materialize a closed ledger's txdb rows, reusing the close pass's
    parsed_txs/parsed_metas memos instead of re-parsing blobs. Pure
    Python tail work: close_and_advance runs it overlapped with the seal
    tree-hash (LedgerMaster.persist_prep), and the close pipeline's txdb
    stage falls back to it for adopted/repaired ledgers."""
    from ..protocol.meta import affected_accounts

    rows = []
    for txn_seq, (txid, blob, meta) in enumerate(ledger.tx_entries()):
        tx = ledger.parse_tx(txid, blob)
        meta_src = ledger.parsed_metas.get(txid, meta)
        affected = affected_accounts(meta_src) if meta else [tx.account]
        rows.append((
            txid,
            tx.tx_type.name,
            tx.account,
            tx.sequence,
            ledger.seq,
            _result_token(txid, results, meta),
            blob,
            meta,
            affected,
            txn_seq,
        ))
    return rows


def _result_token(txid: bytes, results: dict, meta: Optional[bytes]) -> str:
    """TER token for a committed tx: the local apply result when we
    closed the round ourselves, else the sfTransactionResult byte from
    the tx metadata (catch-up-adopted ledgers were not applied locally,
    and recording a blanket tesSUCCESS would misreport tec-class txs)."""
    if txid in results:
        return TER(results[txid]).token
    if meta:
        try:
            from ..protocol.sfields import sfTransactionResult
            from ..protocol.stobject import STObject

            code = STObject.from_bytes(meta).get(sfTransactionResult)
            if code is not None:
                return TER(code).token
        except Exception:  # noqa: BLE001 — unparseable meta: fall through
            pass
    return TER.tesSUCCESS.token


class Node:
    """One stellard-tpu node. Construct → setup() → (serve / drive)."""

    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config()
        cfg = self.config

        # tracing plane FIRST ([trace]): every subsystem below records
        # its lifecycle spans into this node's ring (node/tracer.py);
        # trace_status/trace_dump serve it, [insight] ships span-derived
        # stage percentiles
        from .tracer import Tracer

        self.tracer = Tracer.from_config(cfg)

        # storage plane (reference: NodeStore Manager + main db :330)
        db_kwargs = {}
        if cfg.node_db_path:
            db_kwargs["path"] = cfg.node_db_path
        if cfg.node_db_compression and cfg.node_db_type == "cpplog":
            db_kwargs["compression"] = cfg.node_db_compression
        if cfg.node_db_type == "segstore":
            db_kwargs.update(
                durability=cfg.node_db_durability,
                group_commit_ms=cfg.node_db_group_commit_ms,
                segment_bytes=cfg.node_db_segment_mb << 20,
                checkpoint_bytes=cfg.node_db_checkpoint_mb << 20,
                compact_ratio=cfg.node_db_compact_ratio,
                tracer=self.tracer,
            )
        if cfg.node_db_type == "sqlite" and cfg.node_db_synchronous:
            db_kwargs["synchronous"] = cfg.node_db_synchronous
        self.nodestore = make_database(type=cfg.node_db_type, **db_kwargs)
        # [node] mode=archive (doc/archive.md): the full-history
        # reporting tier — follower ingest + deep-history shard
        # backfill + a txdb that NEVER trims + forever-cached
        # immutable-seq results
        self.archive = cfg.node_mode == "archive"
        if self.archive:
            from .archive import ArchiveTxDatabase

            self.txdb = ArchiveTxDatabase(cfg.database_path or ":memory:")
        else:
            self.txdb = TxDatabase(cfg.database_path or ":memory:")

        # out-of-core state plane ([tree] cache_mb): the process-wide
        # hot-node cache is the resident set for lazily-faulted trees —
        # apply the operator's budget before anything loads a ledger
        from ..state.shamap import configure_inner_cache, inner_node_cache

        configure_inner_cache(cfg.tree_cache_mb)
        inner_node_cache().tracer = self.tracer  # `cache.fault` spans

        # history shards ([node_db] shards=): rotation seals retired
        # ranges here instead of discarding them (doc/storage.md)
        self.shardstore = None
        if cfg.node_db_shards:
            from ..nodestore.shards import HistoryShardStore

            shards_path = cfg.node_db_shards
            if shards_path.lower() in ("1", "true", "yes", "on"):
                shards_path = (cfg.node_db_path or "nodestore") + ".shards"
            self.shardstore = HistoryShardStore(shards_path)
        elif self.archive:
            # an archive ALWAYS has a shard store: it is the import
            # target of the backfill, the serving source for deep
            # account_tx, and (via the segment manifest) this node's
            # own advertisement in the shard distribution network —
            # imported shards re-serve downstream ([archive] path=)
            from ..nodestore.shards import HistoryShardStore

            self.shardstore = HistoryShardStore(
                cfg.archive_path
                or (cfg.node_db_path or cfg.database_path or "archive")
                + ".archive-shards"
            )

        # stellar CLF plane: SQL mirror + LCL pointer (reference:
        # stellar::gLedgerMaster + workingledger.db, Application.cpp:716)
        from ..state.clf import CLFMirror, LedgerSqlDatabase

        clf_path = (
            cfg.database_path + ".clf" if cfg.database_path else ":memory:"
        )
        self.clf = CLFMirror(LedgerSqlDatabase(clf_path))

        # ledger-close pipeline: closed ledgers persist on a bounded,
        # strictly-ordered drain OFF the close path (reference:
        # pendSaveValidated; ordered because concurrent workers could
        # commit ledger N+1's CLF pointer before N's, regressing the
        # resume point). Bounded: a disk that cannot keep up with the
        # close rate back-pressures closes (briefly) instead of pinning
        # an unbounded backlog of whole Ledgers in memory. The worker
        # always exists; [close_pipeline] enabled=0 keeps STANDALONE
        # closes on the serial in-line path (the repair/networked drains
        # still ride the worker, as they always did).
        from .closepipeline import ClosePipeline

        self.close_pipeline = ClosePipeline(
            save_stage=lambda led: led.save(self.nodestore),
            txdb_stage=self._persist_tx_rows,
            clf_stage=self._commit_clf,
            recover_results=_results_from_meta,
            depth=cfg.close_pipeline_depth,
            tracer=self.tracer,
        )

        # online deletion (rippled SHAMapStore online_delete role): a
        # rotation sweep driven from the validated-close stream keeps a
        # validator's disk bounded near the live set ([node_db]
        # online_delete=N; requires a backend with liveness — segstore)
        self.online_deleter = None
        if self.archive and cfg.node_db_online_delete > 0:
            # the archive contract is FULL history; a rotation sweep
            # would silently contradict it (and ArchiveTxDatabase
            # refuses the SQL trim anyway) — reject the config loudly
            raise ValueError(
                "[node_db] online_delete is incompatible with [node] "
                "mode=archive: the archive tier keeps full history "
                "(doc/archive.md)"
            )
        if cfg.node_db_online_delete > 0:
            if not getattr(
                self.nodestore.backend, "supports_online_delete", False
            ):
                raise ValueError(
                    f"[node_db] online_delete requires a backend with "
                    f"liveness accounting (segstore), not "
                    f"{cfg.node_db_type!r}"
                )
            from .ledgercleaner import OnlineDeleter

            self.online_deleter = OnlineDeleter(
                self,
                retain=cfg.node_db_online_delete,
                interval=cfg.node_db_online_delete_interval,
                sql_trim=bool(cfg.node_db_sql_trim),
                shardstore=self.shardstore,
            )

        # crypto plane (north star: pluggable cpu|tpu batch backends).
        # Device hashers run under the wedge watchdog: the tunnel's
        # failure mode is an indefinite hang, and a frozen tree-hash
        # would freeze every ledger close (utils/devicewatch.py).
        if cfg.kernel_tuning and cfg.kernel_tuning.lower() not in (
            "none", "off"
        ):
            # measured-winner kernel config as env defaults (explicit
            # env settings win). Outcomes are operator-visible: a
            # missing DEFAULT path is normal; an explicitly configured
            # path that fails to apply is a loud warning (stated
            # stance: degraded subsystems report, never stay silent).
            import logging

            from ..crypto.backend import apply_kernel_tuning

            tuned = apply_kernel_tuning(cfg.kernel_tuning)
            lg = logging.getLogger("stellard.device")
            if tuned is not None:
                lg.info(
                    "kernel tuning applied from %s (impl=%s batch=%s)",
                    cfg.kernel_tuning, tuned.get("impl", "xla"),
                    tuned.get("batch"),
                )
            elif os.path.exists(cfg.kernel_tuning):
                # present but unusable is a fault at ANY path — the
                # operator believes the measured winner is applied
                lg.warning(
                    "[kernel_tuning] %s exists but is malformed — "
                    "running with hardcoded kernel defaults",
                    cfg.kernel_tuning,
                )
            elif cfg.kernel_tuning != DEFAULT_KERNEL_TUNING:
                # a missing DEFAULT path is normal; a missing
                # explicitly-configured path is an operator mistake
                lg.warning(
                    "[kernel_tuning] %s not found — running with "
                    "hardcoded kernel defaults", cfg.kernel_tuning,
                )
        from ..crypto.backend import make_watched_hasher

        if cfg.signature_backend != "cpu" or cfg.hash_backend not in (
            "cpu", "cpp"
        ):
            # device backends: persistent XLA compilation cache (keyed
            # by host CPU fingerprint, utils/xlacache.py) so a daemon
            # RESTART replays compiled programs instead of re-paying
            # multi-minute compiles inside the prewarm — bench and the
            # smokes already did this; the node itself never had, which
            # left every restart cold
            from ..utils.xlacache import enable_compilation_cache

            enable_compilation_cache()

        # config -> plane plumbing (ISSUE 15): every [hash_backend] /
        # [signature_backend] option reaches its factory — mesh width,
        # routing mode, floors and watchdog deadlines are cfg axes, and
        # unknown keys fail loudly at build, never silently no-op
        self.hasher = make_watched_hasher(
            cfg.hash_backend,
            min_device_nodes=cfg.hash_min_device_nodes,
            mesh=cfg.hash_mesh,
            routing=cfg.hash_routing or None,
            first_timeout=cfg.hash_device_first_timeout_s,
        )
        # [tree] fused=0 kill-switch: compute_hashes / the seal drainer
        # fall back to the staged per-level hash_packed path (one
        # round-trip per level) — the fused-vs-staged identity leg
        self.hasher.fused_enabled = cfg.tree_fused
        self.verify_plane = VerifyPlane(
            backend=cfg.signature_backend,
            window_ms=cfg.verify_batch_window_ms,
            max_batch=cfg.verify_max_batch,
            min_device_batch=cfg.verify_min_device_batch,
            backend_opts=cfg.verify_backend_opts(),
            routing=cfg.verify_routing or None,
            device_first_timeout=cfg.verify_device_first_timeout_s,
            device_warm_timeout=cfg.verify_device_warm_timeout_s,
            tracer=self.tracer,
        )
        self.verify_prewarm: Optional[threading.Thread] = None
        if cfg.signature_backend != "cpu":
            # compile + measure the device shapes in the background;
            # traffic rides the CPU side until the chip is warm (a ~60s
            # XLA compile must never stall a live batch)
            self.verify_prewarm = self.verify_plane.start_prewarm()

        # executor (reference: JobQueue :287)
        self.job_queue = JobQueue(
            threads=cfg.thread_count(), tracer=self.tracer
        )
        self.hash_router = HashRouter()

        # load plane (reference: LoadFeeTrack :346, LoadManager :354)
        from .loadmgr import LoadFeeTrack, LoadManager

        self.fee_track = LoadFeeTrack()
        self.load_manager = LoadManager(self.job_queue, self.fee_track)

        # admission-control plane ([txq], node/txq.py): soft open-ledger
        # cap + escalating fee + bounded fee-priority queue between the
        # verify plane and the open ledger; wired into NetworkOPs
        # (admit) and LedgerMaster (promotion at _open_next) below
        from .txq import TxQ

        self.txq = TxQ.from_config(
            cfg, fee_track=self.fee_track, tracer=self.tracer
        )

        # trust + anti-DoS planes (reference: UNL :323, PoW factory :352,
        # LedgerCleaner)
        from ..utils.pow import PowFactory
        from .ledgercleaner import LedgerCleaner
        from .unl import UniqueNodeList

        unl_path = cfg.database_path + ".unl" if cfg.database_path else None
        self.unl = UniqueNodeList(unl_path)
        if cfg.validators or cfg.validators_file or cfg.validators_site:
            from ..protocol.keys import decode_node_public
            from .sitefiles import fetch_site_validators, load_validators_file

            def add_keys(pairs, default_comment):
                for key, comment in pairs:
                    try:
                        self.unl.add(
                            decode_node_public(key), comment or default_comment
                        )
                    except (ValueError, KeyError):
                        import logging

                        logging.getLogger("stellard.unl").warning(
                            "skipping malformed validator key from %s: %r",
                            default_comment, key,
                        )

            # the INLINE config is operator-written: a malformed key there
            # is a misconfiguration that must fail loudly, not shrink the
            # trusted set silently
            for v in cfg.validators:
                self.unl.add(decode_node_public(v), "config")
            if cfg.validators_file:
                try:
                    add_keys(load_validators_file(cfg.validators_file), "file")
                except OSError:
                    pass  # a missing file must not kill the node
            if cfg.validators_site:
                # fetched on a background thread: startup must not block
                # on a remote site, and NO exception class from urllib
                # may kill the node (reference fetches sites async too)
                def fetch_site():
                    try:
                        add_keys(
                            fetch_site_validators(cfg.validators_site), "site"
                        )
                    except Exception:  # noqa: BLE001 — log-and-skip source
                        pass

                threading.Thread(
                    target=fetch_site, name="validators-site", daemon=True
                ).start()
        self.pow_factory = PowFactory()
        self.ledger_cleaner = LedgerCleaner(self)

        # ops plane: SNTP network clock + insight metrics (reference:
        # SNTPClient init Application.cpp:698-699, CollectorManager :287)
        from .metrics import CollectorManager
        from .netclock import SntpClient

        self.collector = CollectorManager.from_config(cfg.insight)
        if cfg.insight_history:
            # Monarch-stance embedded history: the bounded in-process
            # ring the metrics_history RPC, the GET /metrics door and
            # the health watchdog all read (doc/observability.md)
            self.collector.enable_history(
                cfg.insight_history_interval, cfg.insight_history_window
            )

        # SLO health plane ([health], node/health.py): always-on flight
        # recorder (black box: recent spans + health transitions +
        # counter snapshots, dumped on crash/degradation) + the EWMA/
        # threshold watchdog riding the metrics-history sample stream
        from .health import FlightRecorder, HealthWatchdog, _RANK

        flight_dir = cfg.health_flight_dir or (
            cfg.database_path + ".flight" if cfg.database_path else ""
        )
        self.flight = FlightRecorder(
            directory=flight_dir, spans_cap=cfg.health_flight_spans
        )
        self.tracer.flight = self.flight
        self._degraded_dump_done = False
        self.health: Optional[HealthWatchdog] = None
        if cfg.health_enabled:
            self.health = HealthWatchdog(
                stall_warn_s=cfg.health_stall_warn_s,
                stall_crit_s=cfg.health_stall_crit_s,
                drift_factor=cfg.health_drift_factor,
                lag_warn=cfg.health_lag_warn,
                lag_crit=cfg.health_lag_crit,
                fanout_p99_warn_ms=cfg.health_fanout_p99_warn_ms,
                flips_warn=cfg.health_flips_warn,
                cache_hit_warn=cfg.health_cache_hit_warn,
                persist_depth_warn=cfg.health_persist_depth_warn,
                tracer=self.tracer,
                flight=self.flight,
            )
            self.collector.on_sample(self.health.on_snapshot)

            def _dump_on_degrade(old, new, reasons):
                # the black box ships when health WORSENS; the recovery
                # transition is an instant in the trace, not a dump
                if _RANK.get(new, 0) > _RANK.get(old, 0):
                    self.flight.dump("health-" + new)

            self.health.on_transition.append(_dump_on_degrade)
        self.sntp: Optional[SntpClient] = None
        if cfg.sntp_servers:
            servers = [
                pair
                for pair in (
                    _parse_host_port(spec, 123) for spec in cfg.sntp_servers
                )
                if pair is not None
            ]
            if servers:
                self.sntp = SntpClient(servers)

        # node identity + validator identity must exist before the overlay
        # (the overlay handshakes and proposes with them)
        self.node_keys = self._load_or_create_identity()
        self.validation_keys: Optional[KeyPair] = None
        if cfg.validation_seed:
            self.validation_keys = KeyPair.from_seed(decode_seed(cfg.validation_seed))

        # overlay plane (reference: ApplicationImp Overlay :300 + Peers
        # start :811): when [peer_port] is configured the node joins a
        # TCP net and the overlay's ValidatorNode OWNS the ledger chain —
        # consensus and the RPC plane then share one LedgerMaster and
        # serialize on one master lock
        self.overlay = None
        # [node] mode=follower (doc/follower.md): the read-only serving
        # tier — no consensus rounds, validated ledgers ingested from
        # the net, reads served from the last validated snapshot.
        # mode=archive (doc/archive.md) runs the follower ingest plane
        # unchanged and layers deep-history backfill on top.
        self.follower = cfg.node_mode in ("follower", "archive")
        if self.follower and (cfg.standalone or not cfg.peer_port):
            raise ValueError(
                f"[node] mode={cfg.node_mode} requires a networked node "
                "([peer_port] set, standalone=0) — it ingests "
                "validated ledgers from its peers"
            )
        if cfg.peer_port and not cfg.standalone:
            from ..overlay.tcp import TcpOverlay

            speed = max(cfg.clock_speed, 1e-9)
            clock = None
            ntime = None
            timer_interval = 1.0
            if speed != 1.0:
                import time as _time

                t0 = _time.monotonic()
                clock = lambda: (_time.monotonic() - t0) * speed  # noqa: E731
                # virtual network time is a pure function of WALL time so
                # independently-started peers agree (anchoring to process
                # start would skew peers by (speed-1) x launch offset).
                # Only the delta from a FIXED recent anchor is scaled, so
                # the value stays well inside the u32 close-time wire
                # fields (scaling the whole 2000-epoch offset overflows
                # past speed ~5)
                _ANCHOR = 1_750_000_000  # fixed wall anchor (2025-06-15)
                _BASE = _ANCHOR - 946_684_800
                ntime = lambda: _BASE + int(  # noqa: E731
                    (_time.time() - _ANCHOR) * speed
                )
                timer_interval = max(0.1, 1.0 / speed)
            if cfg.network_time_offset:
                # deliberate clock skew ([network_time_offset], test-net
                # knob) on the overlay's consensus clock; the ops-plane
                # clock gets the same offset below so both agree
                from .networkops import EPOCH_OFFSET

                base_nt = ntime
                if base_nt is None:
                    import time as _time2

                    base_nt = (  # noqa: E731
                        lambda: int(_time2.time()) - EPOCH_OFFSET
                    )
                off = int(cfg.network_time_offset)
                ntime = lambda: base_nt() + off  # noqa: E731
            from ..protocol.keys import decode_node_public

            unl_keys = self.unl.publics()
            signer = self.validation_keys or self.node_keys
            peer_tls = None
            if cfg.peer_ssl in ("allow", "require"):
                import tempfile

                from ..overlay.peertls import PeerTLS

                # database_path is a sqlite FILE path; state files hang
                # suffixes off it (.clf/.unl/.wallet) — same here
                tls_dir = (
                    cfg.database_path + ".tls"
                    if cfg.database_path
                    else tempfile.mkdtemp(prefix="stellard-tls-")
                )
                peer_tls = PeerTLS.from_state_dir(
                    tls_dir, required=(cfg.peer_ssl == "require")
                )
            # follower trees (doc/follower.md): [node] upstream= names
            # this follower's serving tier — usually a peer FOLLOWER one
            # tier up, not the leader — and replaces [ips] as the dial
            # set, so the leader's egress is bounded by its direct
            # children instead of the whole fleet
            dial_addrs = (
                _parse_peer_addrs(cfg.node_upstream)
                if self.follower and cfg.node_upstream
                else _parse_peer_addrs(cfg.ips)
            )
            self.overlay = TcpOverlay(
                key=signer,
                unl=unl_keys,
                quorum=cfg.validation_quorum,
                port=cfg.peer_port,
                peer_addrs=dial_addrs,
                network_time=ntime,
                clock=clock,
                timer_interval=timer_interval,
                hash_batch=self.hasher,
                verify_many=self.verify_plane.verify_many,
                fee_track=self.fee_track,
                unl_store=self.unl,
                bootcache_path=(
                    cfg.database_path + ".bootcache" if cfg.database_path else None
                ),
                proposing=self.validation_keys is not None,
                follower=self.follower,
                # upstream-pinned followers never discovery-dial past
                # their named upstreams (the tree stays a tree even as
                # endpoint gossip spreads the leader's address)
                pinned_upstream=bool(self.follower and cfg.node_upstream),
                router=self.hash_router,
                job_dispatch=self._peer_job_dispatch,
                peer_tls=peer_tls,
                # matched against peer.node_public from the hello, i.e.
                # the key the member HANDSHAKES with: its validation
                # public when it validates, else its node identity
                cluster={
                    decode_node_public(v) for v in cfg.cluster_nodes
                } or None,
                # [overlay] defense plane: squelch subset size/rotation
                # + the per-peer sendq discipline (doc/overlay.md)
                squelch_size=cfg.overlay_squelch,
                squelch_rotate=cfg.overlay_squelch_rotate,
                sendq_cap=cfg.overlay_sendq_cap,
                sendq_evict_drops=cfg.overlay_sendq_evict_drops,
            )

            # catch-up acquisitions resolve nodes from OUR NodeStore
            # before asking peers: near-tip trees are mostly shared, so
            # only the delta crosses the wire (reference: SHAMap node
            # cache + fetch packs)
            def _local_node_blob(h: bytes):
                obj = self.nodestore.fetch(h)
                return obj.data if obj is not None else None

            self.overlay.node.inbound.local_fetch = _local_node_blob

            # segment-granular catch-up (ROADMAP item 4 follow-on): a
            # cold/lagging node bulk-transfers whole store segments from
            # a peer (wire GetSegments/SegmentData over PR 7's
            # fetch_segment read door) so the tree acquisition above
            # resolves locally; timeout/retry/backoff/peer-scoring in
            # node/inbound.SegmentCatchup, counters in get_counts
            backend = self.nodestore.backend
            if hasattr(backend, "fetch_segment"):
                from ..nodestore.core import NodeObjectType
                from .inbound import SegmentCatchup

                from ..overlay.resource import FEE_GARBAGE_SEGMENT

                vn = self.overlay.node
                if self.shardstore is not None:
                    # history tiering: shard rows join the segment
                    # manifest so a cold peer below our trim floor
                    # syncs the gap from cold storage over the same
                    # GetSegments door (nodestore/shards.py)
                    from ..nodestore.shards import CombinedSegmentSource

                    vn.segment_source = CombinedSegmentSource(
                        backend, self.shardstore
                    )
                else:
                    vn.segment_source = backend
                vn.segment_catchup = SegmentCatchup(
                    send=self.overlay.send_segments_request,
                    peers=self.overlay.segment_peers,
                    store=lambda tb, key, blob: self.nodestore.store(
                        NodeObjectType(tb), key, blob
                    ),
                    clock=self.overlay._clock,
                    note_byzantine=vn.note_byzantine,
                    # unified peer scoring: a peer condemned for a
                    # garbage segment transfer takes a FEE_BAD_DATA-
                    # class charge on its overlay endpoint, so the same
                    # balance that gates relay/admission sees the
                    # catch-up offense too (segment_peers() already
                    # excludes WARN-or-worse endpoints)
                    on_condemn=lambda pub: self.overlay.charge_peer(
                        pub, FEE_GARBAGE_SEGMENT
                    ),
                )

            # archive deep-history backfill (doc/archive.md): a second
            # fetcher on the same GetSegments door — peers' manifests
            # advertise sealed shard ranges, the backfill pulls whole
            # verified shard files for every range this node lacks and
            # fans each import out to the nodestore + full-history txdb
            if self.archive and cfg.archive_backfill:
                from ..nodestore.core import NodeObjectType as _NOT
                from ..overlay.resource import (
                    FEE_GARBAGE_SEGMENT as _FEE_GS,
                )
                from .archive import ShardBackfill, feed_shard

                vn = self.overlay.node
                if vn.segment_source is None:
                    # no segment-capable live backend: the archive
                    # still advertises + re-serves its imported shards
                    # (the distribution network's re-serve half)
                    vn.segment_source = self.shardstore

                def _on_shard_imported(res: dict) -> None:
                    feed_shard(
                        self.shardstore, res["id"],
                        store=lambda tb, key, blob: self.nodestore.store(
                            _NOT(tb), key, blob
                        ),
                        txdb=self.txdb,
                    )
                    self._update_archive_floor()

                vn.shard_backfill = ShardBackfill(
                    send=self.overlay.send_segments_request,
                    peers=self.overlay.segment_peers,
                    shardstore=self.shardstore,
                    clock=self.overlay._clock,
                    rescan_s=cfg.archive_rescan_s,
                    note_byzantine=vn.note_byzantine,
                    on_imported=_on_shard_imported,
                    # unified peer scoring (same stance as catch-up): a
                    # peer whose shard fails verification takes the
                    # garbage-segment charge on its overlay endpoint
                    on_condemn=lambda pub: self.overlay.charge_peer(
                        pub, _FEE_GS
                    ),
                )

            # persistence rides the close pipeline's dedicated ORDERED
            # worker, NOT the consensus tick (the hook fires under the
            # master lock and a slow disk must not stall round timing —
            # reference: pendSaveValidated). WS streams + the
            # INCLUDED→COMMITTED promotion fire AFTER the persist, in
            # drain order, exactly as the old dedicated worker did.
            def _persist_async(led):
                self.close_pipeline.submit_close(
                    led,
                    getattr(led, "apply_results", {}),
                    done=lambda results: self.ops.publish_closed_ledger(
                        led, results
                    ),
                )

            self.overlay.accepted_hooks.append(_persist_async)

        # ledger chain + brain (networked: the overlay's chain IS ours)
        if self.overlay is not None:
            self.ledger_master = self.overlay.node.lm
            # the overlay built its own chain before our tracer existed;
            # repoint it so consensus/close spans land in THIS node's ring
            self.ledger_master.tracer = self.tracer
        else:
            self.ledger_master = LedgerMaster(
                hash_batch=self.hasher, router=self.hash_router,
                tracer=self.tracer,
            )

        def _fetch_fallback(h: bytes):
            # history-cache miss -> the in-flight close-pipeline entry
            # (read-your-writes: a queued-but-unpersisted ledger must
            # never miss), then rebuild from the NodeStore (consensus
            # promotion and peers must see everything persisted)
            led = self.close_pipeline.get(h)
            if led is not None:
                return led
            try:
                # lazy: history reads materialize only the nodes the
                # caller actually touches (out-of-core plane) — opening
                # a stored ledger is O(1), not O(state)
                return Ledger.load(self.nodestore, h,
                                   hash_batch=self.hasher, lazy=True)
            except (KeyError, ValueError):
                return None

        self.ledger_master.fetch_fallback = _fetch_fallback

        from ..state.ledger import parse_header, strip_ledger_prefix

        def _header_fetch(h: bytes):
            # LIGHT resolver for the reindex walk: header bytes only
            led = self.close_pipeline.get(h)  # read-your-writes
            if led is not None:
                return led.seq, led.parent_hash
            obj = self.nodestore.fetch(h)
            if obj is None:
                return None
            try:
                f = parse_header(strip_ledger_prefix(obj.data))
            except (ValueError, IndexError):
                return None
            return f["seq"], f["parent_hash"]

        self.ledger_master.header_fetch = _header_fetch
        # close-path overlap seam: close_and_advance materializes the
        # persist rows (Python meta/row tail) WHILE the seal tree-hash
        # runs its GIL-releasing native/device batches on a helper thread
        self.ledger_master.persist_prep = build_tx_rows
        # [close] delta_replay: speculative close-mode execution at
        # submit + optimistic delta splice at close (serial fallback per
        # tx on any read-set conflict)
        self.ledger_master.delta_replay = cfg.close_delta_replay
        # [tree]: incremental O(dirty) seal — speculated writes pre-hash
        # in background batches between closes; the full seal stays the
        # automatic fallback (incremental=0 is the kill-switch)
        self.ledger_master.incremental_seal = cfg.tree_incremental_seal
        self.ledger_master.seal_drain_batch = cfg.tree_drain_batch
        # [spec]: parallel speculative executor — workers>1 dispatches
        # open-window speculation to a Block-STM worker pool with
        # optimistic validation and ordered commit (engine/specexec.py);
        # workers=1 keeps the serial inline path byte-for-byte.
        # workers=auto resolves HERE (loudly disabling the pool below
        # 4 cores); transport picks the shared-memory ring wire or the
        # legacy pickled pipe
        import logging

        from ..engine.specexec import SpecExecutor
        from .config import resolve_spec_workers

        self.spec_executor = SpecExecutor(
            workers=resolve_spec_workers(
                cfg.spec_workers, log=logging.getLogger("stellard.spec")),
            mode=cfg.spec_mode,
            max_retries=cfg.spec_max_retries, tracer=self.tracer,
            drain_timeout_s=cfg.spec_drain_timeout_s,
            transport=cfg.spec_transport,
        )
        if self.spec_executor.active:
            # fork the process workers NOW, before the window machinery
            # is hot (fewer live threads at fork time)
            self.spec_executor.start()
        self.ledger_master.spec_executor = self.spec_executor
        # [txq]: the ledger chain promotes queued txs at _open_next and
        # the queue's deferred (off-close-path) speculation rides the
        # job queue; in networked mode the overlay's shared chain gets
        # the same queue, so consensus closes promote too
        self.ledger_master.txq = self.txq
        from .jobqueue import JobType as _JT

        self.txq.spec_dispatch = lambda thunk: self.job_queue.add_job(
            _JT.jtTRANSACTION, "txqSpeculate", thunk
        )
        self.ops = NetworkOPs(
            self.ledger_master,
            self.job_queue,
            self.verify_plane,
            self.hash_router,
            standalone=cfg.standalone,
            fee_track=self.fee_track,
            tracer=self.tracer,
            txq=self.txq,
        )
        # configured skew applies to the ops-plane clock too (standalone
        # closes, status, staleness checks); the SNTP heartbeat COMPOSES
        # its measured correction with this base (see _heartbeat)
        self.ops.net_time_offset = int(cfg.network_time_offset)
        if self.health is not None:
            # close-cadence feed: fires on standalone closes AND on the
            # networked path (publish_closed_ledger after persist), and
            # on follower adoption — one seam covers every mode
            hw2 = self.health
            self.ops.on_ledger_closed.append(
                lambda led, _res: hw2.note_close(led.seq)
            )

        # RPC-door resource pricing ([overlay] rpc_resource=1): one
        # decaying charge balance per CLIENT IP, priced with the peer
        # fee schedule's FEE_*_RPC charges (overlay/resource.py) —
        # abusive RPC clients warn/drop exactly like abusive peers.
        # [rpc_admin_allow] IPs are exempt (the reference never charges
        # admin requests), swept on the maintenance timer below.
        self.rpc_resources = None
        if cfg.overlay_rpc_resource:
            from ..overlay.resource import ResourceManager as _RM

            self.rpc_resources = _RM(admin=set(cfg.admin_ips))

        # read plane (rpc/readplane.py): the serving side's immutable
        # validated-snapshot pointer + validated-seq result cache. Read
        # RPCs resolve "validated" from the pointer (never the chain
        # lock); the hot four read RPCs memoize whole results per
        # validated seq. The snapshot is min(persisted, validated):
        # publish_closed_ledger feeds the persisted floor after its
        # sinks (a cache epoch never opens before the SQL-index
        # read-your-writes wait can see its ledger), on_validated
        # below feeds the quorum floor.
        from ..rpc.readplane import ReadPlane, ResultCache

        self.read_cache = (
            ResultCache(cfg.rpc_cache_size)
            if cfg.rpc_cache_size > 0 else None
        )
        self.read_plane = ReadPlane(cache=self.read_cache)
        self.ops.read_plane = self.read_plane
        if self.archive:
            # forever-cache eligibility (doc/archive.md): results whose
            # window closes at or below the verified floor are
            # immutable. A restarted archive re-publishes the floor of
            # whatever it already holds before any backfill runs.
            self._update_archive_floor()
        # the validated floor: on a quorum net validations land after
        # the close persisted, and this hook is what opens the epoch
        # (the read plane publishes min(persisted, validated))
        if self.health is None:
            self.ledger_master.on_validated = self.read_plane.note_validated
        else:
            # compose: the read plane opens the epoch, the watchdog's
            # validation-lag rule sees the quorum floor advance
            hw = self.health

            def _note_validated(led):
                self.read_plane.note_validated(led)
                hw.note_validated(led.seq)

            self.ledger_master.on_validated = _note_validated
        # follower consistency contract (doc/follower.md): selector-less
        # read RPCs serve the last VALIDATED snapshot, not the open
        # ledger — the read tier's answers are immutable and identical
        # across every follower at the same validated seq
        self.serve_validated_default = self.follower

        # liquidity plane ([paths], paths/plane.py): the incremental
        # per-close book index + device-routed candidate pre-ranking +
        # per-subscription staleness/shedding. The close hook advances
        # the index from each close's own write set so both the RPC
        # door (books_if_current) and the subscription publisher serve
        # a warm index without ever rescanning unchanged books.
        self.path_plane = None
        if cfg.paths_enabled:
            from ..crypto.backend import make_path_evaluator
            from ..paths.plane import PathPlane

            evaluator = None
            if cfg.paths_device_prune:
                evaluator = make_path_evaluator(
                    mesh=cfg.paths_mesh,
                    min_device_batch=cfg.paths_min_device_batch,
                    routing=cfg.paths_routing,
                )
            self.path_plane = PathPlane(
                incremental=cfg.paths_incremental,
                evaluator=evaluator,
                device_prune=cfg.paths_device_prune,
                prune_floor=cfg.paths_prune_floor,
                prune_keep=cfg.paths_prune_keep,
                max_updates_per_close=cfg.paths_max_updates_per_close,
                resources=self.rpc_resources,
            )
            self.ops.on_ledger_closed.append(
                lambda led, results: self.path_plane.note_close(led)
            )
        if self.overlay is not None:
            # one master lock for consensus + RPC over the shared chain,
            # and the relay/local-retry seams (reference: the relay step
            # of NetworkOPs::processTransaction :544-556 + LocalTxs).
            # Persistence rides the overlay's on_ledger hook (which also
            # fires publish_closed_ledger), NOT the sinks below.
            self.ops.master_lock = self.overlay.node.lock
            self.ops.relay_tx = self.overlay.broadcast_tx
            self.ops.local_push = self.overlay.node.local_txs.push_back
            # a queued local tx the admission plane drops (eviction /
            # expiry / promote-drop) must stop re-applying across
            # rounds; a client resubmit then starts a fresh horizon
            self.txq.on_drop = self.overlay.node.local_txs.remove
        elif cfg.close_pipeline_enabled:
            # standalone: the ledger-closed sink ENQUEUES — ledger N's
            # NodeStore/txdb/CLF writes overlap ledger N+1's verify/apply
            self.ops.on_ledger_closed.append(
                lambda led, results: self.close_pipeline.submit_close(
                    led, results
                )
            )
        else:
            # serial fallback ([close_pipeline] enabled=0): persistence
            # rides the ledger-closed sink in-line, on the close path
            self.ops.on_ledger_closed.append(self._persist_closed_ledger)

        self.master_keys = KeyPair.from_passphrase(MASTER_PASSPHRASE)
        self._running = threading.Event()
        self.started_at = time.monotonic()  # server_info uptime
        self._debug_log_handler = None

        # API doors (started by serve(); reference: WSDoors/RPCDoor
        # Application.cpp:817-891)
        self.http_server = None
        self.ws_server = None
        self.subs = None

    def _peer_job_dispatch(self, kind: str, thunk) -> None:
        """Overlay peer-message scheduler: proposals/validations ride
        their reference job types (latency targets feed LoadMonitor;
        the queue's per-type accounting makes them sheddable)."""
        from .jobqueue import JobType

        jt = (
            JobType.jtPROPOSAL_t
            if kind == "proposal"
            else JobType.jtVALIDATION_t
        )
        self.job_queue.add_job(jt, kind, thunk)

    def _load_or_create_identity(self) -> KeyPair:
        """reference: LocalCredentials::start (wallet.db node seed) — a
        stable per-node keypair, created on first start and persisted."""
        import json
        import os

        path = (
            self.config.database_path + ".wallet"
            if self.config.database_path
            else None
        )
        if path and os.path.exists(path):
            try:
                with open(path) as fh:
                    rec = json.loads(fh.read())
                return KeyPair.from_seed(bytes.fromhex(rec["node_seed"]))
            except (OSError, ValueError, KeyError):
                pass  # unreadable wallet: regenerate below
        kp = KeyPair.random()
        if path:
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(json.dumps({
                    "node_seed": kp.seed.hex(),
                    "node_public": kp.human_node_public,
                }))
            os.replace(tmp, path)
            os.chmod(path, 0o600)
        return kp

    # -- lifecycle --------------------------------------------------------

    def setup(self) -> "Node":
        """reference: ApplicationImp::setup — START_UP switch
        (Application.cpp:733-762)."""
        if self.config.debug_logfile and self._debug_log_handler is None:
            # [debug_logfile]: full-severity mirror on disk regardless of
            # the console/partition levels (reference: setDebugLogFile,
            # Application.cpp:687-689). The handler is owned by this Node
            # and detached on stop() so setup/stop cycles in one process
            # neither duplicate lines nor leak descriptors.
            import logging

            handler = logging.FileHandler(self.config.debug_logfile)
            handler.setLevel(logging.DEBUG)
            handler.setFormatter(logging.Formatter(
                "%(asctime)s %(name)s %(levelname)s %(message)s"
            ))
            root = logging.getLogger("stellard")
            root.addHandler(handler)
            if root.level > logging.DEBUG or root.level == logging.NOTSET:
                root.setLevel(logging.DEBUG)
            self._debug_log_handler = handler
        if self.config.start_up == "fresh":
            self.ledger_master.start_new_ledger(self.master_keys.account_id)
            # persist the genesis close so later offline replay can load
            # every ledger's parent (reference: startNewLedger saves the
            # seq-1 ledger before opening seq 2)
            genesis = self.ledger_master.closed_ledger()
            genesis.save(self.nodestore)
            self.txdb.save_ledger_header(genesis)
        elif self.config.start_up == "load":
            # resume preference order (reference: loadLastKnownCLF
            # Application.cpp:729, then loadOldLedger :737-758): the CLF
            # state pointer is the atomically-committed source of truth;
            # the txdb header index is the fallback
            led = self.clf.load_last_known(
                self.nodestore, hash_batch=self.hasher, lazy=True
            )
            if led is None:
                hdr = self.txdb.get_ledger_header()
                if hdr is not None:
                    # lazy resume (out-of-core plane): boot is O(1) in
                    # state size — the working set faults in on demand
                    led = Ledger.load(
                        self.nodestore, hdr["hash"],
                        hash_batch=self.hasher, lazy=True,
                    )
            if led is None:
                self.ledger_master.start_new_ledger(self.master_keys.account_id)
            else:
                self.ledger_master.load_ledger(led)
        return self

    def serve(self) -> "Node":
        """Open the configured API doors (reference: ApplicationImp::setup
        WSDoors :817-868, RPCDoor :877-891)."""
        from ..rpc.infosub import SubscriptionManager

        cfg0 = self.config
        self.subs = SubscriptionManager(
            self.ops,
            shards=cfg0.subs_shards,
            sendq_cap=cfg0.subs_sendq_cap,
            evict_drops=cfg0.subs_evict_drops,
            push_retries=cfg0.subs_push_retries,
            resume_horizon=cfg0.subs_resume_horizon,
            tracer=self.tracer,
        )
        # `server` stream: publish on load-factor movement (pubServer)
        self.fee_track.on_change.append(self.subs.pub_server_status)
        # path subscriptions ride the liquidity plane's staleness budget
        self.subs.path_plane = self.path_plane
        door_state_dir: list[str] = []  # one shared auto-cert dir per serve

        def _door_ssl(secure: int, cert: str, key: str):
            # reference [rpc_secure]/[websocket_secure] (Config.cpp:475-492)
            if not secure:
                return None
            from ..overlay.peertls import make_door_ssl_context

            if not door_state_dir:
                if self.config.database_path:
                    door_state_dir.append(self.config.database_path + ".tls")
                else:
                    import tempfile

                    d = tempfile.mkdtemp(prefix="stellard-tls-")
                    door_state_dir.append(d)
                    self._tmp_tls_dir = d  # removed on stop()
            return make_door_ssl_context(cert, key, door_state_dir[0])

        if self.config.rpc_port is not None:
            from ..rpc.http_server import HttpRpcServer

            self.http_server = HttpRpcServer(
                self, self.config.rpc_ip, self.config.rpc_port,
                ssl_context=_door_ssl(
                    self.config.rpc_secure,
                    self.config.rpc_ssl_cert,
                    self.config.rpc_ssl_key,
                ),
            ).start()
        if self.config.websocket_port is not None:
            from ..rpc.ws_server import WsRpcServer

            self.ws_server = WsRpcServer(
                self, self.config.websocket_ip, self.config.websocket_port,
                subs=self.subs,
                ssl_context=_door_ssl(
                    self.config.websocket_secure,
                    self.config.websocket_ssl_cert,
                    self.config.websocket_ssl_key,
                ),
            ).start()
        self._running.set()
        self.load_manager.start()
        if self.overlay is not None:
            # chain already set up (fresh/load) by setup(); open the
            # first consensus round over it and join the net
            self.overlay.node.begin_round()
            self.overlay.start_network()
        if self.sntp is not None:
            self.sntp.start()
        # pull-gauges for the metrics plane (insight Hook shape)
        self.collector.hook(
            "jobq",
            lambda: {
                t: s["queued"] + s["running"]
                for t, s in self.job_queue.get_json().items()
            },
        )
        self.collector.hook(
            "verify",
            lambda: {
                "batches": self.verify_plane.batches,
                "verified": self.verify_plane.verified,
            },
        )
        # routing-flip telemetry for the health watchdog: which side
        # (cpu vs device) took the majority of verify batches since the
        # last flush; a majority change is one flip — the thrashing
        # detector's input (health.py rule 4 reads `*.flips`)
        _route = {"side": None, "cpu": 0, "dev": 0, "flips": 0}

        def _verify_routing():
            vp = self.verify_plane
            dc, cc = vp.device_batches, vp.cpu_batches
            d_dev, d_cpu = dc - _route["dev"], cc - _route["cpu"]
            _route["dev"], _route["cpu"] = dc, cc
            if d_dev or d_cpu:
                side = "device" if d_dev >= d_cpu else "cpu"
                if _route["side"] is not None and side != _route["side"]:
                    _route["flips"] += 1
                _route["side"] = side
            return {"flips": _route["flips"]}

        self.collector.hook("verify_routing", _verify_routing)
        self.collector.hook(
            "load", lambda: {"factor": self.fee_track.load_factor}
        )
        self.collector.hook(
            "txq",
            lambda: {
                "size": len(self.txq),
                "expected": self.txq.metrics.txns_expected,
                "evicted": self.txq.stats["evicted"],
                "promoted": self.txq.stats["promoted"],
            },
        )
        self.collector.hook(
            "close_pipeline",
            lambda: {
                "depth": self.close_pipeline.pending(),
                "persisted": self.close_pipeline.persisted,
                "backpressure_waits": self.close_pipeline.backpressure_waits,
            },
        )
        # subscription-fanout + read-cache gauges (ROADMAP item 3):
        # published/delivered/dropped/evicted and cache hit rates ride
        # the same statsd surface as everything else
        self.collector.hook(
            "subs",
            lambda: {
                k: v for k, v in self.subs.get_json().items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            },
        )
        # fanout tree scale-out observability: per-shard queue depth /
        # drop / evict gauges plus the publish→deliver lag histogram
        # through the Prometheus door (previously get_counts-only, so
        # the watchdog's fanout-p99 rule couldn't be scrape-checked)
        self.collector.hook("subs_shard", self.subs.shard_stats)
        self.collector.register_hist("subs_fanout_lag_ms",
                                     self.subs.lag_hist)
        if self.read_cache is not None:
            self.collector.hook(
                "cache",
                lambda: {
                    k: v
                    for k, v in self.read_cache.get_json().items()
                    if isinstance(v, (int, float))
                },
            )
        if self.path_plane is not None:
            # liquidity-plane gauges (`paths.*`): re-ranks, sheds,
            # staleness, index continuity (doc/observability.md)
            self.collector.hook(
                "paths",
                lambda: {
                    k: v for k, v in self.path_plane.get_json().items()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)
                },
            )
        # span-derived per-stage latency percentiles (trace.<stage>.p50_ms
        # et al.): the unified latency surface the tracing plane feeds
        self.collector.hook("trace", self.tracer.statsd_hook)
        if self.spec_executor.active:
            self.collector.hook(
                "spec",
                lambda: {
                    k: v
                    for k, v in self.spec_executor.counters.snapshot()
                    .items()
                    if k in ("dispatched", "committed", "retries",
                             "validation_aborts", "serial_fallbacks")
                },
            )
        self.collector.hook(
            "delta_replay",
            # snapshot via delta_replay_json: it takes the chain lock, so
            # the three counters are mutually consistent per sample
            lambda: {
                k: v
                for k, v in self.ledger_master.delta_replay_json().items()
                if k in ("spliced", "fallback", "invalidated")
            },
        )
        self.collector.start()
        return self

    def run(self) -> None:
        """Block until stopped (reference: ApplicationImp::run)."""
        import time as _time

        from .jobqueue import JobType

        # watchdog armed only once the run loop drives heartbeats
        # (reference: activateDeadlockDetector from ApplicationImp::run
        # :1028); embedders that drive the node directly never arm it
        self.load_manager.arm()
        last_beat = 0.0
        last_sweep = 0.0
        try:
            self._run_loop(last_beat, last_sweep)
        except BaseException:
            # the flight recorder's whole point: the black box ships
            # BEFORE the stack unwinds (doc/observability.md)
            try:
                self.flight.dump("crash")
            except Exception:  # noqa: BLE001 — dump must not mask the crash
                pass
            raise

    def _run_loop(self, last_beat: float, last_sweep: float) -> None:
        import time as _time

        from .jobqueue import JobType

        while self._running.is_set():
            # the heartbeat must flow THROUGH the job queue: a wedged
            # worker pool or master lock then starves the canary reset and
            # the detector fires (reference: the heartbeat is itself a
            # jtNETOP_TIMER job)
            now = _time.monotonic()
            if now - last_sweep >= 30.0:
                # cache sweep (reference: ApplicationImp::doSweep on the
                # sweep timer — jtSWEEP job over the aged caches)
                last_sweep = now
                self.job_queue.add_job(
                    JobType.jtSWEEP,
                    "sweep",
                    self.ledger_master.ledgers_by_hash.sweep,
                )
                # RPC-client charge-table expiry on the same maintenance
                # timer (reference: Logic::periodicActivity rides the
                # sweep timer) — idle client endpoints age out so a
                # long-lived node's map stays bounded. The PEER table's
                # sweep already rides the overlay's own gossip timer.
                if self.rpc_resources is not None:
                    self.job_queue.add_job(
                        JobType.jtSWEEP, "rpcResourceSweep",
                        self.rpc_resources.sweep,
                    )
                # disk-space guard (reference: doSweep fatals under 512MB
                # free, Application.cpp:1098-1106): stopping cleanly now
                # beats corrupting the stores on a full disk later
                if self.config.database_path:
                    import os as _os
                    import shutil

                    try:
                        free = shutil.disk_usage(
                            _os.path.dirname(
                                _os.path.abspath(self.config.database_path)
                            )
                        ).free
                    except OSError:
                        free = None
                    if free is not None and free < 512 * 1024 * 1024:
                        import logging

                        logging.getLogger("stellard.node").critical(
                            "remaining free disk space is less than "
                            "512MB (%d bytes) — shutting down", free,
                        )
                        self._running.clear()
            if now - last_beat >= 1.0:
                last_beat = now
                self.job_queue.add_job(
                    JobType.jtNETOP_TIMER,
                    "heartbeat",
                    self.load_manager.reset_deadlock_detector,
                )
                if self.sntp is not None and self.sntp.synced:
                    # discipline the network clock used for close times
                    # (reference getNetworkTimeNC via the SNTP offset),
                    # composed with any configured deliberate skew
                    self.ops.net_time_offset = int(
                        round(self.sntp.offset)
                    ) + int(self.config.network_time_offset)
                if self.overlay is not None:
                    # operating mode from overlay health (reference:
                    # NetworkOPs::setMode heuristics): FULL only while
                    # rounds are actually completing — a node that closed
                    # rounds once and then lost its peers must degrade
                    from .networkops import OperatingMode

                    vn = self.overlay.node
                    # a follower's "round" is an ingested validated
                    # ledger: TRACKING while the tail advances (it
                    # tracks the net without proposing), CONNECTED/
                    # DISCONNECTED from peer health otherwise
                    rounds = (
                        vn.ledgers_ingested if vn.follower
                        else vn.rounds_completed
                    )
                    if rounds > getattr(self, "_last_rounds", 0):
                        self._last_rounds = rounds
                        self._last_round_at = now
                    recently = now - getattr(self, "_last_round_at", 0.0) < 60.0
                    if vn.follower:
                        if rounds > 0 and recently:
                            self.ops.mode = OperatingMode.TRACKING
                        elif self.overlay.peer_count() > 0:
                            self.ops.mode = OperatingMode.CONNECTED
                        else:
                            self.ops.mode = OperatingMode.DISCONNECTED
                    elif vn.degraded:
                        # closing without quorum validation: report
                        # TRACKING honestly instead of a confident FULL
                        # from a node whose ledgers nobody signs
                        self.ops.mode = OperatingMode.TRACKING
                        if not self._degraded_dump_done:
                            # black box on entering degraded service —
                            # once per episode, not per heartbeat
                            self._degraded_dump_done = True
                            self.flight.dump("degraded-tracking")
                    elif rounds > 0 and recently:
                        self.ops.mode = OperatingMode.FULL
                        self._degraded_dump_done = False
                    elif self.overlay.peer_count() > 0:
                        self.ops.mode = OperatingMode.CONNECTED
                    else:
                        self.ops.mode = OperatingMode.DISCONNECTED
            _time.sleep(0.2)

    def stop(self) -> None:
        self._running.clear()
        self.load_manager.stop()
        # the executor first: any open speculation window completes
        # serially before the chain machinery below winds down
        self.spec_executor.stop()
        self.ledger_master.stop_seal_drainer()
        if self.overlay is not None:
            stop = getattr(self.overlay, "stop", None)
            if stop is not None:  # embedders may attach bare adapters
                stop()
        if self.online_deleter is not None:
            self.online_deleter.stop()
        # drain-on-stop guarantee: everything queued persists before the
        # stores close (the CLF pointer lands on the last closed ledger)
        self.close_pipeline.stop(timeout=60)
        if self.subs is not None:
            self.subs.stop()
        self.collector.stop()
        if self.sntp is not None:
            self.sntp.stop()
        if self.http_server:
            self.http_server.stop()
        if self.ws_server:
            self.ws_server.stop()
        self.job_queue.stop()
        self.verify_plane.stop()
        self.nodestore.close()
        self.txdb.close()
        if self.shardstore is not None:
            self.shardstore.close()
        if self._debug_log_handler is not None:
            import logging

            logging.getLogger("stellard").removeHandler(self._debug_log_handler)
            self._debug_log_handler.close()
            self._debug_log_handler = None
        if getattr(self, "_tmp_tls_dir", None):
            import shutil

            shutil.rmtree(self._tmp_tls_dir, ignore_errors=True)
            self._tmp_tls_dir = None

    def _update_archive_floor(self) -> None:
        """Publish the archive's verified floor — the contiguous
        sealed-shard coverage hi (``HistoryShardStore.contiguous_floor``)
        — to the read plane's forever tier: any result whose request
        window closes at or below it is backed by offline-verified
        shard bytes and immutable, so it is cached forever instead of
        per epoch."""
        rp = getattr(self, "read_plane", None)
        if rp is not None and self.shardstore is not None:
            rp.set_archive_floor(self.shardstore.contiguous_floor())

    # -- persistence on close (reference: pendSaveValidated + CLF commit) --

    def _persist_closed_ledger(self, ledger: Ledger, results: dict) -> None:
        """Serial (in-line) persist: the close-pipeline-disabled path and
        embedders that drive persistence directly."""
        self.persist_ledger_data(ledger, results)
        self._commit_clf(ledger)

    def _commit_clf(self, ledger: Ledger) -> None:
        # CLF commit: one scoped SQL transaction — entry-row delta + LCL
        # pointer (reference: stellar::LedgerMaster::commitLedgerClose).
        # NOT part of persist_ledger_data: a repaired HISTORICAL ledger
        # must never move the CLF resume pointer backwards.
        prev = self.ledger_master.get_ledger_by_hash(ledger.parent_hash)
        self.clf.commit_ledger_close(ledger, prev)
        if self.online_deleter is not None:
            # rotation hook: runs on the drain worker AFTER the ledger
            # is fully durable; cheap check, sweeps happen in background
            self.online_deleter.on_validated(ledger.seq)

    def _persist_tx_rows(self, ledger: Ledger, results: dict) -> None:
        """Header + tx rows in ONE sqlite transaction (close-pipeline txdb
        stage). Rows were usually materialized at close time overlapped
        with the seal tree-hash (LedgerMaster.persist_prep)."""
        rows = getattr(ledger, "persist_rows", None)
        if rows is None:
            rows = build_tx_rows(ledger, results)
        else:
            # one-shot: the memo must not pin row data in the ledger
            # cache for the ledger's whole cache lifetime
            ledger.persist_rows = None
        self.txdb.save_ledger(ledger, rows)

    def persist_ledger_data(self, ledger: Ledger, results: dict) -> None:
        """NodeStore + header + tx rows for one ledger (no CLF pointer) —
        the shared half of close-persistence and history repair."""
        ledger.save(self.nodestore)
        self._persist_tx_rows(ledger, results)

    # -- convenience driving (tests / CLI) --------------------------------

    def submit(self, tx: SerializedTransaction) -> tuple[TER, bool]:
        return self.ops.process_transaction(tx)

    def close_ledger(self):
        """Test/CLI convenience close: synchronous-DURABLE — the close
        pipeline drains before returning, so callers may immediately read
        txdb/CLF state. The perf paths (bench legs, `ledger_accept` RPC,
        networked consensus closes) call ops.accept_ledger directly and
        stay pipelined."""
        out = self.ops.accept_ledger()
        if not self.close_pipeline.flush(timeout=60):
            # the docstring's durability promise must not fail silently
            raise RuntimeError(
                "close_ledger: persistence pipeline failed to drain within "
                "60s — storage stalled or wedged"
            )
        # synchronous contract extends to the admission plane: the
        # deferred open-window replenish (promotion + queue-aware
        # speculation) lands before this returns, so a caller's next
        # close sees the promoted txs (perf paths stay deferred)
        if not self.txq.quiesce(timeout=30):
            raise RuntimeError(
                "close_ledger: admission-queue replenish failed to land "
                "within 30s — job queue stalled or wedged"
            )
        return out

    def tx_status(self, txid: bytes) -> Optional[TxStatus]:
        return self.ops.on_tx_result.get(txid)


