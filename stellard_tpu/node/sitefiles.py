"""Validator source files: local validators.txt and hosted stellar.txt.

Role parity with the reference's validator sourcing
(/root/reference/src/ripple_app/peers/UniqueNodeList.cpp nodeBootstrap /
validators.txt handling, src/ripple/sitefiles + ripple_net HTTPClient):
the trusted-validator set can come from
- the inline `[validators]` config section (already wired),
- a local validators file (`[validators_file]`),
- a hosted site file fetched over HTTP (`stellar.txt` with a
  `[validators]` section).

The fetcher is stdlib urllib (the reference's async HTTPS fetcher role);
zero-egress deployments simply configure no sites.
"""

from __future__ import annotations

import urllib.request
from typing import Optional

__all__ = ["parse_validators_text", "load_validators_file", "fetch_site_validators"]


def parse_validators_text(text: str) -> list[tuple[str, str]]:
    """-> [(node_public, comment)]. Accepts both a bare list of keys and
    the sectioned stellar.txt shape (keys read from [validators] /
    [validation_public_key] sections)."""
    out: list[tuple[str, str]] = []
    section: Optional[str] = None
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#") or line.startswith(";"):
            continue
        if line.startswith("[") and line.endswith("]"):
            section = line[1:-1].lower()
            continue
        if section not in (None, "validators", "validation_public_key"):
            continue
        parts = line.split(None, 1)
        key = parts[0]
        comment = parts[1] if len(parts) > 1 else ""
        out.append((key, comment))
    return out


def load_validators_file(path: str) -> list[tuple[str, str]]:
    """reference: [validators_file] / validators.txt bootstrap."""
    with open(path) as fh:
        return parse_validators_text(fh.read())


def fetch_site_validators(
    url: str, timeout: float = 5.0
) -> list[tuple[str, str]]:
    """Fetch and parse a hosted stellar.txt (reference: SiteFiles::Manager
    + HTTPClient over HTTPS). Raises OSError on network failure; callers
    decide whether a source being down is fatal (the reference logs and
    moves on).

    The validator list is a TRUST ROOT: plain http is refused except to
    loopback (test harnesses), or an on-path attacker could inject
    validator keys.
    """
    from urllib.parse import urlparse

    parsed = urlparse(url)
    if parsed.scheme != "https" and parsed.hostname not in (
        "localhost", "127.0.0.1", "::1",
    ):
        raise ValueError(
            f"validators_site must be https (got {parsed.scheme!r})"
        )
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_validators_text(resp.read().decode("utf-8", "replace"))
