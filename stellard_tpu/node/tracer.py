"""Tracing plane: transaction-lifecycle spans in a bounded ring buffer.

The last two perf PRs (pipelined close, delta replay) each justified
themselves with hand-instrumented stage timers, and each subsystem grew
a private latency tracker. This module is the shared substrate those
timers collapse into: a Dapper-style causal trace (Sigelman et al.,
2010) threaded per TRANSACTION (trace id = txid) and per LEDGER (trace
id = "ledger-<seq>") through submit → verify batch → open apply /
speculation → consensus round → close splice/fallback → persist.

Design constraints, in order:

- the hot paths must not notice it: one short lock around a ring-slot
  write, no allocation before the enabled/sampling gates, and the
  subsystems that already measure intervals (JobQueue, VerifyPlane,
  ClosePipeline) hand their existing timestamps to ``complete()``
  instead of timing twice;
- bounded memory: a fixed ring of ``capacity`` records — wraparound
  overwrites the oldest, and ``dropped`` counts what scrolled away;
- deterministic sampling: the record/skip decision for a transaction is
  a pure function of (txid, sample rate), so every subsystem a tx
  passes through makes the SAME decision and a sampled tx always gets
  its whole tree. Ledger-scoped spans (a handful per close) are always
  recorded;
- three exports: Chrome trace-event JSON (``chrome_trace`` — loadable
  in Perfetto / chrome://tracing, served by the ``trace_dump`` admin
  RPC), span-derived per-stage latency histograms (``stage_hist``,
  pushed through CollectorManager hooks to statsd), and a compact
  recent consensus/close timeline for ``server_state``/``get_counts``.

Cross-thread spans use the explicit ``begin()``/``end()`` token pair
(the verify plane completes futures on its flusher thread; the close
pipeline persists on its drain worker). Same-thread nesting uses the
``span()`` context manager, which maintains a thread-local parent
stack so child spans link without any caller bookkeeping.

Cross-NODE propagation (``[trace] propagate``): overlay frames carry a
compact trace context — trace id + parent span id + sampled bit — in an
optional high-numbered wire extension (overlay/wire.py TraceContext).
Span ids are node-unique (a per-tracer 32-bit tag in the high bits), so
spans recorded on different nodes never collide and a merged dump
(tools/traceview.py --merge) resolves parent links across processes.
The deterministic per-txid sampling means every node makes the SAME
record/skip decision, so a sampled transaction's causal tree is
complete fleet-wide. ``wire_context()`` exports the sender side;
``adopt_context()`` registers the foreign parent on the receiver, and
any span recorded for that trace with no local parent links under it
(marked ``remote`` in the dump — a single-node validation must not
demand the foreign parent resolve locally).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from typing import Optional

from .metrics import LatencyHist

__all__ = ["Tracer", "SpanToken", "get_tracer"]

# bound on the per-trace foreign-parent / last-span maps the propagation
# plane keeps (FIFO eviction; a trace is a txid or "ledger-<seq>")
_CTX_CAP = 4096

# categories whose events feed the server_state consensus/close timeline
_TIMELINE_CATS = frozenset({"close", "consensus", "persist"})

# finer-than-default bounds for span stages: close/persist stages live
# in the 1-500 ms band where the default decade buckets are too coarse
STAGE_BOUNDS = (
    0.1, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 8.0, 12.0, 20.0, 30.0, 50.0,
    80.0, 120.0, 200.0, 300.0, 500.0, 800.0, 1200.0, 2000.0, 5000.0,
)


class SpanToken:
    """Handle for an in-flight span; pass it across threads and hand it
    back to ``end()`` (or as ``parent=`` of a child span)."""

    __slots__ = ("name", "cat", "trace", "span_id", "parent", "t0",
                 "tid", "attrs")

    def __init__(self, name, cat, trace, span_id, parent, t0, tid, attrs):
        self.name = name
        self.cat = cat
        self.trace = trace
        self.span_id = span_id
        self.parent = parent
        self.t0 = t0
        self.tid = tid
        self.attrs = attrs


class _NullSpan:
    """Context manager returned when tracing is off / the tx unsampled."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *_exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanCM:
    """Context-manager wrapper that maintains the thread-local parent
    stack (so nested ``span()`` calls link parent→child) and ends the
    span on exit."""

    __slots__ = ("_tracer", "token")

    def __init__(self, tracer: "Tracer", token: SpanToken):
        self._tracer = tracer
        self.token = token

    def __enter__(self) -> SpanToken:
        stack = self._tracer._stack()
        stack.append(self.token)
        return self.token

    def __exit__(self, *_exc):
        stack = self._tracer._stack()
        if stack and stack[-1] is self.token:
            stack.pop()
        self._tracer.end(self.token)
        return False


def _trace_id(txid, seq) -> Optional[str]:
    """Normalize the two causal keys: a tx trace is the txid hex, a
    ledger trace is "ledger-<seq>"."""
    if txid is not None:
        return txid.hex() if isinstance(txid, (bytes, bytearray)) else str(txid)
    if seq is not None:
        return f"ledger-{seq}"
    return None


class Tracer:
    """Lock-light bounded ring-buffer span recorder."""

    def __init__(self, capacity: int = 16384, enabled: bool = True,
                 sample: float = 0.125, propagate: bool = False,
                 node_tag: Optional[int] = None):
        self.enabled = bool(enabled)
        self.capacity = max(16, int(capacity))
        self.sample = min(1.0, max(0.0, float(sample)))
        self.propagate = bool(propagate)
        # sampling threshold in basis points of 10000, precomputed so the
        # per-tx gate is one crc32 + one compare
        self._sample_bp = int(round(self.sample * 10000))
        self._lock = threading.Lock()
        self._ring: list = [None] * self.capacity
        self._n = 0  # total records ever pushed
        self._ids = itertools.count(1)
        # node-unique span-id prefix: spans from different tracers
        # (nodes / processes) occupy disjoint id ranges, so a merged
        # multi-node dump resolves cross-node parent links directly
        if node_tag is None:
            node_tag = int.from_bytes(os.urandom(4), "big") or 1
        self.node_tag = node_tag & 0xFFFFFFFF
        self._tag = self.node_tag << 32
        self._epoch = time.perf_counter()
        self._tls = threading.local()
        # span-derived per-stage latency histograms (name -> hist)
        self.stage_hist: dict[str, LatencyHist] = {}
        # propagation state: trace -> foreign parent span id (adopted
        # from the wire) and trace -> last locally recorded span id
        # (exported as the parent of outbound frames). Bounded FIFO.
        self._foreign: dict[str, int] = {}
        self._last: dict[str, int] = {}
        # optional flight-recorder feed (node/health.py FlightRecorder):
        # every recorded span/instant also lands in its black box
        self.flight = None

    @classmethod
    def from_config(cls, cfg) -> "Tracer":
        """Build from a node Config's [trace] knobs."""
        return cls(
            capacity=cfg.trace_capacity,
            enabled=cfg.trace_enabled,
            sample=cfg.trace_sample,
            propagate=getattr(cfg, "trace_propagate", False),
        )

    # -- sampling ----------------------------------------------------------

    def sampled(self, txid) -> bool:
        """Deterministic per-transaction record/skip decision: a pure
        function of (txid, rate) so every pipeline stage agrees and a
        sampled tx gets its complete span tree."""
        if not self.enabled:
            return False
        bp = self._sample_bp
        if bp >= 10000:
            return True
        if bp <= 0:
            return False
        key = txid if isinstance(txid, (bytes, bytearray)) else str(txid).encode()
        return (zlib.crc32(key) & 0xFFFFFFFF) % 10000 < bp

    def _admit(self, txid) -> bool:
        """Gate shared by every record path: enabled, and — when the
        event is tx-scoped — the tx is sampled. Ledger/subsystem-scoped
        events (txid None) are always admitted when enabled: there are
        only a handful per close."""
        if not self.enabled:
            return False
        if txid is None:
            return True
        return self.sampled(txid)

    # -- recording ---------------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _now_us(self) -> int:
        return int((time.perf_counter() - self._epoch) * 1e6)

    def _push(self, rec: tuple) -> None:
        with self._lock:
            self._ring[self._n % self.capacity] = rec
            self._n += 1

    def _parent_id(self, parent) -> Optional[int]:
        if parent is not None:
            return parent.span_id if isinstance(parent, SpanToken) else int(parent)
        stack = self._stack()
        return stack[-1].span_id if stack else None

    def _next_id(self) -> int:
        return self._tag | (next(self._ids) & 0xFFFFFFFF)

    def _resolve_parent(self, parent, trace, attrs):
        """Parent resolution order: explicit > thread-local stack >
        foreign parent adopted from the wire for this trace. A foreign
        parent marks the record ``remote`` so single-node validation
        knows the link resolves on another node's dump."""
        parent_id = self._parent_id(parent)
        if parent_id is None and trace is not None and self._foreign:
            parent_id = self._foreign.get(trace)
            if parent_id is not None:
                attrs = {**(attrs or {}), "remote": 1}
        return parent_id, attrs

    def begin(self, name: str, cat: str, txid=None, seq=None, parent=None,
              **attrs) -> Optional[SpanToken]:
        """Open a span; returns a token to ``end()`` (possibly from
        another thread), or None when tracing is off / the tx unsampled.
        Without an explicit ``parent``, the opening thread's innermost
        ``span()`` context is the parent."""
        if not self._admit(txid):
            return None
        trace = _trace_id(txid, seq)
        parent_id, attrs = self._resolve_parent(parent, trace, attrs)
        return SpanToken(
            name, cat, trace, self._next_id(),
            parent_id, time.perf_counter(),
            threading.get_ident(), attrs or None,
        )

    def end(self, token: Optional[SpanToken], **attrs) -> None:
        """Close a span opened with ``begin()``. None tokens are
        accepted so callers never branch on the sampling decision."""
        if token is None:
            return
        t1 = time.perf_counter()
        ms = (t1 - token.t0) * 1000.0
        if attrs:
            token.attrs = {**(token.attrs or {}), **attrs}
        self._record_complete(token, t1, ms)

    def span(self, name: str, cat: str, txid=None, seq=None, parent=None,
             **attrs):
        """``with tracer.span(...):`` — same-thread span with automatic
        parent linkage through the thread-local stack."""
        token = self.begin(name, cat, txid=txid, seq=seq, parent=parent,
                           **attrs)
        if token is None:
            return _NULL_SPAN
        return _SpanCM(self, token)

    def complete(self, name: str, cat: str, t0: float, t1: float,
                 txid=None, seq=None, parent=None, **attrs) -> None:
        """Record an already-measured interval (perf_counter pair) as a
        span — the zero-extra-timing path for subsystems that already
        clock their stages (JobQueue, VerifyPlane, ClosePipeline)."""
        if not self._admit(txid):
            return
        trace = _trace_id(txid, seq)
        parent_id, attrs = self._resolve_parent(parent, trace, attrs)
        token = SpanToken(
            name, cat, trace, self._next_id(),
            parent_id, t0, threading.get_ident(),
            attrs or None,
        )
        self._record_complete(token, t1, (t1 - t0) * 1000.0)

    def _record_complete(self, token: SpanToken, t1: float, ms: float) -> None:
        with self._lock:
            hist = self.stage_hist.get(token.name)
            if hist is None:
                hist = self.stage_hist[token.name] = LatencyHist(
                    bounds=STAGE_BOUNDS, interpolate=True
                )
            hist.record(ms)
            self._ring[self._n % self.capacity] = (
                "X", token.name, token.cat, token.trace, token.span_id,
                token.parent,
                int((token.t0 - self._epoch) * 1e6),
                max(0, int((t1 - token.t0) * 1e6)),
                token.tid, token.attrs,
            )
            self._n += 1
            if self.propagate and token.trace is not None:
                self._note_last_locked(token.trace, token.span_id)
        fl = self.flight
        if fl is not None:
            fl.note_span("X", token.name, token.cat, token.trace, ms)

    def instant(self, name: str, cat: str, txid=None, seq=None, parent=None,
                **attrs) -> None:
        """Point event (consensus round events, splice/fallback marks)."""
        if not self._admit(txid):
            return
        trace = _trace_id(txid, seq)
        parent_id, attrs = self._resolve_parent(parent, trace, attrs)
        span_id = self._next_id()
        self._push((
            "i", name, cat, trace, span_id, parent_id,
            self._now_us(), 0, threading.get_ident(), attrs or None,
        ))
        if self.propagate and trace is not None:
            with self._lock:
                self._note_last_locked(trace, span_id)
        fl = self.flight
        if fl is not None:
            fl.note_span("i", name, cat, trace, 0.0)

    # -- cross-node propagation --------------------------------------------

    def _note_last_locked(self, trace: str, span_id: int) -> None:
        last = self._last
        if trace not in last and len(last) >= _CTX_CAP:
            last.pop(next(iter(last)))
        last[trace] = span_id

    def adopt_context(self, trace: Optional[str], parent: int) -> None:
        """Register a foreign parent span id for a trace (decoded from
        an inbound frame's TraceContext): every span this node records
        for that trace with no local parent links under it, joining the
        sender's tree. No-op when propagation is off."""
        if not (self.enabled and self.propagate) or not trace or not parent:
            return
        with self._lock:
            fg = self._foreign
            if trace not in fg and len(fg) >= _CTX_CAP:
                fg.pop(next(iter(fg)))
            fg[trace] = parent

    def wire_context(self, txid=None, seq=None):
        """Sender side of cross-node propagation: (trace_bytes, parent
        span id, sampled) for an outbound frame, or None when there is
        nothing to join (propagation off, tx unsampled, or no span
        recorded for the trace yet). trace_bytes is the raw 32-byte
        txid for tx traces, the UTF-8 trace id otherwise."""
        if not (self.enabled and self.propagate):
            return None
        if txid is not None and not self.sampled(txid):
            return None
        trace = _trace_id(txid, seq)
        if trace is None:
            return None
        with self._lock:
            parent = self._last.get(trace) or self._foreign.get(trace)
        if parent is None:
            return None
        if isinstance(txid, (bytes, bytearray)) and len(txid) == 32:
            trace_bytes = bytes(txid)
        else:
            trace_bytes = trace.encode()
        return trace_bytes, parent, True

    @staticmethod
    def trace_key(trace_bytes: bytes) -> Optional[str]:
        """Receiver-side inverse of wire_context's trace encoding."""
        if not trace_bytes:
            return None
        if len(trace_bytes) == 32:
            return trace_bytes.hex()
        try:
            return trace_bytes.decode()
        except UnicodeDecodeError:
            return None

    # -- export ------------------------------------------------------------

    def _snapshot_locked(self) -> list[tuple]:
        """Chronological ring contents; caller holds self._lock."""
        n = self._n
        if n <= self.capacity:
            return self._ring[:n]
        i = n % self.capacity
        return self._ring[i:] + self._ring[:i]

    def _snapshot(self) -> list[tuple]:
        with self._lock:
            return list(self._snapshot_locked())

    def chrome_trace(self, reset: bool = False) -> dict:
        """Chrome trace-event JSON (the `trace_dump` payload): complete
        ("X") and instant ("i") events over one pid, tid = recording
        thread, args carrying the causal ids (trace/span/parent) plus
        the span attrs. Loads directly in Perfetto / chrome://tracing.

        `reset=True` drains ATOMICALLY — snapshot and ring clear under
        one lock hold, so a span recorded concurrently lands in exactly
        one window, never between two (stage histograms survive a
        window reset; `reset()` clears those too)."""
        with self._lock:
            recorded = self._n
            snap = list(self._snapshot_locked())
            if reset:
                self._ring = [None] * self.capacity
                self._n = 0
        events = []
        for rec in snap:
            ph, name, cat, trace, span_id, parent, ts, dur, tid, attrs = rec
            args = dict(attrs) if attrs else {}
            if trace is not None:
                args["trace"] = trace
            args["span"] = span_id
            if parent is not None:
                args["parent"] = parent
            ev = {
                "name": name,
                "cat": cat,
                "ph": ph,
                "ts": ts,
                "pid": 1,
                "tid": tid,
                "args": args,
            }
            if ph == "X":
                ev["dur"] = dur
            else:
                ev["s"] = "t"  # instant scope: thread
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "recorded": recorded,
                "dropped": max(0, recorded - self.capacity),
                "sample": self.sample,
            },
        }

    def timeline(self, limit: int = 64) -> list[dict]:
        """Recent consensus/close/persist events, oldest first — the
        compact status timeline block (full detail lives in
        `trace_dump`). Scans the ring BACKWARDS with an early stop so
        a monitoring poll never copies the whole capacity-sized ring
        under the hot-path lock."""
        picked: list[tuple] = []
        with self._lock:
            n = self._n
            ring = self._ring
            start = n - 1
            stop = max(0, n - self.capacity)
            for j in range(start, stop - 1, -1):
                rec = ring[j % self.capacity]
                if rec[2] in _TIMELINE_CATS:
                    picked.append(rec)
                    if len(picked) >= limit:
                        break
        out = []
        for rec in reversed(picked):
            ph, name, cat, trace, _sid, _par, ts, dur, _tid, attrs = rec
            ev = {"name": name, "cat": cat, "ts_ms": round(ts / 1000.0, 3)}
            if trace is not None:
                ev["trace"] = trace
            if ph == "X":
                ev["dur_ms"] = round(dur / 1000.0, 3)
            if attrs:
                ev.update(attrs)
            out.append(ev)
        return out

    # -- introspection / metrics -------------------------------------------

    def get_json(self) -> dict:
        """`trace_status` payload: knobs + ring occupancy + span-derived
        per-stage latency quantiles."""
        with self._lock:
            n = self._n
            stages = {name: h.get_json() for name, h in self.stage_hist.items()}
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "sample": self.sample,
            "propagate": self.propagate,
            "recorded": n,
            "buffered": min(n, self.capacity),
            "dropped": max(0, n - self.capacity),
            "stages": stages,
        }

    def status_json(self, timeline: bool = True) -> dict:
        """One-call status block for the RPC surfaces: get_json plus —
        for ADMIN surfaces — the recent consensus/close timeline (it
        carries txids and peer key prefixes, so GUEST replies must pass
        timeline=False)."""
        out = self.get_json()
        if timeline:
            out["timeline"] = self.timeline()
        return out

    def statsd_hook(self) -> dict:
        """CollectorManager hook: span-derived p50/p90/p99 per stage as
        pull-gauges (`trace.<stage>.p50_ms: v|g` on the wire)."""
        out = {}
        with self._lock:
            hists = list(self.stage_hist.items())
        for name, h in hists:
            if not h.count:
                continue
            out[f"{name}.p50_ms"] = h.quantile(0.5)
            out[f"{name}.p90_ms"] = h.quantile(0.9)
            out[f"{name}.p99_ms"] = h.quantile(0.99)
        return out

    def reset(self) -> None:
        """Drop buffered events and stage histograms (admin
        `trace_dump` with reset=true; test isolation)."""
        with self._lock:
            self._ring = [None] * self.capacity
            self._n = 0
            self.stage_hist = {}
            self._foreign = {}
            self._last = {}


# module-level default: subsystems constructed outside a Node (unit
# tests, embedders) still trace into a shared, bounded recorder; a Node
# builds its own Tracer from [trace] and installs it on the subsystems
# it owns, so two nodes in one process don't interleave rings
_DEFAULT = Tracer()


def get_tracer() -> Tracer:
    return _DEFAULT
