"""Relational history store: transactions + account index + ledger headers.

Reference: src/ripple_app/data (DatabaseCon over SQLite, schemas in
DBInit.cpp) — transaction.db holds Transactions and AccountTransactions
(the `account_tx` / `tx` RPC backing), ledger.db holds Ledgers headers.
SQLite here too (stdlib), WAL mode, single writer thread via the
JobQueue's jtWAL seam when file-backed.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Optional

__all__ = ["TxDatabase"]


class TxDatabase:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._in_batch = False
        # retention floor: rows strictly below this ledger seq were
        # deleted by trim_below (sql_trim rotation). account_tx uses it
        # to reject markers/windows pointing into trimmed history with
        # a clean lgrIdxInvalid instead of a silent empty page.
        self.retain_floor = 0
        cur = self._conn.cursor()
        cur.execute("PRAGMA journal_mode=WAL")
        # reference: DBInit.cpp TxnDBInit / LedgerDBInit
        cur.execute(
            """CREATE TABLE IF NOT EXISTS Transactions (
                 TransID TEXT PRIMARY KEY, TransType TEXT, FromAcct TEXT,
                 FromSeq INTEGER, LedgerSeq INTEGER, Status TEXT,
                 RawTxn BLOB, TxnMeta BLOB)"""
        )
        cur.execute(
            """CREATE TABLE IF NOT EXISTS AccountTransactions (
                 TransID TEXT, Account TEXT, LedgerSeq INTEGER,
                 TxnSeq INTEGER)"""
        )
        cur.execute(
            """CREATE INDEX IF NOT EXISTS AcctTxIndex ON
                 AccountTransactions(Account, LedgerSeq, TxnSeq)"""
        )
        # the per-row DELETE in save_transactions keys on TransID; without
        # this index it full-scans the table per tx — O(n^2) over a run
        # (reference: DBInit.cpp:62-63 AcctTxIDIndex)
        cur.execute(
            """CREATE INDEX IF NOT EXISTS AcctTxIDIndex ON
                 AccountTransactions(TransID)"""
        )
        # retention trimming deletes by ledger-seq range (reference:
        # DBInit.cpp TxLgrIndex / AcctTxLgrIndex back the same walk)
        cur.execute(
            """CREATE INDEX IF NOT EXISTS TxLgrIndex ON
                 Transactions(LedgerSeq)"""
        )
        cur.execute(
            """CREATE INDEX IF NOT EXISTS AcctTxLgrIndex ON
                 AccountTransactions(LedgerSeq)"""
        )
        cur.execute(
            """CREATE TABLE IF NOT EXISTS Ledgers (
                 LedgerHash TEXT PRIMARY KEY, LedgerSeq INTEGER,
                 PrevHash TEXT, TotalCoins INTEGER, ClosingTime INTEGER,
                 PrevClosingTime INTEGER, CloseTimeRes INTEGER,
                 CloseFlags INTEGER, AccountSetHash TEXT, TransSetHash TEXT)"""
        )
        cur.execute(
            """CREATE TABLE IF NOT EXISTS Validations (
                 LedgerHash TEXT, NodePubKey TEXT, SignTime INTEGER,
                 RawData BLOB)"""
        )
        self._conn.commit()

    def batch(self):
        """One commit for many writes (a closed ledger's tx set persists as
        a single SQLite transaction instead of a commit/fsync per tx)."""
        import contextlib

        @contextlib.contextmanager
        def _batch():
            with self._lock:
                self._in_batch = True
            try:
                yield self
                with self._lock:
                    self._conn.commit()
            finally:
                with self._lock:
                    self._in_batch = False

        return _batch()

    def _commit(self) -> None:
        if not self._in_batch:
            self._conn.commit()

    # -- transactions -----------------------------------------------------

    def save_transactions(self, rows: list[tuple]) -> None:
        """Persist a closed ledger's tx rows: three executemany calls
        instead of 3+len(affected) executes per tx (sqlite statement
        dispatch was ~25% of the flood apply path). Each row is
        (txid, tx_type, account, seq, ledger_seq, status, raw, meta,
        affected_accounts, txn_seq)."""
        with self._lock:
            self._insert_tx_rows(rows)
            self._commit()

    def get_transaction(self, txid: bytes) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT TransType, FromAcct, FromSeq, LedgerSeq, Status, "
                "RawTxn, TxnMeta FROM Transactions WHERE TransID = ?",
                (txid.hex(),),
            ).fetchone()
        if row is None:
            return None
        return {
            "type": row[0],
            "account": bytes.fromhex(row[1]),
            "seq": row[2],
            "ledger_seq": row[3],
            "status": row[4],
            "raw": row[5],
            "meta": row[6],
        }

    def account_transactions(
        self,
        account: bytes,
        min_ledger: int = -1,
        max_ledger: int = 1 << 62,
        limit: int = 200,
        forward: bool = True,
        after: "tuple[int, int] | None" = None,
    ) -> list[dict]:
        """reference: handlers/AccountTx.cpp SQL walk. ``after`` is an
        EXCLUSIVE (ledger_seq, txn_seq) resume point in walk order (the
        marker/resumeToken role, AccountTx.cpp:91-93)."""
        order = "ASC" if forward else "DESC"
        resume = ""
        args: list = [account.hex(), min_ledger, max_ledger]
        if after is not None:
            al, at = int(after[0]), int(after[1])
            cmp = ">" if forward else "<"
            resume = (
                f" AND (A.LedgerSeq {cmp} ? OR "
                f"(A.LedgerSeq = ? AND A.TxnSeq {cmp} ?))"
            )
            args += [al, al, at]
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(
                f"""SELECT T.TransID, T.TransType, T.FromAcct, T.FromSeq,
                     T.LedgerSeq, T.Status, T.RawTxn, T.TxnMeta, A.TxnSeq
                    FROM AccountTransactions A JOIN Transactions T
                      ON A.TransID = T.TransID
                    WHERE A.Account = ? AND A.LedgerSeq BETWEEN ? AND ?{resume}
                    ORDER BY A.LedgerSeq {order}, A.TxnSeq {order} LIMIT ?""",
                args,
            ).fetchall()
        return [
            {
                "txid": bytes.fromhex(r[0]),
                "type": r[1],
                "account": bytes.fromhex(r[2]),
                "seq": r[3],
                "ledger_seq": r[4],
                "status": r[5],
                "raw": r[6],
                "meta": r[7],
                "txn_seq": r[8],
            }
            for r in rows
        ]

    def tx_history(self, start: int = 0, limit: int = 20) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT TransID, TransType, FromAcct, FromSeq, LedgerSeq, "
                "Status, RawTxn, TxnMeta FROM Transactions "
                "ORDER BY LedgerSeq DESC LIMIT ? OFFSET ?",
                (limit, start),
            ).fetchall()
        return [
            {
                "txid": bytes.fromhex(r[0]),
                "type": r[1],
                "account": bytes.fromhex(r[2]),
                "seq": r[3],
                "ledger_seq": r[4],
                "status": r[5],
                "raw": r[6],
                "meta": r[7],
            }
            for r in rows
        ]

    # -- whole-ledger persist (close-pipeline txdb stage) -----------------

    def save_ledger(self, ledger, rows: list[tuple]) -> None:
        """Header + all tx rows in ONE sqlite transaction (one fsync per
        closed ledger instead of two, and a crash can never leave the
        header stored without its rows). `rows` is save_transactions'
        row shape, usually pre-materialized at close time."""
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO Ledgers VALUES (?,?,?,?,?,?,?,?,?,?)",
                self._header_row(ledger),
            )
            self._insert_tx_rows(rows)
            self._conn.commit()

    @staticmethod
    def _header_row(ledger) -> tuple:
        return (
            ledger.hash().hex(),
            ledger.seq,
            ledger.parent_hash.hex(),
            ledger.tot_coins,
            ledger.close_time,
            ledger.parent_close_time,
            ledger.close_resolution,
            ledger.close_flags,
            ledger.account_hash.hex(),
            ledger.tx_hash.hex(),
        )

    def _insert_tx_rows(self, rows: list[tuple]) -> None:
        """Three executemany calls over pre-built rows; caller holds the
        lock and owns the commit."""
        tx_rows = []
        del_rows = []
        acct_rows = []
        for (txid, tx_type, account, seq, ledger_seq, status, raw, meta,
             affected, txn_seq) in rows:
            h = txid.hex()
            tx_rows.append((h, tx_type, account.hex(), seq, ledger_seq,
                            status, raw, meta))
            del_rows.append((h,))
            for acct in affected:
                acct_rows.append((h, acct.hex(), ledger_seq, txn_seq))
        cur = self._conn.cursor()
        cur.executemany(
            "INSERT OR REPLACE INTO Transactions VALUES (?,?,?,?,?,?,?,?)",
            tx_rows,
        )
        cur.executemany(
            "DELETE FROM AccountTransactions WHERE TransID = ?", del_rows
        )
        cur.executemany(
            "INSERT INTO AccountTransactions VALUES (?,?,?,?)", acct_rows
        )

    # -- ledger headers ---------------------------------------------------

    def save_ledger_header(self, ledger) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO Ledgers VALUES (?,?,?,?,?,?,?,?,?,?)",
                self._header_row(ledger),
            )
            self._commit()

    def save_header_dicts(self, headers: list[dict]) -> None:
        """Header rows from parsed header DICTS (state.ledger.parse_header
        keys plus ``hash``) — the shard-import feed holds raw header
        records, never Ledger objects. One transaction for the batch."""
        rows = [
            (
                h["hash"].hex(), h["seq"], h["parent_hash"].hex(),
                h.get("tot_coins", 0), h.get("close_time", 0),
                h.get("parent_close_time", 0),
                h.get("close_resolution", 0), h.get("close_flags", 0),
                h["account_hash"].hex(), h["tx_hash"].hex(),
            )
            for h in headers
        ]
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO Ledgers VALUES (?,?,?,?,?,?,?,?,?,?)",
                rows,
            )
            self._commit()

    def get_ledger_header(self, seq: Optional[int] = None,
                          ledger_hash: Optional[bytes] = None) -> Optional[dict]:
        q = "SELECT LedgerHash, LedgerSeq, PrevHash, TotalCoins, ClosingTime, \
             PrevClosingTime, CloseTimeRes, CloseFlags, AccountSetHash, \
             TransSetHash FROM Ledgers WHERE "
        arg: tuple
        if ledger_hash is not None:
            q += "LedgerHash = ?"
            arg = (ledger_hash.hex(),)
        elif seq is not None:
            q += "LedgerSeq = ?"
            arg = (seq,)
        else:
            # newest stored ledger (reference: getNewestLedgerInfo)
            q += "LedgerSeq = (SELECT MAX(LedgerSeq) FROM Ledgers)"
            arg = ()
        with self._lock:
            row = self._conn.execute(q, arg).fetchone()
        if row is None:
            return None
        return {
            "hash": bytes.fromhex(row[0]),
            "seq": row[1],
            "parent_hash": bytes.fromhex(row[2]),
            "total_coins": row[3],
            "close_time": row[4],
            "parent_close_time": row[5],
            "close_resolution": row[6],
            "close_flags": row[7],
            "account_hash": bytes.fromhex(row[8]),
            "tx_hash": bytes.fromhex(row[9]),
        }

    def ledger_seqs(self) -> list[int]:
        """All stored ledger sequences, ascending (gaps possible after an
        LCL switch — callers must not assume contiguity)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT LedgerSeq FROM Ledgers ORDER BY LedgerSeq"
            ).fetchall()
        return [r[0] for r in rows]

    def account_tx_index(self, min_ledger: int,
                         max_ledger: int) -> list[tuple]:
        """Export the account-tx index rows for seqs in [min, max] —
        (account_bytes, ledger_seq, txn_seq, txid_bytes) — the rows a
        history-shard seal captures BEFORE trim_below deletes them, so
        below-floor account_tx pages from cold storage with the same
        (ledger_seq, txn_seq) marker order."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT Account, LedgerSeq, TxnSeq, TransID "
                "FROM AccountTransactions "
                "WHERE LedgerSeq BETWEEN ? AND ? "
                "ORDER BY LedgerSeq, TxnSeq",
                (min_ledger, max_ledger),
            ).fetchall()
        return [
            (bytes.fromhex(r[0]), r[1], r[2], bytes.fromhex(r[3]))
            for r in rows
        ]

    def trim_below(self, ledger_seq: int) -> dict:
        """Delete transaction/ledger history rows STRICTLY below the
        retention horizon — the SQL half of online deletion (the
        NodeStore sweep bounds the tree store; without this the txdb
        mirror grows forever under [node_db] online_delete rotation).
        One transaction, then a WAL truncate so the file's high-water
        mark actually stops climbing. Returns rows deleted per table."""
        with self._lock:
            cur = self._conn.cursor()
            hashes = [
                r[0] for r in cur.execute(
                    "SELECT LedgerHash FROM Ledgers WHERE LedgerSeq < ?",
                    (ledger_seq,),
                )
            ]
            deleted = {}
            cur.executemany(
                "DELETE FROM Validations WHERE LedgerHash = ?",
                [(h,) for h in hashes],
            )
            deleted["validations"] = max(cur.rowcount, 0)
            cur.execute(
                "DELETE FROM Transactions WHERE LedgerSeq < ?",
                (ledger_seq,),
            )
            deleted["transactions"] = cur.rowcount
            cur.execute(
                "DELETE FROM AccountTransactions WHERE LedgerSeq < ?",
                (ledger_seq,),
            )
            deleted["account_transactions"] = cur.rowcount
            cur.execute(
                "DELETE FROM Ledgers WHERE LedgerSeq < ?", (ledger_seq,)
            )
            deleted["ledgers"] = cur.rowcount
            self._conn.commit()
            # the floor rises only once the deletion actually
            # committed: a failed trim must not lock out history whose
            # rows are all still present
            self.retain_floor = max(self.retain_floor, int(ledger_seq))
            # bound the WAL too: a delete-heavy transaction otherwise
            # leaves the whole trimmed range sitting in the -wal file
            cur.execute("PRAGMA wal_checkpoint(TRUNCATE)")
        return deleted

    def counts(self) -> dict:
        """Row counts per table (observability + the disk-bound test)."""
        with self._lock:
            cur = self._conn.cursor()
            return {
                "transactions": cur.execute(
                    "SELECT COUNT(*) FROM Transactions"
                ).fetchone()[0],
                "account_transactions": cur.execute(
                    "SELECT COUNT(*) FROM AccountTransactions"
                ).fetchone()[0],
                "ledgers": cur.execute(
                    "SELECT COUNT(*) FROM Ledgers"
                ).fetchone()[0],
            }

    def save_validation(self, ledger_hash: bytes, node_public: bytes,
                        sign_time: int, raw: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO Validations VALUES (?,?,?,?)",
                (ledger_hash.hex(), node_public.hex(), sign_time, raw),
            )
            self._commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
