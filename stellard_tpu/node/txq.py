"""Admission-control plane: the fee-escalating transaction queue.

Role parity with production XRPL's FeeEscalation/TxQ design (TxQ.cpp):
the reference this repo reproduces predates it and its only overload
story is coarse shedding (job latency targets, the >100-queued-jobs
relay drop), which lets a flood above close capacity grow the open
ledger without bound and collapse close latency. SEDA (Welsh et al.,
SOSP 2001) is the classic argument that a well-conditioned service
needs an explicit bounded queue with admission control at the front
door, not best-effort shedding.

Shape:

- **soft per-ledger cap** (`FeeMetrics`): the number of transactions a
  close can absorb inside its latency budget, adapted continuously from
  an EWMA of the measured per-transaction close cost of recent closes
  (`txns_expected = target_close_ms / ewma_per_tx_ms`, clamped).
- **escalating open-ledger fee**: below the cap the required fee level
  is the reference level (256 = paying exactly the base fee); at or
  above it the requirement rises quadratically with open-ledger size
  (`mult * (n+1)^2 / expected^2`), so a flood prices itself out
  instead of growing the open ledger.
- **bounded fee-priority queue**: transactions paying less than the
  escalated requirement wait in per-account sequence chains, promoted
  in fee-level order (FIFO within a level) into the next open ledger at
  close time. Same (account, seq) resubmissions replace-by-fee (>= 25%
  bump). Overflow evicts the cheapest entry; entries expire after a
  bounded number of ledgers.
- **queue-aware speculation**: promoted transactions are speculatively
  pre-executed against the open window's delta-replay overlay on a
  deferred job OFF the close path, so the close that commits them
  splices recorded deltas instead of re-running the transactor
  (engine/deltareplay.py; records carry origin="promote").
- **kill-switch**: `[txq] enabled=0` restores the direct-apply path
  byte-for-byte (NetworkOPs bypasses `admit`, LedgerMaster re-applies
  the legacy held pile).

Thread model: `admit` runs under the NetworkOPs master lock and
`promote`/`after_close` under the LedgerMaster chain lock; the internal
lock only protects queue structures against concurrent RPC readers and
is NEVER held across an engine apply.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Optional

from ..protocol.sfields import sfBalance, sfSequence
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from .loadmgr import NORMAL_FEE

__all__ = ["TxQ", "FeeMetrics", "NORMAL_LEVEL", "fee_level"]

# the reference fee level: a tx paying exactly the base fee.
# meets_network_floor compares fee levels against LoadFeeTrack factors
# directly, so this MUST stay the same 1/256 scale as loadmgr's
# NORMAL_FEE — imported, not redefined, to keep the coupling explicit.
NORMAL_LEVEL = NORMAL_FEE


def fee_level(fee_drops: int, base_fee: int) -> int:
    """Fee level of a payment of `fee_drops` against `base_fee`."""
    return fee_drops * NORMAL_LEVEL // max(1, base_fee)


def level_to_drops(level: int, base_fee: int) -> int:
    """Smallest drops amount whose fee level is >= `level` (ceil)."""
    return -(-level * base_fee // NORMAL_LEVEL)


class FeeMetrics:
    """The adaptive soft cap + escalation curve.

    `txns_expected` is the per-ledger admission cap: how many txs fit in
    `target_close_ms` at the EWMA of the measured per-tx close cost.
    Slow closes shrink it, fast ones grow it — AIMD on the close budget
    rather than rippled's largest-recent-ledger heuristic, because this
    node's capacity is whatever the hardware measures, not a constant.
    """

    def __init__(self, min_cap: int = 32, max_cap: int = 100_000,
                 target_close_ms: float = 500.0, alpha: float = 0.25,
                 escalation_mult: int = NORMAL_LEVEL * 500):
        self.min_cap = max(1, int(min_cap))
        self.max_cap = max(self.min_cap, int(max_cap))
        self.target_close_ms = float(target_close_ms)
        self.alpha = float(alpha)
        self.escalation_mult = int(escalation_mult)
        self.txns_expected = min(self.max_cap, max(self.min_cap, 256))
        self.per_tx_ms: Optional[float] = None
        self.closes = 0

    def note_close(self, tx_count: int, apply_ms: float) -> None:
        """Fold one close's (size, apply wall ms) into the cap."""
        self.closes += 1
        if tx_count <= 0 or apply_ms < 0:
            return  # empty closes carry no capacity signal
        per_tx = apply_ms / tx_count
        if self.per_tx_ms is None:
            self.per_tx_ms = per_tx
        else:
            self.per_tx_ms = (
                (1.0 - self.alpha) * self.per_tx_ms + self.alpha * per_tx
            )
        if self.per_tx_ms > 1e-9:
            cap = int(self.target_close_ms / self.per_tx_ms)
            self.txns_expected = max(self.min_cap, min(self.max_cap, cap))

    def required_level(self, open_count: int) -> int:
        """Required fee level to enter an open ledger holding
        `open_count` txs (reference: TxQ escalation curve — quadratic
        above the expected size)."""
        expected = max(1, self.txns_expected)
        if open_count < expected:
            return NORMAL_LEVEL
        return max(
            NORMAL_LEVEL,
            self.escalation_mult * (open_count + 1) ** 2 // expected ** 2,
        )

    def get_json(self) -> dict:
        return {
            "txns_expected": self.txns_expected,
            "min_cap": self.min_cap,
            "max_cap": self.max_cap,
            "target_close_ms": self.target_close_ms,
            "per_tx_close_ms": (
                round(self.per_tx_ms, 4) if self.per_tx_ms is not None
                else None
            ),
            "closes": self.closes,
        }


class _Entry:
    __slots__ = ("tx", "fee_level", "order", "expire_seq")

    def __init__(self, tx: SerializedTransaction, level: int, order: int,
                 expire_seq: int):
        self.tx = tx
        self.fee_level = level
        self.order = order
        self.expire_seq = expire_seq


class TxQ:
    """The admission-control subsystem between the verify plane and the
    open ledger. One instance per node, shared by NetworkOPs (admit) and
    LedgerMaster (promotion at `_open_next`)."""

    def __init__(
        self,
        metrics: Optional[FeeMetrics] = None,
        enabled: bool = True,
        ledgers_in_queue: int = 20,
        account_cap: int = 10,
        retry_fee_pct: int = 25,
        retention_ledgers: int = 20,
        fee_track=None,
        tracer=None,
    ):
        from .tracer import get_tracer

        self.metrics = metrics or FeeMetrics()
        self.enabled = enabled
        self.ledgers_in_queue = max(1, int(ledgers_in_queue))
        self.account_cap = max(1, int(account_cap))
        self.retry_fee_pct = max(0, int(retry_fee_pct))
        self.retention_ledgers = max(1, int(retention_ledgers))
        self.fee_track = fee_track  # loadmgr.LoadFeeTrack or None
        self.tracer = tracer if tracer is not None else get_tracer()
        self._lock = threading.RLock()
        # account -> {sequence -> _Entry}; chains are small (account_cap)
        self._accounts: dict[bytes, dict[int, _Entry]] = {}
        # lazy min-heap over (fee_level, order, account, seq) for
        # cheapest-first eviction; stale entries (replaced/promoted/
        # expired) are skipped on pop by order mismatch
        self._heap: list[tuple[int, int, bytes, int]] = []
        self._size = 0
        self._order = 0  # arrival counter: FIFO within a fee level
        # promoted-but-not-yet-speculated txs: (target open seq, tx),
        # drained by a deferred job off the close path (spec_dispatch)
        self._pending_spec: list[tuple[int, SerializedTransaction]] = []
        self.spec_dispatch: Optional[Callable[[Callable], bool]] = None
        self._lm = None  # LedgerMaster backref for the deferred drain
        self._deferred_jobs = 0  # open-window jobs in flight (quiesce)
        # drop notifier (eviction / expiry / promote-drop): wired to
        # LocalTxs.remove in networked mode so a dropped local tx stops
        # re-applying and a client resubmit starts a fresh horizon
        self.on_drop: Optional[Callable[[bytes], None]] = None
        # txids promoted into the CURRENT open window — intersected with
        # the next close's splice/fallback classes for the
        # promote_spliced / promote_fallback counters
        self._promoted_window: set[bytes] = set()
        # promoted txs awaiting relay (fee floor met only at promotion);
        # drained outside the chain lock by publish_closed_ledger
        self._pending_relay: list[SerializedTransaction] = []
        self.stats = {
            "admitted_direct": 0,   # applied straight to the open ledger
            "queued": 0,            # entered the queue (incl. replaces)
            "replaced": 0,          # replace-by-fee of a queued entry
            "rejected": 0,          # refused admission (shed)
            "evicted": 0,           # pushed out by a better-paying tx
            "expired": 0,           # aged out by ledger seq
            "absorbed_held": 0,     # terPRE_SEQ holds folded into the queue
            "promoted": 0,          # applied to a new open ledger at close
            "promote_dropped": 0,   # dropped at promotion (tem/tef/tec)
            "promote_spliced": 0,   # promoted txs spliced at their close
            "promote_fallback": 0,  # promoted txs serially re-applied
            "deferred_specs": 0,    # speculations run off the close path
        }

    @classmethod
    def from_config(cls, cfg, fee_track=None, tracer=None) -> "TxQ":
        return cls(
            metrics=FeeMetrics(
                min_cap=cfg.txq_min_cap,
                max_cap=cfg.txq_max_cap,
                target_close_ms=cfg.txq_target_close_ms,
            ),
            enabled=cfg.txq_enabled,
            ledgers_in_queue=cfg.txq_ledgers_in_queue,
            account_cap=cfg.txq_account_cap,
            retry_fee_pct=cfg.txq_retry_fee_pct,
            retention_ledgers=cfg.txq_retention_ledgers,
            fee_track=fee_track,
            tracer=tracer,
        )

    # -- introspection helpers --------------------------------------------

    @property
    def max_size(self) -> int:
        return self.metrics.txns_expected * self.ledgers_in_queue

    def __len__(self) -> int:
        with self._lock:
            return self._size

    @staticmethod
    def open_size(ledger) -> int:
        """Applied-tx count of an OPEN ledger (parsed_txs is seeded by
        LedgerMaster._open_apply exactly once per applied tx)."""
        return len(ledger.parsed_txs)

    def open_ledger_fee(self, ledger) -> int:
        """Drops required to enter the open ledger RIGHT NOW."""
        level = self.metrics.required_level(self.open_size(ledger))
        return level_to_drops(level, ledger.base_fee)

    def meets_network_floor(self, tx: SerializedTransaction,
                            ledger) -> bool:
        """Whether a queued tx pays at least the current NETWORK fee
        floor (local + remote load fees — NOT our open-ledger escalation,
        which is local admission state other nodes don't share). Queued
        txs below the floor are not relayed until promotion applies them
        (reference: TxQ holds relay for queued txs)."""
        floor = NORMAL_LEVEL
        if self.fee_track is not None:
            floor = self.fee_track.network_floor
        fee = tx.fee
        if not fee.is_native or fee.negative:
            return False
        return fee.mantissa * NORMAL_LEVEL >= floor * ledger.base_fee

    # -- admission (NetworkOPs.process_transaction) ------------------------

    def admit(self, tx: SerializedTransaction, lm,
              params) -> tuple[TER, bool]:
        """Post-verify intake: apply directly when the open ledger has
        room (or the tx pays the escalated fee), else queue/shed.
        Caller holds the master lock; returns (TER, did_apply) with the
        same contract as LedgerMaster.do_transaction."""
        tr = self.tracer
        txid = tx.txid()
        open_ledger = lm.current_ledger()
        fee = tx.fee
        if not fee.is_native or fee.negative:
            # malformed fee: the engine's passes_local_checks gate
            # rejects it (temINVALID) before the transactor's sequence
            # check can run, so this bypass cannot surface terPRE_SEQ
            # today. Guard anyway: NetworkOPs skips the legacy hold pile
            # when the queue is on, so if that check ordering ever
            # changed, returning terPRE_SEQ from here would report HELD
            # while silently dropping the tx — fold it into the queue at
            # level 0 like any other hold instead.
            ter, did_apply = lm.do_transaction(tx, params)
            if ter == TER.terPRE_SEQ:
                with lm._lock:
                    qter = self._try_queue(tx, 0, lm, open_ledger)
                return qter, False
            return ter, did_apply
        level = fee_level(fee.mantissa, open_ledger.base_fee)
        open_count = self.open_size(open_ledger)
        required = self.metrics.required_level(open_count)
        with tr.span("txq.admit", "submit", txid=txid,
                     open_count=open_count, required_level=required,
                     fee_level=level):
            if level >= required:
                ter, did_apply = lm.do_transaction(tx, params)
                if ter == TER.terPRE_SEQ:
                    # fold the would-be held pile into the queue: future-
                    # sequence txs wait fee-ordered like everything else
                    with lm._lock:
                        qter = self._try_queue(tx, level, lm, open_ledger)
                    return qter, False
                if did_apply:
                    self.stats["admitted_direct"] += 1
                return ter, did_apply
            # above the soft cap and paying less than the escalated
            # fee. The chain lock covers the open-ledger reads inside
            # _try_queue (account root, open_tx_seqs): the deferred
            # promotion job mutates the same open window under it.
            with lm._lock:
                ter = self._try_queue(tx, level, lm, open_ledger)
            return ter, False

    def _try_queue(self, tx: SerializedTransaction, level: int, lm,
                   open_ledger) -> TER:
        """Queue-entry path; returns terQUEUED on success or the shed/
        reject code. Never applies state."""
        account = tx.account
        seq = tx.sequence
        with self._lock:
            chain = self._accounts.get(account)
            replacing = chain is not None and seq in chain
            # cheap sanity against the open view: a tx that can never
            # apply must not occupy queue space
            root = open_ledger.read_entry_pristine(
                _account_index(account)
            )
            if root is None:
                self.stats["rejected"] += 1
                return TER.terNO_ACCOUNT
            if not replacing:
                a_seq = root[sfSequence]
                cached = open_ledger.open_tx_seqs.get(account)
                if cached is not None and cached + 1 > a_seq:
                    a_seq = cached + 1
                if seq < a_seq:
                    self.stats["rejected"] += 1
                    return TER.tefPAST_SEQ
            bal = root[sfBalance]
            if bal.is_native and tx.fee.is_native:
                # the WHOLE chain's queued fees must be payable, not
                # just this tx's (reference: TxQ's potential-spend
                # check): otherwise a balance-20 account queues
                # account_cap fee-15 txs of which only the first can
                # ever pay, and the rest squat as terINSUF_FEE_B
                # retries until expiry
                queued_spend = sum(
                    e.tx.fee.mantissa for s, e in chain.items()
                    if s != seq and e.tx.fee.is_native
                ) if chain else 0
                if bal.mantissa < queued_spend + tx.fee.mantissa:
                    self.stats["rejected"] += 1
                    return TER.terINSUF_FEE_B
            if replacing:
                return self._replace_by_fee(chain, seq, tx, level)
            if chain is not None and len(chain) >= self.account_cap:
                self.stats["rejected"] += 1
                return TER.telINSUF_FEE_P
            # overflow: evict strictly-cheaper entries, else shed the
            # newcomer (resubmittable: the fee can be raised). Never
            # evict from the NEWCOMER's own account: dropping its tail
            # to insert a higher sequence would manufacture the exact
            # mid-chain gap eviction is designed to avoid.
            while self._size >= self.max_size:
                if not self._evict_cheaper_than(level, account):
                    self.stats["rejected"] += 1
                    return TER.telINSUF_FEE_P
            if chain is None:
                chain = self._accounts[account] = {}
            expire = self._closed_seq(lm) + self.retention_ledgers
            self._insert(chain, account, seq, tx, level, expire)
            self.stats["queued"] += 1
            return TER.terQUEUED

    def _replace_by_fee(self, chain: dict, seq: int,
                        tx: SerializedTransaction, level: int) -> TER:
        old = chain[seq]
        bump = old.fee_level * (100 + self.retry_fee_pct) // 100
        if level < max(bump, old.fee_level + 1):
            self.stats["rejected"] += 1
            return TER.telINSUF_FEE_P
        account = tx.account
        self._remove(account, seq)  # drops the old entry (heap laziness)
        self._insert(chain if chain else
                     self._accounts.setdefault(account, {}),
                     account, seq, tx, level, old.expire_seq)
        self.stats["replaced"] += 1
        self.stats["queued"] += 1
        return TER.terQUEUED

    def _insert(self, chain: dict, account: bytes, seq: int,
                tx: SerializedTransaction, level: int,
                expire_seq: int) -> None:
        self._order += 1
        entry = _Entry(tx, level, self._order, expire_seq)
        chain[seq] = entry
        self._accounts.setdefault(account, chain)
        self._size += 1
        heapq.heappush(self._heap, (level, entry.order, account, seq))

    def _remove(self, account: bytes, seq: int) -> Optional[_Entry]:
        chain = self._accounts.get(account)
        if chain is None:
            return None
        entry = chain.pop(seq, None)
        if entry is None:
            return None
        if not chain:
            del self._accounts[account]
        self._size -= 1
        return entry  # its heap tuple goes stale; skipped on pop

    def _evict_cheaper_than(self, floor_level: int,
                            newcomer_account: bytes) -> bool:
        """Evict one entry to make room, or return False when nothing
        queued is strictly cheaper than `floor_level`. The cheapest live
        entry picks the victim ACCOUNT, but the eviction takes that
        account's chain TAIL (highest sequence): dropping a mid-chain
        entry would orphan every later sequence behind an unpromotable
        gap (reference: rippled TxQ::erase evicts chain ends for the
        same reason). The newcomer's own account is never the victim —
        evicting its tail to insert a later sequence would create that
        same gap — the newcomer is shed instead (reference: rippled
        rejects in this case too)."""
        while self._heap:
            lvl, order, account, seq = self._heap[0]
            chain = self._accounts.get(account)
            entry = chain.get(seq) if chain else None
            if entry is None or entry.order != order:
                heapq.heappop(self._heap)  # stale
                continue
            if lvl >= floor_level or account == newcomer_account:
                return False
            tail_seq = max(chain)
            victim = self._remove(account, tail_seq)
            # the cheapest entry's heap tuple stays valid unless it WAS
            # the tail; either way stale tuples skip on later pops
            self.stats["evicted"] += 1
            self.tracer.instant("txq.evict", "submit",
                                txid=victim.tx.txid(),
                                fee_level=victim.fee_level)
            self._notify_drop(victim.tx.txid())
            return True
        return False

    def _notify_drop(self, txid: bytes) -> None:
        """A tx left the admission plane without applying (eviction,
        expiry, promote-drop, rejected held absorption): tell LocalTxs
        so networked re-apply stops and a client resubmit starts
        fresh."""
        if self.on_drop is not None:
            try:
                self.on_drop(txid)
            except Exception:  # noqa: BLE001 — observers must not break
                pass           # admission control

    @staticmethod
    def _closed_seq(lm) -> int:
        closed = lm.closed
        return closed.seq if closed is not None else 0

    # -- held-pile absorption (LedgerMaster._open_next) --------------------

    def absorb_held(self, tx: SerializedTransaction, lm,
                    expire_seq: Optional[int] = None) -> TER:
        """Fold a terPRE_SEQ hold (legacy pile / validator path) into the
        queue so holds are fee-ordered and bounded like everything else.
        Caller holds the chain lock."""
        open_ledger = lm.current_ledger()
        level = (
            fee_level(tx.fee.mantissa, open_ledger.base_fee)
            if tx.fee.is_native and not tx.fee.negative else 0
        )
        ter = self._try_queue(tx, level, lm, open_ledger)
        if ter == TER.terQUEUED:
            self.stats["absorbed_held"] += 1
            if expire_seq is not None:
                # preserve the ORIGINAL hold horizon so re-held txs
                # cannot refresh themselves forever
                with self._lock:
                    chain = self._accounts.get(tx.account)
                    entry = chain.get(tx.sequence) if chain else None
                    if entry is not None:
                        entry.expire_seq = min(entry.expire_seq, expire_seq)
        else:
            # the hold is DROPPED (queue full / hopeless): the drop
            # contract applies — LocalTxs must stop the cross-round
            # re-apply or the tx bypasses admission forever
            self._notify_drop(tx.txid())
        return ter

    # -- close integration (LedgerMaster._open_next) -----------------------

    def after_close(self, lm, closed_ledger, apply_ms: float) -> int:
        """The per-close drive: update the capacity model and expire
        aged entries synchronously (cheap), then replenish the new open
        window — promotion in fee order, queue-aware speculation, fee
        feedback — on a deferred job OFF the close path, so the close
        itself stays at its spliced-apply cost (the whole point of the
        admission plane). Falls back to inline replenish when no
        dispatcher is wired (bare LedgerMaster embedders, deterministic
        tests) or the job queue refuses (shutdown). Caller holds the
        chain lock. Returns the promotion count (0 when deferred)."""
        self.metrics.note_close(
            self.open_size(closed_ledger), apply_ms
        )
        self._sweep_expired(closed_ledger.seq)
        self._lm = lm
        if self.spec_dispatch is not None:
            # the job promotes into THIS open window only: if the job
            # queue backs up past the next close (the overload case),
            # a stale job must not stack a second full promotion pass
            # onto a window the newer job already replenished
            target = lm.current_ledger().seq
            with self._lock:
                self._deferred_jobs += 1
            if self.spec_dispatch(lambda: self._deferred_open_work(target)):
                return 0
            with self._lock:
                self._deferred_jobs -= 1
        return self._replenish_open(lm)

    def _promote_and_feed(self, lm) -> int:
        """Promote into the current open window, then feed the
        (post-promotion) escalated requirement back as the queue fee
        component of load_factor, so server_info/fee/pubServer all see
        the admission price and under-payers are priced consistently.
        Caller holds the chain lock."""
        promoted = self._promote(lm)
        if self.fee_track is not None:
            self.fee_track.set_queue_fee(
                self.metrics.required_level(
                    self.open_size(lm.current_ledger())
                )
            )
        return promoted

    def _replenish_open(self, lm) -> int:
        """The inline open-window replenish (no dispatcher wired).
        Caller holds the chain lock."""
        promoted = self._promote_and_feed(lm)
        if self._pending_spec:
            self._drain_deferred_spec()
        return promoted

    def _deferred_open_work(self, target_seq: int) -> None:
        lm = self._lm
        try:
            if lm is not None:
                with lm._lock:
                    cur = lm.current
                    if cur is None or cur.seq != target_seq:
                        return  # window moved on; the newer job owns it
                    self._promote_and_feed(lm)
                self._drain_deferred_spec()
        finally:
            with self._lock:
                self._deferred_jobs -= 1

    def quiesce(self, timeout: float = 5.0) -> bool:
        """Wait until no deferred open-window work is outstanding
        (promotion jobs + pending speculations) — the bench/smoke
        drivers model the inter-close open window with this."""
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._lock:
                if self._deferred_jobs == 0 and not self._pending_spec:
                    return True
            time.sleep(0.002)
        return False

    def _promote(self, lm) -> int:
        """Fill the new open ledger from the queue in fee-level order
        (FIFO within a level), per-account lowest sequence first so
        chains stay ordered. Budget = the soft cap."""
        t0 = time.perf_counter()
        target_seq = lm.current_ledger().seq
        with self._lock:
            self._promoted_window = set()
            heads: list[tuple[int, int, bytes, int]] = []
            for account, chain in self._accounts.items():
                s = min(chain)
                e = chain[s]
                heads.append((-e.fee_level, e.order, account, s))
            heapq.heapify(heads)
        # fill UP TO the soft cap: consensus leftovers (networked close)
        # already re-applied into this window count against it, so a
        # close never carries leftovers + a full promotion pass
        budget = max(
            0,
            self.metrics.txns_expected - self.open_size(lm.current_ledger()),
        )
        applied = attempts = 0
        from ..engine.engine import TxParams

        while heads and applied < budget:
            _neg, order, account, seq = heapq.heappop(heads)
            with self._lock:
                chain = self._accounts.get(account)
                entry = chain.get(seq) if chain else None
            if entry is None or entry.order != order:
                continue  # replaced/evicted since the snapshot
            attempts += 1
            ter, did_apply = lm._open_apply(
                entry.tx, TxParams.OPEN_LEDGER | TxParams.RETRY,
                speculate=False,
            )
            if did_apply or ter == TER.tesSUCCESS:
                with self._lock:
                    self._remove(account, seq)
                    self.stats["promoted"] += 1
                    self._promoted_window.add(entry.tx.txid())
                    self._pending_spec.append((target_seq, entry.tx))
                    self._pending_relay.append(entry.tx)
                    nxt = self._head_of(account)
                applied += 1
                if nxt is not None:
                    heapq.heappush(heads, nxt)
            elif ter == TER.terPRE_SEQ:
                # still a future sequence: the whole chain stays queued
                continue
            elif ter.is_ter or ter == TER.telINSUF_FEE_P:
                # retriable next ledger (expiry bounds the wait)
                continue
            else:
                # tem/tef/tec: never going to land from the queue
                with self._lock:
                    self._remove(account, seq)
                    self.stats["promote_dropped"] += 1
                    self._notify_drop(entry.tx.txid())
                    nxt = self._head_of(account)
                if nxt is not None:
                    heapq.heappush(heads, nxt)
        self.tracer.complete(
            "txq.promote", "close", t0, time.perf_counter(),
            promoted=applied, attempts=attempts, queue=len(self),
        )
        return applied

    def _head_of(self, account: bytes) -> Optional[tuple]:
        chain = self._accounts.get(account)
        if not chain:
            return None
        s = min(chain)
        e = chain[s]
        return (-e.fee_level, e.order, account, s)

    def _sweep_expired(self, closed_seq: int) -> None:
        with self._lock:
            for account in list(self._accounts):
                chain = self._accounts[account]
                for seq in [s for s, e in chain.items()
                            if e.expire_seq < closed_seq]:
                    entry = self._remove(account, seq)
                    self.stats["expired"] += 1
                    if entry is not None:
                        self._notify_drop(entry.tx.txid())

    # -- deferred queue-aware speculation ----------------------------------

    def _drain_deferred_spec(self) -> None:
        """Run the promoted txs' delta-replay speculation in promotion
        order, in small chain-lock batches so submissions interleave.
        Any tx whose open window already moved on is skipped — its close
        simply falls back to the serial apply (counted)."""
        lm = self._lm
        if lm is None:
            return
        ex = getattr(lm, "spec_executor", None)
        # with the parallel executor active, _speculate_open is an O(1)
        # dispatch instead of a full close-mode execution, so a much
        # larger batch fits under one chain-lock hold and the worker
        # pool fills in one burst
        step = 128 if ex is not None and ex.active else 16
        while True:
            with self._lock:
                batch = self._pending_spec[:step]
                del self._pending_spec[:step]
            if not batch:
                return
            with lm._lock:
                cur = lm.current
                for target_seq, tx in batch:
                    if cur is None or cur.seq != target_seq:
                        continue
                    lm._speculate_open(cur, tx, origin="promote")
                    self.stats["deferred_specs"] += 1

    def note_close_classes(self, classes: dict[bytes, str]) -> None:
        """Per-close splice/fallback outcome for the txs THIS queue
        promoted into the just-closed window — the honesty counter for
        the queue-aware-speculation claim (get_counts.txq)."""
        with self._lock:
            window = self._promoted_window
            if not window:
                return
            for txid, cls in classes.items():
                if txid in window:
                    if cls == "spliced":
                        self.stats["promote_spliced"] += 1
                    else:
                        self.stats["promote_fallback"] += 1
            self._promoted_window = set()

    def drain_relay(self) -> list[SerializedTransaction]:
        """Promoted txs whose relay was deferred past the chain lock
        (NetworkOPs.publish_closed_ledger relays them)."""
        with self._lock:
            out = self._pending_relay
            self._pending_relay = []
        return out

    # -- RPC surfaces ------------------------------------------------------

    def account_json(self, account: bytes) -> dict:
        """`account_info` queue block (reference: queue_data)."""
        with self._lock:
            chain = self._accounts.get(account)
            if not chain:
                return {"txn_count": 0}
            seqs = sorted(chain)
            return {
                "txn_count": len(chain),
                "lowest_sequence": seqs[0],
                "highest_sequence": seqs[-1],
                "max_spend_drops_total": str(sum(
                    chain[s].tx.fee.mantissa for s in seqs
                    if chain[s].tx.fee.is_native
                )),
                "transactions": [
                    {
                        "seq": s,
                        "fee_level": str(chain[s].fee_level),
                        "hash": chain[s].tx.txid().hex().upper(),
                    }
                    for s in seqs
                ],
            }

    def fee_json(self, ledger) -> dict:
        """The `fee` RPC body (reference: handlers/Fee1.cpp shape)."""
        with self._lock:
            open_count = self.open_size(ledger)
            required = self.metrics.required_level(open_count)
            base = ledger.base_fee
            return {
                "current_ledger_size": str(open_count),
                "current_queue_size": str(self._size),
                "expected_ledger_size": str(self.metrics.txns_expected),
                "max_queue_size": str(self.max_size),
                "ledger_current_index": ledger.seq,
                "levels": {
                    "reference_level": str(NORMAL_LEVEL),
                    "minimum_level": str(NORMAL_LEVEL),
                    "open_ledger_level": str(required),
                },
                "drops": {
                    "base_fee": str(base),
                    "minimum_fee": str(base),
                    "open_ledger_fee": str(level_to_drops(required, base)),
                },
            }

    def get_json(self) -> dict:
        with self._lock:
            out = {
                "enabled": self.enabled,
                "size": self._size,
                "max_size": self.max_size,
                "accounts": len(self._accounts),
                "pending_spec": len(self._pending_spec),
                **self.stats,
            }
        out["metrics"] = self.metrics.get_json()
        return out


def _account_index(account_id: bytes) -> bytes:
    from ..state import indexes

    return indexes.account_root_index(account_id)
