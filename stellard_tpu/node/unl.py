"""UniqueNodeList: the managed trusted-validator registry.

Role parity with the reference's UNL plane
(/root/reference/src/ripple_app/peers/UniqueNodeList.cpp, 2.1k LoC, plus
src/ripple/validators/): the UNL seeds from config `[validators]`,
supports runtime add/remove with comments, persists across restarts
(wallet.db role — a JSON-lines file here), and keeps per-validator
bookkeeping from received validations (the modern replacement for the
deprecated scoring crawler: observed validation counts + last-seen
times, which `unl_score`/`unl_list` report).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterable, Optional

from ..protocol.keys import decode_node_public, encode_node_public

__all__ = ["UniqueNodeList"]


class UniqueNodeList:
    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        # pubkey -> {"comment": str, "added_at": float}
        self._nodes: dict[bytes, dict] = {}
        # received-validation bookkeeping (validators/ Manager role)
        self._seen: dict[bytes, dict] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    for line in f:
                        rec = json.loads(line)
                        self._nodes[decode_node_public(rec["public"])] = {
                            "comment": rec.get("comment", ""),
                            "added_at": rec.get("added_at", 0.0),
                        }
            except (OSError, ValueError, KeyError):
                self._nodes = {}

    # -- membership -------------------------------------------------------

    def add(self, public: bytes, comment: str = "") -> bool:
        with self._lock:
            if public in self._nodes:
                return False
            self._nodes[public] = {"comment": comment, "added_at": time.time()}
        self.save()
        return True

    def remove(self, public: bytes) -> bool:
        with self._lock:
            if public not in self._nodes:
                return False
            del self._nodes[public]
        self.save()
        return True

    def reset(self) -> None:
        with self._lock:
            self._nodes.clear()
        self.save()

    def load_from(self, publics: Iterable[bytes], comment: str = "config") -> int:
        n = 0
        for pk in publics:
            if self.add(pk, comment):
                n += 1
        return n

    def __contains__(self, public: bytes) -> bool:
        with self._lock:
            return public in self._nodes

    def publics(self) -> set[bytes]:
        with self._lock:
            return set(self._nodes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._nodes)

    # -- validation bookkeeping ------------------------------------------

    def on_validation(self, public: bytes, ledger_seq: Optional[int]) -> None:
        with self._lock:
            rec = self._seen.setdefault(
                public, {"validations": 0, "last_seq": 0, "last_seen": 0.0}
            )
            rec["validations"] += 1
            if ledger_seq:
                rec["last_seq"] = max(rec["last_seq"], ledger_seq)
            rec["last_seen"] = time.time()

    def on_byzantine(self, public: bytes, kind: str) -> None:
        """Per-validator misbehavior bookkeeping: recognized hostile
        inputs attributable to a SIGNING key (equivocating proposals,
        conflicting validations, bad signatures claiming this key).
        Reported by `unl_list`/`unl_score` so an operator can see WHICH
        trusted validator is misbehaving, not just that one is."""
        with self._lock:
            rec = self._seen.setdefault(
                public, {"validations": 0, "last_seq": 0, "last_seen": 0.0}
            )
            byz = rec.setdefault("byzantine", {})
            byz[kind] = byz.get(kind, 0) + 1

    # -- persistence ------------------------------------------------------

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            items = list(self._nodes.items())
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for pk, meta in items:
                f.write(json.dumps({
                    "public": encode_node_public(pk),
                    "comment": meta["comment"],
                    "added_at": meta["added_at"],
                }))
                f.write("\n")
        os.replace(tmp, self.path)

    # -- reporting --------------------------------------------------------

    def get_json(self) -> list[dict]:
        with self._lock:
            out = []
            for pk, meta in sorted(self._nodes.items()):
                seen = self._seen.get(pk, {})
                out.append({
                    "pubkey_validator": encode_node_public(pk),
                    "comment": meta["comment"],
                    "trusted": True,
                    "validations": seen.get("validations", 0),
                    "last_ledger_seq": seen.get("last_seq", 0),
                    "byzantine_events": dict(seen.get("byzantine", {})),
                })
            return out
