"""ValidatorNode: the consensus-facing orchestration of one validator —
round lifecycle, peer message handling, and quorum acceptance.

Reference: this is the slice of NetworkOPs that owns consensus
(tryStartConsensus/beginConsensus, NetworkOPs.cpp:741-975; recvValidation
:1668; processTrustedProposal) plus LedgerMaster::checkAccept. It is
transport-agnostic: the deterministic simnet (overlay.simnet) and the
TCP overlay both drive it through the same entry points, mirroring how
the reference tests consensus through testoverlay without sockets.

TPU shape: bursts of peer validations/proposals are signature-checked
through the VerifyPlane as one device batch per timer tick rather than
one libsodium call per message.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from ..consensus.consensus import ConsensusAdapter, LedgerConsensus
from ..consensus.proposal import LedgerProposal
from ..consensus.timing import LEDGER_IDLE_INTERVAL, LEDGER_MIN_CONSENSUS_MS
from ..consensus.txset import TxSet
from ..consensus.validation import STValidation
from ..consensus.validations import ValidationsStore
from ..engine.engine import TxParams
from ..protocol.keys import KeyPair
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger
from .hashrouter import SF_BAD, SF_SIGGOOD, HashRouter
from .ledgermaster import LedgerMaster

__all__ = ["ValidatorNode"]


def _locked(method):
    """Serialize a ValidatorNode entry point on the master lock (RLock:
    accept callbacks re-enter from within a locked timer tick)."""
    import functools

    @functools.wraps(method)
    def wrapper(self, *args, **kwargs):
        with self.lock:
            return method(self, *args, **kwargs)

    return wrapper


class ValidatorNode:
    # closed-vs-validated lag (in ledgers) beyond which the node reports
    # itself degraded: it is still CLOSING rounds (closing needs no
    # quorum) but the network is not validating them — an operator must
    # see "tracking", not a confident "proposing/full" from a node whose
    # chain nobody else signs (reference: NetworkOPs::setMode demotes on
    # lost consensus)
    DEGRADE_LAG = 4

    def __init__(
        self,
        key: KeyPair,
        unl: set[bytes],
        adapter: ConsensusAdapter,
        quorum: int,
        network_time: Callable[[], int],
        clock: Callable[[], float] = _time.monotonic,
        hash_batch: Optional[Callable] = None,
        verify_many: Optional[Callable] = None,
        proposing: bool = True,
        idle_interval: int = LEDGER_IDLE_INTERVAL,
        voting=None,
        lock=None,
        router: Optional[HashRouter] = None,
        follower: bool = False,
    ):
        import threading

        # master lock: consensus timer / peer-message threads and the RPC
        # plane mutate the SAME LedgerMaster when this validator backs an
        # application container (reference: getApp().getMasterLock());
        # every public entry point below serializes on it
        self.lock = lock if lock is not None else threading.RLock()
        self.key = key
        self.unl = set(unl) | {key.public}  # we trust ourselves
        self.adapter = adapter
        self.network_time = network_time
        self.clock = clock
        self.hash_batch = hash_batch
        self.verify_many = verify_many  # VerifyPlane.verify_many or None
        self.proposing = proposing and not follower
        # follower mode ([node] mode=follower, ROADMAP item 3): this
        # node NEVER runs consensus rounds — it tails validated-ledger
        # announcements from trusted validators, acquires each validated
        # ledger (bulk GetSegments catch-up + the node-granular tree
        # walk, every record/hash content-verified), and adopts it.
        # The whole read RPC + subscription surface then serves from
        # the ingested chain at wire speed, off the write path.
        self.follower = follower
        self.idle_interval = idle_interval
        self.voting = voting  # consensus.voting.VotingBox or None

        self.lm = LedgerMaster(hash_batch=hash_batch)
        self.lm.min_validations = quorum
        # byzantine-defense counters (`byzantine.*` in get_counts): every
        # hostile input the node recognized and neutralized bumps one of
        # these and emits a `byzantine.<kind>` tracer instant — the
        # anti-vacuity evidence the adversarial scenarios assert on
        from .metrics import AtomicCounters

        self.defense = AtomicCounters(
            "bad_proposal_sig", "bad_validation_sig",
            "conflicting_proposal", "duplicate_proposal",
            "conflicting_validation", "duplicate_validation",
            "stale_validation", "untrusted_validation",
            "oversized_txset", "txset_mismatch", "malformed_frame",
            "garbage_segment",
        )
        # optional sink for per-peer misbehavior bookkeeping (the overlay
        # wires UniqueNodeList.on_byzantine here)
        self.on_byzantine: Optional[Callable[[str, Optional[bytes]], None]] = None
        self.validations = ValidationsStore(
            is_trusted=lambda pk: pk in self.unl, now=network_time
        )
        self.validations.note_byzantine = self.note_byzantine
        # shared with the application container when one embeds this
        # validator: RPC-plane and peer-plane sig verdicts / suppression
        # must be ONE state (reference: a single getApp().getHashRouter())
        self.router = router if router is not None else HashRouter()
        # close-time re-application skips re-verifying SF_SIGGOOD txs
        self.lm.router = self.router
        from .localtxs import LocalTxs

        self.local_txs = LocalTxs()
        # trusted proposer -> (its proposal's prev-ledger hash, seen-at):
        # the peer-LCL votes of the reference's checkLastClosedLedger
        self._peer_prevs: dict[bytes, tuple[bytes, int]] = {}
        self._lcl_candidate: Optional[bytes] = None  # election hysteresis
        self._lcl_acquiring: Optional[bytes] = None  # single-flight catch-up
        # highest trusted-validation seq seen for the pinned target when
        # the session started — the election retargets past a transfer
        # the net has clearly outrun (see _check_lcl)
        self._lcl_acquiring_seq: Optional[int] = None
        self._tick = 0
        # fired for EVERY ledger that becomes our LCL — locally-closed
        # rounds AND catch-up adoptions — so the persistence plane never
        # gaps (reference: pendSaveValidated covers both paths)
        self.on_ledger: list[Callable[[Ledger], None]] = []
        self.round: Optional[LedgerConsensus] = None
        self.prev_proposers = 0
        self.prev_round_ms = LEDGER_MIN_CONSENSUS_MS
        self.rounds_completed = 0
        # peer tx sets seen this round (simnet share / TMHaveTransactionSet)
        self.txset_cache: dict[bytes, TxSet] = {}
        # recent trusted proposals, stashed ACROSS rounds (reference:
        # Consensus::recentPeerPositions_ + playbackProposals): a node
        # that adopts the network LCL mid-round must be able to replay
        # the positions that flew by BEFORE its begin_round, or it sits
        # in the round alone, closes a late solo ledger, and diverges —
        # the scenario fuzzer's catch-up limit cycle (fuzz_convergence)
        self._recent_proposals: dict[bytes, list] = {}
        # catch-up: ledger acquisition sessions (reference: InboundLedgers)
        from .inbound import InboundLedgers

        self.inbound = InboundLedgers(
            send=adapter.request_ledger_data, hash_batch=hash_batch,
            clock=clock,
        )
        self.inbound.on_complete = self._ledger_acquired
        # segment-granular catch-up plane (node/inbound.SegmentCatchup):
        # wired by the owner when a segment-capable store exists.
        # `segment_source` answers peers' GetSegments (an object with
        # segments()/fetch_segment(), i.e. the segstore backend).
        self.segment_catchup = None
        self.segment_source = None
        # archive mode: the deep-history shard backfill driver
        # (node/archive.ShardBackfill), ticked next to segment_catchup
        self.shard_backfill = None
        # follower ingest observability (`follower.ingest` spans +
        # get_counts block): validation-seen -> adopted latency per
        # ingested ledger, plus plain counters
        from .metrics import LatencyHist
        from .tracer import STAGE_BOUNDS

        self.ingest_hist = LatencyHist(bounds=STAGE_BOUNDS, interpolate=True)
        self.ledgers_ingested = 0
        self._ingest_t0: dict[bytes, float] = {}
        # follower ingest kick coalescing: a close produces one trusted
        # validation PER UNL MEMBER for the same seq, and kicking the
        # LCL election inline on every one ran |UNL| elections (and up
        # to |UNL| acquisition attempts) per close. One kick per target
        # seq suffices — on_timer()'s unconditional _check_lcl remains
        # the liveness backstop for anything the kick missed.
        self._lcl_kick_seq = 0
        self.lcl_inline_kicks = 0
        self.lcl_kicks_coalesced = 0
        # honest health reporting (see DEGRADE_LAG): transitions are
        # tracer-visible and counted, state rides consensus_info and the
        # container's operating mode
        self._degraded = False
        self.degrade_transitions = 0
        # last VALIDATED seq the LocalTxs inclusion-sweep ran against
        self._local_sweep_seq = 0

    # -- byzantine defense -------------------------------------------------

    def note_byzantine(self, kind: str, peer: Optional[bytes] = None,
                       **info) -> None:
        """Record one recognized-and-neutralized hostile input: counter
        (`defense`), tracer instant (`byzantine.<kind>`), and — when the
        offender is an identified signer — the per-validator misbehavior
        bookkeeping hook (UNL plane)."""
        self.defense.add(kind)
        self.lm.tracer.instant(
            "byzantine." + kind, "consensus",
            peer=peer.hex()[:16] if peer else None, **info,
        )
        if self.on_byzantine is not None and peer is not None:
            try:
                self.on_byzantine(kind, peer)
            except Exception:  # noqa: BLE001 — bookkeeping must not
                pass           # interfere with message handling

    # how long a live LCL acquisition may sit with NO progress before
    # the election may retarget past it (node clock: seconds on a real
    # node, virtual steps on the simnet — roughly two rounds)
    ACQ_PIN_S = 10.0

    # -- lifecycle --------------------------------------------------------

    def start(self, root_account_id: bytes, close_time: int = 0) -> None:
        self.lm.start_new_ledger(root_account_id, close_time)
        self.begin_round()

    def begin_round(self) -> None:
        """reference: NetworkOPs::beginConsensus → make_LedgerConsensus"""
        if self.follower:
            # a follower never drives rounds: its chain advances only by
            # adopting validated ledgers (the catch-up/tailing path)
            self.round = None
            return
        # txsets stay cached ACROSS rounds (bounded in handle_txset):
        # a late joiner replaying stashed proposals needs the candidate
        # set that was shared before its begin_round
        self.round = LedgerConsensus(
            prev_ledger=self.lm.closed_ledger(),
            ledger_master=self.lm,
            adapter=self.adapter,
            validations=self.validations,
            key=self.key,
            unl=self.unl,
            network_time=self.network_time,
            clock=self.clock,
            prev_proposers=self.prev_proposers,
            prev_round_ms=self.prev_round_ms,
            proposing=self.proposing,
            hash_batch=self.hash_batch,
            idle_interval=self.idle_interval,
            voting=self.voting,
            note_byzantine=self.note_byzantine,
        )
        # playback (reference: Consensus::playbackProposals): replay
        # stashed positions that belong to THIS round's prior ledger.
        # Sorted by signer so replay order never leaks PYTHONHASHSEED
        # into round state (the PR 8 dispute-order lesson).
        now = self.network_time()
        for pub in sorted(self._recent_proposals):
            for when, prop in self._recent_proposals[pub]:
                if now - when <= 60 and \
                        prop.prev_ledger == self.round.prev_hash:
                    self.round.peer_proposal(prop)

    @_locked
    def on_timer(self) -> None:
        """Heartbeat → consensus timer + catch-up check (reference:
        processHeartbeatTimer → timerEntry / checkLastClosedLedger)."""
        if self.round is not None:
            self.round.timer_entry()
        self._check_lcl()
        # re-trigger stalled acquisitions every other tick (reference:
        # PeerSet timeouts); progress-driven triggers do the steady-state
        self._tick += 1
        if self._tick % 2 == 0:
            self.inbound.expire_stale()
            for il in list(self.inbound.live.values()):
                self.inbound.trigger(il)
        # the segment bulk path's timeout/retry/backoff clock
        if self.segment_catchup is not None:
            self.segment_catchup.tick(self.clock())
        if self.shard_backfill is not None:
            self.shard_backfill.tick(self.clock())
        self._update_health()

    # -- health ------------------------------------------------------------

    @property
    def degraded(self) -> bool:
        """True while we close ledgers the network does not validate
        (quorum lost — partition, killed peers, or a fork we are on the
        wrong side of)."""
        return self._degraded

    @property
    def validator_state(self) -> str:
        if self.follower:
            return "follower"
        if self._degraded:
            return "tracking"
        return "proposing" if self.proposing else "observing"

    def follower_json(self) -> dict:
        """Ingest-plane counters for get_counts (follower mode)."""
        out = {
            "ledgers_ingested": self.ledgers_ingested,
            "validated_seq": (
                self.lm.validated.seq if self.lm.validated else 0
            ),
            "acquisitions_live": len(self.inbound.live),
            "lcl_inline_kicks": self.lcl_inline_kicks,
            "lcl_kicks_coalesced": self.lcl_kicks_coalesced,
        }
        if self.ingest_hist.count:
            out["ingest_p50_ms"] = self.ingest_hist.quantile(0.5)
            out["ingest_p99_ms"] = self.ingest_hist.quantile(0.99)
        sc = self.segment_catchup
        if sc is not None:
            out["segfetch"] = sc.get_json()
        sb = self.shard_backfill
        if sb is not None:
            out["shard_backfill"] = sb.get_json()
        return out

    def _update_health(self) -> None:
        closed = self.lm.closed_ledger().seq
        validated = self.lm.validated.seq if self.lm.validated else 0
        degraded = (closed - validated) > self.DEGRADE_LAG
        if degraded == self._degraded:
            return
        self._degraded = degraded
        self.degrade_transitions += 1
        self.lm.tracer.instant(
            "consensus.degraded" if degraded else "consensus.recovered",
            "consensus",
            closed_seq=closed, validated_seq=validated,
            state=self.validator_state,
        )

    # -- catch-up ---------------------------------------------------------

    def _check_lcl(self) -> None:
        """Elect the network LCL from current trusted validations and
        switch if another ledger has strictly more weight than ours —
        this is both the lag (we're behind) and the fork (same seq,
        different hash) repair path (reference: checkLastClosedLedger,
        NetworkOPs.cpp:776-925). A candidate must win two consecutive
        ticks before we act, so a healthy node mid-accept doesn't churn
        on the transient where peer validations beat its own close."""
        ours = self.lm.closed_ledger()
        ours_hash = ours.hash()
        # floor: the last QUORUM-VALIDATED seq. Validations below it are
        # history; validations between it and our closed seq stay
        # eligible — a node that solo-closed AHEAD of a starved net must
        # be pullable BACK onto the authoritative chain (filtering by
        # our own closed seq let a runaway fork ratchet forever; the
        # reference's checkLastClosedLedger weighs all current
        # validations, NetworkOPs.cpp:776-925)
        floor = self.lm.validated.seq if self.lm.validated is not None else 0
        val_votes: dict[bytes, int] = {}
        val_seq: dict[bytes, int] = {}
        for v in self.validations.current_trusted():
            if v.ledger_seq is None or v.ledger_seq <= floor:
                continue
            val_votes[v.ledger_hash] = val_votes.get(v.ledger_hash, 0) + 1
            val_seq[v.ledger_hash] = max(
                val_seq.get(v.ledger_hash, 0), v.ledger_seq
            )
        # peer-LCL votes from current proposals (the reference's
        # nodesUsing, NetworkOPs.cpp:821-843) — these break a symmetric
        # validation split (every closed chain diverged 1-1-...-1) that
        # validations alone can never heal
        now = self.network_time()
        using: dict[bytes, int] = {ours_hash: 1}  # ourselves
        for pub, (prev, seen) in list(self._peer_prevs.items()):
            if now - seen > 60:
                del self._peer_prevs[pub]
                continue
            using[prev] = using.get(prev, 0) + 1
        # election key mirrors ValidationCount::operator> with the
        # LEDGER HASH as the final deterministic tie-break, so a split
        # net elects ONE winner everywhere
        def key(h: bytes) -> tuple[int, int, bytes]:
            return (val_votes.get(h, 0), using.get(h, 0), h)

        candidates = set(val_votes) | set(using)
        candidates.discard(ours.parent_hash)  # never our own previous
        best = max(candidates, key=key)
        if key(best) <= key(ours_hash):  # covers best == ours_hash
            self._lcl_candidate = None
            return
        # hysteresis bypass when we are clearly LAGGING: the two-tick
        # confirm protects a healthy node's mid-accept transient, where
        # peer validations momentarily beat its own same-seq close. A
        # candidate >= 2 seqs ahead of our closed chain is not that
        # transient — it is catch-up, and paying the hysteresis there
        # put a straggler in a permanent limit cycle: elect -> confirm
        # -> acquire -> adopt costs one full round, so it tracked the
        # net at a constant 2-ledger offset and a high-quorum net
        # (e.g. 5-of-6 after an even partition healed) could never
        # re-assemble a validation quorum on one seq (found by the
        # scenario fuzzer; corpus fuzz_convergence pins it)
        lagging = val_seq.get(best, 0) >= ours.seq + 2
        if self._lcl_candidate != best and not self.follower and not lagging:
            # hysteresis: confirm next tick. A follower skips it — it
            # never closes rounds of its own, so there is no healthy
            # mid-accept transient to protect, and tailing latency is
            # the product (validation seen -> adoption kicked at once)
            self._lcl_candidate = best
            return
        self._lcl_candidate = best
        if self.follower and best not in self._ingest_t0:
            # ingest span clock starts at the first sighting of the
            # target (bounded: adoption pops; a never-adopted target
            # ages out with the oldest entries)
            if len(self._ingest_t0) >= 256:
                self._ingest_t0.pop(next(iter(self._ingest_t0)))
            self._ingest_t0[best] = _time.perf_counter()
        led = self.lm.get_ledger_by_hash(best)
        if led is not None:
            self._adopt_network_lcl(led)
        else:
            # single-flight: while one catch-up acquisition is live AND
            # viable, finishing it beats chasing every newer validation —
            # an adopted slightly-stale LCL still moves us forward, and
            # the next election closes the remaining gap. Without this, a
            # moving target (net closes faster than one acquisition
            # completes) re-targets forever and catch-up never lands. A
            # session that never even got a header (an unserveable —
            # possibly fabricated — hash) must not pin catch-up: retarget.
            cur = self._lcl_acquiring
            if cur is not None and cur in self.inbound.live:
                il = self.inbound.live[cur]
                # the pin holds only while the session is (a) still
                # progressing, (b) not already resolvable locally (we
                # may have closed/acquired the target through another
                # path since), and (c) chasing a target the election
                # has not left far behind. Violating any of these held
                # a node hostage to a moot transfer — the scenario
                # fuzzer caught a validator wedged ~70 rounds acquiring
                # a deep order-book tree for its OWN orphaned close
                # while the net validated 6 seqs past it.
                fresh = (
                    self.clock() - il.last_progress <= self.ACQ_PIN_S
                )
                have_local = self.lm.get_ledger_by_hash(cur) is not None
                superseded = (
                    self._lcl_acquiring_seq is not None
                    and val_seq.get(best, 0)
                    > self._lcl_acquiring_seq + 2
                )
                if (
                    (cur == best or il.header is not None)
                    and fresh and not have_local and not superseded
                ):
                    return
                self.inbound.abandon(cur)
            self._lcl_acquiring = best
            self._lcl_acquiring_seq = val_seq.get(best)
            self.inbound.acquire(best, for_lcl=True)
            # a cold/lagging node kicking off catch-up also starts the
            # segment bulk transfer: whole store segments land locally
            # so the tree walk above resolves via local_fetch instead of
            # per-node network waves. can_start rate-limits to one
            # session at a time, re-armed REARM_S after the last ended.
            if (
                self.segment_catchup is not None
                and self.segment_catchup.can_start(self.clock())
            ):
                self.segment_catchup.start()

    def _ledger_acquired(self, ledger: Ledger) -> None:
        """Acquisition finished (reference: InboundLedger LADispatch →
        checkAccept)."""
        self._adopt_network_lcl(ledger)

    def _adopt_network_lcl(self, ledger: Ledger) -> None:
        ours = self.lm.closed_ledger()
        if ledger.hash() == ours.hash():
            return
        # adopting a LOWER-seq ledger is legal fork repair (we solo-ran
        # ahead); the floor is the validated chain, which never regresses
        floor = (
            self.lm.validated.seq if self.lm.validated is not None else 0
        )
        if ledger.seq <= floor:
            return
        self.lm.switch_lcl(ledger)
        self._lcl_candidate = None
        self.lm.check_accept(
            ledger.hash(), self.validations.trusted_count_for(ledger.hash())
        )
        if self.follower:
            # ingest observability: validation-seen -> adopted latency
            now = _time.perf_counter()
            t0 = self._ingest_t0.pop(ledger.hash(), None)
            self.ledgers_ingested += 1
            if t0 is not None:
                self.ingest_hist.record((now - t0) * 1000.0)
                self.lm.tracer.complete(
                    "follower.ingest", "follower", t0, now, seq=ledger.seq
                )
            tracer = self.lm.tracer
            if tracer.enabled:
                # per-sampled-tx ingest evidence: the leaf every cross-
                # node tx tree needs on the follower (deterministic
                # sampling means the leader sampled the same txids)
                for txid, _blob, _meta in ledger.tx_entries():
                    tracer.instant(
                        "follower.ingest.tx", "follower", txid=txid,
                        ledger_seq=ledger.seq,
                    )
        # a multi-ledger jump must hand EVERY resolvable intermediate
        # ledger to the persistence plane oldest-first, or the txdb gets
        # a permanent hole for the skipped range (unresolvable ancestors
        # are the LedgerCleaner's repair territory)
        chain = [ledger]
        cursor = ledger
        while cursor.seq > ours.seq + 1:
            parent = self.lm.get_ledger_by_hash(cursor.parent_hash)
            if parent is None:
                break
            chain.append(parent)
            cursor = parent
        for led in reversed(chain):
            self._fire_on_ledger(led)
        self.begin_round()
        # fork-repair client contract: local submissions that rode the
        # LOSING chain re-apply against the adopted one with a fresh
        # retry horizon — without the rebase, the adoption's seq jump
        # silently expired them out of LocalTxs (found by the
        # partition_kills scenario: 40/69 client txs lost)
        if len(self.local_txs):
            self.local_txs.rebase(ledger.seq)
            self._sweep_local_txs()
            self.local_txs.apply_to_open(
                self.lm, TxParams.OPEN_LEDGER | TxParams.RETRY
            )

    def _fire_on_ledger(self, ledger: Ledger) -> None:
        for cb in self.on_ledger:
            try:
                cb(ledger)
            except Exception:  # noqa: BLE001 — hooks must not kill consensus
                import logging

                logging.getLogger("stellard.validator").exception(
                    "on_ledger hook failed"
                )

    @_locked
    def round_accepted(self, ledger: Ledger, round_ms: int) -> None:
        """Adapter callback after accept(): record stats and start the
        next round (reference: endConsensus → NetworkOPs::endConsensus)."""
        self.prev_proposers = (
            len(self.round.peer_positions) + 1 if self.round else 1
        )
        self.prev_round_ms = max(round_ms, LEDGER_MIN_CONSENSUS_MS)
        self.rounds_completed += 1
        self._fire_on_ledger(ledger)
        # local submissions that missed this ledger re-apply to the new
        # open ledger; landed/expired ones sweep (reference LocalTxs).
        # The sweep runs against VALIDATED ledgers only — sweeping the
        # just-closed ledger treated inclusion in a ledger the network
        # never validated as done, so a client tx committed on a LOSING
        # solo fork vanished at fork repair instead of re-applying
        # (found by the partition_kills scenario)
        self._sweep_local_txs()
        if len(self.local_txs):
            self.local_txs.apply_to_open(
                self.lm, TxParams.OPEN_LEDGER | TxParams.RETRY
            )
        self.begin_round()

    def _sweep_local_txs(self) -> None:
        """Inclusion/expiry sweep against the latest quorum-validated
        ledger (once per validated seq)."""
        val = self.lm.validated
        if val is not None and val.seq != self._local_sweep_seq:
            self._local_sweep_seq = val.seq
            self.local_txs.sweep(val)

    # -- transaction submission ------------------------------------------

    @_locked
    def submit(
        self, tx: SerializedTransaction, local: bool = True
    ) -> tuple[TER, bool]:
        txid = tx.txid()
        flags = self.router.get_flags(txid)
        if flags & SF_BAD:
            return TER.temINVALID, False
        if not (flags & SF_SIGGOOD):
            ok, _ = tx.passes_local_checks()
            if not ok or not self._check_tx_sig(tx):
                self.router.set_flag(txid, SF_BAD)
                return TER.temINVALID, False
            self.router.set_flag(txid, SF_SIGGOOD)
        tx.set_sig_verdict(True)
        with self.lm.tracer.span(
            "submit", "submit", txid=txid,
            source="local" if local else "overlay",
        ):
            ter, applied = self.lm.do_transaction(
                tx, TxParams.OPEN_LEDGER | TxParams.RETRY
            )
        if ter == TER.terPRE_SEQ:
            self.lm.add_held_transaction(tx)
        if local and not ter.is_tem:
            # client submissions (NOT relayed gossip) re-apply across
            # rounds (reference: LocalTxs.cpp push_back fed only from the
            # client submit path — tracking relays would grow with total
            # network traffic)
            self.local_txs.push_back(self.lm.closed_ledger().seq, tx)
        return ter, applied

    @staticmethod
    def _tx_verify_request(tx: SerializedTransaction):
        from ..crypto.backend import VerifyRequest

        return VerifyRequest(
            public=tx.signing_pub_key,
            signing_hash=tx.signing_hash(),
            signature=tx.signature,
        )

    def _check_tx_sig(self, tx: SerializedTransaction) -> bool:
        """Tx signature through the verify plane when one is wired —
        relayed network txs are the bulk of a real validator's verify
        load (reference: PeerImp::checkTransaction, the #1 hot call),
        and the per-signature host-library path left them off the
        batched/native/device plane entirely (close-p50 profile: ~45%%
        of busy samples in keys.verify_signature)."""
        if self.verify_many is not None:
            good = bool(self.verify_many([self._tx_verify_request(tx)])[0])
            tx.set_sig_verdict(good)
            return good
        return tx.check_sign()

    def prefetch_tx_sigs(self, txs: list) -> None:
        """Batch-verify a burst of relayed txs' signatures through the
        verify plane in ONE call, recording verdicts in the HashRouter —
        submit() then sees SF_SIGGOOD/SF_BAD and never verifies again.
        The per-message path costs a full verify per tx regardless of
        backend (singleton marshaling ~= host-lib verify); one network
        read often carries many TxMessages, and THIS is the seam that
        puts relayed traffic on the batched/native/device plane
        (reference: PeerImp::checkTransaction, the #1 hot call)."""
        if self.verify_many is None:
            return
        pending = []
        seen: set[bytes] = set()  # dedupe: N copies of one tx in a burst
        for tx in txs:            # must cost ONE verify, not N
            txid = tx.txid()
            if txid in seen:
                continue
            seen.add(txid)
            flags = self.router.get_flags(txid)
            if flags & (SF_SIGGOOD | SF_BAD):
                continue
            # structural validity gates the SIGGOOD flag exactly as the
            # per-tx path does (submit() skips its checks when the flag
            # is already set; reference: checkTransaction runs
            # checkValid before any signature work)
            ok, _why = tx.passes_local_checks()
            if not ok:
                self.router.set_flag(tx.txid(), SF_BAD)
                continue
            pending.append(tx)
        if not pending:
            return
        results = self.verify_many(
            [self._tx_verify_request(tx) for tx in pending]
        )
        for tx, good in zip(pending, results):
            good = bool(good)
            tx.set_sig_verdict(good)
            self.router.set_flag(
                tx.txid(), SF_SIGGOOD if good else SF_BAD
            )

    # -- peer message handlers -------------------------------------------

    @_locked
    def handle_tx(self, tx: SerializedTransaction) -> bool:
        """Relayed network tx (reference: PeerImp::checkTransaction).
        Returns True when it should be re-relayed."""
        ter, _ = self.submit(tx, local=False)
        return int(ter) == 0 or -99 <= int(ter) < 0

    def handle_proposal(self, prop: LedgerProposal) -> bool:
        """reference: PeerImp::checkPropose → peerPosition. Signature is
        verified once per suppression id OUTSIDE the master lock (the
        reference checks on jtVALIDATION jobs off the lock too — a device
        verify batch must not serialize RPC tx application), then the
        round mutation runs locked."""
        pid = prop.suppression_id()
        flags = self.router.get_flags(pid)
        if flags & SF_BAD:
            return False
        if not (flags & SF_SIGGOOD):
            if not self._verify([prop]):
                self.router.set_flag(pid, SF_BAD)
                self.note_byzantine(
                    "bad_proposal_sig", peer=prop.node_public
                )
                return False
            self.router.set_flag(pid, SF_SIGGOOD)
        prop.set_sig_verdict(True)
        with self.lock:
            # remember each trusted proposer's view of the LCL even when
            # its proposal is for ANOTHER chain — these are the
            # "nodesUsing" votes of the reference's LCL election
            # (NetworkOPs.cpp:821-843 counts peer closed-ledger hashes)
            if prop.node_public in self.unl and not prop.is_bowout():
                self._peer_prevs[prop.node_public] = (
                    prop.prev_ledger, self.network_time()
                )
                # stash for playback into a later begin_round (bounded
                # per signer; see _recent_proposals)
                stash = self._recent_proposals.setdefault(
                    prop.node_public, []
                )
                stash.append((self.network_time(), prop))
                del stash[:-8]
            if self.round is None:
                return False
            return self.round.peer_proposal(prop)

    def handle_validation(self, val: STValidation) -> bool:
        """reference: PeerImp::checkValidation → recvValidation →
        Validations::addValidation → LedgerMaster::checkAccept.
        Signature check runs outside the master lock (see handle_proposal)."""
        vid = val.validation_id()
        flags = self.router.get_flags(vid)
        if flags & SF_BAD:
            return False
        if not (flags & SF_SIGGOOD):
            if not self._verify([val]):
                self.router.set_flag(vid, SF_BAD)
                self.note_byzantine(
                    "bad_validation_sig", peer=val.signer or None
                )
                return False
            self.router.set_flag(vid, SF_SIGGOOD)
        val.set_sig_verdict(True)
        if val.signer not in self.unl:
            # a correctly-signed validation from a key outside the UNL
            # (byzantine "self-signed" validation): stored untrusted —
            # zero quorum weight — but counted as evidence
            self.note_byzantine(
                "untrusted_validation", peer=val.signer or None
            )
        with self.lock:
            # validation arrival on the round timeline (trace id = the
            # validated ledger's seq when the peer reported one)
            self.lm.tracer.instant(
                "consensus.validation_in", "consensus",
                seq=val.ledger_seq,
                peer=val.signer.hex()[:16] if val.signer else None,
            )
            current = self.validations.add(val)
            self.lm.check_accept(
                val.ledger_hash,
                self.validations.trusted_count_for(val.ledger_hash),
            )
            if current and self.follower:
                # steady-state tailing: a fresh trusted validation IS
                # the new-validated-ledger announcement — elect/acquire
                # now instead of waiting out the next timer tick.
                # Coalesced per target seq: the 2nd..|UNL|th validation
                # of one close changes no election input worth a fresh
                # run (pinned by test_follower_kick_coalescing)
                seq = val.ledger_seq or 0
                if seq > self._lcl_kick_seq:
                    self._lcl_kick_seq = seq
                    self.lcl_inline_kicks += 1
                    self._check_lcl()
                else:
                    self.lcl_kicks_coalesced += 1
            return current

    @_locked
    def handle_ledger_data(self, msg) -> bool:
        """Route a LedgerData reply into the acquisition machinery.
        Returns True when the reply made progress (callers score the
        sending peer on this — unsolicited data must earn nothing)."""
        return bool(self.inbound.take_ledger_data(msg))

    @_locked
    def has_acquisition(self, ledger_hash: bytes) -> bool:
        """Live OR recently-completed: late LedgerData from peers we
        legitimately queried must not be charged as unwanted."""
        return (
            ledger_hash in self.inbound.live
            or self.inbound.recently_done(ledger_hash)
        )

    @_locked
    def serve_get_ledger(self, msg):
        """Answer a peer's GetLedger from our closed-ledger cache."""
        from ..state.shamap import MissingNodeError
        from .inbound import serve_get_ledger

        try:
            return serve_get_ledger(
                self.lm.get_ledger_by_hash(msg.ledger_hash), msg
            )
        except MissingNodeError:
            # a lazily-opened historical ledger whose nodes a sweep has
            # since retired: we cannot serve it — answer with silence
            # and the requester's acquisition retries another peer
            return None

    def snapshot_epoch(self) -> int:
        """Epoch stamp for the snapshot-handoff leg (doc/follower.md):
        a fingerprint of the SEALED segment set served over GetSegments.
        Rotation, compaction, and online deletion all change the sealed
        set — exactly the moments a mid-transfer fetcher's offsets go
        stale — while steady appends to the active segment do not.
        Nonzero by construction; 0 on the wire means "no epoch" (a
        pre-epoch peer), which fetchers treat as don't-care."""
        import zlib

        src = self.segment_source
        if src is None:
            return 0
        sealed = sorted(
            int(d["id"]) for d in src.segments() if not d["active"]
        )
        blob = ",".join(str(i) for i in sealed).encode()
        return zlib.crc32(blob) or 1

    def serve_get_segments(self, msg):
        """Answer a peer's GetSegments from the wired segment source
        (segstore backend): manifest for seg_id < 0, else one bounded
        chunk of the segment's raw bytes. NOT under the master lock —
        segment reads are pure store IO and must not stall consensus.

        Snapshot handoff (follower trees): every reply carries our
        current snapshot epoch + validated seq. The manifest doubles as
        the `snapshot_offer`; epoch-pinned chunk fetches are the
        `snapshot_fetch` — a fetcher seeing the epoch move mid-transfer
        restarts from a fresh manifest instead of splicing records from
        two different snapshots."""
        from ..overlay.wire import SEGMENT_CHUNK, SegmentData

        src = self.segment_source
        if src is None:
            return None
        epoch = self.snapshot_epoch()
        snap_seq = self.lm.validated.seq if self.lm.validated else 0
        if msg.seg_id < 0:
            # shard rows carry their sealed seq range + full file size
            # (nonzero-only on the wire: segstore rows encode exactly
            # as before) so range-selecting peers never probe
            rows = [
                (d["id"], d["size"], d["live_bytes"], bool(d["active"]),
                 int(d.get("lo", 0)), int(d.get("hi", 0)),
                 int(d.get("file_bytes", 0)))
                for d in src.segments()
            ]
            return SegmentData(seg_id=-1, segments=rows,
                               snap_epoch=epoch, snap_seq=snap_seq)
        off = max(0, int(msg.offset))
        try:
            # chunked read: serving a multi-chunk transfer must not
            # re-read the whole segment per request
            got = src.fetch_segment(msg.seg_id, offset=off,
                                    length=SEGMENT_CHUNK)
        except TypeError:  # sources without the chunk signature
            got = src.fetch_segment(msg.seg_id)
            if got is None:
                return None
            meta, data = got
            return SegmentData(
                seg_id=msg.seg_id, total=len(data), offset=off,
                data=data[off: off + SEGMENT_CHUNK],
                snap_epoch=epoch, snap_seq=snap_seq,
            )
        if got is None:
            return None
        meta, data = got
        return SegmentData(
            seg_id=msg.seg_id,
            total=int(meta["size"]),
            offset=off,
            data=data,
            snap_epoch=epoch,
            snap_seq=snap_seq,
        )

    def handle_segment_data(self, peer, msg) -> None:
        """Route a SegmentData reply into the bulk catch-up machinery
        (`peer` is the transport's peer id — simnet nid / node public).
        Archive nodes run a second fetcher on the same door: manifests
        feed BOTH (each selects its own rows), whole-shard-file chunks
        (ids at or above SHARD_FILE_BASE) go to the backfill."""
        from ..nodestore.shards import SHARD_FILE_BASE

        sb = self.shard_backfill
        sc = self.segment_catchup
        if msg.seg_id < 0:
            if sc is not None:
                sc.on_manifest(peer, msg.segments, epoch=msg.snap_epoch,
                               snap_seq=msg.snap_seq)
            if sb is not None:
                sb.on_manifest(peer, msg.segments, epoch=msg.snap_epoch,
                               snap_seq=msg.snap_seq)
            return
        if msg.seg_id >= SHARD_FILE_BASE:
            if sb is not None:
                sb.on_data(peer, msg)
            return
        if sc is not None:
            sc.on_data(peer, msg)

    @_locked
    def handle_txset(self, txset: TxSet) -> None:
        """A shared/acquired candidate set arrived
        (reference: TMHaveTransactionSet/TransactionAcquire completion)."""
        h = txset.hash()
        self.txset_cache.pop(h, None)  # refresh insertion order
        self.txset_cache[h] = txset
        while len(self.txset_cache) > 16:
            # bounded cross-round cache (was: cleared per round; late
            # round joins need the sets shared before their begin_round)
            del self.txset_cache[next(iter(self.txset_cache))]
        if self.round is not None:
            self.round.have_tx_set(h, txset)

    def _verify(self, objs) -> bool:
        """Verify a burst of signed consensus objects (proposals or
        validations); batched on the VerifyPlane when available. Returns
        True only when every signature in the burst is good."""
        if self.verify_many is not None:
            from ..crypto.backend import VerifyRequest

            reqs = [
                VerifyRequest(
                    public=getattr(o, "node_public", None) or o.signer,
                    signing_hash=o.signing_hash(),
                    signature=o.signature,
                )
                for o in objs
            ]
            return bool(all(self.verify_many(reqs)))
        ok = True
        for o in objs:
            good = o.is_valid() if hasattr(o, "is_valid") else o.check_sign()
            ok = ok and good
        return ok

    # -- introspection ----------------------------------------------------

    def consensus_info(self) -> dict:
        info = {
            "rounds_completed": self.rounds_completed,
            "validation_count": self.validations.size(),
            # honest health: "tracking" while we close ledgers nobody
            # validates, "proposing"/"observing" otherwise
            "validator_state": self.validator_state,
            "degraded": self._degraded,
            "closed_seq": self.lm.closed_ledger().seq,
            "validated_seq": (
                self.lm.validated.seq if self.lm.validated else 0
            ),
        }
        if self.round is not None:
            info["round"] = self.round.get_json()
        return info
