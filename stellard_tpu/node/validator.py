"""ValidatorNode: the consensus-facing orchestration of one validator —
round lifecycle, peer message handling, and quorum acceptance.

Reference: this is the slice of NetworkOPs that owns consensus
(tryStartConsensus/beginConsensus, NetworkOPs.cpp:741-975; recvValidation
:1668; processTrustedProposal) plus LedgerMaster::checkAccept. It is
transport-agnostic: the deterministic simnet (overlay.simnet) and the
TCP overlay both drive it through the same entry points, mirroring how
the reference tests consensus through testoverlay without sockets.

TPU shape: bursts of peer validations/proposals are signature-checked
through the VerifyPlane as one device batch per timer tick rather than
one libsodium call per message.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from ..consensus.consensus import ConsensusAdapter, LedgerConsensus
from ..consensus.proposal import LedgerProposal
from ..consensus.timing import LEDGER_IDLE_INTERVAL, LEDGER_MIN_CONSENSUS_MS
from ..consensus.txset import TxSet
from ..consensus.validation import STValidation
from ..consensus.validations import ValidationsStore
from ..engine.engine import TxParams
from ..protocol.keys import KeyPair
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger
from .hashrouter import SF_BAD, SF_SIGGOOD, HashRouter
from .ledgermaster import LedgerMaster

__all__ = ["ValidatorNode"]


class ValidatorNode:
    def __init__(
        self,
        key: KeyPair,
        unl: set[bytes],
        adapter: ConsensusAdapter,
        quorum: int,
        network_time: Callable[[], int],
        clock: Callable[[], float] = _time.monotonic,
        hash_batch: Optional[Callable] = None,
        verify_many: Optional[Callable] = None,
        proposing: bool = True,
        idle_interval: int = LEDGER_IDLE_INTERVAL,
    ):
        self.key = key
        self.unl = set(unl) | {key.public}  # we trust ourselves
        self.adapter = adapter
        self.network_time = network_time
        self.clock = clock
        self.hash_batch = hash_batch
        self.verify_many = verify_many  # VerifyPlane.verify_many or None
        self.proposing = proposing
        self.idle_interval = idle_interval

        self.lm = LedgerMaster(hash_batch=hash_batch)
        self.lm.min_validations = quorum
        self.validations = ValidationsStore(
            is_trusted=lambda pk: pk in self.unl, now=network_time
        )
        self.router = HashRouter()
        self.round: Optional[LedgerConsensus] = None
        self.prev_proposers = 0
        self.prev_round_ms = LEDGER_MIN_CONSENSUS_MS
        self.rounds_completed = 0
        # peer tx sets seen this round (simnet share / TMHaveTransactionSet)
        self.txset_cache: dict[bytes, TxSet] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self, root_account_id: bytes, close_time: int = 0) -> None:
        self.lm.start_new_ledger(root_account_id, close_time)
        self.begin_round()

    def begin_round(self) -> None:
        """reference: NetworkOPs::beginConsensus → make_LedgerConsensus"""
        self.txset_cache.clear()
        self.round = LedgerConsensus(
            prev_ledger=self.lm.closed_ledger(),
            ledger_master=self.lm,
            adapter=self.adapter,
            validations=self.validations,
            key=self.key,
            unl=self.unl,
            network_time=self.network_time,
            clock=self.clock,
            prev_proposers=self.prev_proposers,
            prev_round_ms=self.prev_round_ms,
            proposing=self.proposing,
            hash_batch=self.hash_batch,
            idle_interval=self.idle_interval,
        )

    def on_timer(self) -> None:
        """Heartbeat → consensus timer (reference:
        processHeartbeatTimer → timerEntry)."""
        if self.round is not None:
            self.round.timer_entry()

    def round_accepted(self, ledger: Ledger, round_ms: int) -> None:
        """Adapter callback after accept(): record stats and start the
        next round (reference: endConsensus → NetworkOPs::endConsensus)."""
        self.prev_proposers = (
            len(self.round.peer_positions) + 1 if self.round else 1
        )
        self.prev_round_ms = max(round_ms, LEDGER_MIN_CONSENSUS_MS)
        self.rounds_completed += 1
        self.begin_round()

    # -- transaction submission ------------------------------------------

    def submit(self, tx: SerializedTransaction) -> tuple[TER, bool]:
        txid = tx.txid()
        flags = self.router.get_flags(txid)
        if flags & SF_BAD:
            return TER.temINVALID, False
        if not (flags & SF_SIGGOOD):
            ok, _ = tx.passes_local_checks()
            if not ok or not tx.check_sign():
                self.router.set_flag(txid, SF_BAD)
                return TER.temINVALID, False
            self.router.set_flag(txid, SF_SIGGOOD)
        tx.set_sig_verdict(True)
        ter, applied = self.lm.do_transaction(
            tx, TxParams.OPEN_LEDGER | TxParams.RETRY
        )
        if ter == TER.terPRE_SEQ:
            self.lm.add_held_transaction(tx)
        return ter, applied

    # -- peer message handlers -------------------------------------------

    def handle_tx(self, tx: SerializedTransaction) -> bool:
        """Relayed network tx (reference: PeerImp::checkTransaction).
        Returns True when it should be re-relayed."""
        ter, _ = self.submit(tx)
        return int(ter) == 0 or -99 <= int(ter) < 0

    def handle_proposal(self, prop: LedgerProposal) -> bool:
        """reference: PeerImp::checkPropose → peerPosition. Signature is
        verified once per suppression id, then routed to the round."""
        pid = prop.suppression_id()
        flags = self.router.get_flags(pid)
        if flags & SF_BAD:
            return False
        if not (flags & SF_SIGGOOD):
            if not self._verify([prop]):
                self.router.set_flag(pid, SF_BAD)
                return False
            self.router.set_flag(pid, SF_SIGGOOD)
        prop.set_sig_verdict(True)
        if self.round is None:
            return False
        return self.round.peer_proposal(prop)

    def handle_validation(self, val: STValidation) -> bool:
        """reference: PeerImp::checkValidation → recvValidation →
        Validations::addValidation → LedgerMaster::checkAccept."""
        vid = val.validation_id()
        flags = self.router.get_flags(vid)
        if flags & SF_BAD:
            return False
        if not (flags & SF_SIGGOOD):
            if not self._verify([val]):
                self.router.set_flag(vid, SF_BAD)
                return False
            self.router.set_flag(vid, SF_SIGGOOD)
        val.set_sig_verdict(True)
        current = self.validations.add(val)
        self.lm.check_accept(
            val.ledger_hash,
            self.validations.trusted_count_for(val.ledger_hash),
        )
        return current

    def handle_txset(self, txset: TxSet) -> None:
        """A shared/acquired candidate set arrived
        (reference: TMHaveTransactionSet/TransactionAcquire completion)."""
        h = txset.hash()
        self.txset_cache[h] = txset
        if self.round is not None:
            self.round.have_tx_set(h, txset)

    def _verify(self, objs) -> bool:
        """Verify a burst of signed consensus objects (proposals or
        validations); batched on the VerifyPlane when available. Returns
        True only when every signature in the burst is good."""
        if self.verify_many is not None:
            from ..crypto.backend import VerifyRequest

            reqs = [
                VerifyRequest(
                    public=getattr(o, "node_public", None) or o.signer,
                    signing_hash=o.signing_hash(),
                    signature=o.signature,
                )
                for o in objs
            ]
            return bool(all(self.verify_many(reqs)))
        ok = True
        for o in objs:
            good = o.is_valid() if hasattr(o, "is_valid") else o.check_sign()
            ok = ok and good
        return ok

    # -- introspection ----------------------------------------------------

    def consensus_info(self) -> dict:
        info = {
            "rounds_completed": self.rounds_completed,
            "validation_count": self.validations.size(),
        }
        if self.round is not None:
            info["round"] = self.round.get_json()
        return info
