"""VerifyPlane: the coalescing device-batched signature pipeline.

This is the north-star seam (SURVEY §2.9 mapping #1): the reference
verifies each signature synchronously inside its own job
(PeerImp::checkTransaction → STTx::checkSign → libsodium); here,
verification requests from concurrent jobs are coalesced across an
adaptive window and dispatched as ONE device program over the whole
batch (crypto.backend.BatchVerifier).

Dispatch is LATENCY-AWARE (VERDICT r2 #1b): the plane continuously
measures both backends on the batches it actually runs — a per-signature
EWMA for the threaded CPU path, a per-pad-bucket EWMA for the device
kernel (whose cost is dominated by a fixed per-invocation latency) — and
routes each batch to whichever model predicts faster. Small/trickled
batches therefore stay on the CPU even when a device is configured; the
device wins exactly where it is faster. Per-batch latencies are kept as
histograms per backend (the SURVEY §5 tracing ask) and exported through
get_json.

Callers either:
- `submit(req) -> Future[bool]` — async, coalesced (the JobQueue path),
- `verify_many(reqs) -> ndarray` — blocking whole-batch (consensus close
  verifying a round's validations at once).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from ..crypto.backend import BatchVerifier, VerifyRequest, make_verifier
from ..utils.devicewatch import (
    DeviceWedged,
    call_with_deadline,
    resolve_timeouts,
)
from .metrics import LatencyHist

log = logging.getLogger("stellard.device")

__all__ = ["VerifyPlane"]

# per-batch latency bucket upper bounds (ms); the +inf overflow bucket
# is implicit in LatencyHist
_HIST_BOUNDS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                1000.0)


class _LatencyModel:
    """Measured-cost models for the routing decision, generalized from
    cpu-vs-device to cpu + N device ARMS: with a multi-chip mesh
    configured the plane carries a 1-chip arm ("dev1") and an N-chip
    arm ("devN") of the same program, so small batches stay on the CPU,
    medium batches on one chip, and only batches that amortize the
    collective go wide (ISSUE 15 three-way routing)."""

    # after this many CPU-routed eligible batches, retry a device arm
    # once (load characteristics drift; a one-shot loss must not be
    # forever)
    REEXPLORE_EVERY = 512

    def __init__(self, min_device_batch: int,
                 device_arms: Sequence[str] = ("device",)):
        self.min_device_batch = min_device_batch
        self.device_arms = tuple(device_arms)
        # CPU: cost ~ linear in batch size
        self.cpu_persig_ms: Optional[float] = None
        # device: cost ~ flat per pad-bucket (kernel latency dominates),
        # one bucket map per arm
        self._bucket_ms: dict[str, dict[int, float]] = {
            a: {} for a in self.device_arms
        }
        # (arm, bucket)s that have absorbed their first (compile-laden)
        # sample
        self._device_warm: set[tuple[str, int]] = set()
        self._since: dict[str, int] = {a: 0 for a in self.device_arms}
        self.lock = threading.Lock()

    @property
    def device_bucket_ms(self) -> dict[int, float]:
        """Legacy single-arm view: the primary device arm's buckets."""
        return self._bucket_ms[self.device_arms[-1]]

    @property
    def _since_device(self) -> int:
        return self._since[self.device_arms[-1]]

    @staticmethod
    def _bucket(n: int, lo: int) -> int:
        size = lo
        while size < n:
            size *= 2
        return size

    def observe_cpu(self, n: int, ms: float) -> None:
        if n <= 0:
            return
        with self.lock:
            per = ms / n
            if self.cpu_persig_ms is None:
                self.cpu_persig_ms = per
            else:
                self.cpu_persig_ms += 0.25 * (per - self.cpu_persig_ms)

    def observe_device(self, n: int, ms: float,
                       arm: Optional[str] = None) -> None:
        arm = arm if arm is not None else self.device_arms[-1]
        b = self._bucket(max(n, 1), self.min_device_batch)
        with self.lock:
            self._since[arm] = 0
            if (arm, b) not in self._device_warm:
                # first sample per bucket includes XLA compilation —
                # recording it would poison the model and route every
                # later batch to the CPU; discard it and measure the
                # steady state from the second sample on
                self._device_warm.add((arm, b))
                return
            buckets = self._bucket_ms[arm]
            cur = buckets.get(b)
            buckets[b] = ms if cur is None else cur + 0.25 * (ms - cur)

    def expected_cpu_ms(self, n: int) -> Optional[float]:
        with self.lock:
            if self.cpu_persig_ms is None:
                return None
            return self.cpu_persig_ms * n

    def expected_device_ms(self, n: int,
                           arm: Optional[str] = None) -> Optional[float]:
        arm = arm if arm is not None else self.device_arms[-1]
        b = self._bucket(max(n, 1), self.min_device_batch)
        with self.lock:
            buckets = self._bucket_ms[arm]
            if b in buckets:
                return buckets[b]
            # nearest measured bucket as an estimate; device cost is
            # near-flat, so any measurement beats none
            if buckets:
                near = min(buckets, key=lambda k: abs(k - b))
                return buckets[near]
            return None

    def route(self, n: int, count: bool = True,
              arms: Optional[Sequence[str]] = None) -> str:
        """Pick the side for this batch: ``"cpu"`` or a device arm
        name. Unmeasured arms are explored optimistically (in declared
        order) once a batch reaches min_device_batch, after which real
        measurements drive every later decision. `count=False` asks the
        same question without advancing the re-exploration counters
        (the coalescing-window decision polls this every wake-up and
        must not inflate the re-explore cadence)."""
        avail = [a for a in (arms if arms is not None else self.device_arms)
                 if a in self._bucket_ms]
        if n < self.min_device_batch or not avail:
            return "cpu"
        costs: dict[str, float] = {}
        for a in avail:
            d = self.expected_device_ms(n, a)
            if d is None:
                return a  # explore: one measurement teaches the model
            costs[a] = d
        cpu = self.expected_cpu_ms(n)
        if cpu is None:
            return "cpu"  # CPU unmeasured: measure it too
        best_arm = min(costs, key=lambda a: costs[a])
        if costs[best_arm] < cpu:
            return best_arm
        if not count:
            return "cpu"
        # periodic re-exploration so a stale loss can be unlearned — but
        # only within striking distance: a ~300 ms kernel invocation must
        # never be retried on a 64-sig batch it cannot possibly win
        for a in avail:
            if cpu * 4.0 < costs[a]:
                continue
            with self.lock:
                self._since[a] += 1
                if self._since[a] >= self.REEXPLORE_EVERY:
                    self._since[a] = 0
                    return a
        return "cpu"

    def use_device(self, n: int, count: bool = True) -> bool:
        return self.route(n, count=count) != "cpu"

    def get_json(self) -> dict:
        with self.lock:
            return {
                "cpu_persig_ms": self.cpu_persig_ms,
                "device_bucket_ms": dict(
                    self._bucket_ms[self.device_arms[-1]]
                ),
                "arms": {a: dict(b) for a, b in self._bucket_ms.items()},
            }


class VerifyPlane:
    def __init__(
        self,
        backend: str = "cpu",
        window_ms: float = 2.0,
        max_batch: int = 16384,
        min_device_batch: int = 64,
        cpu_fallback: Optional[BatchVerifier] = None,
        device_first_timeout: Optional[float] = None,
        device_warm_timeout: Optional[float] = None,
        tracer=None,
        backend_opts: Optional[dict] = None,
        routing: Optional[str] = None,
    ):
        from ..crypto.backend import mesh_wants_width
        from .tracer import get_tracer

        self.tracer = tracer if tracer is not None else get_tracer()
        self.backend_name = backend
        # backend_opts flow to the factory VERBATIM (and unknown keys
        # fail loudly there): this is the config->plane plumbing that
        # makes [signature_backend] options like mesh= reachable —
        # before it, make_verifier(backend) dropped every kwarg and
        # TpuVerifier's knobs were dead config (ISSUE 15)
        self.backend_opts = dict(backend_opts or {})
        self.verifier: BatchVerifier = make_verifier(
            backend, **self.backend_opts
        )
        # the 1-chip arm of the three-way cpu/1-chip/N-chip routing:
        # when the opts request a multi-chip mesh, the same program is
        # also built at width 1, and the latency model measures both
        # arms — medium batches take one chip, only batches that
        # amortize the collective go wide
        self._one_chip: Optional[BatchVerifier] = None
        if "mesh" in self.backend_opts and mesh_wants_width(
            self.backend_opts["mesh"]
        ):
            one_opts = dict(self.backend_opts)
            one_opts["mesh"] = "0"
            self._one_chip = make_verifier(backend, **one_opts)
        self.cpu: BatchVerifier = cpu_fallback or (
            self.verifier if backend == "cpu" else make_verifier("cpu")
        )
        self.window = window_ms / 1000.0
        self.max_batch = max_batch
        self.min_device_batch = min_device_batch
        arms = ("dev1", "devN") if self._one_chip is not None else ("device",)
        self.model = _LatencyModel(min_device_batch, device_arms=arms)
        self._device_capable = backend != "cpu"
        # routing=device forces every eligible (>= min_device_batch)
        # batch onto the widest device arm — the anti-vacuity mode the
        # meshsmoke gate and on-chip benches use; cost (default) is the
        # measured-latency routing. Explicit arg > env > default.
        mode = routing if routing else os.environ.get(
            "STELLARD_VERIFY_ROUTING", "cost"
        )
        if mode not in ("cost", "device"):
            raise ValueError(
                f"verify routing must be cost|device, got {mode!r}"
            )
        self.routing = mode
        self._route_by_cost = mode != "device"
        # device-wedge watchdog deadlines (utils.devicewatch): the first
        # call to a pad-bucket shape legitimately compiles (~1-3 min on
        # chip), so unseen shapes get the generous deadline and warmed
        # shapes the tight one. On overrun the device is dead for the
        # process and every batch (including the stalled one, re-run on
        # the CPU side) still gets verified.
        self._t_first, self._t_warm = resolve_timeouts(
            device_first_timeout, device_warm_timeout
        )
        # warm pad-bucket shapes per device arm (each arm compiles its
        # own programs: a warm wide shape says nothing about the 1-chip
        # program of the same size)
        self._warm_buckets: dict[str, set[int]] = {
            a: set() for a in self.model.device_arms
        }
        self.device_wedged = False
        # while a prewarm runs, traffic routes to the CPU side — the
        # device must never pay its first (compile-laden) invocation on
        # live batches
        self._prewarm_pending = False

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list[tuple[VerifyRequest, Future]] = []
        self._stopping = False
        self.batches = 0
        self.verified = 0
        self.device_batches = 0
        self.cpu_batches = 0
        # per-SIGNATURE routing counters: a bench leg's "device share"
        # (device_sigs / verified) proves the device actually did work —
        # latency-aware routing can otherwise zero the device out while
        # the leg still reports a healthy ~1.0 ratio (VERDICT r3 weak #6)
        self.device_sigs = 0
        self.cpu_sigs = 0
        # per-arm routing counters (provenance: which kernel width the
        # device traffic actually ran on)
        self._arm_batches: dict[str, int] = {
            a: 0 for a in self.model.device_arms
        }
        self._arm_sigs: dict[str, int] = {
            a: 0 for a in self.model.device_arms
        }
        self._hist: dict[str, LatencyHist] = {
            "cpu": LatencyHist(bounds=_HIST_BOUNDS),
            "device": LatencyHist(bounds=_HIST_BOUNDS),
        }
        self._flusher = threading.Thread(
            target=self._flush_loop, name="verify-plane", daemon=True
        )
        self._flusher.start()

    # -- async coalesced path --------------------------------------------

    def submit(self, req: VerifyRequest) -> "Future[bool]":
        fut: Future = Future()
        with self._lock:
            self._pending.append((req, fut))
            if len(self._pending) >= self.max_batch:
                self._cv.notify()
        return fut

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cv.wait(timeout=0.05)
                if self._stopping and not self._pending:
                    return
                # coalescing window: wait for more arrivals while the
                # backlog is still below a device-worthwhile batch AND the
                # device would win at the larger size (holding a batch the
                # CPU can clear immediately only adds latency)
                if len(self._pending) < self.max_batch and (
                    self._device_capable
                    and not self._prewarm_pending
                    and (
                        not self._route_by_cost
                        or self.model.route(
                            max(len(self._pending), self.min_device_batch),
                            count=False,
                            arms=self._device_arms(),
                        ) != "cpu"
                    )
                ):
                    self._cv.wait(timeout=self.window)
                batch = self._pending[: self.max_batch]
                self._pending = self._pending[self.max_batch :]
            reqs = [r for r, _ in batch]
            try:
                results = self.verify_many(reqs)
            except Exception as exc:  # noqa: BLE001 — fail the futures, not the plane
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                continue
            for (_, fut), ok in zip(batch, results):
                fut.set_result(bool(ok))

    # -- blocking whole-batch path ---------------------------------------

    def _record(self, kind: str, ms: float) -> None:
        self._hist[kind].record(ms)

    def _device_arms(self) -> tuple:
        """The device arms currently worth routing between. Once the
        wide verifier RESOLVES to a single device (mesh= wider than the
        box), the 1-chip arm is the identical program — collapse it."""
        if (self._one_chip is not None
                and getattr(self.verifier, "n_devices", 0) == 1):
            self._one_chip = None
        if self._one_chip is None and len(self.model.device_arms) > 1:
            return self.model.device_arms[-1:]
        return self.model.device_arms

    def _verifier_of(self, arm: str) -> BatchVerifier:
        if arm == "dev1" and self._one_chip is not None:
            return self._one_chip
        return self.verifier

    def _pad_buckets(self, n: int, arm: Optional[str] = None) -> set[int]:
        """Pad-bucket shapes the arm's verifier will compile for a batch
        of n (one chunk per max_batch, each padded per its own policy)."""
        ver = self._verifier_of(arm) if arm is not None else self.verifier
        pad = getattr(ver, "_pad_size", None)
        lo = getattr(ver, "min_batch", self.min_device_batch)
        hi = getattr(ver, "max_batch", self.max_batch)
        buckets = set()
        for start in range(0, n, hi):
            chunk = min(hi, n - start)
            buckets.add(pad(chunk, lo, hi) if pad else chunk)
        return buckets

    def _device_deadline(self, n: int, arm: Optional[str] = None) -> float:
        """Generous while any chunk's pad-bucket shape is uncompiled,
        tight (per chunk) once every shape is warm."""
        arm = arm if arm is not None else self.model.device_arms[-1]
        if self._pad_buckets(n, arm) - self._warm_buckets[arm]:
            return self._t_first
        ver = self._verifier_of(arm)
        hi = getattr(ver, "max_batch", self.max_batch)
        nchunks = max(1, -(-n // max(1, hi)))
        return self._t_warm * nchunks

    def _mark_warm(self, n: int, arm: Optional[str] = None) -> None:
        arm = arm if arm is not None else self.model.device_arms[-1]
        self._warm_buckets[arm] |= self._pad_buckets(n, arm)

    def start_prewarm(
        self, sizes: Optional[Sequence[int]] = None, rounds: int = 2
    ) -> threading.Thread:
        """Compile and measure the device's pad-bucket shapes OFF the
        traffic path. Until the thread finishes, every live batch routes
        to the CPU side; afterwards the routing model holds real
        steady-state device measurements (the first sample per bucket is
        compile-laden and discarded by observe_device). The reference
        needs no analog — libsodium is ready at link time; XLA
        compilation is the TPU build's equivalent and belongs in node
        startup, never inside live traffic. Join the returned thread for
        a deterministic warm start (bench legs do)."""
        if sizes is None:
            # derive from this plane's own routing range: every pad
            # bucket between the smallest batch the model can route to
            # the device and the largest it can coalesce — live traffic
            # must find EVERY shape warm (under the TPU "max" pad
            # policy the whole ladder collapses to one canonical shape)
            lo = max(
                self.min_device_batch,
                getattr(self.verifier, "min_batch", self.min_device_batch),
            )
            ladder = []
            size = lo
            while size < self.max_batch:
                ladder.append(size)
                size *= 2
            ladder.append(self.max_batch)
            sizes = sorted(set(ladder))
        if self._device_capable:
            self._prewarm_pending = True

        def run() -> None:
            try:
                if not self._device_capable:
                    return
                req = VerifyRequest(b"\x66" * 32, b"\x77" * 32, b"\x88" * 64)
                # warm EVERY device arm the router can pick: the 1-chip
                # and N-chip programs compile separately. Forced-device
                # mode only ever routes the widest arm, so only that
                # one needs warming. WIDEST FIRST, re-reading the live
                # arm set between arms: resolving the wide program may
                # collapse the 1-chip arm (mesh wider than the box), and
                # a stale snapshot would warm a duplicate width-1
                # program nothing will ever route to.
                for size in sizes:
                    reqs = [req] * size
                    warmed: set = set()
                    while True:
                        arms = self._device_arms()
                        if not self._route_by_cost:
                            arms = arms[-1:]
                        todo = [a for a in reversed(arms)
                                if a not in warmed]
                        if not todo:
                            break
                        arm = todo[0]
                        warmed.add(arm)
                        ver = self._verifier_of(arm)
                        for _ in range(max(2, rounds)):
                            t0 = time.perf_counter()
                            call_with_deadline(
                                lambda v=ver: v.verify_batch(reqs),
                                self._device_deadline(size, arm),
                                label="verify-prewarm",
                            )
                            ms = (time.perf_counter() - t0) * 1000.0
                            self._mark_warm(size, arm)
                            self.model.observe_device(size, ms, arm=arm)
            except DeviceWedged as exc:
                self._device_capable = False
                self.device_wedged = True
                log.error("verify prewarm: %s — device plane disabled", exc)
            except Exception:  # noqa: BLE001 — a prewarm failure must not kill startup
                log.exception("verify prewarm failed; device unwarmed")
            finally:
                self._prewarm_pending = False

        t = threading.Thread(target=run, name="verify-prewarm", daemon=True)
        t.start()
        return t

    def verify_many(self, reqs: Sequence[VerifyRequest]) -> np.ndarray:
        if not reqs:
            return np.zeros(0, bool)
        n = len(reqs)
        arm = "cpu"
        if self._device_capable and not self._prewarm_pending:
            if self._route_by_cost:
                arm = self.model.route(n, arms=self._device_arms())
            elif n >= self.min_device_batch:
                # forced-device mode: the widest available arm
                arm = self._device_arms()[-1]
        wedged_now = False
        if arm != "cpu":
            ver = self._verifier_of(arm)
            t0 = time.perf_counter()
            try:
                out = call_with_deadline(
                    lambda: ver.verify_batch(reqs),
                    self._device_deadline(n, arm),
                    label="verify-device",
                )
                t1 = time.perf_counter()
                ms = (t1 - t0) * 1000.0
                self._mark_warm(n, arm)
                self.model.observe_device(n, ms, arm=arm)
                self.device_batches += 1
                self.device_sigs += n
                self._arm_batches[arm] = self._arm_batches.get(arm, 0) + 1
                self._arm_sigs[arm] = self._arm_sigs.get(arm, 0) + n
                self._record("device", ms)
                self.batches += 1
                self.verified += n
                # batch formation + routing decision evidence: size and
                # the arm the latency model picked (device width rides
                # the name), kernel wall time as the span duration
                self.tracer.complete(
                    "verify.batch", "verify", t0, t1,
                    n=n, routed=arm if arm != "device" else "device",
                )
                return out
            except DeviceWedged as exc:
                # wedged tunnel: device plane is dead for the process
                # (BOTH arms — they share the tunnel); this batch (and
                # all future ones) verifies on the CPU
                self._device_capable = False
                self.device_wedged = True
                wedged_now = True
                log.error("verify plane: %s — falling back to CPU", exc)
        t0 = time.perf_counter()
        out = self.cpu.verify_batch(reqs)
        t1 = time.perf_counter()
        ms = (t1 - t0) * 1000.0
        # tiny batches (the synchronous RPC-submit path is n=1) carry
        # un-amortized fixed overhead; folding them into the per-sig
        # EWMA would inflate expected_cpu_ms for LARGE batches and bias
        # routing toward the device on evidence that doesn't transfer
        if n >= 8:
            self.model.observe_cpu(n, ms)
        self.cpu_batches += 1
        self.cpu_sigs += n
        self._record("cpu", ms)
        self.batches += 1
        self.verified += n
        self.tracer.complete(
            "verify.batch", "verify", t0, t1, n=n, routed="cpu",
            **({"wedged_fallback": True} if wedged_now else {}),
        )
        return out

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            self._cv.notify_all()
        self._flusher.join(timeout=10)

    def _transfer_json(self):
        """Aggregate the device arms' TransferMeters (N-chip inner plus
        the 1-chip arm when built). None for host-only backends."""
        agg = None
        for v in (self.verifier, self._one_chip):
            meter = getattr(v, "transfers", None) if v is not None else None
            if meter is None:
                continue
            j = meter.get_json()
            if agg is None:
                agg = dict(j)
            else:
                for k, val in j.items():
                    agg[k] = agg.get(k, 0) + val
        return agg

    def get_json(self) -> dict:
        model = self.model.get_json()
        describe = getattr(self.verifier, "describe", None)
        return {
            "backend": self.backend_name,
            "routing": self.routing,
            # mesh provenance: requested width, effective width,
            # devices visible and the kernel actually selected — a
            # BENCH/ops reader must see what ran (ISSUE 15)
            "mesh": describe() if describe is not None else None,
            # transfer honesty: host<->device traffic across both device
            # arms — per-close deltas of this block pin residency
            "transfers": self._transfer_json(),
            "arms": {
                a: {
                    "batches": self._arm_batches.get(a, 0),
                    "sigs": self._arm_sigs.get(a, 0),
                }
                for a in self.model.device_arms
            },
            # which host implementation fills the cpu side (native C++
            # batch kernel vs per-signature host library) — a silent
            # toolchain degrade must be visible to operators (this dict
            # is embedded in the get_counts / print RPC replies)
            "host_impl": getattr(self.cpu, "impl", "?"),
            "batches": self.batches,
            "verified": self.verified,
            "device_batches": self.device_batches,
            "cpu_batches": self.cpu_batches,
            "device_sigs": self.device_sigs,
            "cpu_sigs": self.cpu_sigs,
            "device_wedged": self.device_wedged,
            "device_share": (
                round(self.device_sigs / self.verified, 4)
                if self.verified
                else 0.0
            ),
            "pending": len(self._pending),
            "model": model,
            "latency_histogram_ms": {
                "edges": list(_HIST_BOUNDS),
                "cpu": list(self._hist["cpu"].counts),
                "device": list(self._hist["device"].counts),
                "cpu_quantiles": self._hist["cpu"].get_json(),
                "device_quantiles": self._hist["device"].get_json(),
            },
        }
