"""VerifyPlane: the coalescing device-batched signature pipeline.

This is the north-star seam (SURVEY §2.9 mapping #1): the reference
verifies each signature synchronously inside its own job
(PeerImp::checkTransaction → STTx::checkSign → libsodium); here,
verification requests from concurrent jobs are coalesced across an
adaptive window and dispatched as ONE device program over the whole
batch (crypto.backend.BatchVerifier).

Dispatch is LATENCY-AWARE (VERDICT r2 #1b): the plane continuously
measures both backends on the batches it actually runs — a per-signature
EWMA for the threaded CPU path, a per-pad-bucket EWMA for the device
kernel (whose cost is dominated by a fixed per-invocation latency) — and
routes each batch to whichever model predicts faster. Small/trickled
batches therefore stay on the CPU even when a device is configured; the
device wins exactly where it is faster. Per-batch latencies are kept as
histograms per backend (the SURVEY §5 tracing ask) and exported through
get_json.

Callers either:
- `submit(req) -> Future[bool]` — async, coalesced (the JobQueue path),
- `verify_many(reqs) -> ndarray` — blocking whole-batch (consensus close
  verifying a round's validations at once).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from ..crypto.backend import BatchVerifier, VerifyRequest, make_verifier
from ..utils.devicewatch import (
    DeviceWedged,
    call_with_deadline,
    resolve_timeouts,
)
from .metrics import LatencyHist

log = logging.getLogger("stellard.device")

__all__ = ["VerifyPlane"]

# per-batch latency bucket upper bounds (ms); the +inf overflow bucket
# is implicit in LatencyHist
_HIST_BOUNDS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
                1000.0)


class _LatencyModel:
    """Measured-cost models for the routing decision."""

    # after this many CPU-routed eligible batches, retry the device once
    # (load characteristics drift; a one-shot loss must not be forever)
    REEXPLORE_EVERY = 512

    def __init__(self, min_device_batch: int):
        self.min_device_batch = min_device_batch
        # CPU: cost ~ linear in batch size
        self.cpu_persig_ms: Optional[float] = None
        # device: cost ~ flat per pad-bucket (kernel latency dominates)
        self.device_bucket_ms: dict[int, float] = {}
        # buckets that have absorbed their first (compile-laden) sample
        self._device_warm: set[int] = set()
        self._since_device = 0
        self.lock = threading.Lock()

    @staticmethod
    def _bucket(n: int, lo: int) -> int:
        size = lo
        while size < n:
            size *= 2
        return size

    def observe_cpu(self, n: int, ms: float) -> None:
        if n <= 0:
            return
        with self.lock:
            per = ms / n
            if self.cpu_persig_ms is None:
                self.cpu_persig_ms = per
            else:
                self.cpu_persig_ms += 0.25 * (per - self.cpu_persig_ms)

    def observe_device(self, n: int, ms: float) -> None:
        b = self._bucket(max(n, 1), self.min_device_batch)
        with self.lock:
            self._since_device = 0
            if b not in self._device_warm:
                # first sample per bucket includes XLA compilation —
                # recording it would poison the model and route every
                # later batch to the CPU; discard it and measure the
                # steady state from the second sample on
                self._device_warm.add(b)
                return
            cur = self.device_bucket_ms.get(b)
            self.device_bucket_ms[b] = (
                ms if cur is None else cur + 0.25 * (ms - cur)
            )

    def expected_cpu_ms(self, n: int) -> Optional[float]:
        with self.lock:
            if self.cpu_persig_ms is None:
                return None
            return self.cpu_persig_ms * n

    def expected_device_ms(self, n: int) -> Optional[float]:
        b = self._bucket(max(n, 1), self.min_device_batch)
        with self.lock:
            if b in self.device_bucket_ms:
                return self.device_bucket_ms[b]
            # nearest measured bucket as an estimate; device cost is
            # near-flat, so any measurement beats none
            if self.device_bucket_ms:
                near = min(
                    self.device_bucket_ms, key=lambda k: abs(k - b)
                )
                return self.device_bucket_ms[near]
            return None

    def use_device(self, n: int, count: bool = True) -> bool:
        """True when the device model predicts a win for this batch.
        Unmeasured sides are explored optimistically: the device gets
        tried once a batch reaches min_device_batch, after which real
        measurements drive every later decision. `count=False` asks the
        same question without advancing the re-exploration counter (the
        coalescing-window decision polls this every wake-up and must not
        inflate the re-explore cadence)."""
        if n < self.min_device_batch:
            return False
        dev = self.expected_device_ms(n)
        cpu = self.expected_cpu_ms(n)
        if dev is None:
            return True  # explore: one measurement teaches the model
        if cpu is None:
            return False  # CPU unmeasured: measure it too
        if dev < cpu:
            return True
        if not count:
            return False
        # periodic re-exploration so a stale loss can be unlearned — but
        # only within striking distance: a ~300 ms kernel invocation must
        # never be retried on a 64-sig batch it cannot possibly win
        if cpu * 4.0 < dev:
            return False
        with self.lock:
            self._since_device += 1
            if self._since_device >= self.REEXPLORE_EVERY:
                self._since_device = 0
                return True
        return False


class VerifyPlane:
    def __init__(
        self,
        backend: str = "cpu",
        window_ms: float = 2.0,
        max_batch: int = 16384,
        min_device_batch: int = 64,
        cpu_fallback: Optional[BatchVerifier] = None,
        device_first_timeout: Optional[float] = None,
        device_warm_timeout: Optional[float] = None,
        tracer=None,
    ):
        from .tracer import get_tracer

        self.tracer = tracer if tracer is not None else get_tracer()
        self.backend_name = backend
        self.verifier: BatchVerifier = make_verifier(backend)
        self.cpu: BatchVerifier = cpu_fallback or (
            self.verifier if backend == "cpu" else make_verifier("cpu")
        )
        self.window = window_ms / 1000.0
        self.max_batch = max_batch
        self.min_device_batch = min_device_batch
        self.model = _LatencyModel(min_device_batch)
        self._device_capable = backend != "cpu"
        # device-wedge watchdog deadlines (utils.devicewatch): the first
        # call to a pad-bucket shape legitimately compiles (~1-3 min on
        # chip), so unseen shapes get the generous deadline and warmed
        # shapes the tight one. On overrun the device is dead for the
        # process and every batch (including the stalled one, re-run on
        # the CPU side) still gets verified.
        self._t_first, self._t_warm = resolve_timeouts(
            device_first_timeout, device_warm_timeout
        )
        self._warm_buckets: set[int] = set()
        self.device_wedged = False
        # while a prewarm runs, traffic routes to the CPU side — the
        # device must never pay its first (compile-laden) invocation on
        # live batches
        self._prewarm_pending = False

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list[tuple[VerifyRequest, Future]] = []
        self._stopping = False
        self.batches = 0
        self.verified = 0
        self.device_batches = 0
        self.cpu_batches = 0
        # per-SIGNATURE routing counters: a bench leg's "device share"
        # (device_sigs / verified) proves the device actually did work —
        # latency-aware routing can otherwise zero the device out while
        # the leg still reports a healthy ~1.0 ratio (VERDICT r3 weak #6)
        self.device_sigs = 0
        self.cpu_sigs = 0
        self._hist: dict[str, LatencyHist] = {
            "cpu": LatencyHist(bounds=_HIST_BOUNDS),
            "device": LatencyHist(bounds=_HIST_BOUNDS),
        }
        self._flusher = threading.Thread(
            target=self._flush_loop, name="verify-plane", daemon=True
        )
        self._flusher.start()

    # -- async coalesced path --------------------------------------------

    def submit(self, req: VerifyRequest) -> "Future[bool]":
        fut: Future = Future()
        with self._lock:
            self._pending.append((req, fut))
            if len(self._pending) >= self.max_batch:
                self._cv.notify()
        return fut

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cv.wait(timeout=0.05)
                if self._stopping and not self._pending:
                    return
                # coalescing window: wait for more arrivals while the
                # backlog is still below a device-worthwhile batch AND the
                # device would win at the larger size (holding a batch the
                # CPU can clear immediately only adds latency)
                if len(self._pending) < self.max_batch and (
                    self._device_capable
                    and not self._prewarm_pending
                    and self.model.use_device(
                        max(len(self._pending), self.min_device_batch),
                        count=False,
                    )
                ):
                    self._cv.wait(timeout=self.window)
                batch = self._pending[: self.max_batch]
                self._pending = self._pending[self.max_batch :]
            reqs = [r for r, _ in batch]
            try:
                results = self.verify_many(reqs)
            except Exception as exc:  # noqa: BLE001 — fail the futures, not the plane
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                continue
            for (_, fut), ok in zip(batch, results):
                fut.set_result(bool(ok))

    # -- blocking whole-batch path ---------------------------------------

    def _record(self, kind: str, ms: float) -> None:
        self._hist[kind].record(ms)

    def _pad_buckets(self, n: int) -> set[int]:
        """Pad-bucket shapes the device verifier will compile for a batch
        of n (one chunk per max_batch, each padded per its own policy)."""
        pad = getattr(self.verifier, "_pad_size", None)
        lo = getattr(self.verifier, "min_batch", self.min_device_batch)
        hi = getattr(self.verifier, "max_batch", self.max_batch)
        buckets = set()
        for start in range(0, n, hi):
            chunk = min(hi, n - start)
            buckets.add(pad(chunk, lo, hi) if pad else chunk)
        return buckets

    def _device_deadline(self, n: int) -> float:
        """Generous while any chunk's pad-bucket shape is uncompiled,
        tight (per chunk) once every shape is warm."""
        if self._pad_buckets(n) - self._warm_buckets:
            return self._t_first
        hi = getattr(self.verifier, "max_batch", self.max_batch)
        nchunks = max(1, -(-n // max(1, hi)))
        return self._t_warm * nchunks

    def _mark_warm(self, n: int) -> None:
        self._warm_buckets |= self._pad_buckets(n)

    def start_prewarm(
        self, sizes: Optional[Sequence[int]] = None, rounds: int = 2
    ) -> threading.Thread:
        """Compile and measure the device's pad-bucket shapes OFF the
        traffic path. Until the thread finishes, every live batch routes
        to the CPU side; afterwards the routing model holds real
        steady-state device measurements (the first sample per bucket is
        compile-laden and discarded by observe_device). The reference
        needs no analog — libsodium is ready at link time; XLA
        compilation is the TPU build's equivalent and belongs in node
        startup, never inside live traffic. Join the returned thread for
        a deterministic warm start (bench legs do)."""
        if sizes is None:
            # derive from this plane's own routing range: every pad
            # bucket between the smallest batch the model can route to
            # the device and the largest it can coalesce — live traffic
            # must find EVERY shape warm (under the TPU "max" pad
            # policy the whole ladder collapses to one canonical shape)
            lo = max(
                self.min_device_batch,
                getattr(self.verifier, "min_batch", self.min_device_batch),
            )
            ladder = []
            size = lo
            while size < self.max_batch:
                ladder.append(size)
                size *= 2
            ladder.append(self.max_batch)
            sizes = sorted(set(ladder))
        if self._device_capable:
            self._prewarm_pending = True

        def run() -> None:
            try:
                if not self._device_capable:
                    return
                req = VerifyRequest(b"\x66" * 32, b"\x77" * 32, b"\x88" * 64)
                for size in sizes:
                    reqs = [req] * size
                    for _ in range(max(2, rounds)):
                        t0 = time.perf_counter()
                        call_with_deadline(
                            lambda: self.verifier.verify_batch(reqs),
                            self._device_deadline(size),
                            label="verify-prewarm",
                        )
                        ms = (time.perf_counter() - t0) * 1000.0
                        self._mark_warm(size)
                        self.model.observe_device(size, ms)
            except DeviceWedged as exc:
                self._device_capable = False
                self.device_wedged = True
                log.error("verify prewarm: %s — device plane disabled", exc)
            except Exception:  # noqa: BLE001 — a prewarm failure must not kill startup
                log.exception("verify prewarm failed; device unwarmed")
            finally:
                self._prewarm_pending = False

        t = threading.Thread(target=run, name="verify-prewarm", daemon=True)
        t.start()
        return t

    def verify_many(self, reqs: Sequence[VerifyRequest]) -> np.ndarray:
        if not reqs:
            return np.zeros(0, bool)
        n = len(reqs)
        use_device = (
            self._device_capable
            and not self._prewarm_pending
            and self.model.use_device(n)
        )
        wedged_now = False
        if use_device:
            t0 = time.perf_counter()
            try:
                out = call_with_deadline(
                    lambda: self.verifier.verify_batch(reqs),
                    self._device_deadline(n),
                    label="verify-device",
                )
                t1 = time.perf_counter()
                ms = (t1 - t0) * 1000.0
                self._mark_warm(n)
                self.model.observe_device(n, ms)
                self.device_batches += 1
                self.device_sigs += n
                self._record("device", ms)
                self.batches += 1
                self.verified += n
                # batch formation + routing decision evidence: size and
                # the side the latency model picked, kernel wall time as
                # the span duration
                self.tracer.complete(
                    "verify.batch", "verify", t0, t1,
                    n=n, routed="device",
                )
                return out
            except DeviceWedged as exc:
                # wedged tunnel: device plane is dead for the process;
                # this batch (and all future ones) verifies on the CPU
                self._device_capable = False
                self.device_wedged = True
                wedged_now = True
                log.error("verify plane: %s — falling back to CPU", exc)
        t0 = time.perf_counter()
        out = self.cpu.verify_batch(reqs)
        t1 = time.perf_counter()
        ms = (t1 - t0) * 1000.0
        self.model.observe_cpu(n, ms)
        self.cpu_batches += 1
        self.cpu_sigs += n
        self._record("cpu", ms)
        self.batches += 1
        self.verified += n
        self.tracer.complete(
            "verify.batch", "verify", t0, t1, n=n, routed="cpu",
            **({"wedged_fallback": True} if wedged_now else {}),
        )
        return out

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            self._cv.notify_all()
        self._flusher.join(timeout=10)

    def get_json(self) -> dict:
        with self.model.lock:
            model = {
                "cpu_persig_ms": self.model.cpu_persig_ms,
                "device_bucket_ms": dict(self.model.device_bucket_ms),
            }
        return {
            "backend": self.backend_name,
            # which host implementation fills the cpu side (native C++
            # batch kernel vs per-signature host library) — a silent
            # toolchain degrade must be visible to operators (this dict
            # is embedded in the get_counts / print RPC replies)
            "host_impl": getattr(self.cpu, "impl", "?"),
            "batches": self.batches,
            "verified": self.verified,
            "device_batches": self.device_batches,
            "cpu_batches": self.cpu_batches,
            "device_sigs": self.device_sigs,
            "cpu_sigs": self.cpu_sigs,
            "device_wedged": self.device_wedged,
            "device_share": (
                round(self.device_sigs / self.verified, 4)
                if self.verified
                else 0.0
            ),
            "pending": len(self._pending),
            "model": model,
            "latency_histogram_ms": {
                "edges": list(_HIST_BOUNDS),
                "cpu": list(self._hist["cpu"].counts),
                "device": list(self._hist["device"].counts),
                "cpu_quantiles": self._hist["cpu"].get_json(),
                "device_quantiles": self._hist["device"].get_json(),
            },
        }
