"""VerifyPlane: the coalescing device-batched signature pipeline.

This is the north-star seam (SURVEY §2.9 mapping #1): the reference
verifies each signature synchronously inside its own job
(PeerImp::checkTransaction → STTx::checkSign → libsodium); here,
verification requests from concurrent jobs are coalesced across an
adaptive window and dispatched as ONE device program over the whole
batch (crypto.backend.BatchVerifier), with a CPU fast path for small
batches so standalone latency stays flat (SURVEY §7 "Batching vs
latency").

Callers either:
- `submit(req) -> Future[bool]` — async, coalesced (the JobQueue path),
- `verify_many(reqs) -> ndarray` — blocking whole-batch (consensus close
  verifying a round's validations at once).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from ..crypto.backend import BatchVerifier, VerifyRequest, make_verifier

__all__ = ["VerifyPlane"]


class VerifyPlane:
    def __init__(
        self,
        backend: str = "cpu",
        window_ms: float = 2.0,
        max_batch: int = 16384,
        min_device_batch: int = 64,
        cpu_fallback: Optional[BatchVerifier] = None,
    ):
        self.backend_name = backend
        self.verifier: BatchVerifier = make_verifier(backend)
        self.cpu: BatchVerifier = cpu_fallback or (
            self.verifier if backend == "cpu" else make_verifier("cpu")
        )
        self.window = window_ms / 1000.0
        self.max_batch = max_batch
        self.min_device_batch = min_device_batch

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pending: list[tuple[VerifyRequest, Future]] = []
        self._stopping = False
        self.batches = 0
        self.verified = 0
        self._flusher = threading.Thread(
            target=self._flush_loop, name="verify-plane", daemon=True
        )
        self._flusher.start()

    # -- async coalesced path --------------------------------------------

    def submit(self, req: VerifyRequest) -> "Future[bool]":
        fut: Future = Future()
        with self._lock:
            self._pending.append((req, fut))
            if len(self._pending) >= self.max_batch:
                self._cv.notify()
        return fut

    def _flush_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._cv.wait(timeout=0.05)
                if self._stopping and not self._pending:
                    return
                # open the coalescing window: wait for more arrivals
                if len(self._pending) < self.max_batch:
                    self._cv.wait(timeout=self.window)
                batch = self._pending[: self.max_batch]
                self._pending = self._pending[self.max_batch :]
            reqs = [r for r, _ in batch]
            try:
                results = self.verify_many(reqs)
            except Exception as exc:  # noqa: BLE001 — fail the futures, not the plane
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(exc)
                continue
            for (_, fut), ok in zip(batch, results):
                fut.set_result(bool(ok))

    # -- blocking whole-batch path ---------------------------------------

    def verify_many(self, reqs: Sequence[VerifyRequest]) -> np.ndarray:
        if not reqs:
            return np.zeros(0, bool)
        use_cpu = len(reqs) < self.min_device_batch
        verifier = self.cpu if use_cpu else self.verifier
        out = verifier.verify_batch(reqs)
        self.batches += 1
        self.verified += len(reqs)
        return out

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            self._cv.notify_all()
        self._flusher.join(timeout=10)

    def get_json(self) -> dict:
        return {
            "backend": self.backend_name,
            "batches": self.batches,
            "verified": self.verified,
            "pending": len(self._pending),
        }
