"""NodeStore: content-addressed object store (hash → NodeObject).

Reference scope: src/ripple_core/nodestore ({api,impl,backend}).
The pluggable Backend/Factory registry is the same seam the crypto plane
copies for `signature_backend` (nodestore/api/Factory.h:27-44).
"""

from .core import (
    NodeObject,
    NodeObjectType,
    Backend,
    Database,
    register_backend,
    make_backend,
    make_database,
)
from . import backends as _backends  # noqa: F401  (registers built-ins)
from . import segstore as _segstore  # noqa: F401  (registers segstore)
from .segstore import SegStoreBackend

__all__ = [
    "NodeObject",
    "NodeObjectType",
    "Backend",
    "Database",
    "SegStoreBackend",
    "register_backend",
    "make_backend",
    "make_database",
]
