"""Built-in NodeStore backends: memory, null, sqlite.

Reference: src/ripple_core/nodestore/backend/{Memory,Null}Factory.cpp and
src/ripple_app/node/SqliteFactory.cpp. The reference's LevelDB/RocksDB
roles are filled by sqlite-WAL here (stdlib, zero deps); the Backend seam
means a real LSM store can be registered without touching callers.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator, Optional

from .core import Backend, NodeObject, NodeObjectType, register_backend

__all__ = ["MemoryBackend", "NullBackend", "SqliteBackend"]


class MemoryBackend(Backend):
    """reference: backend/MemoryFactory.cpp"""

    name = "memory"

    def __init__(self, **_):
        self._map: dict[bytes, NodeObject] = {}
        self._lock = threading.Lock()

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        with self._lock:
            return self._map.get(hash)

    def store_batch(self, batch: list[NodeObject]) -> None:
        with self._lock:
            for obj in batch:
                self._map[obj.hash] = obj

    def iterate(self) -> Iterator[NodeObject]:
        with self._lock:
            objs = list(self._map.values())
        yield from objs


class NullBackend(Backend):
    """Discards everything (reference: backend/NullFactory.cpp)."""

    name = "null"

    def __init__(self, **_):
        pass

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        return None

    def store_batch(self, batch: list[NodeObject]) -> None:
        pass

    def iterate(self) -> Iterator[NodeObject]:
        return iter(())


class SqliteBackend(Backend):
    """Durable backend over sqlite WAL (reference:
    src/ripple_app/node/SqliteFactory.cpp — same schema shape: one table,
    hash primary key, type + blob columns).

    WAL hygiene: sqlite's passive autocheckpoint cannot keep up with a
    sustained store_batch flood (readers + back-to-back commits keep the
    WAL pinned), so the -wal file grows without bound. After every
    ``WAL_CHECKPOINT_BYTES`` of batched writes we force a
    ``wal_checkpoint(TRUNCATE)``, which blocks briefly but resets the
    WAL to zero — bounded disk beats a stall-free unbounded log.

    ``synchronous=`` is the ``[node_db]`` passthrough to PRAGMA
    synchronous (off|normal|full|extra) — the sqlite flavor of the
    segstore durability knob."""

    name = "sqlite"

    WAL_CHECKPOINT_BYTES = 16 << 20

    _SYNC_LEVELS = ("off", "normal", "full", "extra")

    def __init__(self, path: str = ":memory:", synchronous: str = "",
                 **_):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        self._path = path
        self._wal_bytes = 0
        self.wal_checkpoints = 0
        sync_level = (synchronous or "normal").lower()
        if sync_level not in self._SYNC_LEVELS:
            # a durability toggle must not fail open into a default
            raise ValueError(
                f"[node_db] synchronous must be one of "
                f"{self._SYNC_LEVELS}, got {synchronous!r}"
            )
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(f"PRAGMA synchronous={sync_level.upper()}")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS nodes ("
                " hash BLOB PRIMARY KEY, type INTEGER, data BLOB)"
            )
            self._conn.commit()

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        with self._lock:
            row = self._conn.execute(
                "SELECT type, data FROM nodes WHERE hash=?", (hash,)
            ).fetchone()
        if row is None:
            return None
        return NodeObject(NodeObjectType(row[0]), hash, row[1])

    def store_batch(self, batch: list[NodeObject]) -> None:
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO nodes (hash, type, data) VALUES (?,?,?)",
                [(o.hash, int(o.type), o.data) for o in batch],
            )
            self._conn.commit()
            self._wal_bytes += sum(len(o.data) + 40 for o in batch)
            if self._wal_bytes >= self.WAL_CHECKPOINT_BYTES:
                self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                self._wal_bytes = 0
                self.wal_checkpoints += 1

    def iterate(self) -> Iterator[NodeObject]:
        with self._lock:
            rows = self._conn.execute("SELECT hash, type, data FROM nodes").fetchall()
        for h, t, d in rows:
            yield NodeObject(NodeObjectType(t), h, d)

    def get_json(self) -> dict:
        return {
            "backend": self.name,
            "wal_checkpoints": self.wal_checkpoints,
        }

    def close(self) -> None:
        with self._lock:
            try:
                self._conn.execute("PRAGMA wal_checkpoint(TRUNCATE)")
            except sqlite3.Error:
                pass
            self._conn.close()


register_backend("memory", MemoryBackend)
register_backend("null", NullBackend)
register_backend("sqlite", SqliteBackend)


class CppLogBackend(Backend):
    """Native log-structured backend (native/src/nodestore.cc) — the
    C++ store filling the LevelDB/RocksDB role (SURVEY §2.8): append-only
    data log + in-memory hash index, replayed on open.

    ``compression="zlib"`` fills the snappy role (the reference vendors
    snappy for its LevelDB blocks): blobs are deflated before the append
    when that saves space, flagged in the record's type byte (high bit),
    so compressed and raw records coexist and old stores read unchanged.
    SHAMap inner nodes (child-hash vectors) are near-incompressible, but
    serialized account/tx leaves deflate well."""

    name = "cpplog"

    _ZLIB_FLAG = 0x80  # type-byte high bit: NodeObjectType is 0..4

    def __init__(self, path: str = "nodestore.cpplog",
                 compression: str = "", **_):
        from ..native import CppLogLib

        self._db = CppLogLib(path)
        self._path = path
        if compression not in ("", "none", "zlib"):
            raise ValueError(f"unknown nodestore compression {compression!r}")
        self._compress = compression == "zlib"

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        got = self._db.get(hash)
        if got is None:
            return None
        type_byte, blob = got
        if type_byte & self._ZLIB_FLAG:
            import zlib

            type_byte &= ~self._ZLIB_FLAG
            blob = zlib.decompress(blob)
        return NodeObject(NodeObjectType(type_byte), hash, blob)

    def store_batch(self, batch: list[NodeObject]) -> None:
        if self._compress:
            import zlib

            for obj in batch:
                packed = zlib.compress(obj.data, 1)
                if len(packed) < len(obj.data):
                    self._db.put(obj.hash, int(obj.type) | self._ZLIB_FLAG,
                                 packed)
                else:  # incompressible (e.g. inner-node hash vectors)
                    self._db.put(obj.hash, int(obj.type), obj.data)
        else:
            for obj in batch:
                self._db.put(obj.hash, int(obj.type), obj.data)
        self._db.sync()

    def iterate(self) -> Iterator[NodeObject]:
        """Full segment scan — online deletion, export, and the
        crash-recovery audits need iteration on every durable backend.
        Prefers the native callback scan (cpplog_iterate); ONLY a stale
        prebuilt library without the symbol falls back to parsing the
        log file directly (same record layout the replay reads) — a
        native scan error is corruption (an indexed record that cannot
        be read back) and must propagate, never silently degrade to a
        best-effort prefix of the records."""
        if getattr(self._db.lib, "has_cpplog_iterate", False):
            records = self._db.iterate()
        else:
            records = self._scan_log()
        for key, type_byte, blob in records:
            if type_byte & self._ZLIB_FLAG:
                import zlib

                type_byte &= ~self._ZLIB_FLAG
                blob = zlib.decompress(blob)
            yield NodeObject(NodeObjectType(type_byte), key, blob)

    def _scan_log(self):
        """Python fallback: parse the on-disk log
        ([u32 body_len | u8 flags | 32B key | u8 type | blob] records).
        sync() first so buffered appends are visible; content-addressed
        keys mean a duplicate record carries identical bytes, so
        first-wins matches the native index's behavior."""
        import struct

        self._db.sync()
        with open(self._path, "rb") as f:
            data = f.read()
        seen: set[bytes] = set()
        off = 0
        end = len(data)
        while off + 37 <= end:
            body_len = struct.unpack_from("<I", data, off)[0]
            if body_len < 1 or off + 37 + body_len > end:
                break  # torn tail
            key = data[off + 5: off + 37]
            if key not in seen:
                seen.add(key)
                yield (key, data[off + 37],
                       data[off + 38: off + 37 + body_len])
            off += 37 + body_len

    def get_json(self) -> dict:
        return {"backend": self.name, "objects": self._db.count()}

    def close(self) -> None:
        self._db.close()


# registered unconditionally: construction raises a clean error when the
# native toolchain is unavailable, and the one-time build cost lands on
# first use, never at import
register_backend("cpplog", CppLogBackend)
