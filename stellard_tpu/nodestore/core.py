"""NodeStore core: NodeObject, Backend interface, factory registry,
Database façade with cache + async batch writer.

Reference: src/ripple_core/nodestore/api/{Backend,Factory,Manager}.h,
impl/{DatabaseImp.h,BatchWriter.cpp}. The write path preserves the
reference's shape — callers store synchronously into a pending map while a
writer thread drains batches to the backend (BatchWriter.cpp) — because
that's also the right shape for TPU-adjacent IO: large sequential batches,
no per-object fsync.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from enum import IntEnum
from typing import Callable, Iterator, Optional

__all__ = [
    "NodeObjectType",
    "NodeObject",
    "Backend",
    "Database",
    "register_backend",
    "make_backend",
    "make_database",
]


class NodeObjectType(IntEnum):
    """reference: nodestore/api/NodeObject.h:30-36"""

    UNKNOWN = 0
    LEDGER = 1
    TRANSACTION = 2
    ACCOUNT_NODE = 3
    TRANSACTION_NODE = 4


@dataclass(frozen=True)
class NodeObject:
    type: NodeObjectType
    hash: bytes  # 32-byte content hash (the key)
    data: bytes  # payload (prefix-format SHAMap node / ledger header)


class Backend:
    """Key-value backend interface (reference: nodestore/api/Backend.h:35-85)."""

    name = "abstract"

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        raise NotImplementedError

    def store(self, obj: NodeObject) -> None:
        self.store_batch([obj])

    def store_batch(self, batch: list[NodeObject]) -> None:
        raise NotImplementedError

    def store_packed(self, type: NodeObjectType, hashes, buf,
                     offsets) -> int:
        """Batch store straight from the flat-buffer node encoding
        (state.shamap.encode_nodes: node i's blob — which IS its hashed
        byte sequence — lives at buf[offsets[i]:offsets[i+1]]).
        `hashes` is a list of 32-byte keys or one packed 32n buffer.
        Backends with a one-append door (segstore) override this; the
        default decodes into NodeObjects for plain store_batch."""
        n = len(offsets) - 1
        if n <= 0:
            return 0
        if isinstance(hashes, (bytes, bytearray)):
            hashes = [bytes(hashes[32 * i: 32 * i + 32]) for i in range(n)]
        mv = memoryview(buf)
        self.store_batch([
            NodeObject(type, hashes[i],
                       bytes(mv[offsets[i]: offsets[i + 1]]))
            for i in range(n)
        ])
        return n

    def iterate(self) -> Iterator[NodeObject]:
        raise NotImplementedError

    def close(self) -> None:
        pass


_FACTORIES: dict[str, Callable[..., Backend]] = {}


def register_backend(name: str, factory: Callable[..., Backend]) -> None:
    """reference: nodestore/api/Factory.h + Manager::addFactory"""
    _FACTORIES[name] = factory


def make_backend(type: str = "memory", **kwargs) -> Backend:
    if type not in _FACTORIES:
        raise KeyError(f"unknown nodestore backend {type!r}; have {sorted(_FACTORIES)}")
    return _FACTORIES[type](**kwargs)


class Database:
    """Backend + in-memory cache + async batched write-behind
    (reference: nodestore/impl/DatabaseImp.h, BatchWriter.cpp).

    Writes land synchronously in `_pending` (so reads always see them) and
    a background thread drains them to the backend in batches of up to
    `batch_size`.
    """

    def __init__(self, backend: Backend, cache_size: int = 65536,
                 batch_size: int = 256, async_writes: bool = True):
        self.backend = backend
        # hashes known to be durably in THIS store — the `known` set for
        # SHAMap.flush incremental writes
        self.flushed: set[bytes] = set()
        # fetch counters (the node_store observability block)
        self.cache_hits = 0
        self.backend_fetches = 0
        self.backend_misses = 0
        self._cache: dict[bytes, NodeObject] = {}
        self._cache_size = cache_size
        self._pending: dict[bytes, NodeObject] = {}
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._batch_size = batch_size
        self._stopping = False
        self._write_error: Optional[BaseException] = None
        self._writer: Optional[threading.Thread] = None
        if async_writes:
            self._writer = threading.Thread(
                target=self._write_loop, name="nodestore-writer", daemon=True
            )
            self._writer.start()

    # -- public api -------------------------------------------------------

    def fetch(self, hash: bytes, *,
              populate_cache: bool = True) -> Optional[NodeObject]:
        """`populate_cache=False` serves O(store) scans (the online-
        deletion mark walk) that must still see pending writes but must
        not flush the hot close-path entries out of the LRU."""
        with self._lock:
            obj = self._pending.get(hash) or self._cache.get(hash)
            if obj is not None:
                self.cache_hits += 1
                return obj
            self.backend_fetches += 1
        obj = self.backend.fetch(hash)
        if obj is not None:
            if populate_cache:
                self._cache_put(obj)
        else:
            with self._lock:
                self.backend_misses += 1
        return obj

    def store(self, type: NodeObjectType, hash: bytes, data: bytes) -> None:
        obj = NodeObject(type, hash, data)
        with self._lock:
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") from self._write_error
            self._pending[hash] = obj
            if self._writer is None:
                self.backend.store(obj)
                self._pending.pop(hash)
                self._cache_unlocked(obj)
            else:
                self._wake.notify()

    def store_fn(self, type: NodeObjectType) -> Callable[[bytes, bytes], None]:
        """Adapter with the (hash, blob) signature SHAMap.flush expects."""
        return lambda h, d: self.store(type, h, d)

    def store_many(self, type: NodeObjectType,
                   pairs: list[tuple[bytes, bytes]]) -> None:
        """Batch store: every (hash, blob) pair lands in `_pending` under
        ONE lock hold (the flat-buffer flush path — a per-close tree
        delta is thousands of nodes, and per-node lock round-trips were
        pure overhead). Async mode wakes the writer once; sync mode
        drains through the backend's own batch call."""
        if not pairs:
            return
        batch = [NodeObject(type, h, d) for h, d in pairs]
        with self._lock:
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") from self._write_error
            for obj in batch:
                self._pending[obj.hash] = obj
            if self._writer is not None:
                self._wake.notify()
        if self._writer is None:
            self.backend.store_batch(batch)
            with self._lock:
                for obj in batch:
                    if self._pending.get(obj.hash) is obj:
                        del self._pending[obj.hash]
                    self._cache_unlocked(obj)

    def store_many_fn(self, type: NodeObjectType) -> Callable[[list], None]:
        """Adapter with the batch signature SHAMap.flush's `store_many`
        expects."""
        return lambda pairs: self.store_many(type, pairs)

    def store_packed(self, type: NodeObjectType, hashes, buf,
                     offsets) -> int:
        """Flat-buffer batch door (SHAMap.flush `store_packed` sink):
        the whole chunk goes to the backend in ONE synchronous call —
        blob == hashed bytes, zero per-node objects on the segstore
        path. Runs on the caller's thread (the close pipeline's drain
        worker), bypassing the pending map: content-addressed writes
        need no ordering against the async writer, and read-your-writes
        holds because the backend indexes the batch before returning."""
        with self._lock:
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") \
                    from self._write_error
        return self.backend.store_packed(type, hashes, buf, offsets)

    def store_packed_fn(self, type: NodeObjectType) -> Callable:
        """Adapter with the (hashes, buf, offsets) signature
        SHAMap.flush's `store_packed` expects."""
        return lambda hashes, buf, offsets: self.store_packed(
            type, hashes, buf, offsets
        )

    # -- online deletion ---------------------------------------------------

    def begin_sweep(self) -> None:
        """Arm the backend's sweep guards (see SegStoreBackend)."""
        begin = getattr(self.backend, "begin_sweep", None)
        if begin is None:
            raise NotImplementedError(
                f"backend {self.backend.name!r} does not support "
                f"online deletion"
            )
        begin()

    def cancel_sweep(self) -> None:
        cancel = getattr(self.backend, "cancel_sweep", None)
        if cancel is not None:
            cancel()

    def apply_sweep(self, live: set) -> int:
        """Remove every stored node not in `live`, then purge the
        façade's own state for the removed keys: the cache must stop
        resolving them and — critically — the `flushed` known-set must
        forget them, or a later flush would skip re-writing a deleted
        node a new ledger re-created. Returns nodes removed."""
        apply = getattr(self.backend, "apply_sweep", None)
        if apply is None:
            raise NotImplementedError(
                f"backend {self.backend.name!r} does not support "
                f"online deletion"
            )
        removed = apply(live)
        with self._lock:
            for key in removed:
                self._cache.pop(key, None)
        self.flushed.difference_update(removed)
        return len(removed)

    def sync(self) -> None:
        """Block until all pending writes hit the backend. Raises the
        writer thread's error if the backend failed (otherwise a dead
        writer would make this hang forever)."""
        with self._lock:
            while self._pending:
                if self._write_error is not None:
                    raise RuntimeError("nodestore writer failed") from self._write_error
                self._wake.notify()
                self._wake.wait(0.01)
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") from self._write_error
        # durability barrier: backends with deferred fsync (segstore
        # durability=batch|async) flush their group-commit window too
        backend_sync = getattr(self.backend, "sync", None)
        if backend_sync is not None:
            backend_sync()

    def close(self) -> None:
        try:
            self.sync()
        finally:
            with self._lock:
                self._stopping = True
                self._wake.notify()
            if self._writer:
                self._writer.join(timeout=5)
            self.backend.close()

    def get_json(self) -> dict:
        """The `node_store` observability block (server_state /
        get_counts): façade cache + write-behind stats, plus whatever
        the backend itself reports (segstore: segments, live ratio,
        appends/fsyncs, compaction and checkpoint counters)."""
        with self._lock:
            out = {
                "cache_size": len(self._cache),
                "cache_hits": self.cache_hits,
                "backend_fetches": self.backend_fetches,
                "backend_misses": self.backend_misses,
                "pending_writes": len(self._pending),
                "flushed_known": len(self.flushed),
                "backend": self.backend.name,
            }
        backend_json = getattr(self.backend, "get_json", None)
        if backend_json is not None:
            out["backend_stats"] = backend_json()
        return out

    # -- internals --------------------------------------------------------

    def _cache_put(self, obj: NodeObject) -> None:
        with self._lock:
            self._cache_unlocked(obj)

    def _cache_unlocked(self, obj: NodeObject) -> None:
        if len(self._cache) >= self._cache_size:
            # simple clock-less eviction: drop ~25% oldest-inserted
            drop = len(self._cache) // 4 or 1
            for k in list(self._cache)[:drop]:
                del self._cache[k]
        self._cache[obj.hash] = obj

    def _write_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._wake.wait(0.1)
                if self._stopping and not self._pending:
                    return
                keys = list(self._pending)[: self._batch_size]
                batch = [self._pending[k] for k in keys]
            try:
                self.backend.store_batch(batch)
            except BaseException as exc:  # surface via sync(); keep pending
                with self._lock:
                    self._write_error = exc
                    self._wake.notify_all()
                return
            with self._lock:
                for k, o in zip(keys, batch):
                    if self._pending.get(k) is o:
                        del self._pending[k]
                    self._cache_unlocked(o)
                self._wake.notify_all()


def make_database(type: str = "memory", *, cache_size: int = 65536,
                  async_writes: bool = True, **backend_kwargs) -> Database:
    """reference: NodeStore::Manager::make_Database; `type=` is the config
    knob ([node_db] type=..., doc/stellard-example.cfg:795-802)."""
    return Database(
        make_backend(type, **backend_kwargs),
        cache_size=cache_size,
        async_writes=async_writes,
    )
