"""NodeStore core: NodeObject, Backend interface, factory registry,
Database façade with cache + async batch writer.

Reference: src/ripple_core/nodestore/api/{Backend,Factory,Manager}.h,
impl/{DatabaseImp.h,BatchWriter.cpp}. The write path preserves the
reference's shape — callers store synchronously into a pending map while a
writer thread drains batches to the backend (BatchWriter.cpp) — because
that's also the right shape for TPU-adjacent IO: large sequential batches,
no per-object fsync.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from enum import IntEnum
from typing import Callable, Iterator, Optional

__all__ = [
    "NodeObjectType",
    "NodeObject",
    "Backend",
    "Database",
    "register_backend",
    "make_backend",
    "make_database",
]


class NodeObjectType(IntEnum):
    """reference: nodestore/api/NodeObject.h:30-36"""

    UNKNOWN = 0
    LEDGER = 1
    TRANSACTION = 2
    ACCOUNT_NODE = 3
    TRANSACTION_NODE = 4


@dataclass(frozen=True)
class NodeObject:
    type: NodeObjectType
    hash: bytes  # 32-byte content hash (the key)
    data: bytes  # payload (prefix-format SHAMap node / ledger header)


class Backend:
    """Key-value backend interface (reference: nodestore/api/Backend.h:35-85)."""

    name = "abstract"

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        raise NotImplementedError

    def store(self, obj: NodeObject) -> None:
        self.store_batch([obj])

    def store_batch(self, batch: list[NodeObject]) -> None:
        raise NotImplementedError

    def iterate(self) -> Iterator[NodeObject]:
        raise NotImplementedError

    def close(self) -> None:
        pass


_FACTORIES: dict[str, Callable[..., Backend]] = {}


def register_backend(name: str, factory: Callable[..., Backend]) -> None:
    """reference: nodestore/api/Factory.h + Manager::addFactory"""
    _FACTORIES[name] = factory


def make_backend(type: str = "memory", **kwargs) -> Backend:
    if type not in _FACTORIES:
        raise KeyError(f"unknown nodestore backend {type!r}; have {sorted(_FACTORIES)}")
    return _FACTORIES[type](**kwargs)


class Database:
    """Backend + in-memory cache + async batched write-behind
    (reference: nodestore/impl/DatabaseImp.h, BatchWriter.cpp).

    Writes land synchronously in `_pending` (so reads always see them) and
    a background thread drains them to the backend in batches of up to
    `batch_size`.
    """

    def __init__(self, backend: Backend, cache_size: int = 65536,
                 batch_size: int = 256, async_writes: bool = True):
        self.backend = backend
        # hashes known to be durably in THIS store — the `known` set for
        # SHAMap.flush incremental writes
        self.flushed: set[bytes] = set()
        self._cache: dict[bytes, NodeObject] = {}
        self._cache_size = cache_size
        self._pending: dict[bytes, NodeObject] = {}
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._batch_size = batch_size
        self._stopping = False
        self._write_error: Optional[BaseException] = None
        self._writer: Optional[threading.Thread] = None
        if async_writes:
            self._writer = threading.Thread(
                target=self._write_loop, name="nodestore-writer", daemon=True
            )
            self._writer.start()

    # -- public api -------------------------------------------------------

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        with self._lock:
            obj = self._pending.get(hash) or self._cache.get(hash)
        if obj is not None:
            return obj
        obj = self.backend.fetch(hash)
        if obj is not None:
            self._cache_put(obj)
        return obj

    def store(self, type: NodeObjectType, hash: bytes, data: bytes) -> None:
        obj = NodeObject(type, hash, data)
        with self._lock:
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") from self._write_error
            self._pending[hash] = obj
            if self._writer is None:
                self.backend.store(obj)
                self._pending.pop(hash)
                self._cache_unlocked(obj)
            else:
                self._wake.notify()

    def store_fn(self, type: NodeObjectType) -> Callable[[bytes, bytes], None]:
        """Adapter with the (hash, blob) signature SHAMap.flush expects."""
        return lambda h, d: self.store(type, h, d)

    def store_many(self, type: NodeObjectType,
                   pairs: list[tuple[bytes, bytes]]) -> None:
        """Batch store: every (hash, blob) pair lands in `_pending` under
        ONE lock hold (the flat-buffer flush path — a per-close tree
        delta is thousands of nodes, and per-node lock round-trips were
        pure overhead). Async mode wakes the writer once; sync mode
        drains through the backend's own batch call."""
        if not pairs:
            return
        batch = [NodeObject(type, h, d) for h, d in pairs]
        with self._lock:
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") from self._write_error
            for obj in batch:
                self._pending[obj.hash] = obj
            if self._writer is not None:
                self._wake.notify()
        if self._writer is None:
            self.backend.store_batch(batch)
            with self._lock:
                for obj in batch:
                    if self._pending.get(obj.hash) is obj:
                        del self._pending[obj.hash]
                    self._cache_unlocked(obj)

    def store_many_fn(self, type: NodeObjectType) -> Callable[[list], None]:
        """Adapter with the batch signature SHAMap.flush's `store_many`
        expects."""
        return lambda pairs: self.store_many(type, pairs)

    def sync(self) -> None:
        """Block until all pending writes hit the backend. Raises the
        writer thread's error if the backend failed (otherwise a dead
        writer would make this hang forever)."""
        with self._lock:
            while self._pending:
                if self._write_error is not None:
                    raise RuntimeError("nodestore writer failed") from self._write_error
                self._wake.notify()
                self._wake.wait(0.01)
            if self._write_error is not None:
                raise RuntimeError("nodestore writer failed") from self._write_error

    def close(self) -> None:
        try:
            self.sync()
        finally:
            with self._lock:
                self._stopping = True
                self._wake.notify()
            if self._writer:
                self._writer.join(timeout=5)
            self.backend.close()

    # -- internals --------------------------------------------------------

    def _cache_put(self, obj: NodeObject) -> None:
        with self._lock:
            self._cache_unlocked(obj)

    def _cache_unlocked(self, obj: NodeObject) -> None:
        if len(self._cache) >= self._cache_size:
            # simple clock-less eviction: drop ~25% oldest-inserted
            drop = len(self._cache) // 4 or 1
            for k in list(self._cache)[:drop]:
                del self._cache[k]
        self._cache[obj.hash] = obj

    def _write_loop(self) -> None:
        while True:
            with self._lock:
                while not self._pending and not self._stopping:
                    self._wake.wait(0.1)
                if self._stopping and not self._pending:
                    return
                keys = list(self._pending)[: self._batch_size]
                batch = [self._pending[k] for k in keys]
            try:
                self.backend.store_batch(batch)
            except BaseException as exc:  # surface via sync(); keep pending
                with self._lock:
                    self._write_error = exc
                    self._wake.notify_all()
                return
            with self._lock:
                for k, o in zip(keys, batch):
                    if self._pending.get(k) is o:
                        del self._pending[k]
                    self._cache_unlocked(o)
                self._wake.notify_all()


def make_database(type: str = "memory", *, cache_size: int = 65536,
                  async_writes: bool = True, **backend_kwargs) -> Database:
    """reference: NodeStore::Manager::make_Database; `type=` is the config
    knob ([node_db] type=..., doc/stellard-example.cfg:795-802)."""
    return Database(
        make_backend(type, **backend_kwargs),
        cache_size=cache_size,
        async_writes=async_writes,
    )
