"""Segmented log-structured NodeStore backend.

The LSM-tree argument (O'Neil et al. 1996) specialized to a ledger
store: keys are immutable 32-byte content hashes, so random keyed
writes convert to ONE sequential segment append per flush and the
"merge" component degenerates to segment compaction — no levels, no
range order to maintain. Three production properties the flat cpplog
backend lacks:

- **one-append flush**: ``store_packed`` consumes the flat-buffer node
  encoding (state/shamap.py ``pack_nodes``: blob == hashed bytes) as
  one contiguous buffer and lands the whole batch as a single
  ``write()`` + (durability-dependent) one ``fsync`` — replacing the
  per-key put loop that dominated the persist stage;
- **checkpointed open**: the in-memory index snapshots to
  ``index.ckpt`` every ``checkpoint_bytes`` of appends, so open loads
  the snapshot and replays only the post-checkpoint tail instead of
  scanning the whole log (O(tail), not O(store));
- **online deletion + compaction**: rippled's ``SHAMapStore``
  online_delete role — a sweep (driven by node/ledgercleaner.py's
  rotation) removes index entries for unreachable nodes, per-segment
  liveness accounting flags segments below ``compact_ratio``, and a
  background maintenance thread rewrites their live records into the
  active segment and deletes the file, keeping a validator's disk
  bounded near the live set.

Record layout is shared with cpplog so torn-tail recovery stays
uniform: ``[u32 body_len LE | u8 flags | 32B key | u8 type | blob]``
(body_len counts type byte + blob). A torn tail on the active segment
(crash mid-append) is truncated away on open, exactly like cpplog.

``loc`` encoding (shared contract with native segstore_replay):
``(seg_id << 44) | record_offset``.

Durability modes (``[node_db] durability=``):

- ``fsync`` (default): one fsync per store batch — the equal-durability
  comparison point against cpplog's fsync-per-batch;
- ``batch``: group commit — appends mark the store dirty and the
  maintenance thread fsyncs once per ``group_commit_ms`` window, so a
  flood shares fsyncs across batches (bounded loss window on crash);
- ``async``: no explicit fsync outside segment rolls, checkpoints,
  compaction and close (the OS page cache decides).

Compaction and checkpoints always fsync regardless of mode: a moved
record's only remaining copy and a checkpoint's covered region must be
durable before the old bytes (or the replay work) are dropped.

The native fast paths (native/src/nodestore.cc: segidx_* index,
segstore_pack, segstore_replay) carry the O(store)/O(batch) inner
loops; every one has a pure-Python mirror below, differential-tested,
so a toolchain-less box runs the same semantics slower.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Iterator, Optional

from .core import Backend, NodeObject, NodeObjectType, register_backend

__all__ = ["SegStoreBackend"]

_REC_HEADER = 37  # u32 body_len + u8 flags + 32B key
_SEG_SHIFT = 44
_SEG_NAME = "seg-%08d.seg"
_CKPT_NAME = "index.ckpt"
_CKPT_MAGIC = b"SEGCKPT1"
_CKPT_VERSION = 1


def _seg_path(root: str, sid: int) -> str:
    return os.path.join(root, _SEG_NAME % sid)


def _loc(sid: int, off: int) -> int:
    return (sid << _SEG_SHIFT) | off


def _loc_split(loc: int) -> tuple[int, int]:
    return loc >> _SEG_SHIFT, loc & ((1 << _SEG_SHIFT) - 1)


# --------------------------------------------------------------------------
# pure-Python mirrors of the native primitives


class _PyIndex:
    """dict-backed mirror of native SegIdxNative (same API)."""

    def __init__(self, cap_hint: int = 0):
        self._d: dict[bytes, int] = {}

    def __len__(self) -> int:
        return len(self._d)

    def get(self, key: bytes) -> Optional[int]:
        return self._d.get(key)

    def put_batch(self, packed_keys: bytes, locs: list[int]) -> None:
        d = self._d
        for i, loc in enumerate(locs):
            d[packed_keys[32 * i: 32 * i + 32]] = loc

    def remove(self, key: bytes, expect_loc: Optional[int] = None) -> bool:
        cur = self._d.get(key)
        if cur is None or (expect_loc is not None and cur != expect_loc):
            return False
        del self._d[key]
        return True

    def filter_new(self, packed_keys: bytes, n: int) -> bytes:
        d = self._d
        out = bytearray(n)
        seen: set[bytes] = set()
        for i in range(n):
            k = packed_keys[32 * i: 32 * i + 32]
            if k not in d and k not in seen:
                out[i] = 1
                seen.add(k)
        return bytes(out)

    def dump(self) -> bytes:
        parts = bytearray()
        for k, loc in self._d.items():
            parts += k
            parts += struct.pack("<Q", loc)
        return bytes(parts)

    def load(self, blob: bytes) -> None:
        d = self._d
        for i in range(len(blob) // 40):
            base = i * 40
            d[blob[base: base + 32]] = struct.unpack_from(
                "<Q", blob, base + 32
            )[0]

    def items(self):
        return self._d.items()


def _pack_records_py(packed_keys: bytes, types: bytes, buf,
                     offsets) -> bytes:
    out = bytearray()
    mv = memoryview(buf)
    for i in range(len(types)):
        blen = offsets[i + 1] - offsets[i]
        out += struct.pack("<IB", blen + 1, 0)
        out += packed_keys[32 * i: 32 * i + 32]
        out.append(types[i])
        out += mv[offsets[i]: offsets[i + 1]]
    return bytes(out)


def _replay_py(index, path: str, sid: int, start: int) -> tuple[int, int, int]:
    """Mirror of native segstore_replay: scan `path` from `start`,
    inserting key -> loc; -> (clean_end, records, bytes)."""
    start = min(start, os.path.getsize(path))  # clamp like the C side
    with open(path, "rb") as f:
        f.seek(start)
        data = f.read()
    off = 0
    end = len(data)
    keys = bytearray()
    locs: list[int] = []
    while off + _REC_HEADER <= end:
        body_len = struct.unpack_from("<I", data, off)[0]
        if body_len < 1 or off + _REC_HEADER + body_len > end:
            break  # torn tail
        keys += data[off + 5: off + 37]
        locs.append(_loc(sid, start + off))
        off += _REC_HEADER + body_len
    if locs:
        index.put_batch(bytes(keys), locs)
    return start + off, len(locs), off


def _parse_records(data: bytes, sid: int, base: int):
    """-> [(key, loc, record_bytes)] for every clean record in `data`
    (a whole-segment read; `base` is data's file offset)."""
    out = []
    off = 0
    end = len(data)
    while off + _REC_HEADER <= end:
        body_len = struct.unpack_from("<I", data, off)[0]
        if body_len < 1 or off + _REC_HEADER + body_len > end:
            break
        rec = data[off: off + _REC_HEADER + body_len]
        out.append((rec[5:37], _loc(sid, base + off), rec))
        off += _REC_HEADER + body_len
    return out


class _Seg:
    __slots__ = ("size", "live_bytes")

    def __init__(self, size: int = 0, live_bytes: int = 0):
        self.size = size
        self.live_bytes = live_bytes


# --------------------------------------------------------------------------


class SegStoreBackend(Backend):
    """Segmented log-structured backend (see module docstring)."""

    name = "segstore"
    supports_online_delete = True

    DURABILITY_MODES = ("fsync", "batch", "async")

    def __init__(self, path: str = "nodestore.segstore", *,
                 durability: str = "fsync",
                 segment_bytes: int = 64 << 20,
                 checkpoint_bytes: int = 32 << 20,
                 compact_ratio: float = 0.5,
                 group_commit_ms: float = 5.0,
                 tracer=None, use_native: Optional[bool] = None, **_):
        if durability not in self.DURABILITY_MODES:
            raise ValueError(
                f"[node_db] durability must be one of "
                f"{self.DURABILITY_MODES}, got {durability!r}"
            )
        self.root = path
        self.durability = durability
        self.segment_bytes = max(1 << 16, int(segment_bytes))
        self.checkpoint_bytes = max(1 << 16, int(checkpoint_bytes))
        self.compact_ratio = float(compact_ratio)
        self.group_commit_ms = float(group_commit_ms)
        self._tracer = tracer
        os.makedirs(path, exist_ok=True)

        self._native = False
        if use_native is not False:
            try:
                from ..native import SegIdxNative, load_native

                lib = load_native()
                if lib is not None and getattr(lib, "has_segstore", False):
                    self._idx = SegIdxNative()
                    self._lib = lib
                    self._native = True
            except Exception:  # noqa: BLE001 — toolchain-less box
                pass
        if not self._native:
            if use_native is True:
                raise RuntimeError("native segstore primitives unavailable")
            self._idx = _PyIndex()
            self._lib = None

        self._lock = threading.RLock()
        self._segs: dict[int, _Seg] = {}
        self._read_fds: dict[int, int] = {}
        self._active_id = 0
        self._active_f = None
        self._failed = False
        self._fail_reason = ""
        self._dirty = False
        self._last_fsync = time.monotonic()
        self._bytes_since_ckpt = 0
        self._sweep_active = False
        self._recent_keys: set[bytes] = set()
        # counters (get_json / the node_store observability block)
        self.appends = 0
        self.records = 0
        self.bytes_appended = 0
        self.fsyncs = 0
        self.dedup_skips = 0
        self.fetches = 0
        self.fetch_misses = 0
        self.checkpoints = 0
        self.compactions = 0
        self.compacted_bytes_in = 0
        self.compacted_bytes_out = 0
        self.sweeps = 0
        self.swept_records = 0
        self.swept_bytes = 0
        # open-time replay evidence (the checkpointed-open tests pin it)
        self.replayed_records = 0
        self.replayed_bytes = 0
        self.opened_from_checkpoint = False

        self._open_store()

        # maintenance thread: group-commit fsync (durability=batch),
        # compaction, post-sweep checkpoints. Lazy wake via condition.
        self._compact_mutex = threading.Lock()
        self._maint_wake = threading.Condition(self._lock)
        self._stopping = False
        self._compact_requested = False
        self._ckpt_requested = False
        self._maint: Optional[threading.Thread] = None

    # -- open / replay -----------------------------------------------------

    def _discover_segs(self) -> list[int]:
        ids = []
        for name in os.listdir(self.root):
            if name.startswith("seg-") and name.endswith(".seg"):
                try:
                    ids.append(int(name[4:-4]))
                except ValueError:
                    continue
        return sorted(ids)

    def _open_store(self) -> None:
        ids = self._discover_segs()
        ckpt = self._load_checkpoint(ids)
        if ckpt is not None:
            start_sid, start_off = ckpt
            self.opened_from_checkpoint = True
        elif ids:
            start_sid, start_off = ids[0], 0
        else:
            start_sid, start_off = 1, 0
        # tail replay: every segment at/after the checkpoint position
        for sid in ids:
            if sid < start_sid:
                continue
            begin = start_off if sid == start_sid else 0
            path = _seg_path(self.root, sid)
            file_size = os.path.getsize(path)
            if self._native:
                end, recs, byts = self._idx.replay(path, sid, begin)
            else:
                end, recs, byts = _replay_py(self._idx, path, sid, begin)
            self.replayed_records += recs
            self.replayed_bytes += byts
            seg = self._segs.setdefault(sid, _Seg())
            if end < file_size:
                if sid == ids[-1]:
                    # torn tail from a crash mid-append: truncate so the
                    # next append lands on a clean record boundary
                    with open(path, "rb+") as f:
                        f.truncate(end)
                    file_size = end
                # non-final segments are sealed; a torn record there
                # leaves the tail unreachable but the segment readable
            seg.size = end if sid == ids[-1] else max(seg.size, end)
            seg.live_bytes += byts
        if not ids:
            self._segs[1] = _Seg()
            self._active_id = 1
        else:
            self._active_id = ids[-1]
        self._ensure_active_file()
        if self._segs[self._active_id].size >= self.segment_bytes:
            self._roll_locked()

    def _ensure_active_file(self) -> None:
        if self._active_f is None:
            self._active_f = open(
                _seg_path(self.root, self._active_id), "ab"
            )

    def _load_checkpoint(self, ids: list[int]) -> Optional[tuple[int, int]]:
        """Load index.ckpt when valid; -> (active_sid, covered_offset)
        replay start position, or None for a full replay."""
        path = os.path.join(self.root, _CKPT_NAME)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        if len(blob) < 44 or blob[:8] != _CKPT_MAGIC:
            return None
        body, crc = blob[:-4], struct.unpack("<I", blob[-4:])[0]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            return None
        ver, n_segs = struct.unpack_from("<II", blob, 8)
        if ver != _CKPT_VERSION:
            return None
        active_sid, covered = struct.unpack_from("<IQ", blob, 16)
        n_entries = struct.unpack_from("<Q", blob, 28)[0]
        pos = 36
        seg_stats = []
        for _ in range(n_segs):
            sid, size, live = struct.unpack_from("<IQQ", blob, pos)
            pos += 20
            seg_stats.append((sid, size, live))
        entries_end = pos + n_entries * 40
        if entries_end > len(body):
            return None
        # the checkpoint must reference only segments that still exist —
        # a manual deletion (or a crash between compaction's file remove
        # and its checkpoint) degrades to a full replay, never to index
        # entries pointing at missing files
        have = set(ids)
        if any(sid not in have for sid, _, _ in seg_stats):
            return None
        self._idx.load(body[pos:entries_end])
        for sid, size, live in seg_stats:
            self._segs[sid] = _Seg(size, live)
        return active_sid, covered

    # -- append path -------------------------------------------------------

    def store_batch(self, batch: list[NodeObject]) -> None:
        if not batch:
            return
        keys = b"".join(o.hash for o in batch)
        types = bytes(int(o.type) & 0xFF for o in batch)
        offsets = [0]
        parts = []
        pos = 0
        for o in batch:
            parts.append(o.data)
            pos += len(o.data)
            offsets.append(pos)
        self._append(keys, types, b"".join(parts), offsets)

    def store_packed(self, type: NodeObjectType, hashes: list[bytes],
                     buf, offsets) -> int:
        """The one-append flush door: consumes the flat-buffer node
        encoding AS-IS (blob == hashed bytes), no per-node objects.
        `hashes` is a list of 32-byte keys or one packed 32n buffer.
        Returns the number of records actually appended (dedup may
        skip already-stored nodes)."""
        n = len(offsets) - 1
        if n <= 0:
            return 0
        packed_keys = (
            hashes if isinstance(hashes, (bytes, bytearray))
            else b"".join(hashes)
        )
        return self._append(
            bytes(packed_keys), bytes([int(type) & 0xFF]) * n, buf, offsets
        )

    def _append(self, packed_keys: bytes, types: bytes, buf,
                offsets) -> int:
        n = len(types)
        with self._lock:
            if self._failed:
                raise OSError(f"segstore failed ({self._fail_reason})")
            # dedup: content-addressed, a second write of a key is a
            # no-op — EXCEPT while a sweep is marking: a node re-written
            # mid-sweep must get a fresh record + loc so the sweep's
            # compare-and-delete can never drop the only copy (only
            # in-batch duplicates are still collapsed)
            if not self._sweep_active:
                mask = self._idx.filter_new(packed_keys, n)
            else:
                seen: set[bytes] = set()
                m = bytearray(n)
                for i in range(n):
                    k = packed_keys[32 * i: 32 * i + 32]
                    if k not in seen:
                        m[i] = 1
                        seen.add(k)
                mask = bytes(m)
            if not any(mask):
                self.dedup_skips += n
                return 0
            if all(mask):
                sel_keys, sel_types, sel_buf, sel_offsets = (
                    packed_keys, types, buf, offsets
                )
                n_sel = n
            else:
                mv = memoryview(buf)
                kparts, tparts, bparts = bytearray(), bytearray(), bytearray()
                sel_offsets = [0]
                for i in range(n):
                    if not mask[i]:
                        continue
                    kparts += packed_keys[32 * i: 32 * i + 32]
                    tparts.append(types[i])
                    bparts += mv[offsets[i]: offsets[i + 1]]
                    sel_offsets.append(len(bparts))
                sel_keys, sel_types, sel_buf = (
                    bytes(kparts), bytes(tparts), bytes(bparts)
                )
                n_sel = len(sel_types)
                self.dedup_skips += n - n_sel
            if self._native:
                img = self._idx.pack_records(
                    sel_keys, sel_types, sel_buf, sel_offsets
                )
            else:
                img = _pack_records_py(
                    sel_keys, sel_types, sel_buf, sel_offsets
                )
            seg = self._segs[self._active_id]
            if seg.size and seg.size + len(img) > self.segment_bytes:
                self._roll_locked()
                seg = self._segs[self._active_id]
            base = seg.size
            t0 = time.perf_counter()
            try:
                self._active_f.write(img)
                self._active_f.flush()  # page cache: preads must see it
            except OSError:
                # a torn record would desynchronize replay at its header
                # — truncate back to the last clean boundary; if THAT
                # fails the store cannot guarantee a clean tail: fail it
                try:
                    os.ftruncate(self._active_f.fileno(), base)
                except OSError:
                    self._mark_failed_locked("torn append not truncatable")
                raise
            t1 = time.perf_counter()
            locs = []
            off = base
            for i in range(n_sel):
                locs.append(_loc(self._active_id, off))
                off += _REC_HEADER + 1 + (
                    sel_offsets[i + 1] - sel_offsets[i]
                )
            self._idx.put_batch(sel_keys, locs)
            if self._sweep_active:
                self._recent_keys.update(
                    sel_keys[32 * i: 32 * i + 32] for i in range(n_sel)
                )
            seg.size += len(img)
            seg.live_bytes += len(img)
            self.appends += 1
            self.records += n_sel
            self.bytes_appended += len(img)
            self._bytes_since_ckpt += len(img)
            tr = self._tracer
            if tr is not None:
                tr.complete("persist.nodestore.append", "persist", t0, t1,
                            records=n_sel, bytes=len(img),
                            seg=self._active_id)
            if self.durability == "fsync":
                self._fsync_locked()
            else:
                self._dirty = True
                if self.durability == "batch":
                    now = time.monotonic()
                    if (now - self._last_fsync) * 1000.0 >= \
                            self.group_commit_ms:
                        self._fsync_locked()
                    else:
                        self._kick_maint_locked()
            if self._bytes_since_ckpt >= self.checkpoint_bytes:
                self._checkpoint_locked()
            return n_sel

    def _fsync_locked(self) -> None:
        t0 = time.perf_counter()
        self._active_f.flush()
        os.fsync(self._active_f.fileno())
        t1 = time.perf_counter()
        self.fsyncs += 1
        self._dirty = False
        self._last_fsync = time.monotonic()
        tr = self._tracer
        if tr is not None:
            tr.complete("persist.nodestore.fsync", "persist", t0, t1,
                        seg=self._active_id)

    def _group_fsync(self) -> None:
        """Maintenance-thread group commit: fsync OUTSIDE the store lock
        so appenders never block behind the barrier (the whole point of
        durability=batch — on a slow filesystem an in-lock fsync would
        re-serialize every append behind ~100ms waits). The fd is duped
        so a concurrent segment roll closing the file object cannot
        invalidate the descriptor mid-fsync; dirtiness re-checks after:
        bytes appended while the barrier ran stay dirty for the next
        window."""
        with self._lock:
            if self._active_f is None or not self._dirty:
                return
            self._active_f.flush()
            fd = os.dup(self._active_f.fileno())
            seg_id = self._active_id
            covered = self._segs[seg_id].size
        t0 = time.perf_counter()
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        t1 = time.perf_counter()
        with self._lock:
            self.fsyncs += 1
            self._last_fsync = time.monotonic()
            if self._active_id == seg_id and \
                    self._segs[seg_id].size == covered:
                self._dirty = False
            tr = self._tracer
            if tr is not None:
                tr.complete("persist.nodestore.fsync", "persist", t0, t1,
                            seg=seg_id, group=True)

    def _roll_locked(self) -> None:
        """Seal the active segment and start a new one. A sealed segment
        is always fsynced (it will never be written again; compaction
        and deletion decisions assume its bytes are durable)."""
        if self._active_f is not None:
            self._active_f.flush()
            os.fsync(self._active_f.fileno())
            self.fsyncs += 1
            self._dirty = False
            self._active_f.close()
        self._active_id += 1
        self._segs[self._active_id] = _Seg()
        self._active_f = open(_seg_path(self.root, self._active_id), "ab")

    # -- read path ---------------------------------------------------------

    def _read_fd(self, sid: int) -> int:
        fd = self._read_fds.get(sid)
        if fd is None:
            fd = os.open(_seg_path(self.root, sid), os.O_RDONLY)
            self._read_fds[sid] = fd
        return fd

    # speculative single-pread size: tree nodes are ≤ ~1KB (an inner is
    # 517B with the type byte), so one read covers header + body for
    # nearly every record; only oversized blobs pay a second pread.
    # Sized so the out-of-core fault path (state/shamap.NodeSource) is
    # one syscall per cold node.
    FETCH_CHUNK = 1536

    def fetch(self, hash: bytes) -> Optional[NodeObject]:
        with self._lock:
            self.fetches += 1
            loc = self._idx.get(hash)
            if loc is None:
                self.fetch_misses += 1
                return None
            sid, off = _loc_split(loc)
            fd = self._read_fd(sid)
            buf = os.pread(fd, self.FETCH_CHUNK, off)
            if len(buf) < 5:
                raise OSError(
                    f"segstore: index points past segment {sid} end"
                )
            body_len = struct.unpack_from("<I", buf)[0]
            end = _REC_HEADER + body_len
            if end <= len(buf):
                body = buf[_REC_HEADER:end]
            else:
                body = os.pread(fd, body_len, off + _REC_HEADER)
            if len(body) != body_len:
                raise OSError(f"segstore: short record read in seg {sid}")
        return NodeObject(NodeObjectType(body[0]), hash, body[1:])

    def iterate(self) -> Iterator[NodeObject]:
        """Every LIVE node (index snapshot order). Records whose key was
        swept are invisible even when their bytes still sit in an
        uncompacted segment."""
        with self._lock:
            blob = self._idx.dump()
        for i in range(len(blob) // 40):
            key = blob[i * 40: i * 40 + 32]
            obj = self.fetch(key)
            if obj is not None:
                yield obj

    # -- segment-granular read door (catch-up serving) ---------------------

    def segments(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "id": sid,
                    "size": seg.size,
                    "live_bytes": seg.live_bytes,
                    "active": sid == self._active_id,
                }
                for sid, seg in sorted(self._segs.items())
            ]

    def fetch_segment(self, seg_id: int, offset: int = 0,
                      length: Optional[int] = None,
                      ) -> Optional[tuple[dict, bytes]]:
        """(meta, raw bytes) of one segment — contiguous hashed byte
        ranges for catch-up serving: every record's blob is exactly its
        hashed prefix-format bytes, so a receiver can verify each record
        against its key without per-node round-trips. ``offset``/
        ``length`` bound the read so a chunked wire transfer costs
        O(chunk) per request, not O(segment); meta always carries the
        FULL segment size."""
        with self._lock:
            seg = self._segs.get(seg_id)
            if seg is None:
                return None
            fd = self._read_fd(seg_id)
            off = max(0, int(offset))
            n = seg.size - off
            if length is not None:
                n = min(n, int(length))
            data = os.pread(fd, n, off) if n > 0 else b""
            return (
                {
                    "id": seg_id,
                    "size": seg.size,
                    "live_bytes": seg.live_bytes,
                    "active": seg_id == self._active_id,
                },
                data,
            )

    # -- online deletion (sweep) -------------------------------------------

    def begin_sweep(self) -> None:
        """Arm the sweep guards: until apply_sweep, (a) every incoming
        key is recorded so the sweep never deletes a node written after
        its mark started, and (b) dedup is disabled so re-written keys
        get fresh records (see _append)."""
        with self._lock:
            self._sweep_active = True
            self._recent_keys = set()

    def cancel_sweep(self) -> None:
        """Disarm the sweep guards without deleting anything (a mark
        pass aborted by shutdown must not leave dedup disabled)."""
        with self._lock:
            self._sweep_active = False
            self._recent_keys = set()

    def apply_sweep(self, live: set) -> list[bytes]:
        """Remove every indexed key not in `live` (mark-and-sweep's
        sweep half). Returns the removed keys so the Database façade can
        purge its cache/flushed sets. Compare-and-delete per key: a key
        re-appended since the snapshot has a new loc and survives."""
        with self._lock:
            blob = self._idx.dump()
        # candidate selection + size reads happen OFF the lock (an
        # O(store) pass must not stall the close path's appends)
        dead: list[tuple[bytes, int]] = []
        for i in range(len(blob) // 40):
            key = blob[i * 40: i * 40 + 32]
            if key in live:
                continue
            loc = struct.unpack_from("<Q", blob, i * 40 + 32)[0]
            dead.append((key, loc))
        sized: list[tuple[bytes, int, int]] = []
        for key, loc in dead:
            sid, off = _loc_split(loc)
            with self._lock:
                if sid not in self._segs:
                    continue
                hdr = os.pread(self._read_fd(sid), 4, off)
            if len(hdr) == 4:
                body_len = struct.unpack("<I", hdr)[0]
                sized.append((key, loc, _REC_HEADER + body_len))
        removed: list[bytes] = []
        removed_bytes = 0
        with self._lock:
            for key, loc, size in sized:
                if key in self._recent_keys:
                    continue
                if self._idx.remove(key, expect_loc=loc):
                    sid, _ = _loc_split(loc)
                    seg = self._segs.get(sid)
                    if seg is not None:
                        seg.live_bytes = max(0, seg.live_bytes - size)
                    removed.append(key)
                    removed_bytes += size
            self._sweep_active = False
            self._recent_keys = set()
            self.sweeps += 1
            self.swept_records += len(removed)
            self.swept_bytes += removed_bytes
            # deletions become durable through the checkpoint (replay
            # starts past the swept records); compaction then reclaims
            # the dead bytes
            self._compact_requested = True
            self._ckpt_requested = True
            self._kick_maint_locked()
        return removed

    # -- compaction --------------------------------------------------------

    def _mark_failed_locked(self, reason: str) -> None:
        self._failed = True
        self._fail_reason = reason

    def _kick_maint_locked(self) -> None:
        if self._maint is None:
            self._maint = threading.Thread(
                target=self._maint_loop, name="segstore-maint", daemon=True
            )
            self._maint.start()
        self._maint_wake.notify_all()

    def request_compact(self) -> None:
        with self._lock:
            self._compact_requested = True
            self._kick_maint_locked()

    def _maint_loop(self) -> None:
        while True:
            with self._maint_wake:
                while not (self._compact_requested or self._ckpt_requested
                           or self._stopping):
                    if self._dirty and self.durability == "batch":
                        remaining = (
                            self.group_commit_ms / 1000.0
                            - (time.monotonic() - self._last_fsync)
                        )
                        if remaining <= 0:
                            break  # group-commit window elapsed
                        self._maint_wake.wait(timeout=remaining)
                    else:
                        self._maint_wake.wait(timeout=1.0)
                if self._stopping:
                    return
                do_compact = self._compact_requested
                do_ckpt = self._ckpt_requested
                self._compact_requested = False
                self._ckpt_requested = False
                do_fsync = self._dirty and self.durability == "batch" and (
                    (time.monotonic() - self._last_fsync) * 1000.0
                    >= self.group_commit_ms
                )
            try:
                if do_fsync:
                    self._group_fsync()  # out-of-lock: appends continue
            except OSError:
                # a failed fsync means the kernel may have DROPPED the
                # dirty pages (fsyncgate semantics): bytes the caller
                # believes are headed to disk can be silently gone, so
                # the store must refuse further writes, loudly
                with self._lock:
                    self._mark_failed_locked("group-commit fsync failed")
                return
            # checkpoint and compaction are OPTIMIZATIONS over an intact
            # log: a transient failure (disk briefly full, EINTR) must
            # not brick the store or kill this thread — log it and let
            # the next trigger retry. _compact_pass marks the store
            # failed itself for the one genuinely dangerous sub-case (a
            # torn move-append it cannot truncate away).
            try:
                if do_compact:
                    self._compact_once()
            except OSError:
                import logging

                logging.getLogger("stellard.segstore").exception(
                    "segment compaction failed (will retry on next "
                    "trigger)"
                )
                if self._failed:
                    return
            try:
                if do_ckpt:
                    self.checkpoint()
            except OSError:
                import logging

                logging.getLogger("stellard.segstore").exception(
                    "index checkpoint failed (open will replay a longer "
                    "tail until one lands)"
                )

    def compact(self) -> int:
        """Synchronous compaction pass (tests / admin); -> segments
        rewritten."""
        return self._compact_once()

    def _compact_once(self) -> int:
        # one pass at a time: a synchronous compact() racing the
        # maintenance thread's pass must not double-process a segment
        with self._compact_mutex:
            return self._compact_pass()

    def _compact_pass(self) -> int:
        with self._lock:
            # a mostly-dead ACTIVE segment would otherwise never be
            # reclaimed (compaction only rewrites sealed segments):
            # seal it first so it joins the candidate set
            active = self._segs[self._active_id]
            if active.size > 0 and \
                    active.live_bytes < active.size * self.compact_ratio:
                self._roll_locked()
            candidates = [
                sid for sid, seg in self._segs.items()
                if sid != self._active_id and seg.size > 0
                and seg.live_bytes < seg.size * self.compact_ratio
            ]
        done = 0
        for sid in sorted(candidates):
            t0 = time.perf_counter()
            with self._lock:
                seg = self._segs.get(sid)
                if seg is None or sid == self._active_id:
                    continue
                size = seg.size
                fd = self._read_fd(sid)
                data = os.pread(fd, size, 0)
            # parse OFF the lock; validate + move under ONE lock hold so
            # no record can change ownership between check and copy
            records = _parse_records(data, sid, 0)
            with self._lock:
                if sid not in self._segs or sid == self._active_id:
                    continue
                live = [
                    (key, rec) for key, loc, rec in records
                    if self._idx.get(key) == loc
                ]
                img = b"".join(rec for _, rec in live)
                if img:
                    active = self._segs[self._active_id]
                    if active.size and \
                            active.size + len(img) > self.segment_bytes:
                        self._roll_locked()
                        active = self._segs[self._active_id]
                    base = active.size
                    try:
                        self._active_f.write(img)
                        self._active_f.flush()
                        # the moved records' only copy must be durable
                        # BEFORE the old segment is deleted, in every
                        # durability mode
                        os.fsync(self._active_f.fileno())
                    except OSError:
                        # same contract as _append: a torn move-append
                        # must truncate away or the store is failed
                        try:
                            os.ftruncate(self._active_f.fileno(), base)
                        except OSError:
                            self._mark_failed_locked(
                                "torn compaction append not truncatable"
                            )
                        raise
                    self.fsyncs += 1
                    keys = bytearray()
                    locs = []
                    off = base
                    for key, rec in live:
                        keys += key
                        locs.append(_loc(self._active_id, off))
                        off += len(rec)
                    self._idx.put_batch(bytes(keys), locs)
                    active.size += len(img)
                    active.live_bytes += len(img)
                    self.bytes_appended += len(img)
                rfd = self._read_fds.pop(sid, None)
                if rfd is not None:
                    os.close(rfd)
                del self._segs[sid]
                try:
                    os.remove(_seg_path(self.root, sid))
                except OSError:
                    pass
                self.compactions += 1
                self.compacted_bytes_in += size
                self.compacted_bytes_out += len(img)
                self._ckpt_requested = True
                done += 1
                tr = self._tracer
                if tr is not None:
                    tr.complete(
                        "store.compact", "persist", t0,
                        time.perf_counter(), seg=sid, bytes_in=size,
                        bytes_out=len(img), moved=len(live),
                    )
        return done

    # -- checkpoint --------------------------------------------------------

    def checkpoint(self) -> None:
        with self._lock:
            self._checkpoint_locked()

    def _checkpoint_locked(self) -> None:
        t0 = time.perf_counter()
        # the covered region must be durable: index entries referencing
        # bytes the page cache later loses would survive the crash
        if self._active_f is not None:
            self._fsync_locked()
        entries = self._idx.dump()
        seg_items = sorted(self._segs.items())
        head = _CKPT_MAGIC + struct.pack(
            "<IIIQQ", _CKPT_VERSION, len(seg_items), self._active_id,
            self._segs[self._active_id].size, len(entries) // 40,
        )
        stats = b"".join(
            struct.pack("<IQQ", sid, seg.size, seg.live_bytes)
            for sid, seg in seg_items
        )
        body = head + stats + entries
        blob = body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)
        tmp = os.path.join(self.root, _CKPT_NAME + ".tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.root, _CKPT_NAME))
        try:
            dfd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # some filesystems refuse directory fsync
        self.checkpoints += 1
        self._bytes_since_ckpt = 0
        tr = self._tracer
        if tr is not None:
            tr.complete("store.checkpoint", "persist", t0,
                        time.perf_counter(),
                        entries=len(entries) // 40,
                        bytes=len(blob))

    # -- misc --------------------------------------------------------------

    def sync(self) -> None:
        """Flush + fsync outstanding appends (all durability modes) —
        the explicit durability barrier Database.sync drives."""
        with self._lock:
            if self._failed:
                raise OSError(f"segstore failed ({self._fail_reason})")
            if self._active_f is not None and self._dirty:
                self._fsync_locked()

    def count(self) -> int:
        with self._lock:
            return len(self._idx)

    def disk_bytes(self) -> int:
        with self._lock:
            return sum(seg.size for seg in self._segs.values())

    def live_bytes(self) -> int:
        with self._lock:
            return sum(seg.live_bytes for seg in self._segs.values())

    def get_json(self) -> dict:
        with self._lock:
            disk = sum(seg.size for seg in self._segs.values())
            live = sum(seg.live_bytes for seg in self._segs.values())
            return {
                "backend": self.name,
                "durability": self.durability,
                "native_index": self._native,
                "objects": len(self._idx),
                "segments": len(self._segs),
                "disk_bytes": disk,
                "live_bytes": live,
                "live_ratio": round(live / disk, 4) if disk else 1.0,
                "appends": self.appends,
                "records": self.records,
                "bytes_appended": self.bytes_appended,
                "fsyncs": self.fsyncs,
                "dedup_skips": self.dedup_skips,
                "fetches": self.fetches,
                "fetch_misses": self.fetch_misses,
                "checkpoints": self.checkpoints,
                "compactions": self.compactions,
                "compacted_bytes_in": self.compacted_bytes_in,
                "compacted_bytes_out": self.compacted_bytes_out,
                "sweeps": self.sweeps,
                "swept_records": self.swept_records,
                "swept_bytes": self.swept_bytes,
                "replayed_records": self.replayed_records,
                "replayed_bytes": self.replayed_bytes,
                "opened_from_checkpoint": self.opened_from_checkpoint,
            }

    def close(self) -> None:
        with self._lock:
            self._stopping = True
            self._maint_wake.notify_all()
            maint = self._maint
        if maint is not None:
            maint.join(timeout=5)
        with self._lock:
            if self._active_f is not None and not self._failed:
                try:
                    self._checkpoint_locked()  # next open: zero replay
                except OSError:
                    pass
            if self._active_f is not None:
                try:
                    self._active_f.close()
                except OSError:
                    pass
                self._active_f = None
            for fd in self._read_fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._read_fds.clear()


register_backend("segstore", SegStoreBackend)
