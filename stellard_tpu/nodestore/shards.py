"""History shards: trimmed ledger ranges sealed as offline-verifiable
cold-storage units (rippled's history-shard role, the PR 7/9 follow-on
that turns online-deletion *trimming* into *tiering*).

Online deletion bounds a validator's disk near the live set by sweeping
nodes only reachable from ledgers below the retain floor — which also
makes deep ``account_tx`` and cold-node catch-up below the floor
unanswerable (``lgrIdxInvalid``). With ``[node_db] shards=<dir>`` the
retired range is SEALED into a shard file *before* the sweep deletes
it, so history tiers to cold storage instead of vanishing:

- **record section**: every node that was about to be swept (ledger
  headers, state/tx tree nodes), in the exact segstore record layout
  ``[u32 body_len LE | u8 flags | 32B key | u8 type | blob]`` — the
  same self-verifying bytes (key == SHA-512-half(blob)) the
  ``fetch_segment``/GetSegments catch-up door already moves, so a cold
  node ingests shards with the machinery it already has
  (node/inbound.SegmentCatchup, unchanged);
- **account index**: ``(account, ledger_seq, txn_seq, txid)`` rows
  exported from the txdb SQL mirror before ``trim_below`` drops them,
  so ``account_tx`` below the floor routes here (rpc/handlers.py) and
  pages with the same marker semantics;
- **offline verification contract** (doc/storage.md): per-record
  content hashes, a whole-file CRC, and the header chain — every seq
  in [lo, hi] has a stored header and consecutive headers link by
  parent_hash — are all checkable from the file alone, no live node.

``CombinedSegmentSource`` splices shards into the segment manifest
(ids offset by ``SHARD_SEG_BASE``) so a cold node whose serving peer
has trimmed a range syncs it from shards over the SAME wire path.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Iterator, Optional

from ..utils.hashes import (
    HP_INNER_NODE, HP_LEAF_NODE, HP_LEDGER_MASTER, HP_TX_NODE, HP_TXN_ID,
    sha512_half,
)

__all__ = [
    "HistoryShardStore", "CombinedSegmentSource", "collect_retired",
    "mark_live", "rotate_into_shards", "verify_shard_blob",
    "SHARD_SEG_BASE", "SHARD_FILE_BASE",
]

_MAGIC = b"SHARD1\x00\x00"
_VERSION = 1
_HDR = struct.Struct("<IIIQQQQ")  # version, lo, hi, rec_off/len, acct_off/len
_HDR_SIZE = len(_MAGIC) + _HDR.size + 64  # + first/last ledger hash
_ACCT_ROW = struct.Struct("<20sII32s")  # account, ledger_seq, txn_seq, txid
_REC_HEADER = 37  # u32 body_len + u8 flags + 32B key (segstore layout)

# manifest-id offset for shard rows in the combined GetSegments door:
# far above any plausible segstore segment id, well below the 44-bit
# loc shift, so the two id spaces can never collide
SHARD_SEG_BASE = 1 << 30

# id offset for the WHOLE-FILE shard distribution door (archive
# backfill): ``SHARD_FILE_BASE + sid`` serves the complete shard file
# (header + records + account index + CRC) so a fetching archive can
# run the full offline-verification contract against the transferred
# image before installing it. Disjoint from — and above — the
# record-section id space at SHARD_SEG_BASE.
SHARD_FILE_BASE = 1 << 31

# NodeObjectType values (nodestore.core) — plain ints here so the shard
# format is self-contained for offline verifiers
_T_LEDGER = 1
_T_ACCOUNT_NODE = 3
_T_TRANSACTION_NODE = 4


def _pack_records(records: list) -> bytes:
    """[(key, type_byte, blob)] -> segstore-layout record image."""
    out = bytearray()
    for key, type_byte, blob in records:
        out += struct.pack("<IB", len(blob) + 1, 0)
        out += key
        out.append(type_byte & 0xFF)
        out += blob
    return bytes(out)


def _iter_records_py(data: bytes) -> Iterator[tuple[bytes, int, int, int]]:
    """(key, type, blob_off, blob_len) per clean record in `data`."""
    off, end = 0, len(data)
    while off + _REC_HEADER <= end:
        body_len = struct.unpack_from("<I", data, off)[0]
        if body_len < 1 or off + _REC_HEADER + body_len > end:
            break
        yield (
            data[off + 5: off + 37],
            data[off + _REC_HEADER],
            off + _REC_HEADER + 1,
            body_len - 1,
        )
        off += _REC_HEADER + body_len


def collect_retired(fetch, headers: list[dict], live: set,
                    ) -> list[tuple[bytes, int, bytes]]:
    """Gather every node of the retiring ledgers that the sweep is about
    to delete: walk each header's state/tx tree through raw stored
    blobs (no SHAMap materialization — the ledgercleaner mark walk's
    shape), keeping nodes NOT in `live` (nodes shared with retained
    ledgers stay in the live store and need no cold copy). `fetch` is
    ``hash -> blob|None``; `headers` rows are txdb ``get_ledger_header``
    dicts. Returns [(key, type_byte, blob)] with headers first — a
    shard is self-describing even when its trees share everything."""
    from ..state.shamap import ZERO256

    inner_prefix = HP_INNER_NODE.to_bytes(4, "big")
    out: list[tuple[bytes, int, bytes]] = []
    seen: set[bytes] = set()

    def walk(root_hash: bytes, type_byte: int) -> None:
        stack = [root_hash]
        while stack:
            h = stack.pop()
            if h == ZERO256 or h in seen or h in live:
                continue
            seen.add(h)
            blob = fetch(h)
            if blob is None:
                continue  # history gap: seal what exists
            out.append((h, type_byte, blob))
            if blob[:4] == inner_prefix:
                for i in range(16):
                    stack.append(blob[4 + 32 * i: 36 + 32 * i])

    for hdr in headers:
        h = hdr["hash"]
        if h not in seen:
            blob = fetch(h)
            if blob is not None:
                seen.add(h)
                out.append((h, _T_LEDGER, blob))
    for hdr in headers:
        walk(hdr["account_hash"], _T_ACCOUNT_NODE)
        walk(hdr["tx_hash"], _T_TRANSACTION_NODE)
    return out


def mark_live(fetch, headers: list[dict], live: set) -> None:
    """Add every node reachable from `headers`' roots (plus the header
    objects) to `live` — the retained-set mark walk in fetch-callable
    form, shared by the testkit's in-scenario rotation."""
    from ..state.shamap import ZERO256

    inner_prefix = HP_INNER_NODE.to_bytes(4, "big")
    for hdr in headers:
        live.add(hdr["hash"])
        for root in (hdr["account_hash"], hdr["tx_hash"]):
            stack = [root]
            while stack:
                h = stack.pop()
                if h == ZERO256 or h in live:
                    continue
                blob = fetch(h)
                if blob is None:
                    continue
                live.add(h)
                if blob[:4] == inner_prefix:
                    for i in range(16):
                        stack.append(blob[4 + 32 * i: 36 + 32 * i])


def verify_shard_blob(blob: bytes) -> dict:
    """The offline verification contract run against RAW SHARD BYTES
    alone — the archive-import gate (doc/archive.md). Checks magic +
    header geometry, the whole-file CRC, every record's content hash,
    and the lo..hi ledger-header chain anchored at the header's
    first/last ledger hashes; the records count is DERIVED during the
    pass (it lives in the store index, not the file), so a fetched
    image is installable without trusting anything but its bytes. On
    success the report carries the parsed geometry (`lo`/`hi`/
    `rec_off`/`rec_len`/`acct_off`/`acct_len`/`records`/`first_hash`/
    `last_hash`) an importer needs to index the file."""
    report: dict = {"ok": False}
    if len(blob) < _HDR_SIZE + 4 or blob[:8] != _MAGIC:
        report["error"] = "bad magic/size"
        return report
    version, lo, hi, rec_off, rec_len, acct_off, acct_len = \
        _HDR.unpack_from(blob, len(_MAGIC))
    first_hash = blob[len(_MAGIC) + _HDR.size: len(_MAGIC) + _HDR.size + 32]
    last_hash = blob[len(_MAGIC) + _HDR.size + 32: _HDR_SIZE]
    report.update({"lo": lo, "hi": hi})
    if version != _VERSION:
        report["error"] = "bad version"
        return report
    if not (0 < lo <= hi):
        report["error"] = "bad range"
        return report
    if (rec_off != _HDR_SIZE or acct_off != rec_off + rec_len
            or acct_len < 4 or acct_off + acct_len + 4 != len(blob)):
        report["error"] = "bad geometry"
        return report
    body, crc = blob[:-4], struct.unpack("<I", blob[-4:])[0]
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        report["error"] = "crc mismatch"
        return report
    (n_acct,) = struct.unpack_from("<I", blob, acct_off)
    if 4 + n_acct * _ACCT_ROW.size != acct_len:
        report["error"] = "bad acct index"
        return report
    rec_img = blob[rec_off: rec_off + rec_len]
    n_checked = bad = consumed = 0
    headers: dict[int, dict] = {}
    ledger_prefix = HP_LEDGER_MASTER.to_bytes(4, "big")
    for key, type_byte, off, ln in _iter_records_py(rec_img):
        node = rec_img[off: off + ln]
        if sha512_half(node) != key:
            bad += 1
        n_checked += 1
        consumed = off + ln
        if type_byte == _T_LEDGER and node[:4] == ledger_prefix:
            from ..state.ledger import parse_header

            h = parse_header(node[4:])
            headers[h["seq"]] = {
                "hash": key, "parent_hash": h["parent_hash"],
            }
    report["records"] = n_checked
    report["bad_records"] = bad
    chain_ok = True
    for seq in range(lo, hi + 1):
        if seq not in headers:
            chain_ok = False
            break
        if seq > lo and \
                headers[seq]["parent_hash"] != headers[seq - 1]["hash"]:
            chain_ok = False
            break
    report["header_chain_ok"] = chain_ok
    report["first_hash_ok"] = headers.get(lo, {}).get("hash") == first_hash
    report["last_hash_ok"] = headers.get(hi, {}).get("hash") == last_hash
    report["ok"] = (
        bad == 0 and consumed == rec_len and chain_ok
        and report["first_hash_ok"] and report["last_hash_ok"]
    )
    if report["ok"]:
        report.update({
            "rec_off": rec_off, "rec_len": rec_len,
            "acct_off": acct_off, "acct_len": acct_len,
            "first_hash": first_hash, "last_hash": last_hash,
        })
    elif "error" not in report:
        report["error"] = "content verification failed"
    return report


class _Shard:
    __slots__ = ("sid", "path", "lo", "hi", "rec_off", "rec_len",
                 "acct_off", "acct_len", "records", "bytes",
                 "first_hash", "last_hash", "_txid_index")

    def __init__(self, sid, path, lo, hi, rec_off, rec_len, acct_off,
                 acct_len, records, nbytes, first_hash, last_hash):
        self.sid = sid
        self.path = path
        self.lo = lo
        self.hi = hi
        self.rec_off = rec_off
        self.rec_len = rec_len
        self.acct_off = acct_off
        self.acct_len = acct_len
        self.records = records
        self.bytes = nbytes
        self.first_hash = first_hash
        self.last_hash = last_hash
        self._txid_index: Optional[dict] = None  # txid -> (blob_off, len)


class HistoryShardStore:
    """Directory of sealed shard files + a JSON index (``shards.json``).

    Thread-safe: sealing happens on the close pipeline's drain worker,
    reads come from RPC threads and the overlay serving path."""

    INDEX_NAME = "shards.json"

    def __init__(self, path: str):
        self.root = path
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()
        self._shards: dict[int, _Shard] = {}
        self._fds: dict[int, int] = {}
        # counters (get_counts.history_shards)
        self.sealed = 0
        self.sealed_records = 0
        self.sealed_bytes = 0
        self.segment_reads = 0
        self.account_tx_queries = 0
        self.account_tx_rows = 0
        self.tx_faults = 0
        self.verifies = 0
        # archive-backfill import counters
        self.imported = 0
        self.imported_bytes = 0
        self.import_rejects = 0
        self._load_index()

    # -- open --------------------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.root, self.INDEX_NAME)

    def _load_index(self) -> None:
        try:
            with open(self._index_path()) as f:
                idx = json.load(f)
        except (OSError, ValueError):
            idx = {"shards": []}
        for row in idx.get("shards", []):
            path = os.path.join(self.root, row["file"])
            if not os.path.exists(path):
                continue  # manual deletion: drop the row, keep the rest
            sh = _Shard(
                int(row["id"]), path, int(row["lo"]), int(row["hi"]),
                int(row["rec_off"]), int(row["rec_len"]),
                int(row["acct_off"]), int(row["acct_len"]),
                int(row["records"]), int(row["bytes"]),
                bytes.fromhex(row["first_hash"]),
                bytes.fromhex(row["last_hash"]),
            )
            self._shards[sh.sid] = sh

    def _write_index_locked(self) -> None:
        rows = [
            {
                "id": sh.sid, "file": os.path.basename(sh.path),
                "lo": sh.lo, "hi": sh.hi,
                "rec_off": sh.rec_off, "rec_len": sh.rec_len,
                "acct_off": sh.acct_off, "acct_len": sh.acct_len,
                "records": sh.records, "bytes": sh.bytes,
                "first_hash": sh.first_hash.hex(),
                "last_hash": sh.last_hash.hex(),
            }
            for sh in sorted(self._shards.values(), key=lambda s: s.sid)
        ]
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": _VERSION, "shards": rows}, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._index_path())

    # -- seal ---------------------------------------------------------------

    def seal(self, lo: int, hi: int,
             records: list[tuple[bytes, int, bytes]],
             acct_rows: list[tuple[bytes, int, int, bytes]],
             first_hash: bytes, last_hash: bytes) -> int:
        """Write one shard covering validated seqs [lo, hi]. `records`
        are (key, type_byte, blob) — self-verifying, headers included;
        `acct_rows` are (account20, ledger_seq, txn_seq, txid). The file
        lands atomically (tmp + rename + fsync): a crash mid-seal leaves
        the previous shard set intact and the sweep that follows a
        FAILED seal is the caller's responsibility to skip.

        STREAMED: records are written one at a time with an incremental
        CRC — a multi-GB retired range never materializes a second (or
        third) in-RAM copy of its byte image — and the store lock is
        held only to allocate the shard id and to publish the finished
        file, so concurrent shard READS never stall behind the write
        and its fsync."""
        with self._lock:
            sid = max(self._shards, default=0) + 1
        rec_len = sum(
            _REC_HEADER + 1 + len(blob) for _k, _t, blob in records
        )
        acct_len = 4 + _ACCT_ROW.size * len(acct_rows)
        rec_off = _HDR_SIZE
        acct_off = rec_off + rec_len
        head = _MAGIC + _HDR.pack(
            _VERSION, lo, hi, rec_off, rec_len, acct_off, acct_len,
        ) + first_hash + last_hash
        name = f"shard-{sid:06d}.shard"
        path = os.path.join(self.root, name)
        tmp = path + ".tmp"
        crc = 0
        total = 0
        with open(tmp, "wb") as f:
            def emit(chunk: bytes) -> None:
                nonlocal crc, total
                f.write(chunk)
                crc = zlib.crc32(chunk, crc)
                total += len(chunk)

            emit(head)
            for key, type_byte, blob in records:
                emit(struct.pack("<IB", len(blob) + 1, 0))
                emit(key)
                emit(bytes((type_byte & 0xFF,)))
                emit(blob)
            emit(struct.pack("<I", len(acct_rows)))
            for acct, seq, txn_seq, txid in acct_rows:
                emit(_ACCT_ROW.pack(acct[:20], seq, txn_seq, txid))
            f.write(struct.pack("<I", crc & 0xFFFFFFFF))
            total += 4
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            sh = _Shard(sid, path, lo, hi, rec_off, rec_len,
                        acct_off, acct_len, len(records), total,
                        first_hash, last_hash)
            self._shards[sid] = sh
            self._write_index_locked()
            self.sealed += 1
            self.sealed_records += len(records)
            self.sealed_bytes += total
            return sid

    # -- introspection ------------------------------------------------------

    def shards(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "id": sh.sid, "lo": sh.lo, "hi": sh.hi,
                    "records": sh.records, "bytes": sh.bytes,
                    "first_hash": sh.first_hash.hex(),
                    "last_hash": sh.last_hash.hex(),
                }
                for sh in sorted(self._shards.values(),
                                 key=lambda s: s.sid)
            ]

    def covers(self, seq: int) -> Optional[int]:
        """Shard id whose range contains `seq`, else None."""
        with self._lock:
            for sh in self._shards.values():
                if sh.lo <= seq <= sh.hi:
                    return sh.sid
        return None

    def range(self) -> Optional[tuple[int, int]]:
        with self._lock:
            if not self._shards:
                return None
            return (min(s.lo for s in self._shards.values()),
                    max(s.hi for s in self._shards.values()))

    def get_json(self) -> dict:
        with self._lock:
            return {
                "shards": len(self._shards),
                "range": list(self.range() or ()),
                "sealed": self.sealed,
                "sealed_records": self.sealed_records,
                "sealed_bytes": self.sealed_bytes,
                "segment_reads": self.segment_reads,
                "account_tx_queries": self.account_tx_queries,
                "account_tx_rows": self.account_tx_rows,
                "tx_faults": self.tx_faults,
                "verifies": self.verifies,
                "imported": self.imported,
                "imported_bytes": self.imported_bytes,
                "import_rejects": self.import_rejects,
                "contiguous_floor": self.contiguous_floor(),
            }

    def contiguous_floor(self) -> int:
        """Highest seq covered by an UNBROKEN run of sealed shards
        starting at the store's lowest covered seq (0 = empty). This is
        the archive's verified floor: every result whose window closes
        at or below it is backed by offline-verified shard bytes and
        immutable, so the read plane may cache it forever."""
        with self._lock:
            spans = sorted((sh.lo, sh.hi) for sh in self._shards.values())
        if not spans:
            return 0
        hi = spans[0][1]
        for s_lo, s_hi in spans[1:]:
            if s_lo > hi + 1:
                break
            hi = max(hi, s_hi)
        return hi

    # -- the segment-manifest door (cold catch-up) -------------------------

    def segments(self) -> list[dict]:
        """Manifest rows in the segstore ``segments()`` shape, ids
        offset by SHARD_SEG_BASE — the record section is byte-served so
        the existing SegmentCatchup ingest verifies it unchanged.

        Shard rows additionally advertise the sealed range (``lo``/
        ``hi``) and the full on-disk file size (``file_bytes``) so
        catch-up and archive peers SELECT by seq range without probing;
        the wire encoder rides all three nonzero-only, keeping legacy
        manifest frames byte-identical."""
        with self._lock:
            return [
                {
                    "id": SHARD_SEG_BASE + sh.sid,
                    "size": sh.rec_len,
                    "live_bytes": sh.rec_len,
                    "active": False,
                    "lo": sh.lo,
                    "hi": sh.hi,
                    "file_bytes": sh.bytes,
                }
                for sh in sorted(self._shards.values(),
                                 key=lambda s: s.sid)
            ]

    def _fd(self, sh: _Shard) -> int:
        fd = self._fds.get(sh.sid)
        if fd is None:
            fd = os.open(sh.path, os.O_RDONLY)
            self._fds[sh.sid] = fd
        return fd

    def fetch_segment(self, seg_id: int, offset: int = 0,
                      length: Optional[int] = None,
                      ) -> Optional[tuple[dict, bytes]]:
        """One bounded chunk of a shard's RECORD section (same contract
        as segstore.fetch_segment: meta carries the full section size).
        Ids at or above SHARD_FILE_BASE serve the WHOLE shard file
        instead — the archive-backfill distribution door."""
        if seg_id >= SHARD_FILE_BASE:
            return self._fetch_file(seg_id, offset, length)
        sid = seg_id - SHARD_SEG_BASE
        with self._lock:
            sh = self._shards.get(sid)
            if sh is None:
                return None
            off = max(0, int(offset))
            n = sh.rec_len - off
            if length is not None:
                n = min(n, int(length))
            data = b""
            if n > 0:
                data = os.pread(self._fd(sh), n, sh.rec_off + off)
            self.segment_reads += 1
            return (
                {
                    "id": seg_id,
                    "size": sh.rec_len,
                    "live_bytes": sh.rec_len,
                    "active": False,
                },
                data,
            )

    def _fetch_file(self, seg_id: int, offset: int = 0,
                    length: Optional[int] = None,
                    ) -> Optional[tuple[dict, bytes]]:
        """One bounded chunk of the COMPLETE shard file (header +
        records + account index + CRC): the transferred image is
        exactly what ``verify_shard_blob`` checks and ``import_shard``
        installs, so a fetching archive trusts nothing but the bytes."""
        sid = seg_id - SHARD_FILE_BASE
        with self._lock:
            sh = self._shards.get(sid)
            if sh is None:
                return None
            off = max(0, int(offset))
            n = sh.bytes - off
            if length is not None:
                n = min(n, int(length))
            data = b""
            if n > 0:
                data = os.pread(self._fd(sh), n, off)
            self.segment_reads += 1
            return (
                {
                    "id": seg_id,
                    "size": sh.bytes,
                    "live_bytes": sh.bytes,
                    "active": False,
                },
                data,
            )

    # -- archive import (shard distribution network) -----------------------

    def import_shard(self, data: bytes) -> dict:
        """Verify-then-install a peer-fetched shard image. The bytes
        run the FULL offline contract in memory (``verify_shard_blob``)
        BEFORE anything touches the store directory — a failed
        verification retains zero hostile bytes. A range the store
        already holds is an idempotent duplicate; a partial overlap is
        rejected (two honest seals never straddle a rotation point)."""
        report = verify_shard_blob(data)
        if not report["ok"]:
            with self._lock:
                self.import_rejects += 1
            return {
                "ok": False,
                "error": report.get("error", "verify failed"),
                "report": report,
            }
        lo, hi = report["lo"], report["hi"]
        with self._lock:
            for sh in self._shards.values():
                if sh.lo == lo and sh.hi == hi:
                    return {"ok": True, "duplicate": True, "id": sh.sid,
                            "lo": lo, "hi": hi}
                if sh.hi >= lo and sh.lo <= hi:
                    self.import_rejects += 1
                    return {"ok": False, "error": "overlapping range"}
            sid = max(self._shards, default=0) + 1
        path = os.path.join(self.root, f"shard-{sid:06d}.shard")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            sh = _Shard(sid, path, lo, hi,
                        report["rec_off"], report["rec_len"],
                        report["acct_off"], report["acct_len"],
                        report["records"], len(data),
                        report["first_hash"], report["last_hash"])
            self._shards[sid] = sh
            self._write_index_locked()
            self.imported += 1
            self.imported_bytes += len(data)
        return {"ok": True, "id": sid, "lo": lo, "hi": hi,
                "records": report["records"]}

    def iter_records(self, sid: int) -> Iterator[tuple[bytes, int, bytes]]:
        """(key, type_byte, blob) per record of one shard — the import
        fan-out walk (archive nodestore + txdb feed)."""
        with self._lock:
            sh = self._shards.get(sid)
            if sh is None:
                return
            data = os.pread(self._fd(sh), sh.rec_len, sh.rec_off)
        for key, type_byte, off, ln in _iter_records_py(data):
            yield key, type_byte, data[off: off + ln]

    def acct_rows(self, sid: int) -> list[tuple[bytes, int, int, bytes]]:
        """(account20, ledger_seq, txn_seq, txid) rows of one shard."""
        with self._lock:
            sh = self._shards.get(sid)
        if sh is None:
            return []
        raw = self._acct_rows(sh)
        if len(raw) < 4:
            return []
        (n,) = struct.unpack_from("<I", raw, 0)
        out = []
        pos = 4
        for _ in range(n):
            if pos + _ACCT_ROW.size > len(raw):
                break
            out.append(_ACCT_ROW.unpack_from(raw, pos))
            pos += _ACCT_ROW.size
        return out

    def tx_blob(self, sid: int, txid: bytes,
                ) -> Optional[tuple[bytes, bytes]]:
        """(raw_tx, meta) for one txid of one shard (import feed +
        byte-match audits)."""
        with self._lock:
            sh = self._shards.get(sid)
        if sh is None:
            return None
        return self._tx_blob(sh, txid)

    # -- account_tx below the retain floor ---------------------------------

    def _acct_rows(self, sh: _Shard) -> bytes:
        with self._lock:
            return os.pread(self._fd(sh), sh.acct_len, sh.acct_off)

    def _txid_index(self, sh: _Shard) -> dict:
        """txid -> (file_off, blob_len) over the shard's TX-tree leaf
        records, built once per shard on first account_tx touch (the
        native segrecs_scan pass when available)."""
        with self._lock:
            idx = sh._txid_index
            if idx is not None:
                return idx
        recs = None
        try:
            from ..native import scan_segment_records

            recs = scan_segment_records(sh.path, sh.rec_off)
        except Exception:  # noqa: BLE001 — python mirror below
            recs = None
        entries: dict[bytes, tuple[int, int]] = {}
        tx_prefix = HP_TX_NODE.to_bytes(4, "big")
        if recs is not None:
            with self._lock:
                fd = self._fd(sh)
            for key, type_byte, blob_off, blob_len in recs:
                if blob_off + blob_len > sh.rec_off + sh.rec_len:
                    break  # past the record section (acct rows / crc)
                if type_byte != _T_TRANSACTION_NODE or blob_len < 36:
                    continue
                if os.pread(fd, 4, blob_off) != tx_prefix:
                    continue  # inner node of the tx tree
                txid = os.pread(fd, 32, blob_off + blob_len - 32)
                entries[txid] = (blob_off, blob_len)
        else:
            with self._lock:
                data = os.pread(self._fd(sh), sh.rec_len, sh.rec_off)
            for key, type_byte, off, ln in _iter_records_py(data):
                if type_byte != _T_TRANSACTION_NODE or ln < 36:
                    continue
                if data[off: off + 4] != tx_prefix:
                    continue
                txid = data[off + ln - 32: off + ln]
                entries[txid] = (sh.rec_off + off, ln)
        with self._lock:
            sh._txid_index = entries
        return entries

    def _tx_blob(self, sh: _Shard, txid: bytes,
                 ) -> Optional[tuple[bytes, bytes]]:
        """(raw_tx, meta) decoded on demand from the shard file."""
        loc = self._txid_index(sh).get(txid)
        if loc is None:
            return None
        off, ln = loc
        with self._lock:
            blob = os.pread(self._fd(sh), ln, off)
        self.tx_faults += 1
        # TX_MD leaf: 4B prefix + VL(tx) || VL(meta) + 32B tag
        from ..protocol.serializer import BinaryParser

        p = BinaryParser(blob[4:-32])
        return p.read_vl(), p.read_vl()

    def account_tx(self, account: bytes, min_ledger: int, max_ledger: int,
                   limit: int = 200, forward: bool = True,
                   after: Optional[tuple[int, int]] = None) -> list[dict]:
        """txdb.account_transactions-shaped rows served from shards —
        same walk order, same EXCLUSIVE (ledger_seq, txn_seq) resume
        marker, so the handler merges the two tiers seamlessly."""
        self.account_tx_queries += 1
        acct20 = account[:20]
        hits: list[tuple[int, int, bytes, _Shard]] = []
        with self._lock:
            shards = [
                sh for sh in self._shards.values()
                if sh.hi >= min_ledger and sh.lo <= max_ledger
            ]
        for sh in shards:
            raw = self._acct_rows(sh)
            if len(raw) < 4:
                continue
            (n,) = struct.unpack_from("<I", raw, 0)
            pos = 4
            for _ in range(n):
                if pos + _ACCT_ROW.size > len(raw):
                    break
                a, lseq, tseq, txid = _ACCT_ROW.unpack_from(raw, pos)
                pos += _ACCT_ROW.size
                if a != acct20 or not (min_ledger <= lseq <= max_ledger):
                    continue
                if after is not None:
                    al, at = after
                    if forward:
                        if (lseq, tseq) <= (al, at):
                            continue
                    elif (lseq, tseq) >= (al, at):
                        continue
                hits.append((lseq, tseq, txid, sh))
        hits.sort(key=lambda r: (r[0], r[1]), reverse=not forward)
        out = []
        for lseq, tseq, txid, sh in hits[:limit]:
            got = self._tx_blob(sh, txid)
            if got is None:
                continue  # index row without a record: skip, not crash
            raw_tx, meta = got
            out.append({
                "txid": txid,
                "ledger_seq": lseq,
                "txn_seq": tseq,
                "raw": raw_tx,
                "meta": meta,
                "shard": sh.sid,
            })
            self.account_tx_rows += 1
        return out

    # -- offline verification ----------------------------------------------

    def verify(self, sid: int) -> dict:
        """The offline verification contract (doc/storage.md): CRC over
        the whole file, every record's content hash, and the header
        chain — run against the file alone."""
        with self._lock:
            sh = self._shards.get(sid)
        if sh is None:
            return {"ok": False, "error": "unknown shard"}
        self.verifies += 1
        with open(sh.path, "rb") as f:
            blob = f.read()
        report: dict = {"ok": False, "id": sid, "lo": sh.lo, "hi": sh.hi}
        if len(blob) < _HDR_SIZE + 4 or blob[:8] != _MAGIC:
            report["error"] = "bad magic/size"
            return report
        body, crc = blob[:-4], struct.unpack("<I", blob[-4:])[0]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            report["error"] = "crc mismatch"
            return report
        rec_img = blob[sh.rec_off: sh.rec_off + sh.rec_len]
        n_checked = bad = 0
        headers: dict[int, dict] = {}
        ledger_prefix = HP_LEDGER_MASTER.to_bytes(4, "big")
        for key, type_byte, off, ln in _iter_records_py(rec_img):
            node = rec_img[off: off + ln]
            if sha512_half(node) != key:
                bad += 1
            n_checked += 1
            if type_byte == _T_LEDGER and node[:4] == ledger_prefix:
                from ..state.ledger import parse_header

                h = parse_header(node[4:])
                headers[h["seq"]] = {
                    "hash": key, "parent_hash": h["parent_hash"],
                }
        report["records"] = n_checked
        report["bad_records"] = bad
        chain_ok = True
        for seq in range(sh.lo, sh.hi + 1):
            if seq not in headers:
                chain_ok = False
                break
            if seq > sh.lo and \
                    headers[seq]["parent_hash"] != headers[seq - 1]["hash"]:
                chain_ok = False
                break
        report["header_chain_ok"] = chain_ok
        report["first_hash_ok"] = (
            headers.get(sh.lo, {}).get("hash") == sh.first_hash
        )
        report["last_hash_ok"] = (
            headers.get(sh.hi, {}).get("hash") == sh.last_hash
        )
        report["ok"] = (
            bad == 0 and n_checked == sh.records and chain_ok
            and report["first_hash_ok"] and report["last_hash_ok"]
        )
        return report

    def close(self) -> None:
        with self._lock:
            for fd in self._fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._fds.clear()


def rotate_into_shards(db, shardstore: HistoryShardStore,
                       retired: list[dict], retained: list[dict],
                       acct_rows: Optional[list] = None) -> Optional[int]:
    """One whole rotation against a nodestore Database: seal the
    `retired` ledgers (header dicts: hash/seq/account_hash/tx_hash)
    into a shard, then sweep everything not reachable from `retained`
    out of the live store. The embedder/testkit form of what
    OnlineDeleter does on the drain worker — seal FIRST, delete only
    what sealed. Returns the new shard id, or None when there was
    nothing to retire."""
    if not retired:
        return None
    retired = sorted(retired, key=lambda h: h["seq"])

    def fetch(h: bytes):
        obj = db.fetch(h, populate_cache=False)
        return obj.data if obj is not None else None

    live: set = set()
    mark_live(fetch, retained, live)
    records = collect_retired(fetch, retired, live)
    sid = shardstore.seal(
        retired[0]["seq"], retired[-1]["seq"], records,
        list(acct_rows or ()),
        first_hash=retired[0]["hash"], last_hash=retired[-1]["hash"],
    )
    db.begin_sweep()
    db.apply_sweep(live)
    return sid


class CombinedSegmentSource:
    """segstore backend + shard store behind ONE fetch_segment door:
    the manifest concatenates live segments and shard rows, and ids at
    or above SHARD_SEG_BASE route to the shard store. Wired as
    ``vn.segment_source`` so a cold peer below the leader's trim floor
    syncs the gap from shards over the unchanged GetSegments path."""

    def __init__(self, backend, shardstore: HistoryShardStore):
        self.backend = backend
        self.shardstore = shardstore

    def segments(self) -> list[dict]:
        return self.backend.segments() + self.shardstore.segments()

    def fetch_segment(self, seg_id: int, offset: int = 0,
                      length: Optional[int] = None):
        if seg_id >= SHARD_SEG_BASE:
            return self.shardstore.fetch_segment(
                seg_id, offset=offset, length=length
            )
        return self.backend.fetch_segment(
            seg_id, offset=offset, length=length
        )
