"""Device-side kernels: batched SHA-512 and Ed25519 over JAX/XLA (Pallas
variants where profitable). These fill the role of the reference's
libsodium/OpenSSL hot calls (SerializedTransaction::checkSign,
SHAMapTreeNode::updateHash) as batched, device-resident primitives.
"""
