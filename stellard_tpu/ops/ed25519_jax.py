"""Batched Ed25519 signature verification in JAX — the north-star kernel.

Replaces the per-signature libsodium calls on the reference's hot paths
(SerializedTransaction::checkSign, SerializedValidation::isValid —
/root/reference/src/ripple_app/misc/SerializedTransaction.cpp:192-230,
/root/reference/src/ripple_app/ledger/SerializedValidation.cpp:90-108)
with one data-parallel kernel over the whole batch:

    R' = [S]B + [h](-A),  accept iff encode(R') == R  and  S < l

Design notes (TPU-first):
- Points are [..., 4, 20] int32 (X, Y, Z, T extended coords over the
  13-bit-limb field of fe25519). The batch dim feeds the vector lanes.
- The twisted-Edwards addition law is COMPLETE for ed25519 (a = -1 is a
  square mod p, d is a non-square), so one branch-free formula covers
  identity/doubling/adversarial small-order inputs — exactly what a
  lock-step SIMD batch needs.
- [S]B uses a 64-window fixed-base comb (no doublings, table built host-side
  once); [h](-A) uses 4-bit windowed double-and-add with a per-element
  16-entry table. All loops are lax.fori_loop (rolled: fast XLA compile).
- h = SHA512(R||A||M) mod l and the 4-bit window decomposition are computed
  host-side (cheap C-backed hashlib; the device does the ~3k field muls).
"""

from __future__ import annotations

import hashlib

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import ed25519_ref as ref
from .fe25519 import (
    D2,
    L,
    NLIMB,
    P,
    SQRT_M1,
    fe_add,
    fe_const,
    fe_eq,
    fe_invert,
    fe_is_odd,
    fe_is_zero,
    fe_mul,
    fe_neg,
    fe_pow,
    fe_reduce_full,
    fe_select,
    fe_square,
    fe_sub,
    int_to_limbs_np,
    limbs_from_words_le,
    limbs_to_words_le,
)

WINDOW = 4
NWINDOWS = 64  # ceil(256/4); scalars are < l < 2^253


# --------------------------------------------------------------------------
# point helpers: points are [..., 4, 20] int32 stacks of (X, Y, Z, T)


def pt_stack(x, y, z, t):
    return jnp.stack([x, y, z, t], axis=-2)


def pt_identity(batch_shape=()):
    return pt_stack(
        fe_const(0, batch_shape),
        fe_const(1, batch_shape),
        fe_const(1, batch_shape),
        fe_const(0, batch_shape),
    )


def pt_add(p, q):
    """Complete unified addition (extended coords, a=-1, k=2d)."""
    x1, y1, z1, t1 = p[..., 0, :], p[..., 1, :], p[..., 2, :], p[..., 3, :]
    x2, y2, z2, t2 = q[..., 0, :], q[..., 1, :], q[..., 2, :], q[..., 3, :]
    a = fe_mul(fe_sub(y1, x1), fe_sub(y2, x2))
    b = fe_mul(fe_add(y1, x1), fe_add(y2, x2))
    c = fe_mul(fe_mul(t1, t2), fe_const(D2))
    d = fe_mul(z1, z2)
    d = fe_add(d, d)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    return pt_stack(fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_double(p):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4S + 4M."""
    x1, y1, z1 = p[..., 0, :], p[..., 1, :], p[..., 2, :]
    a = fe_square(x1)
    b = fe_square(y1)
    zz = fe_square(z1)
    c = fe_add(zz, zz)
    e = fe_sub(fe_sub(fe_square(fe_add(x1, y1)), a), b)
    g = fe_sub(b, a)  # a_coeff=-1: G = aA + B = B - A
    f = fe_sub(g, c)  # note: F = G - C
    h = fe_sub(fe_neg(a), b)  # H = aA - B = -A - B
    return pt_stack(fe_mul(e, f), fe_mul(g, h), fe_mul(f, g), fe_mul(e, h))


def pt_neg(p):
    return pt_stack(
        fe_neg(p[..., 0, :]), p[..., 1, :], p[..., 2, :], fe_neg(p[..., 3, :])
    )


def pt_encode_words(p):
    """-> [..., 8] uint32 LE words of the canonical compressed encoding."""
    zi = fe_invert(p[..., 2, :])
    x = fe_reduce_full(fe_mul(p[..., 0, :], zi))
    y = fe_reduce_full(fe_mul(p[..., 1, :], zi))
    words = limbs_to_words_le(y)
    sign = (x[..., 0] & 1).astype(jnp.uint32)
    return words.at[..., 7].set(words[..., 7] | (sign << 31))


# --------------------------------------------------------------------------
# decompression


def pt_decompress(words_u32):
    """[..., 8] u32 LE encoding -> (point [..., 4, 20], valid [...])."""
    y = limbs_from_words_le(words_u32, mask_high=True)
    sign = (words_u32[..., 7] >> 31).astype(jnp.int32)
    y2 = fe_square(y)
    u = fe_sub(y2, fe_const(1))
    v = fe_add(fe_mul(y2, fe_const(ref.D)), fe_const(1))
    v3 = fe_mul(fe_square(v), v)
    v7 = fe_mul(fe_square(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow(fe_mul(u, v7), (P - 5) // 8))
    vxx = fe_mul(fe_square(x), v)
    ok1 = fe_eq(vxx, u)
    ok2 = fe_eq(vxx, fe_neg(u))
    x = fe_select(ok1, x, fe_mul(x, fe_const(SQRT_M1)))
    valid = ok1 | ok2
    x_zero = fe_is_zero(x)
    valid = valid & ~(x_zero & (sign == 1))
    flip = fe_is_odd(x) != (sign == 1)
    x = fe_select(flip, fe_neg(x), x)
    point = pt_stack(x, y, fe_const(1, x.shape[:-1]), fe_mul(x, y))
    return point, valid


# --------------------------------------------------------------------------
# fixed-base comb table for B (host-side, Python ints, computed once)

_COMB_NP: np.ndarray | None = None


def _comb_table_np() -> np.ndarray:
    """[NWINDOWS, 16, 4, 20] int32: T[j][w] = (w << 4j) * B, extended Z=1."""
    global _COMB_NP
    if _COMB_NP is None:
        out = np.zeros((NWINDOWS, 16, 4, NLIMB), np.int32)
        base = ref.BASE
        step = base  # 2^(4j) * B
        for j in range(NWINDOWS):
            acc = ref.IDENTITY
            for w in range(16):
                x, y, z, t = acc
                zi = pow(z, P - 2, P)
                xa, ya = x * zi % P, y * zi % P
                out[j, w, 0] = int_to_limbs_np(xa)
                out[j, w, 1] = int_to_limbs_np(ya)
                out[j, w, 2] = int_to_limbs_np(1)
                out[j, w, 3] = int_to_limbs_np(xa * ya % P)
                acc = ref.pt_add(acc, step)
            for _ in range(4):
                step = ref.pt_double(step)
        _COMB_NP = out
    return _COMB_NP


def _batch_zero(ref_arr):
    """[..., 1, 1] int32 zero carrying the batch 'varying' tag of ref_arr,
    so fori_loop carries seeded from constants stay shard_map-compatible."""
    return (ref_arr[..., :1] * 0)[..., None]


def _onehot16(w):
    """[...] int32 in [0,16) -> [..., 16] int32 one-hot. Table selection
    by one-hot contraction instead of gather: per-lane gathers serialize
    on TPU, while the contraction is a dense (MXU/VPU) op."""
    return (w[..., None] == jnp.arange(16, dtype=w.dtype)).astype(jnp.int32)


def _comb_mult(s_windows):
    """[S]B via the comb: s_windows [..., 64] int32 (4-bit, LSB window
    first). 64 complete additions, no doublings; each table entry is
    selected with a [B,16] x [16,80] one-hot matmul (shared table → this
    rides the MXU)."""
    table = jnp.asarray(_comb_table_np())  # [64, 16, 4, 20]
    flat = table.reshape(NWINDOWS, 16, 4 * NLIMB)
    acc0 = pt_identity(s_windows.shape[:-1]) + _batch_zero(s_windows)

    def body(j, acc):
        tj = lax.dynamic_index_in_dim(flat, j, axis=0, keepdims=False)  # [16,80]
        onehot = _onehot16(s_windows[..., j])  # [..., 16]
        entry = jnp.matmul(onehot, tj).reshape(onehot.shape[:-1] + (4, NLIMB))
        return pt_add(acc, entry)

    return lax.fori_loop(0, NWINDOWS, body, acc0)


def _windowed_mult(h_windows, point):
    """[h]P via 4-bit windows, MSB window first: h_windows [..., 64].
    The per-element multiples table is built with an unrolled chain of 14
    additions; selection is a one-hot weighted sum over the table axis
    (again: no gathers)."""
    batch = h_windows.shape[:-1]
    # unrolled per-element table 0P..15P: [..., 16, 4, 20]
    entries = [pt_identity(batch) + _batch_zero(h_windows), point]
    for _ in range(14):
        entries.append(pt_add(entries[-1], point))
    tbl = jnp.stack(entries, axis=-3)  # [..., 16, 4, 20]

    def body(i, acc):
        for _ in range(WINDOW):
            acc = pt_double(acc)
        w = h_windows[..., NWINDOWS - 1 - i]  # windows LSB-first; walk MSB->LSB
        onehot = _onehot16(w)[..., :, None, None]  # [..., 16, 1, 1]
        entry = jnp.sum(onehot * tbl, axis=-3)  # [..., 4, 20]
        return pt_add(acc, entry)

    acc0 = pt_identity(batch) + _batch_zero(h_windows)
    return lax.fori_loop(0, NWINDOWS, body, acc0)


# --------------------------------------------------------------------------
# the batched verify kernel


@jax.jit
def verify_kernel(a_words, r_words, s_windows, h_windows, s_canonical):
    """Batched core: all inputs leading dim B.

    a_words: [B, 8] u32 public keys (LE words)
    r_words: [B, 8] u32 signature R
    s_windows/h_windows: [B, 64] int32 4-bit windows (LSB window first)
    s_canonical: [B] bool (S < l, checked host-side)
    -> [B] bool
    """
    a_point, a_valid = pt_decompress(a_words)
    sb = _comb_mult(s_windows)
    ha = _windowed_mult(h_windows, pt_neg(a_point))
    rp = pt_add(sb, ha)
    enc = pt_encode_words(rp)
    eq = jnp.all(enc == r_words, axis=-1)
    return eq & a_valid & s_canonical


# --------------------------------------------------------------------------
# host-side preparation


_L_BYTES = np.frombuffer(L.to_bytes(32, "little"), np.uint8)
_NATIVE_PREP = None
_NATIVE_PREP_TRIED = False


def _native_prep():
    """Cached native host-prep kernel, or None when unavailable."""
    global _NATIVE_PREP, _NATIVE_PREP_TRIED
    if not _NATIVE_PREP_TRIED:
        _NATIVE_PREP_TRIED = True
        try:
            from ..native import Ed25519HostPrep

            _NATIVE_PREP = Ed25519HostPrep()
        except Exception:
            _NATIVE_PREP = None
    return _NATIVE_PREP


def _nibbles_le(b: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 LE scalar bytes -> [B, 64] int32 4-bit windows,
    LSB window first."""
    lo = b & 0xF
    hi = b >> 4
    return np.stack([lo, hi], axis=-1).reshape(b.shape[0], 64).astype(np.int32)


def prepare_batch(publics, messages, signatures, device_put: bool = True):
    """Host prep: pack keys/sigs, compute h = SHA512(R||A||M) mod l and the
    window decompositions. Returns dict of arrays for verify_kernel.

    Fully vectorized: byte packing / window extraction / canonical checks
    are numpy over the whole batch; the SHA-512 + mod-l per-signature work
    runs in one threaded native call (native/src/ed25519_host.cc), with a
    hashlib+bigint fallback when the native library is unavailable.
    """
    B = len(publics)
    # sanitize malformed entries to zero-filled rows; s_canonical stays
    # False for them so verification fails without branching later
    bad = [
        i
        for i, (pk, sig) in enumerate(zip(publics, signatures))
        if len(pk) != 32 or len(sig) != 64
    ]
    if bad:
        publics = list(publics)
        signatures = list(signatures)
        for i in bad:
            publics[i] = b"\x00" * 32
            signatures[i] = b"\x00" * 64
    pk_packed = b"".join(publics)
    sig_arr = np.frombuffer(b"".join(signatures), np.uint8).reshape(B, 64)
    a_words = np.frombuffer(pk_packed, np.uint8).reshape(B, 32)
    a_words = np.ascontiguousarray(a_words).view("<u4").astype(np.uint32)
    r_bytes = np.ascontiguousarray(sig_arr[:, :32])
    s_bytes = np.ascontiguousarray(sig_arr[:, 32:])
    r_words = r_bytes.view("<u4").astype(np.uint32)

    # canonical S < l: lexicographic compare from the most significant byte
    rev_diff = (s_bytes != _L_BYTES)[:, ::-1]
    any_diff = rev_diff.any(axis=1)
    msb = 31 - np.argmax(rev_diff, axis=1)
    s_canonical = any_diff & (s_bytes[np.arange(B), msb] < _L_BYTES[msb])
    if bad:
        s_canonical[bad] = False
    s_windows = _nibbles_le(s_bytes)

    native = _native_prep()
    if native is not None:
        h_scalars = native.h_batch(r_bytes.tobytes(), pk_packed, messages, B)
    else:
        h_scalars = np.empty((B, 32), np.uint8)
        r_packed = r_bytes.tobytes()
        for i, (pk, msg) in enumerate(zip(publics, messages)):
            h = int.from_bytes(
                hashlib.sha512(r_packed[32 * i : 32 * i + 32] + pk + msg).digest(),
                "little",
            ) % L
            h_scalars[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
    h_windows = _nibbles_le(h_scalars)

    put = jnp.asarray if device_put else (lambda x: x)
    return dict(
        a_words=put(a_words),
        r_words=put(r_words),
        s_windows=put(s_windows),
        h_windows=put(h_windows),
        s_canonical=put(s_canonical),
    )


def verify_batch(publics, messages, signatures) -> np.ndarray:
    """End-to-end batched verification -> [B] bool numpy array."""
    inputs = prepare_batch(publics, messages, signatures)
    return np.asarray(verify_kernel(**inputs))


def verify_stream(batches):
    """Double-buffered end-to-end verification over an iterable of
    (publics, messages, signatures) tuples.

    JAX dispatch is asynchronous, so the host prep (native SHA-512 +
    mod-l + numpy packing) of batch i+1 runs while the device executes
    batch i — the steady-state pipeline the round-1 bench only asserted.
    Yields [B] bool numpy arrays in submission order.
    """
    pending = None
    for batch in batches:
        inputs = prepare_batch(*batch)
        out = verify_kernel(**inputs)  # async dispatch
        if pending is not None:
            yield np.asarray(pending)  # blocks on batch i-1 only
        pending = out
    if pending is not None:
        yield np.asarray(pending)
