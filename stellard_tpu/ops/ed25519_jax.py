"""Batched Ed25519 signature verification in JAX — the north-star kernel.

Replaces the per-signature libsodium calls on the reference's hot paths
(SerializedTransaction::checkSign, SerializedValidation::isValid —
/root/reference/src/ripple_app/misc/SerializedTransaction.cpp:192-230,
/root/reference/src/ripple_app/ledger/SerializedValidation.cpp:90-108)
with one data-parallel kernel over the whole batch:

    R' = [S]B + [h](-A),  accept iff encode(R') == R  and  S < l

Design notes (TPU-first):
- Field elements are LIMB-MAJOR [20, B] int32 (13-bit limbs, fe25519):
  the batch axis is minor, so it maps onto the 128-wide TPU lane axis
  and every elementwise limb op runs at full vector width. The public
  kernel signature stays batch-major ([B, ...]); inputs are transposed
  once on entry, the verdict once on exit.
- Points are [4, 20, B] int32 (X, Y, Z, T extended coords).
- The twisted-Edwards addition law is COMPLETE for ed25519 (a = -1 is a
  square mod p, d is a non-square), so one branch-free formula covers
  identity/doubling/adversarial small-order inputs — exactly what a
  lock-step SIMD batch needs.
- [S]B uses a 64-window fixed-base comb (no doublings; table host-built
  once in precomputed "niels" form (y+x, y-x, 2dxy)), so each comb step
  is a 7M mixed addition. Table entries are selected with a
  [60,16] x [16,B] one-hot f32 matmul — a dense MXU op; per-lane gathers
  serialize on TPU.
- [h](-A) uses SIGNED 4-bit windows (digits in [-8, 7], recoded
  host-side): the per-element table holds only 9 cached multiples
  0..8, negation is a (y+x)/(y-x) swap plus a t2d negation. 256
  doublings + 64 cached additions (8M each).
- Both scalar walks share ONE fori_loop (64 iterations), halving loop
  overhead vs separate comb/windowed loops.
- h = SHA512(R||A||M) mod l and both digit decompositions are computed
  host-side (native C prep when available; the device does the ~3k
  field muls).
"""

from __future__ import annotations

import hashlib
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import ed25519_ref as ref
from .fe25519 import (
    D,
    D2,
    L,
    NLIMB,
    P,
    SQRT_M1,
    fe_add,
    fe_const,
    fe_eq,
    fe_invert,
    fe_is_odd,
    fe_is_zero,
    fe_mul,
    fe_neg,
    fe_pow_p58,
    fe_reduce_full,
    fe_select,
    fe_square,
    fe_sub,
    int_to_limbs_np,
    limbs_from_words_le,
    limbs_lt_p,
    limbs_to_words_le,
)

WINDOW = 4
NWINDOWS = 64  # ceil(256/4); scalars are < l < 2^253

# static unroll factor for the 64-iteration scalar-walk loop: >1 gives
# XLA a bigger window to software-pipeline at the cost of compile time.
# Read once at import (a jit-time constant); default 1 keeps the graph
# byte-identical to the rolled form (and the compilation cache warm).
_UNROLL = int(os.environ.get("STELLARD_VERIFY_UNROLL", "1"))

# comb-table selection strategy (A/B'd by tools/kernel_sweep.py):
#   mxu       — one [60,16]@[16,B] f32 matmul at HIGHEST precision
#               (3 MXU passes; exact for 13-bit limbs)
#   mxu_split — TWO one-pass matmuls on 7-bit/6-bit limb halves
#               (halves are bf16-exact, so default precision suffices;
#               2 passes of MXU work + a shift-add recombine)
#   vpu       — int32 one-hot contraction on the VPU (no int<->float
#               converts, ~960 lane mult-adds per window)
_COMB_SELECT = os.environ.get("STELLARD_COMB_SELECT", "mxu")

# hoist ALL 64 window selections of both scalar walks out of the loop
# into two wide contractions (1) vs select per-iteration inside the loop
# (0). Hoisting materialises [64, 4, 20, B] / [64, 3, 20, B] selected-
# window tensors in HBM; measured on-chip (r4) that LOSES to in-loop
# selection and the gap grows with batch (16384: 63.7k vs 99.9k sigs/s),
# so the default is the measured winner. Kept as a knob because the
# op-count model says it should win — future XLA versions may differ.
_HOIST_SELECT = os.environ.get("STELLARD_HOIST_SELECT", "0") == "1"

# merge the 3-4 independent field muls/squares inside each point formula
# into one wider op (concat along the batch axis). Measured on-chip (r4,
# batch 16384): grouping LOSES 100.7k -> 63.2k sigs/s — the concats and
# slices around each widened op cost more than the op-count saving —
# so the default is ungrouped. Knob kept for re-measurement.
_GROUP_OPS = os.environ.get("STELLARD_GROUP_OPS", "0") == "1"

# final-check formulation:
#   bytes — encode([S]B + [h](-A)) and byte-compare against R: the
#           reference's exact verify shape (ref10 crypto_sign_open),
#           costing a 254S+11M inversion chain.
#   point — decompress R as a point too (its sqrt chain STACKED with
#           A's into ONE double-width chain) and compare projectively
#           (Z_r = 1: X3 == Xr*Z3, Y3 == Yr*Z3) — no inversion; a
#           canonical-y_r check replaces the byte comparison's implicit
#           rejection of non-canonical R encodings. ~15% fewer
#           sequential wide ops; equivalence with `bytes` is pinned by
#           the adversarial oracle corpus (non-canonical R, x=0 sign
#           edge, off-curve R).
_VERIFY_CHECK = os.environ.get("STELLARD_VERIFY_CHECK", "bytes")
if _VERIFY_CHECK not in ("bytes", "point"):
    raise ValueError(
        f"STELLARD_VERIFY_CHECK={_VERIFY_CHECK!r}: expected 'bytes' or "
        "'point'"
    )


# --------------------------------------------------------------------------
# point helpers
#
# extended point: [4, 20, *batch] stack of (X, Y, Z, T), x = X/Z, y = Y/Z,
#                 T = XY/Z
# cached point:   [4, 20, *batch] stack of (Y+X, Y-X, 2d*T, 2Z) — the
#                 precomputed operand form of add-2008-hwcd
# niels point:    [3, 20, *batch] stack of (y+x, y-x, 2d*x*y) — cached
#                 with Z = 1, so the 2Z slot is the constant 2


def pt_stack(x, y, z, t):
    return jnp.stack([x, y, z, t], axis=0)


def pt_identity(batch_shape=()):
    return pt_stack(
        fe_const(0, batch_shape),
        fe_const(1, batch_shape),
        fe_const(1, batch_shape),
        fe_const(0, batch_shape),
    )


def _mul_many(pairs):
    """K independent field multiplies as ONE wide multiply.

    A TPU core executes the post-fusion op sequence serially, so K
    narrow multiplies cost ~K times one wide one; concatenating the
    operands along the minor (lane) axis turns them into a single
    K-times-wider op at the same lane-op count. All operands must share
    one shape [20, *batch]."""
    k = len(pairs)
    if k == 1 or not _GROUP_OPS:
        return [fe_mul(a, b) for a, b in pairs]
    n = pairs[0][0].shape[-1]
    a = jnp.concatenate([p[0] for p in pairs], axis=-1)
    b = jnp.concatenate([p[1] for p in pairs], axis=-1)
    c = fe_mul(a, b)
    return [c[..., i * n : (i + 1) * n] for i in range(k)]


def _square_many(xs):
    """K independent field squarings as ONE wide squaring (see
    _mul_many)."""
    if len(xs) == 1 or not _GROUP_OPS:
        return [fe_square(x) for x in xs]
    n = xs[0].shape[-1]
    c = fe_square(jnp.concatenate(xs, axis=-1))
    return [c[..., i * n : (i + 1) * n] for i in range(len(xs))]


def pt_to_cached(p):
    """extended -> cached: 1M + 3 add."""
    x, y, z, t = p[0], p[1], p[2], p[3]
    return jnp.stack(
        [fe_add(y, x), fe_sub(y, x), fe_mul(t, fe_const(D2)), fe_add(z, z)],
        axis=0,
    )


def pt_add_cached(p, q_cached):
    """Complete unified addition, q in cached form: 8M (2 wide ops)."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    ypx2, ymx2, t2d2, z22 = q_cached[0], q_cached[1], q_cached[2], q_cached[3]
    a, b, c, d = _mul_many(
        [(fe_sub(y1, x1), ymx2), (fe_add(y1, x1), ypx2), (t1, t2d2), (z1, z22)]
    )
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    x3, y3, z3, t3 = _mul_many([(e, f), (g, h), (f, g), (e, h)])
    return pt_stack(x3, y3, z3, t3)


def pt_add_mixed(p, q_niels):
    """Complete unified addition, q in niels form (Z2 = 1): 7M."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    ypx2, ymx2, t2d2 = q_niels[0], q_niels[1], q_niels[2]
    a, b, c = _mul_many(
        [(fe_sub(y1, x1), ymx2), (fe_add(y1, x1), ypx2), (t1, t2d2)]
    )
    d = fe_add(z1, z1)
    e = fe_sub(b, a)
    f = fe_sub(d, c)
    g = fe_add(d, c)
    h = fe_add(b, a)
    x3, y3, z3, t3 = _mul_many([(e, f), (g, h), (f, g), (e, h)])
    return pt_stack(x3, y3, z3, t3)


def pt_add(p, q):
    """Complete unified addition, both extended: 9M (one-off uses)."""
    return pt_add_cached(p, pt_to_cached(q))


def pt_double(p, need_t: bool = True):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4S + 4M, or 4S + 3M
    with ``need_t=False`` — T is consumed only by ADDITIONS, so every
    doubling except the last of a consecutive chain can skip the E*H
    multiply (the doubling itself reads just X/Y/Z). The T slot of a
    ``need_t=False`` result is a placeholder and must not be read."""
    x1, y1, z1 = p[0], p[1], p[2]
    a, b, zz, sq = _square_many([x1, y1, z1, fe_add(x1, y1)])
    c = fe_add(zz, zz)
    e = fe_sub(fe_sub(sq, a), b)
    g = fe_sub(b, a)  # a_coeff=-1: G = aA + B = B - A
    f = fe_sub(g, c)  # F = G - C
    h = fe_sub(fe_neg(a), b)  # H = aA - B = -A - B
    if need_t:
        x3, y3, z3, t3 = _mul_many([(e, f), (g, h), (f, g), (e, h)])
    else:
        x3, y3, z3 = _mul_many([(e, f), (g, h), (f, g)])
        t3 = z3  # placeholder, never read (any bounded value works)
    return pt_stack(x3, y3, z3, t3)


def pt_neg(p):
    return pt_stack(fe_neg(p[0]), p[1], p[2], fe_neg(p[3]))


def pt_encode_words(p):
    """-> [8, *batch] uint32 LE words of the canonical compressed encoding."""
    zi = fe_invert(p[2])
    x = fe_reduce_full(fe_mul(p[0], zi))
    y = fe_reduce_full(fe_mul(p[1], zi))
    words = limbs_to_words_le(y)
    sign = (x[0] & 1).astype(jnp.uint32)
    # concatenate, not .at[7].set — a scatter has no Mosaic lowering,
    # and this function is shared with the Pallas kernel
    return jnp.concatenate(
        [words[:7], (words[7] | (sign << 31))[None]], axis=0
    )


# --------------------------------------------------------------------------
# decompression


def pt_decompress(words_u32):
    """[8, *batch] u32 LE encoding -> (point [4, 20, *batch], valid [*batch])."""
    y = limbs_from_words_le(words_u32, mask_high=True)
    sign = (words_u32[7] >> 31).astype(jnp.int32)
    y2 = fe_square(y)
    u = fe_sub(y2, fe_const(1))
    v = fe_add(fe_mul(y2, fe_const(D)), fe_const(1))
    v3 = fe_mul(fe_square(v), v)
    v7 = fe_mul(fe_square(v3), v)
    x = fe_mul(fe_mul(u, v3), fe_pow_p58(fe_mul(u, v7)))
    vxx = fe_mul(fe_square(x), v)
    ok1 = fe_eq(vxx, u)
    ok2 = fe_eq(vxx, fe_neg(u))
    x = fe_select(ok1, x, fe_mul(x, fe_const(SQRT_M1)))
    valid = ok1 | ok2
    x_zero = fe_is_zero(x)
    valid = valid & ~(x_zero & (sign == 1))
    flip = fe_is_odd(x) != (sign == 1)
    x = fe_select(flip, fe_neg(x), x)
    point = pt_stack(x, y, fe_const(1, x.shape[1:]), fe_mul(x, y))
    return point, valid


# --------------------------------------------------------------------------
# per-element cached table of 0..8 multiples (for signed 4-bit windows)


def _build_cached_table(p):
    """p extended [4, 20, *batch] -> [9, 4, 20, *batch] cached multiples
    0..8P.

    4 doublings + 3 cached adds + 8 cached conversions; the doubling-
    based ladder keeps the dependency chain at 4 instead of 14."""
    batch = p.shape[2:]
    ident = jnp.stack(
        [
            fe_const(1, batch),
            fe_const(1, batch),
            fe_const(0, batch),
            fe_const(2, batch),
        ],
        axis=0,
    )
    m1 = p
    c1 = pt_to_cached(m1)
    m2 = pt_double(m1)
    c2 = pt_to_cached(m2)
    m3 = pt_add_cached(m2, c1)
    c3 = pt_to_cached(m3)
    m4 = pt_double(m2)
    c4 = pt_to_cached(m4)
    m5 = pt_add_cached(m4, c1)
    m6 = pt_double(m3)
    m7 = pt_add_cached(m6, c1)
    m8 = pt_double(m4)
    cached = [ident, c1, c2, c3, c4] + [pt_to_cached(m) for m in (m5, m6, m7, m8)]
    return jnp.stack(cached, axis=0)


def _build_cached_table_signed(p):
    """p extended [4, 20, *batch] -> [17, 4, 20, *batch] cached multiples
    for signed digits -8..8 (index d + 8).

    Baking the negative entries into the table (cached-form negation:
    swap Y+X/Y-X, negate 2dT) lets the per-window selection be one plain
    one-hot contraction with no post-selection fixups — which in turn
    lets ALL 64 window selections hoist out of the scalar-walk loop as a
    single contraction."""
    pos = _build_cached_table(p)  # [9, 4, 20, *batch], digits 0..8
    negs = [
        jnp.stack([pos[k, 1], pos[k, 0], fe_neg(pos[k, 2]), pos[k, 3]], axis=0)
        for k in range(8, 0, -1)
    ]  # digits -8..-1
    return jnp.concatenate([jnp.stack(negs, axis=0), pos], axis=0)


def _select_cached(tbl, digit):
    """tbl [9, 4, 20, *batch], digit [*batch] int32 in [-8, 7] -> cached
    entry [4, 20, *batch].

    |digit| selects by one-hot contraction (no gathers); a negative digit
    swaps (Y+X)/(Y-X) and negates 2dT — point negation in cached form.
    The one-hot is built with broadcasted_iota so this exact function is
    shared by the Pallas kernel (TPU Pallas rejects 1-D iota)."""
    mag = jnp.abs(digit)
    neg = digit < 0
    sel = lax.broadcasted_iota(mag.dtype, (9,) + mag.shape, 0)
    onehot = (mag[None] == sel).astype(jnp.int32)  # [9, *batch]
    entry = jnp.sum(onehot[:, None, None] * tbl, axis=0)  # [4, 20, *batch]
    ypx, ymx, t2d, z2 = entry[0], entry[1], entry[2], entry[3]
    return jnp.stack(
        [
            fe_select(neg, ymx, ypx),
            fe_select(neg, ypx, ymx),
            fe_select(neg, fe_neg(t2d), t2d),
            z2,
        ],
        axis=0,
    )


def decompress_inputs(aw, rw):
    """Decompress the public key — and, in point-check mode, R too,
    STACKED along the batch into ONE double-width sqrt chain (same wide-
    op count as one chain). -> (a_point, r_point|None, valid,
    r_canonical|None); shared by the XLA and Pallas kernels."""
    if _VERIFY_CHECK == "point":
        # stack on a NEW axis (batch shape (2, B)), not along the batch:
        # lane i of A and R stay together, so a batch-sharded meshed
        # kernel keeps device locality (no resharding collectives
        # around the double-width chain)
        both = jnp.stack([aw, rw], axis=1)  # [8, 2, B]
        pts, valids = pt_decompress(both)  # [4, 20, 2, B], [2, B]
        a_point, r_point = pts[:, :, 0], pts[:, :, 1]
        valid = valids[0] & valids[1]
        # byte-compare implicitly rejects non-canonical R encodings
        # (encode emits canonical y); the point check must do so
        # explicitly: y_r (sign bit already masked by the decoder's
        # view) must be < p
        r_canon = limbs_lt_p(limbs_from_words_le(rw))
        return a_point, r_point, valid, r_canon
    a_point, a_valid = pt_decompress(aw)
    return a_point, None, a_valid, None


def final_check(rp, rw, r_point, valid, r_canon, s_canonical):
    """Verdict for P3 = [S]B + [h](-A) against R (shared by both
    kernels). bytes: encode-and-compare (ref10 crypto_sign_open shape).
    point: projective equality against the decompressed R (whose Z is
    1): X3 == Xr*Z3 and Y3 == Yr*Z3 — no inversion chain. Sign-bit
    equivalence holds because decompression flips x to match the sign
    bit (distinct sign bits decode to distinct points for x != 0, and
    x=0 with sign=1 is rejected as invalid — exactly the encodings the
    byte compare would reject)."""
    if _VERIFY_CHECK == "point":
        ex = fe_eq(rp[0], fe_mul(r_point[0], rp[2]))
        ey = fe_eq(rp[1], fe_mul(r_point[1], rp[2]))
        return ex & ey & valid & r_canon & s_canonical
    enc = pt_encode_words(rp)
    eq = jnp.all(enc == rw, axis=0)
    return eq & valid & s_canonical


def comb_select_vpu(tj, w):
    """Comb window entry select: [60, 16] table x [*batch] digits ->
    [3, 20, *batch] niels entry as ONE exact int32 one-hot contraction
    on the VPU (no int<->float converts). Shared by the XLA kernel's
    vpu comb strategy and the Pallas kernel (whose lowering rejects 1-D
    iota, hence broadcasted_iota)."""
    sel = lax.broadcasted_iota(w.dtype, (16,) + w.shape, 0)
    onehot = (w[None] == sel).astype(jnp.int32)  # [16, *batch]
    picked = jnp.sum(
        tj.astype(jnp.int32)[:, :, None] * onehot[None, :, :], axis=1
    )
    return picked.reshape((3, NLIMB) + w.shape)


# --------------------------------------------------------------------------
# fixed-base comb table for B (host-side, Python ints, computed once)

_COMB_NP: np.ndarray | None = None


def _comb_table_np() -> np.ndarray:
    """[NWINDOWS, 60, 16] f32: column (j, :, w) = niels form
    (y+x, y-x, 2dxy) of (w * 16^j) * B, laid out limb-major so
    table[j] @ onehot[16, B] lands directly in [60, B]. f32 is exact for
    13-bit limbs and routes the one-hot selection through the MXU."""
    global _COMB_NP
    if _COMB_NP is None:
        out = np.zeros((NWINDOWS, 16, 3, NLIMB), np.int32)
        step = ref.BASE  # 16^j * B
        for j in range(NWINDOWS):
            acc = ref.IDENTITY
            for w in range(16):
                x, y, z, _t = acc
                zi = pow(z, P - 2, P)
                xa, ya = x * zi % P, y * zi % P
                out[j, w, 0] = int_to_limbs_np((ya + xa) % P)
                out[j, w, 1] = int_to_limbs_np((ya - xa) % P)
                out[j, w, 2] = int_to_limbs_np(D2 * xa % P * ya % P)
                acc = ref.pt_add(acc, step)
            for _ in range(4):
                step = ref.pt_double(step)
        # [j, w, 3*20] -> [j, 3*20, w] so the in-loop matmul is [60,16]@[16,B]
        _COMB_NP = (
            out.reshape(NWINDOWS, 16, 3 * NLIMB)
            .transpose(0, 2, 1)
            .astype(np.float32)
            .copy()
        )
    return _COMB_NP


def _batch_zero(ref_arr):
    """[1, 1, B] int32 zero carrying the batch 'varying' tag of ref_arr
    ([64, B]), so fori_loop carries seeded from constants stay
    shard_map-compatible."""
    return (ref_arr[:1] * 0)[None]


# --------------------------------------------------------------------------
# the batched verify kernel


@jax.jit
def verify_kernel(a_words, r_words, s_windows, h_digits, s_canonical):
    """Batched core: all inputs leading dim B (public layout; transposed
    to the limb-major internal layout on entry).

    a_words: [B, 8] u32 public keys (LE words)
    r_words: [B, 8] u32 signature R
    s_windows: [B, 64] int32/int8 unsigned 4-bit windows of S (LSB first)
    h_digits: [B, 64] int32/int8 SIGNED 4-bit digits of h in [-8, 7]
        (LSB first)
    s_canonical: [B] bool (S < l, checked host-side)
    -> [B] bool

    The digit arrays may arrive narrow (int8 — prepare_batch's digit
    wire: 4-bit values in int32 tripled the host->device transfer for
    nothing) or as RAW [B, 32] scalar bytes (the default wire — half
    the transfer again); both widen/expand here, ON DEVICE, before use.
    """
    s_windows, h_digits = _maybe_expand_wire(s_windows, h_digits)
    aw = jnp.transpose(a_words)  # [8, B]
    rw = jnp.transpose(r_words)
    sw = jnp.transpose(s_windows).astype(jnp.int32)  # [64, B]
    hd = jnp.transpose(h_digits).astype(jnp.int32)

    a_point, r_point, valid, r_canon = decompress_inputs(aw, rw)
    comb = jnp.asarray(_comb_table_np())  # [64, 60, 16] f32

    def comb_entry(tj, w):
        """Select comb window entries for digits w: [60,16] x [B] ->
        [3, 20, B] int32 (strategy per _COMB_SELECT, see header)."""
        if _COMB_SELECT == "vpu":
            return comb_select_vpu(tj, w)
        onehot = (
            w[None, :] == jnp.arange(16, dtype=w.dtype)[:, None]
        ).astype(jnp.float32)  # [16, B]
        if _COMB_SELECT == "mxu_split":
            # limb halves are bf16-exact (<= 127 / <= 63), so two
            # DEFAULT-precision (single-pass) matmuls are exact
            tji = tj.astype(jnp.int32)
            lo = (tji & 0x7F).astype(jnp.float32)
            hi = (tji >> 7).astype(jnp.float32)
            sel_lo = jnp.matmul(lo, onehot).astype(jnp.int32)
            sel_hi = jnp.matmul(hi, onehot).astype(jnp.int32)
            return ((sel_hi << 7) + sel_lo).reshape((3, NLIMB) + w.shape)
        # default "mxu": HIGHEST precision — default-precision TPU
        # matmuls truncate f32 operands to bf16 (8-bit mantissa), which
        # corrupts 13-bit limbs; the 3-pass f32 form is exact
        return (
            jnp.matmul(tj, onehot, precision=lax.Precision.HIGHEST)
            .astype(jnp.int32)
            .reshape((3, NLIMB) + w.shape)
        )

    if _HOIST_SELECT:
        # Hoisted window selections: ALL 64 windows of both scalar walks
        # selected before the loop in two wide contractions, so the loop
        # body is pure point arithmetic. Measured on-chip (r4) this
        # LOSES — the [64, ., 20, B] selected-window tensors live in HBM
        # and the loop re-reads them — but the knob stays for A/B.
        htbl = _build_cached_table_signed(pt_neg(a_point))  # [17,4,20,B]
        onehot_h = (
            hd[:, None, :]
            == (jnp.arange(17, dtype=hd.dtype) - 8)[None, :, None]
        ).astype(jnp.int32)  # [64, 17, B]
        hsel = jnp.einsum("wsb,scdb->wcdb", onehot_h, htbl)  # [64,4,20,B]
        # [S]B comb windows in one wide contraction (all 64 at once):
        if _COMB_SELECT == "vpu":
            onehot_i = (
                sw[:, None, :]
                == jnp.arange(16, dtype=sw.dtype)[None, :, None]
            ).astype(jnp.int32)  # [64, 16, B]
            csel = jnp.einsum(
                "jlw,jwb->jlb", comb.astype(jnp.int32), onehot_i
            )
        else:
            onehot_s = (
                sw[:, None, :]
                == jnp.arange(16, dtype=sw.dtype)[None, :, None]
            ).astype(jnp.float32)  # [64, 16, B]
            if _COMB_SELECT == "mxu_split":
                comb_i = comb.astype(jnp.int32)
                lo = (comb_i & 0x7F).astype(jnp.float32)
                hi = (comb_i >> 7).astype(jnp.float32)
                sel_lo = jnp.einsum(
                    "jlw,jwb->jlb", lo, onehot_s
                ).astype(jnp.int32)
                sel_hi = jnp.einsum(
                    "jlw,jwb->jlb", hi, onehot_s
                ).astype(jnp.int32)
                csel = (sel_hi << 7) + sel_lo
            else:
                csel = jnp.einsum(
                    "jlw,jwb->jlb",
                    comb,
                    onehot_s,
                    precision=lax.Precision.HIGHEST,
                ).astype(jnp.int32)
        csel = csel.reshape(
            (NWINDOWS, 3, NLIMB) + sw.shape[1:]
        )  # [64, 3, 20, B]
    else:
        htbl = _build_cached_table(pt_neg(a_point))  # [9, 4, 20, B]
        hsel = csel = None

    zero = _batch_zero(sw)
    acc0_h = pt_identity(sw.shape[1:]) + zero
    acc0_s = pt_identity(sw.shape[1:]) + zero

    def body(j, accs):
        acc_h, acc_s = accs
        # [h](-A): MSB-first windows, 4 doublings + 1 cached add
        for i in range(WINDOW):
            # only the add after the chain reads T: skip its multiply
            # on all but the last doubling (saves 3 of ~34 muls/window)
            acc_h = pt_double(acc_h, need_t=(i == WINDOW - 1))
        if _HOIST_SELECT:
            hs = lax.dynamic_index_in_dim(
                hsel, NWINDOWS - 1 - j, axis=0, keepdims=False
            )
            cs = lax.dynamic_index_in_dim(csel, j, axis=0, keepdims=False)
        else:
            d = lax.dynamic_index_in_dim(
                hd, NWINDOWS - 1 - j, axis=0, keepdims=False
            )
            hs = _select_cached(htbl, d)
            tj = lax.dynamic_index_in_dim(comb, j, axis=0, keepdims=False)
            w = lax.dynamic_index_in_dim(sw, j, axis=0, keepdims=False)
            cs = comb_entry(tj, w)
        acc_h = pt_add_cached(acc_h, hs)
        # [S]B: comb window j, mixed add of the selected entry
        acc_s = pt_add_mixed(acc_s, cs)
        return acc_h, acc_s

    if _UNROLL > 1:
        acc_h, acc_s = lax.fori_loop(
            0, NWINDOWS, body, (acc0_h, acc0_s), unroll=_UNROLL
        )
    else:
        acc_h, acc_s = lax.fori_loop(0, NWINDOWS, body, (acc0_h, acc0_s))
    rp = pt_add_cached(acc_s, pt_to_cached(acc_h))
    return final_check(rp, rw, r_point, valid, r_canon, s_canonical)


# --------------------------------------------------------------------------
# host-side preparation

_L_BYTES = np.frombuffer(L.to_bytes(32, "little"), np.uint8)
_NATIVE_PREP = None
_NATIVE_PREP_TRIED = False


def _native_prep():
    """Cached native host-prep kernel, or None when unavailable."""
    global _NATIVE_PREP, _NATIVE_PREP_TRIED
    if not _NATIVE_PREP_TRIED:
        _NATIVE_PREP_TRIED = True
        try:
            from ..native import Ed25519HostPrep

            _NATIVE_PREP = Ed25519HostPrep()
        except Exception:
            _NATIVE_PREP = None
    return _NATIVE_PREP


def expand_s_windows(s_bytes):
    """ON-DEVICE wire expansion: [B, 32] u8 LE scalar bytes -> [B, 64]
    int32 unsigned 4-bit windows (LSB first). The raw-bytes wire halves
    the host->device transfer of this leg vs shipping digit arrays —
    the tunnel's scarce resource — at the cost of two trivial vector
    ops on device."""
    lo = (s_bytes & 0xF).astype(jnp.int32)
    hi = (s_bytes >> 4).astype(jnp.int32)
    return jnp.stack([lo, hi], axis=-1).reshape(s_bytes.shape[0], 64)


def expand_h_digits(h_bytes):
    """ON-DEVICE signed-digit recode: [B, 32] u8 LE scalar bytes ->
    [B, 64] int32 signed digits in [-8, 7] (LSB first), matching
    _signed_digits_le bit-for-bit. The sequential carry ripple becomes
    a log2(64)=6-step generate/propagate associative scan: with
    carry<=1, carry_out(i) = g_i | (p_i & carry_in(i)) where
    g_i = nib_i >= 8, p_i = nib_i == 7. Valid for scalars < 2^253
    (same contract as the host recode)."""
    nib = expand_s_windows(h_bytes)  # [B, 64] in [0, 15]
    g = nib >= 8
    p = nib == 7

    def combine(a, b):
        # a = (G, P) of the earlier prefix, b of the later: composing
        # c -> gb | pb & (ga | pa & c) = (gb | pb&ga) | (pb&pa) & c
        ga, pa = a
        gb, pb = b
        return (gb | (pb & ga), pb & pa)

    G, _ = lax.associative_scan(combine, (g, p), axis=1)
    carry_in = jnp.concatenate(
        [jnp.zeros_like(G[:, :1]), G[:, :-1]], axis=1
    ).astype(jnp.int32)
    v = nib + carry_in
    return v - ((v >= 8).astype(jnp.int32) << 4)


def _maybe_expand_wire(s_windows, h_digits):
    """Accept either wire format: legacy [B, 64] digit arrays pass
    through; raw [B, 32] byte arrays expand on device."""
    s_windows = jnp.asarray(s_windows)
    h_digits = jnp.asarray(h_digits)
    if s_windows.shape[-1] == 32:
        s_windows = expand_s_windows(s_windows)
    if h_digits.shape[-1] == 32:
        h_digits = expand_h_digits(h_digits)
    return s_windows, h_digits


def _nibbles_le(b: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 LE scalar bytes -> [B, 64] int8 4-bit windows,
    LSB window first. int8 is the WIRE dtype (the kernel widens on
    device): 4-bit values shipped as int32 made the host->device
    transfer — the tunnel's scarce resource — 3x larger for nothing."""
    lo = b & 0xF
    hi = b >> 4
    return np.stack([lo, hi], axis=-1).reshape(b.shape[0], 64).astype(np.int8)


def _signed_digits_le(b: np.ndarray) -> np.ndarray:
    """[B, 32] uint8 LE scalar bytes -> [B, 64] int8 signed 4-bit digits
    in [-8, 7], LSB first (int8 is the wire dtype, inherited from
    _nibbles_le; the kernel widens on device). Valid for scalars < 2^253
    (top digit + final carry stays < 8, so no 65th digit is needed)."""
    nib = _nibbles_le(b)
    out = np.empty_like(nib)
    carry = np.zeros(nib.shape[0], np.int32)
    for i in range(64):
        v = nib[:, i] + carry
        ge = v >= 8
        out[:, i] = v - (ge << 4)
        carry = ge.astype(np.int32)
    return out


def prepare_batch(publics, messages, signatures, device_put: bool = True):
    """Host prep: pack keys/sigs, compute h = SHA512(R||A||M) mod l and the
    digit decompositions. Returns dict of arrays for verify_kernel.

    Fully vectorized: byte packing / window extraction / canonical checks
    are numpy over the whole batch; the SHA-512 + mod-l per-signature work
    runs in one threaded native call (native/src/ed25519_host.cc), with a
    hashlib+bigint fallback when the native library is unavailable.
    """
    B = len(publics)
    # sanitize malformed entries to zero-filled rows; s_canonical stays
    # False for them so verification fails without branching later
    bad = [
        i
        for i, (pk, sig) in enumerate(zip(publics, signatures))
        if len(pk) != 32 or len(sig) != 64
    ]
    if bad:
        publics = list(publics)
        signatures = list(signatures)
        for i in bad:
            publics[i] = b"\x00" * 32
            signatures[i] = b"\x00" * 64
    pk_packed = b"".join(publics)
    sig_arr = np.frombuffer(b"".join(signatures), np.uint8).reshape(B, 64)
    a_words = np.frombuffer(pk_packed, np.uint8).reshape(B, 32)
    a_words = np.ascontiguousarray(a_words).view("<u4").astype(np.uint32)
    r_bytes = np.ascontiguousarray(sig_arr[:, :32])
    s_bytes = np.ascontiguousarray(sig_arr[:, 32:])
    r_words = r_bytes.view("<u4").astype(np.uint32)

    # canonical S < l: lexicographic compare from the most significant byte
    rev_diff = (s_bytes != _L_BYTES)[:, ::-1]
    any_diff = rev_diff.any(axis=1)
    msb = 31 - np.argmax(rev_diff, axis=1)
    s_canonical = any_diff & (s_bytes[np.arange(B), msb] < _L_BYTES[msb])
    if bad:
        s_canonical[bad] = False

    native = _native_prep()
    if native is not None:
        h_scalars = native.h_batch(r_bytes.tobytes(), pk_packed, messages, B)
    else:
        h_scalars = np.empty((B, 32), np.uint8)
        r_packed = r_bytes.tobytes()
        for i, (pk, msg) in enumerate(zip(publics, messages)):
            h = int.from_bytes(
                hashlib.sha512(r_packed[32 * i : 32 * i + 32] + pk + msg).digest(),
                "little",
            ) % L
            h_scalars[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)

    # wire format (host->device transfer is the tunnel's scarce
    # resource): "raw" ships the 32-byte S and h scalars and the kernel
    # expands windows/signed digits on device (129 B/sig total); "digits"
    # ships the precomputed [B, 64] int8 arrays (193 B/sig — the r4 form,
    # kept for A/B and for consumers that inspect digits host-side)
    if os.environ.get("STELLARD_WIRE", "raw") == "digits":
        s_windows = _nibbles_le(s_bytes)
        h_digits = _signed_digits_le(h_scalars)
    else:
        s_windows = s_bytes
        h_digits = h_scalars

    put = jnp.asarray if device_put else (lambda x: x)
    return dict(
        a_words=put(a_words),
        r_words=put(r_words),
        s_windows=put(s_windows),
        h_digits=put(h_digits),
        s_canonical=put(s_canonical),
    )


def verify_batch(publics, messages, signatures) -> np.ndarray:
    """End-to-end batched verification -> [B] bool numpy array."""
    inputs = prepare_batch(publics, messages, signatures)
    return np.asarray(verify_kernel(**inputs))


def verify_stream(batches, kernel=None):
    """Double-buffered end-to-end verification over an iterable of
    (publics, messages, signatures) tuples.

    JAX dispatch is asynchronous, so the host prep (native SHA-512 +
    mod-l + numpy packing) of batch i+1 runs while the device executes
    batch i — the steady-state pipeline the round-1 bench only asserted.
    Yields [B] bool numpy arrays in submission order. ``kernel``
    defaults to this module's XLA verify_kernel; pass e.g. the Pallas
    implementation to pipeline that one instead.
    """
    if kernel is None:
        kernel = verify_kernel
    pending = None
    for batch in batches:
        inputs = prepare_batch(*batch)
        out = kernel(**inputs)  # async dispatch
        if pending is not None:
            yield np.asarray(pending)  # blocks on batch i-1 only
        pending = out
    if pending is not None:
        yield np.asarray(pending)
