"""Pallas TPU Ed25519 batch-verify: the whole verification fused in VMEM.

Why this exists (measured, PERF.md r4): the XLA formulation's cost tracks
its op COUNT, not its FLOPs — at production batches every [20, B]
intermediate is megabytes, so the op sequence streams HBM between fusion
clusters, and both "fewer, wider ops" transforms that added data movement
(grouped point ops, hoisted window selects) measured SLOWER. The logical
endpoint of that finding is to stop paying per-op data movement at all:
process the batch in blocks whose entire working set (accumulators,
cached table, comb table, every field-op temporary) stays resident in
VMEM for the whole verification, with HBM touched only for the kernel's
true input/output (~600 B per signature).

Same math as ops.ed25519_jax.verify_kernel — rowpad fe ops (fe25519),
9-entry cached table + signed 4-bit windows for [h](-A), fixed-base comb
for [S]B — via the same helpers, so the differential oracle suite pins
both. Selection one-hots are built with broadcasted_iota (TPU Pallas
rejects 1-D iota). The comb select runs as an int32 VPU contraction
(exact; no f32 precision carve-outs needed inside the kernel).

Reference role: the batched replacement for libsodium
crypto_sign_verify_detached in SerializedTransaction::checkSign
(/root/reference/src/ripple_app/misc/SerializedTransaction.cpp:192-230).

Knobs (read at import, like the XLA kernel's):
  STELLARD_PALLAS_BLOCK — batch lanes per grid step (default 512).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from .ed25519_jax import (
    NWINDOWS,
    WINDOW,
    _build_cached_table,
    _comb_table_np,
    _select_cached,
    comb_select_vpu,
    decompress_inputs,
    final_check,
    pt_add_cached,
    pt_add_mixed,
    pt_double,
    pt_identity,
    pt_neg,
    pt_to_cached,
)
from .fe25519 import NLIMB, const_mode, const_table_np

BLOCK = int(os.environ.get("STELLARD_PALLAS_BLOCK", "512"))


def _verify_block(aw, rw, sw, hd, sc, comb, window_loader=None):
    """One VMEM-resident block: aw/rw [8, B] u32, sw/hd [64, B] i32,
    sc [B] i32, comb [64, 60, 16] i32 -> [B] i32 verdicts.

    ``window_loader(j) -> (d [B], tj [60, 16], w [B])`` supplies window
    j's inputs inside the scalar-walk loop. The default indexes the
    VALUES (plain XLA trace; used by the collect trace and tests); the
    Pallas kernel passes a ref-based loader (with sw/hd/comb None so no
    dead full-block loads are traced) because Mosaic has no lowering
    for dynamic_slice on values — dynamic indexing must go through the
    VMEM refs."""
    a_point, r_point, valid, r_canon = decompress_inputs(aw, rw)
    htbl = _build_cached_table(pt_neg(a_point))  # [9, 4, 20, B]

    if window_loader is None:
        assert sw is not None and hd is not None and comb is not None

        def window_loader(j):
            d = lax.dynamic_index_in_dim(
                hd, NWINDOWS - 1 - j, 0, keepdims=False
            )
            tj = lax.dynamic_index_in_dim(comb, j, 0, keepdims=False)
            w = lax.dynamic_index_in_dim(sw, j, 0, keepdims=False)
            return d, tj, w

    # pt_identity broadcasts its constants to a concrete [4, 20, B]
    acc0_h = pt_identity(aw.shape[1:])
    acc0_s = pt_identity(aw.shape[1:])

    def body(j, accs):
        acc_h, acc_s = accs
        for i in range(WINDOW):
            # T is only read by the add after the chain (see pt_double)
            acc_h = pt_double(acc_h, need_t=(i == WINDOW - 1))
        d, tj, w = window_loader(j)
        acc_h = pt_add_cached(acc_h, _select_cached(htbl, d))
        acc_s = pt_add_mixed(acc_s, comb_select_vpu(tj, w))
        return acc_h, acc_s

    acc_h, acc_s = lax.fori_loop(0, NWINDOWS, body, (acc0_h, acc0_s))
    rp = pt_add_cached(acc_s, pt_to_cached(acc_h))
    return final_check(
        rp, rw, r_point, valid, r_canon, sc != 0
    ).astype(jnp.int32)


def _kernel(aw_ref, rw_ref, sw_ref, hd_ref, sc_ref, comb_ref, ktab_ref,
            out_ref):
    # consume mode: every fe25519 [20]-limb constant the math touches is
    # served as a row of the ktab input (Pallas cannot capture array
    # constants); the collect trace in _ensure_const_table guarantees
    # the table is complete before this kernel ever traces.
    def ref_loader(j):
        d = hd_ref[pl.ds(NWINDOWS - 1 - j, 1), :][0]
        tj = comb_ref[pl.ds(j, 1), :, :][0]
        w = sw_ref[pl.ds(j, 1), :][0]
        return d, tj, w

    with const_mode("consume", ktab_ref[:]):
        out = _verify_block(
            aw_ref[:],
            rw_ref[:],
            None,  # sw/hd/comb only feed the default loader; passing
            None,  # the values would trace dead full-block loads
            sc_ref[0, :],
            None,
            window_loader=ref_loader,
        )
    out_ref[0, :] = out


@functools.partial(jax.jit, static_argnames=("interpret", "nconst"))
def _call(aw, rw, sw, hd, sc, comb, ktab, *, interpret: bool, nconst: int):
    bp = aw.shape[1]
    grid = bp // BLOCK
    blk = lambda rows: pl.BlockSpec((rows, BLOCK), lambda i: (0, i))
    return pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            blk(8),
            blk(8),
            blk(64),
            blk(64),
            blk(1),
            pl.BlockSpec((NWINDOWS, 60, 16), lambda i: (0, 0, 0)),
            pl.BlockSpec((nconst, NLIMB), lambda i: (0, 0)),
        ],
        out_specs=blk(1),
        out_shape=jax.ShapeDtypeStruct((1, bp), jnp.int32),
        interpret=interpret,
    )(aw, rw, sw, hd, sc, comb, ktab)


_COMB_I32: np.ndarray | None = None
_KTAB: np.ndarray | None = None
_TRACE_LOCK = __import__("threading").Lock()


def _comb_i32() -> np.ndarray:
    global _COMB_I32
    if _COMB_I32 is None:
        _COMB_I32 = _comb_table_np().astype(np.int32)
    return _COMB_I32


def _ensure_const_table() -> np.ndarray:
    """Collect-trace the block math once to enumerate every fe25519
    constant, then freeze them as the [K, 20] kernel input. Caller must
    hold _TRACE_LOCK."""
    global _KTAB
    if _KTAB is None:
        with const_mode("collect"):
            jax.eval_shape(
                _verify_block,
                jax.ShapeDtypeStruct((8, BLOCK), jnp.uint32),
                jax.ShapeDtypeStruct((8, BLOCK), jnp.uint32),
                jax.ShapeDtypeStruct((NWINDOWS, BLOCK), jnp.int32),
                jax.ShapeDtypeStruct((NWINDOWS, BLOCK), jnp.int32),
                jax.ShapeDtypeStruct((BLOCK,), jnp.int32),
                jax.ShapeDtypeStruct((NWINDOWS, 60, 16), jnp.int32),
            )
            _KTAB = const_table_np()
    return _KTAB


def verify_kernel_pallas(a_words, r_words, s_windows, h_digits, s_canonical):
    """Drop-in for ed25519_jax.verify_kernel (same prepare_batch inputs,
    public batch-major layout) running the Pallas block kernel."""
    from .ed25519_jax import _maybe_expand_wire

    # raw-bytes wire expands in an XLA prologue on device; the Pallas
    # grid kernel always sees the [B, 64] digit arrays
    s_windows, h_digits = _maybe_expand_wire(s_windows, h_digits)
    a_words = jnp.asarray(a_words)
    b = a_words.shape[0]
    bp = -(-b // BLOCK) * BLOCK
    pad = bp - b

    def prep(x, dtype):
        x = jnp.asarray(x)
        if pad:
            x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
        x = x.T if x.ndim == 2 else x[None, :]
        return x.astype(dtype)

    # _TRACE_LOCK spans the collect trace AND the _call invocation: the
    # first call per padded shape traces the Pallas kernel, whose
    # consume-mode const_mode mutates fe25519's process-global mode —
    # concurrent unlocked traces could restore the mode mid-trace and
    # reintroduce captured-constant lowering errors. Execution also runs
    # under the lock, which is moot: device calls are serialized by the
    # plane's single flusher thread anyway.
    with _TRACE_LOCK:
        ktab = _ensure_const_table()
        out = _call(
            prep(a_words, jnp.uint32),
            prep(r_words, jnp.uint32),
            prep(s_windows, jnp.int32),
            prep(h_digits, jnp.int32),
            prep(s_canonical, jnp.int32),
            jnp.asarray(_comb_i32()),
            jnp.asarray(ktab),
            interpret=jax.default_backend() == "cpu",
            nconst=ktab.shape[0],
        )
    return out[0, :b].astype(bool)
