"""Pure-Python Ed25519 reference (big ints) — oracle for the JAX kernels
and generator for the fixed-base comb table.

Implements the same verification equation as libsodium's 2014-era
crypto_sign_verify_detached used by the reference
(/root/reference/src/ripple_data/crypto/StellarPublicKey.cpp:67-77):
R' = [S]B + [h](-A), accept iff encode(R') == R_bytes, with h =
SHA512(R || A || M) mod l. Written from the RFC 8032 / curve equations,
not ported code.
"""

from __future__ import annotations

import hashlib

P = (1 << 255) - 19
L = (1 << 252) + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# base point
_BY = (4 * pow(5, P - 2, P)) % P
_BX = None  # recovered below


def _recover_x(y: int, sign: int) -> int | None:
    y2 = (y * y) % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    # candidate root of u/v via (u/v)^((p+3)/8) = u v^3 (u v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    if (v * x * x) % P == u:
        pass
    elif (v * x * x) % P == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x == 0 and sign:
        return None
    if (x & 1) != sign:
        x = P - x
    return x


_BX = _recover_x(_BY, 0)
BASE = (_BX, _BY, 1, (_BX * _BY) % P)  # extended coords
IDENTITY = (0, 1, 1, 0)


def pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (t1 * 2 * D * t2) % P
    d = (z1 * 2 * z2) % P
    e, f, g, h = (b - a) % P, (d - c) % P, (d + c) % P, (b + a) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def pt_double(p):
    return pt_add(p, p)


def scalar_mult(s: int, p):
    q = IDENTITY
    while s:
        if s & 1:
            q = pt_add(q, p)
        p = pt_double(p)
        s >>= 1
    return q


def pt_encode(p) -> bytes:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    x, y = (x * zi) % P, (y * zi) % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def pt_decompress(data: bytes):
    val = int.from_bytes(data, "little")
    y = val & ((1 << 255) - 1)
    sign = val >> 255
    x = _recover_x(y % P, sign)
    if x is None:
        return None
    return (x, y % P, 1, (x * (y % P)) % P)


# -- fixed-base path (key derivation + signing) -----------------------------
#
# RFC 8032 key derivation and signing, so the pure-Python path is
# byte-identical with the host library (cryptography / libsodium):
# a = clamp(SHA512(seed)[:32]), A = [a]B, r = SHA512(prefix || M) mod l,
# R = [r]B, S = (r + SHA512(R||A||M)·a) mod l. Base-point multiples are
# comb-precomputed (64 radix-16 windows) so a sign is ~64 point adds
# instead of a full double-and-add ladder.

_BASE_COMB: list | None = None


def _base_comb() -> list:
    """[window][digit] -> [digit * 16^window]B (digit 0 = identity)."""
    global _BASE_COMB
    if _BASE_COMB is None:
        comb = []
        step = BASE
        for _ in range(64):
            row = [IDENTITY]
            for _d in range(15):
                row.append(pt_add(row[-1], step))
            comb.append(row)
            step = pt_add(row[-1], step)  # 16^(w+1) * B
        _BASE_COMB = comb
    return _BASE_COMB


def scalar_mult_base(s: int):
    """[s]B via the fixed-base comb (≈64 adds; sign/derive hot path)."""
    comb = _base_comb()
    q = IDENTITY
    for w in range(64):
        d = (s >> (4 * w)) & 0xF
        if d:
            q = pt_add(q, comb[w][d])
    return q


def _clamp(h32: bytes) -> int:
    a = int.from_bytes(h32, "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a


def secret_expand(seed: bytes) -> tuple[int, bytes]:
    """seed -> (clamped secret scalar, 32-byte nonce prefix)."""
    h = hashlib.sha512(seed).digest()
    return _clamp(h[:32]), h[32:]


def derive_public(seed: bytes) -> bytes:
    """crypto_sign_seed_keypair's public half: encode([clamp(h)]B)."""
    a, _ = secret_expand(seed)
    return pt_encode(scalar_mult_base(a))


def sign(seed: bytes, public: bytes, msg: bytes) -> bytes:
    """Detached RFC 8032 signature (byte-identical with the host lib)."""
    a, prefix = secret_expand(seed)
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    r_bytes = pt_encode(scalar_mult_base(r))
    h = int.from_bytes(
        hashlib.sha512(r_bytes + public + msg).digest(), "little"
    ) % L
    s = (r + h * a) % L
    return r_bytes + s.to_bytes(32, "little")


def verify(public: bytes, msg: bytes, sig: bytes) -> bool:
    if len(public) != 32 or len(sig) != 64:
        return False
    a = pt_decompress(public)
    if a is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:  # canonical-S (reference signatureIsCanonical)
        return False
    h = int.from_bytes(hashlib.sha512(sig[:32] + public + msg).digest(), "little") % L
    neg_a = ((P - a[0]) % P, a[1], a[2], (P - a[3]) % P)
    rp = pt_add(scalar_mult(s, BASE), scalar_mult(h, neg_a))
    return pt_encode(rp) == sig[:32]
