"""Field arithmetic mod p = 2^255 - 19 for batched Ed25519 on TPU.

Representation: 20 limbs x 13 bits, int32, little-endian limb order, shape
[..., 20]. All ops are batched over leading axes — the batch dimension is
the vector-lane parallelism; limb loops are tiny and static.

Why 13-bit limbs in int32: schoolbook products are < 2^26.1 and a 20-term
column sum stays < 2^31, so the whole multiply runs in native int32 lanes
(TPU VPU width) with no 64-bit emulation. Reduction uses
2^260 ≡ 608 (mod p) folding (608 = 19 * 2^5, since 13*20 = 260 = 255 + 5).

Invariant maintained by every op: limbs in [0, 8192] ("bounded redundant",
mul-safe since 20 * 8192^2 < 2^31) and value < 2^255 + 2^19 < 2p.
Canonical form (value in [0, p), limbs < 2^13) only where bytes/equality
are produced (`fe_reduce_full`).

This fills the role of libsodium's ref10 fe25519 used by the reference's
crypto_sign_verify_detached path
(/root/reference/src/ripple_data/protocol/RippleAddress.cpp:190-252).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1
P = (1 << 255) - 19
FOLD = 608  # 2^260 mod p = 19 * 2^5

D = (-121665 * pow(121666, P - 2, P)) % P  # Edwards d
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)
L = (1 << 252) + 27742317777372353535851937790883648493  # group order l


def int_to_limbs_np(x: int, n: int = NLIMB) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def limbs_to_int(limbs) -> int:
    limbs = np.asarray(limbs)
    return sum(int(v) << (BITS * i) for i, v in enumerate(limbs))


_P_LIMBS = int_to_limbs_np(P)
# Subtraction bias: 33p, laid out limb-wise as 33 * (limbs of p) so every
# bias limb (min 33*255 = 8415) dominates any normalized limb (<= 8192).
# a + bias - b is then limb-wise non-negative: carries stay positive.
_BIAS_LIMBS = (33 * _P_LIMBS).astype(np.int32)


def fe_const(x: int, batch_shape=()) -> jnp.ndarray:
    limbs = jnp.asarray(int_to_limbs_np(x % P))
    return jnp.broadcast_to(limbs, tuple(batch_shape) + (NLIMB,))


def _carry(c: jnp.ndarray, steps: int) -> jnp.ndarray:
    """Global carry-propagation steps (arithmetic shifts, so signed values
    borrow correctly). Does not change the represented value; callers size
    buffers so the top limb never overflows."""
    for _ in range(steps):
        hi = c >> BITS
        c = (c & MASK) + jnp.concatenate(
            [jnp.zeros_like(hi[..., :1]), hi[..., :-1]], axis=-1
        )
    return c


def _fold_top(c: jnp.ndarray, over: jnp.ndarray | None = None) -> jnp.ndarray:
    """Fold bits >= 2^255 of a 20-limb value (plus an optional 2^260-weight
    overflow limb) back onto limb 0: 2^255 ≡ 19, 2^260 ≡ 608 (mod p)."""
    h = c[..., 19] >> 8
    c = c.at[..., 19].set(c[..., 19] & 0xFF)
    add = 19 * h
    if over is not None:
        add = add + FOLD * over
    return c.at[..., 0].add(add)


def _fold260(acc: jnp.ndarray) -> jnp.ndarray:
    """Reduce a 39-limb (< 2^511) non-negative value to the invariant form."""
    pad = 40 - acc.shape[-1]
    if pad:
        acc = jnp.concatenate(
            [acc, jnp.zeros(acc.shape[:-1] + (pad,), acc.dtype)], axis=-1
        )
    acc = _carry(acc, 3)  # limbs <= 8192
    lo, hi = acc[..., :20], acc[..., 20:]
    c = lo + FOLD * hi  # <= 8192 + 608*8192 < 2^22.3
    c = jnp.concatenate([c, jnp.zeros(c.shape[:-1] + (1,), c.dtype)], axis=-1)
    c = _carry(c, 2)  # limbs <= 8192, over-limb <= 2^9.3
    c = _fold_top(c[..., :20], over=c[..., 20])
    return _carry(c, 2)


def fe_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    shape = jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1])
    acc = jnp.zeros(shape + (39,), jnp.int32)
    for i in range(NLIMB):  # static 20-step schoolbook, vectorized over batch
        acc = acc.at[..., i : i + 20].add(a[..., i : i + 1] * b)
    return _fold260(acc)


def fe_square(a: jnp.ndarray) -> jnp.ndarray:
    return fe_mul(a, a)


def _finish21(c: jnp.ndarray) -> jnp.ndarray:
    """Normalize a 21-limb non-negative value (< 2^261, limbs < 2^19)."""
    c = _carry(c, 2)
    c = _fold_top(c[..., :20], over=c[..., 20])
    return _carry(c, 2)


def fe_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    c = a + b
    c = jnp.concatenate([c, jnp.zeros(c.shape[:-1] + (1,), c.dtype)], axis=-1)
    return _finish21(c)


def fe_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    c = a + jnp.asarray(_BIAS_LIMBS) - b  # limb-wise >= 0; value = a-b+33p
    c = jnp.concatenate([c, jnp.zeros(c.shape[:-1] + (1,), c.dtype)], axis=-1)
    return _finish21(c)


def fe_neg(a: jnp.ndarray) -> jnp.ndarray:
    return fe_sub(jnp.zeros_like(a), a)


def fe_reduce_full(a: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical form: value in [0, p), limbs < 2^13.

    Input satisfies the invariant (value < 2p). Exact long carry chains are
    possible here, so propagation runs the full limb count.
    """
    c = _fold_top(a)  # clears bits >= 255; adds <= 19*32 to limb 0
    c = _carry(c, NLIMB + 1)
    # now limbs < 2^13 exactly and value < 2^255 + eps; subtract p once if >= p
    ge = (
        (c[..., 19] >= 0x100)
        | (
            (c[..., 19] == 0xFF)
            & jnp.all(c[..., 1:19] == MASK, axis=-1)
            & (c[..., 0] >= MASK - 18)
        )
    )
    p_limbs = jnp.asarray(_P_LIMBS)
    c = c - jnp.where(ge[..., None], p_limbs, jnp.zeros_like(p_limbs))
    return _carry(c, NLIMB + 1)


def fe_pow(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a static exponent, rolled as a fori_loop over bits (keeps the
    XLA graph small — unrolled 255-bit chains explode CPU compile time)."""
    bits = [int(b) for b in bin(e)[2:]]
    bits_arr = jnp.asarray(np.array(bits, dtype=np.int32))
    nbits = len(bits)

    def body(i, r):
        r = fe_square(r)
        return jnp.where(bits_arr[i][..., None] == 1, fe_mul(r, a), r)

    return lax.fori_loop(1, nbits, body, a)


def fe_invert(a: jnp.ndarray) -> jnp.ndarray:
    return fe_pow(a, P - 2)


def fe_is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(fe_reduce_full(a) == 0, axis=-1)


def fe_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(fe_reduce_full(a) == fe_reduce_full(b), axis=-1)


def fe_is_odd(a: jnp.ndarray) -> jnp.ndarray:
    return (fe_reduce_full(a)[..., 0] & 1) == 1


def fe_select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a where cond else b; cond is [...] bool."""
    return jnp.where(cond[..., None], a, b)


def limbs_from_words_le(words_u32: jnp.ndarray, mask_high: bool = True) -> jnp.ndarray:
    """[..., 8] uint32 little-endian words -> [..., 20] int32 limbs.

    With mask_high, bit 255 (the point-compression sign bit) is dropped.
    """
    w = words_u32
    out = []
    for k in range(NLIMB):
        bit = BITS * k
        a, r = divmod(bit, 32)
        lo = w[..., a] >> r
        if r + BITS > 32 and a + 1 < 8:
            lo = lo | (w[..., a + 1] << (32 - r))
        out.append((lo & MASK).astype(jnp.int32))
    limbs = jnp.stack(out, axis=-1)
    if mask_high:
        limbs = limbs.at[..., 19].set(limbs[..., 19] & 0xFF)
    return limbs


def limbs_to_words_le(limbs: jnp.ndarray) -> jnp.ndarray:
    """Canonical [..., 20] limbs -> [..., 8] uint32 little-endian words."""
    l = limbs.astype(jnp.uint32)
    words = []
    for wi in range(8):
        bit0 = 32 * wi
        w = jnp.zeros(limbs.shape[:-1], jnp.uint32)
        for k in range(NLIMB):
            lb = BITS * k
            if lb + BITS <= bit0 or lb >= bit0 + 32:
                continue
            sh = lb - bit0
            if sh >= 0:
                w = w | (l[..., k] << sh)
            else:
                w = w | (l[..., k] >> (-sh))
        words.append(w)
    return jnp.stack(words, axis=-1)
