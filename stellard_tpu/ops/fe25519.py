"""Field arithmetic mod p = 2^255 - 19 for batched Ed25519 on TPU.

Representation: 20 limbs x 13 bits, int32, little-endian limb order,
LIMB-MAJOR layout: shape [20, *batch]. The batch axes TRAIL, so the batch
dimension lands in the TPU minor (lane) axis — a [20, B] tensor tiles as
(sublane=20, lane=B) and fills all 128 vector lanes for B >= 128, where
the previous batch-major [B, 20] layout left 108 of 128 lanes idle (the
limb axis, size 20, was minor). Measured on-chip this layout bound was
the kernel's dominant cost, not FLOPs.

Why 13-bit limbs in int32: schoolbook products are < 2^26.3 and a 20-term
column sum stays < 2^31, so the whole multiply runs in native int32 lanes
(TPU VPU width) with no 64-bit emulation. Reduction uses
2^260 ≡ 608 (mod p) folding (608 = 19 * 2^5, since 13*20 = 260 = 255 + 5).

Invariant maintained by every op: limbs in [0, 9500] ("bounded redundant",
mul-safe since 20 * 9500^2 < 2^31). The represented value is any 260-bit
integer; it is brought into canonical [0, p) form only where bytes /
equality / parity are produced (`fe_reduce_full`).

Engineering notes (all from profiling the batched verify kernel):
- multiply/square accumulate columns as pure SSA values (no
  scatter-style `.at[].add` updates — those materialize a fresh buffer
  per limb step and defeat XLA fusion),
- squaring uses the symmetric column halving (210 lane products instead
  of 400),
- additions/subtractions do ONE carry sweep plus a 2^260-overflow fold
  (the loose 9500 invariant absorbs the slack; full normalization would
  triple their cost),
- inversion and the decompression square root run fixed addition chains
  (254S+11M / 252S+11M) instead of a generic 2-ops-per-bit square&multiply
  ladder.

This fills the role of libsodium's ref10 fe25519 used by the reference's
crypto_sign_verify_detached path
(/root/reference/src/ripple_data/protocol/RippleAddress.cpp:190-252).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax
from contextlib import contextmanager

import os

NLIMB = 20
BITS = 13
MASK = (1 << BITS) - 1
P = (1 << 255) - 19
FOLD = 608  # 2^260 mod p = 19 * 2^5

D = (-121665 * pow(121666, P - 2, P)) % P  # Edwards d
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)
L = (1 << 252) + 27742317777372353535851937790883648493  # group order l

# Loose limb bound maintained by every op (see module docstring).
BOUND = 9500


def int_to_limbs_np(x: int, n: int = NLIMB) -> np.ndarray:
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= BITS
    if x:
        raise ValueError("value does not fit in limbs")
    return out


def limbs_to_int(limbs) -> int:
    limbs = np.asarray(limbs)
    return sum(int(v) << (BITS * i) for i, v in enumerate(limbs))


_P_LIMBS = int_to_limbs_np(P)
# Subtraction bias: 38p, laid out limb-wise as 38 * (limbs of p) so every
# bias limb (min 38*255 = 9690) dominates any invariant limb (<= 9500):
# a + bias - b is limb-wise non-negative, and bias ≡ 0 (mod p).
_BIAS_LIMBS = (38 * _P_LIMBS).astype(np.int32)


# --- constant provisioning ------------------------------------------------
# Pallas kernels cannot capture array constants ("pass them as inputs"),
# so every [20]-limb constant routes through _const20(). Outside a kernel
# it just materializes the numpy array (jaxpr constant, status quo). For
# a Pallas trace, ed25519_pallas first traces the math in COLLECT mode to
# enumerate the distinct constants, then passes the stacked [K, 20] table
# as a kernel input and sets CONSUME mode so _const20 returns rows of it.
_CONST_MODE: str | None = None  # None | "collect" | "consume"
_CONST_INDEX: dict[bytes, int] = {}
_CONST_ROWS: list[np.ndarray] = []
_CONST_TABLE: jnp.ndarray | None = None  # [K, 20] while consuming


def _const20(limbs_np: np.ndarray) -> jnp.ndarray:
    row = np.asarray(limbs_np, np.int32)
    if _CONST_MODE is None:
        return jnp.asarray(row)
    key = row.tobytes()
    idx = _CONST_INDEX.get(key)
    if idx is None:
        if _CONST_MODE == "consume":
            raise KeyError(
                "fe25519 constant not seen during the collect trace — "
                "the Pallas const table is incomplete"
            )
        idx = len(_CONST_ROWS)
        _CONST_INDEX[key] = idx
        _CONST_ROWS.append(row)
    if _CONST_MODE == "collect":
        return jnp.asarray(row)
    return _CONST_TABLE[idx]


def _col(limbs_1d, ndim: int) -> jnp.ndarray:
    """[20] constant -> [20, 1, 1, ...] so it broadcasts against a
    limb-major [20, *batch] tensor of rank `ndim`."""
    arr = _const20(limbs_1d)
    return arr.reshape((NLIMB,) + (1,) * (ndim - 1)) if ndim > 1 else arr


@contextmanager
def const_mode(mode: str, table: jnp.ndarray | None = None):
    """Scope the constant-provisioning mode (see _const20). ``collect``
    records every distinct [20]-limb constant a trace touches;
    ``consume`` serves them from ``table`` ([K, 20], normally a Pallas
    kernel input). Traces are single-threaded per kernel build; the
    caller (ed25519_pallas) holds a lock around nested use."""
    global _CONST_MODE, _CONST_TABLE
    prev_mode, prev_table = _CONST_MODE, _CONST_TABLE
    _CONST_MODE, _CONST_TABLE = mode, table
    try:
        yield
    finally:
        _CONST_MODE, _CONST_TABLE = prev_mode, prev_table


def const_table_np() -> np.ndarray:
    """The collected constants as one [K, 20] int32 table."""
    if not _CONST_ROWS:
        raise RuntimeError("no constants collected — run a collect trace")
    return np.stack(_CONST_ROWS, axis=0)


def _align2(a: jnp.ndarray, b: jnp.ndarray):
    """Limb-major rank alignment: numpy broadcasting prepends axes, but a
    [20] constant must align with [20, *batch] by APPENDING singleton
    batch axes. Every binary fe op routes through this."""
    if a.ndim < b.ndim:
        a = a.reshape(a.shape + (1,) * (b.ndim - a.ndim))
    elif b.ndim < a.ndim:
        b = b.reshape(b.shape + (1,) * (a.ndim - b.ndim))
    return a, b


def fe_const(x: int, batch_shape=()) -> jnp.ndarray:
    limbs = _const20(int_to_limbs_np(x % P))
    out = limbs.reshape((NLIMB,) + (1,) * len(batch_shape))
    return jnp.broadcast_to(out, (NLIMB,) + tuple(batch_shape))


def _carry(c: jnp.ndarray, steps: int) -> jnp.ndarray:
    """Global carry-propagation steps (arithmetic shifts, so signed values
    borrow correctly). Value-preserving; callers size buffers so the top
    limb's carry-out is never dropped."""
    for _ in range(steps):
        hi = c >> BITS
        c = (c & MASK) + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    return c


def _carry20_fold(c: jnp.ndarray) -> jnp.ndarray:
    """One carry sweep over a 20-limb value with limbs < 2^18.3, folding
    the limb-19 carry-out (weight 2^260) onto limb 0 as * FOLD.
    Output limbs <= 8191 + 40 + FOLD*3 < BOUND."""
    hi = c >> BITS
    lo = c & MASK
    shifted = jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
    out = lo + shifted
    return jnp.concatenate([(out[0] + FOLD * hi[19])[None], out[1:]], axis=0)


def _finish_mul_t(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Shared tail of multiply/square: fold the 19 high columns
    (weights 2^260..) onto the 20 low ones via 2^260 ≡ FOLD, then carry.

    lo: [20, *batch] column sums < 2^31. hi: [19, *batch] column sums."""
    # carry hi first so FOLD*hi stays in int32; 2 spare limbs so no
    # carry-out is ever dropped
    hi = jnp.concatenate(
        [hi, jnp.zeros((2,) + hi.shape[1:], hi.dtype)], axis=0
    )
    hi = _carry(hi, 2)  # limbs <= MASK + 33
    c = lo + FOLD * hi[:20]  # < 2^31
    # hi[20] (weight 2^260 * 2^260) folds with FOLD^2; hi's own carrying
    # makes it tiny (<= 33)
    c0 = c[0] + (FOLD * FOLD) * hi[20]
    c = jnp.concatenate(
        [c0[None], c[1:], jnp.zeros((2,) + c.shape[1:], c.dtype)], axis=0
    )
    c = _carry(c, 2)  # limbs <= MASK + 33; c[20] <= MASK + 33, c[21] <= 33
    h = c[19] >> 8  # bits >= 2^255 in limb 19
    c0 = c[0] + 19 * h + FOLD * (c[20] + (c[21] << BITS))
    c = jnp.concatenate([c0[None], c[1:19], (c[19] & 0xFF)[None]], axis=0)
    return _carry(c, 2)  # limbs <= MASK + 33 < BOUND


def _finish_mul(lo_cols: list, hi_cols: list) -> jnp.ndarray:
    return _finish_mul_t(jnp.stack(lo_cols, axis=0), jnp.stack(hi_cols, axis=0))


# Multiply formulation. The original "legacy" form emits every one of the
# ~400 limb products and ~580 column adds as its own [*batch]-shaped 1-D
# XLA op (the per-limb Python slicing drops the limb axis), and measured
# on-chip the kernel's cost tracks that op COUNT, not its FLOPs — a TPU
# core runs the post-fusion op sequence serially, so thousands of
# vector-register-sized ops are pure sequencing overhead. The "rowpad"
# form keeps the limb axis inside the tensors: 20 shifted row-products,
# padded to the 39-column width and summed in one reduction — ~45 wide
# ops instead of ~1000 tiny ones, identical arithmetic and bounds.
_FE_MUL_IMPL = os.environ.get("STELLARD_FE_MUL", "rowpad")
if _FE_MUL_IMPL not in ("rowpad", "legacy"):
    raise ValueError(
        f"STELLARD_FE_MUL={_FE_MUL_IMPL!r}: expected 'rowpad' or 'legacy'"
    )


def _rows_padsum(rows: list) -> jnp.ndarray:
    """rows[i]: [len_i, *batch] partial products whose limb 0 sits at
    column offset off_i; returns [39, *batch] column sums."""
    nb = rows[0][1].ndim - 1
    padded = [
        jnp.pad(r, ((off, 2 * NLIMB - 1 - off - r.shape[0]),) + ((0, 0),) * nb)
        for off, r in rows
    ]
    return jnp.sum(jnp.stack(padded, axis=0), axis=0)


def fe_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook 20x20 product -> 39 column sums + fold."""
    a, b = jnp.broadcast_arrays(*_align2(a, b))
    if _FE_MUL_IMPL == "legacy":
        ai = [a[i] for i in range(NLIMB)]
        bi = [b[i] for i in range(NLIMB)]
        lo_cols, hi_cols = [], []
        for k in range(2 * NLIMB - 1):
            terms = [
                ai[i] * bi[k - i]
                for i in range(max(0, k - 19), min(NLIMB, k + 1))
            ]
            s = terms[0]
            for t in terms[1:]:
                s = s + t
            (lo_cols if k < NLIMB else hi_cols).append(s)
        return _finish_mul(lo_cols, hi_cols)
    # rowpad: row i = a_i * b lands at columns i..i+19
    cols = _rows_padsum([(i, a[i] * b) for i in range(NLIMB)])
    return _finish_mul_t(cols[:NLIMB], cols[NLIMB:])


def fe_square(a: jnp.ndarray) -> jnp.ndarray:
    """Symmetric schoolbook square: halved off-diagonal work."""
    if _FE_MUL_IMPL == "legacy":
        ai = [a[i] for i in range(NLIMB)]
        lo_cols, hi_cols = [], []
        for k in range(2 * NLIMB - 1):
            i = max(0, k - 19)
            j = k - i
            terms = []
            while i < j:
                terms.append(ai[i] * ai[j])
                i += 1
                j -= 1
            s = None
            if terms:
                s = terms[0]
                for t in terms[1:]:
                    s = s + t
                s = s + s  # off-diagonal pairs count twice
            if i == j:
                d = ai[i] * ai[i]
                s = d if s is None else s + d
            (lo_cols if k < NLIMB else hi_cols).append(s)
        return _finish_mul(lo_cols, hi_cols)
    # rowpad: row i = a_i * (a_i, 2a_{i+1}, .., 2a_19) lands at columns
    # 2i..i+19; every i<j pair appears once, doubled. Bounds: column k
    # sums the pairs (i, k-i) with i <= k-i < 20 — at most 10 of them
    # (k = 19: (0,19)..(9,10); k = 20: (1,19)..(10,10)) — each term
    # <= 2*BOUND^2 = 1.805e8, so the worst column is 10 * 1.805e8 =
    # 1.805e9 < 2^31, the same slack the legacy halved form relied on.
    rows = []
    for i in range(NLIMB):
        seg = a[i] * a[i:]  # [NLIMB - i, *batch]
        if seg.shape[0] > 1:
            seg = jnp.concatenate([seg[:1], seg[1:] + seg[1:]], axis=0)
        rows.append((2 * i, seg))
    cols = _rows_padsum(rows)
    return _finish_mul_t(cols[:NLIMB], cols[NLIMB:])


def fe_add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = _align2(a, b)
    c = a + b  # limbs <= 2*BOUND < 2^14.3
    return _carry20_fold(c)


def fe_sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = _align2(a, b)
    ndim = max(a.ndim, b.ndim)
    c = a + _col(_BIAS_LIMBS, ndim) - b  # limb-wise >= 0; value = a-b+38p
    return _carry20_fold(c)


def fe_neg(a: jnp.ndarray) -> jnp.ndarray:
    return fe_sub(jnp.zeros_like(a), a)


def fe_reduce_full(a: jnp.ndarray) -> jnp.ndarray:
    """Exact canonical form: value in [0, p), limbs < 2^13.

    Folding limb 19's bits >= 2^255 FIRST (2^255 ≡ 19) brings the value
    under 2p before any carry sweep, so no 2^260 carry-out ever exists
    to drop; the conditional subtract then handles the last excess."""
    h = a[19] >> 8
    c = jnp.concatenate(
        [(a[0] + 19 * h)[None], a[1:19], (a[19] & 0xFF)[None]], axis=0
    )
    c = _carry(c, NLIMB + 1)
    # limbs < 2^13 exactly, value < 2^255 + eps; subtract p once if >= p
    ge = (
        (c[19] >= 0x100)
        | (
            (c[19] == 0xFF)
            & jnp.all(c[1:19] == MASK, axis=0)
            & (c[0] >= MASK - 18)
        )
    )
    p_col = _col(_P_LIMBS, c.ndim)
    c = c - jnp.where(ge, p_col, jnp.zeros_like(p_col))
    return _carry(c, NLIMB + 1)


def limbs_lt_p(a: jnp.ndarray) -> jnp.ndarray:
    """[20, *batch] CANONICAL-per-limb value (each limb < 2^13, e.g.
    straight from limbs_from_words_le) -> [*batch] bool: value < p.

    Unrolled most-significant-first compare (no cumprod/scan — the
    helper must lower inside Pallas kernels)."""
    p_col = _col(_P_LIMBS, a.ndim)
    lt = jnp.zeros(a.shape[1:], bool)
    all_eq = jnp.ones(a.shape[1:], bool)
    for k in range(NLIMB - 1, -1, -1):
        lt = lt | (all_eq & (a[k] < p_col[k]))
        all_eq = all_eq & (a[k] == p_col[k])
    return lt


def _sqn(a: jnp.ndarray, n: int) -> jnp.ndarray:
    """a^(2^n): n chained squarings. Rolled for large n (small XLA graph,
    the loop body is one fused square); unrolled when tiny."""
    if n <= 4:
        for _ in range(n):
            a = fe_square(a)
        return a
    return lax.fori_loop(0, n, lambda i, x: fe_square(x), a)


def _chain_250(a: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Core of the curve25519 inversion/sqrt addition chains: returns
    (a^(2^250 - 1), a^11)."""
    z2 = fe_square(a)  # a^2
    z9 = fe_mul(_sqn(z2, 2), a)  # a^9
    z11 = fe_mul(z9, z2)  # a^11
    z2_5_0 = fe_mul(fe_square(z11), z9)  # a^(2^5 - 1)
    z2_10_0 = fe_mul(_sqn(z2_5_0, 5), z2_5_0)  # a^(2^10 - 1)
    z2_20_0 = fe_mul(_sqn(z2_10_0, 10), z2_10_0)
    z2_40_0 = fe_mul(_sqn(z2_20_0, 20), z2_20_0)
    z2_50_0 = fe_mul(_sqn(z2_40_0, 10), z2_10_0)
    z2_100_0 = fe_mul(_sqn(z2_50_0, 50), z2_50_0)
    z2_200_0 = fe_mul(_sqn(z2_100_0, 100), z2_100_0)
    z2_250_0 = fe_mul(_sqn(z2_200_0, 50), z2_50_0)
    return z2_250_0, z11


def fe_invert(a: jnp.ndarray) -> jnp.ndarray:
    """a^(p-2) = a^(2^255 - 21): 254 squarings + 11 multiplies."""
    z2_250_0, z11 = _chain_250(a)
    return fe_mul(_sqn(z2_250_0, 5), z11)


def fe_pow_p58(a: jnp.ndarray) -> jnp.ndarray:
    """a^((p-5)/8) = a^(2^252 - 3): 252 squarings + 11 multiplies."""
    z2_250_0, _ = _chain_250(a)
    return fe_mul(_sqn(z2_250_0, 2), a)


def fe_pow(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e for a static exponent. The two hot exponents route to their
    addition chains; anything else falls back to a rolled ladder."""
    if e == P - 2:
        return fe_invert(a)
    if e == (P - 5) // 8:
        return fe_pow_p58(a)
    bits = [int(b) for b in bin(e)[2:]]
    bits_arr = jnp.asarray(np.array(bits, dtype=np.int32))

    def body(i, r):
        r = fe_square(r)
        return jnp.where(bits_arr[i] == 1, fe_mul(r, a), r)

    return lax.fori_loop(1, len(bits), body, a)


def fe_is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(fe_reduce_full(a) == 0, axis=0)


def fe_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    ra, rb = _align2(fe_reduce_full(a), fe_reduce_full(b))
    return jnp.all(ra == rb, axis=0)


def fe_is_odd(a: jnp.ndarray) -> jnp.ndarray:
    return (fe_reduce_full(a)[0] & 1) == 1


def fe_select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a where cond else b; cond is [*batch], a/b are [20, *batch] —
    trailing-axis broadcasting aligns cond with the batch axes."""
    return jnp.where(cond, *_align2(a, b))


def limbs_from_words_le(words_u32: jnp.ndarray, mask_high: bool = True) -> jnp.ndarray:
    """[8, *batch] uint32 little-endian words -> [20, *batch] int32 limbs.

    With mask_high, bit 255 (the point-compression sign bit) is dropped.
    """
    w = words_u32
    out = []
    for k in range(NLIMB):
        bit = BITS * k
        a, r = divmod(bit, 32)
        lo = w[a] >> r
        if r + BITS > 32 and a + 1 < 8:
            lo = lo | (w[a + 1] << (32 - r))
        lo = lo & MASK
        if mask_high and k == NLIMB - 1:
            lo = lo & 0xFF
        out.append(lo.astype(jnp.int32))
    return jnp.stack(out, axis=0)


def limbs_to_words_le(limbs: jnp.ndarray) -> jnp.ndarray:
    """Canonical [20, *batch] limbs -> [8, *batch] uint32 LE words."""
    l = limbs.astype(jnp.uint32)
    words = []
    for wi in range(8):
        bit0 = 32 * wi
        w = jnp.zeros(limbs.shape[1:], jnp.uint32)
        for k in range(NLIMB):
            lb = BITS * k
            if lb + BITS <= bit0 or lb >= bit0 + 32:
                continue
            sh = lb - bit0
            if sh >= 0:
                w = w | (l[k] << sh)
            else:
                w = w | (l[k] >> (-sh))
        words.append(w)
    return jnp.stack(words, axis=0)
