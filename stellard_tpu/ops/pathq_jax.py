"""Batched path-quality composition in Q16.16 fixed point.

A candidate payment path is flattened to a fixed-width row of per-hop
rates (hop-padded with the identity rate 1.0): book hops carry the
book's best-tier quality, account hops the issuer's transfer rate. The
composite rate of a path is the saturating product of its hops — lower
is better (fewer units in per unit delivered). The fold is a pure
uint32 pipeline so one algorithm serves two arms byte-identically:

* ``path_quality_host``  — NumPy, the sequential reference arm;
* ``path_quality_kernel``— jax.numpy, jit/shard-able over the batch dim.

Q16.16 multiplies are decomposed into 16-bit limbs (the default JAX
configuration has no uint64) with explicit carry/saturation detection,
so host and device agree bit-for-bit at every batch size and mesh
width — the same contract the sig/hash planes pin for their kernels.

Layout: rates is [B, H] uint32; output is [B] uint32 composites.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

Q16_ONE = 1 << 16  # 1.0 in Q16.16
Q16_MAX = (1 << 32) - 1  # saturation rail


def _qmul(xp, a, b):
    """Saturating Q16.16 multiply via 16-bit limbs: the true product is
    (a*b) >> 16 over 64 bits; build it from the four 32-bit partials and
    saturate when the high word or any partial sum overflows uint32."""
    a_hi, a_lo = a >> 16, a & 0xFFFF
    b_hi, b_lo = b >> 16, b & 0xFFFF
    hh = a_hi * b_hi  # contributes << 16 after the global >> 16
    m1 = a_hi * b_lo
    m2 = a_lo * b_hi
    ll = (a_lo * b_lo) >> 16
    sat = hh > 0xFFFF
    r = (hh & 0xFFFF) << 16
    r1 = r + m1
    sat = sat | (r1 < m1)
    r2 = r1 + m2
    sat = sat | (r2 < m2)
    r3 = r2 + ll
    sat = sat | (r3 < ll)
    return xp.where(sat, xp.uint32(Q16_MAX), r3)


def _fold(xp, rates):
    """Composite per row: identity-seeded left fold of _qmul over the
    hop columns. The fold order is part of the byte-identity contract —
    both arms unroll the same static column loop."""
    rates = rates.astype(xp.uint32)
    n_hops = rates.shape[-1]
    acc = xp.full(rates.shape[:-1], Q16_ONE, dtype=xp.uint32)
    for h in range(n_hops):
        acc = _qmul(xp, acc, rates[..., h])
    return acc


def path_quality_host(rates: np.ndarray) -> np.ndarray:
    """NumPy reference arm: [B, H] uint32 -> [B] uint32."""
    return _fold(np, np.asarray(rates, dtype=np.uint32))


def path_quality_kernel(rates):
    """JAX arm, shape-identical to the host arm; jit/shard over batch."""
    return _fold(jnp, rates)
