"""Batched SHA-512 in JAX (uint32 lane pairs).

TPU-native replacement for the reference's OpenSSL SHA-512 calls
(Serializer::getSHA512Half, SHAMapTreeNode::updateHash —
src/ripple_data/protocol/Serializer.cpp:342-390,
src/ripple_app/shamap/SHAMapTreeNode.cpp:253-295). Every 64-bit word is a
(hi, lo) pair of uint32s because the TPU VPU works in 32-bit lanes; the
batch dimension carries the parallelism.

Control flow is rolled (`lax.fori_loop` over the 80 rounds) rather than
unrolled: XLA compile time explodes superlinearly on the unrolled
SHA dependency DAG, and a small rolled body is also the idiomatic XLA
shape — the sequential rounds cost nothing when the batch dimension fills
the vector lanes.

Layout: a message block is [..., 32] uint32 = 16 big-endian 64-bit words as
(hi, lo) pairs; state is [..., 16] uint32 = 8 words.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# SHA-512 round constants (FIPS 180-4) split into (hi, lo) uint32 pairs.
_K = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F, 0xE9B5DBA58189DBBC,
    0x3956C25BF348B538, 0x59F111F1B605D019, 0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118,
    0xD807AA98A3030242, 0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235, 0xC19BF174CF692694,
    0xE49B69C19EF14AD2, 0xEFBE4786384F25E3, 0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65,
    0x2DE92C6F592B0275, 0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F, 0xBF597FC7BEEF0EE4,
    0xC6E00BF33DA88FC2, 0xD5A79147930AA725, 0x06CA6351E003826F, 0x142929670A0E6E70,
    0x27B70A8546D22FFC, 0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6, 0x92722C851482353B,
    0xA2BFE8A14CF10364, 0xA81A664BBC423001, 0xC24B8B70D0F89791, 0xC76C51A30654BE30,
    0xD192E819D6EF5218, 0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99, 0x34B0BCB5E19B48A8,
    0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB, 0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3,
    0x748F82EE5DEFB2FC, 0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915, 0xC67178F2E372532B,
    0xCA273ECEEA26619C, 0xD186B8C721C0C207, 0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178,
    0x06F067AA72176FBA, 0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC, 0x431D67C49C100D4C,
    0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A, 0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F, 0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_KHI = np.array([k >> 32 for k in _K], dtype=np.uint32)
_KLO = np.array([k & 0xFFFFFFFF for k in _K], dtype=np.uint32)
_IV32 = np.array(
    [w for v in _IV for w in (v >> 32, v & 0xFFFFFFFF)], dtype=np.uint32
)


def _add64(ahi, alo, bhi, blo):
    lo = alo + blo
    carry = (lo < alo).astype(jnp.uint32)
    return ahi + bhi + carry, lo


def _add64_many(*pairs):
    hi, lo = pairs[0]
    for phi, plo in pairs[1:]:
        hi, lo = _add64(hi, lo, phi, plo)
    return hi, lo


def _rotr64(hi, lo, n):
    if n == 0:
        return hi, lo
    if n < 32:
        return (hi >> n) | (lo << (32 - n)), (lo >> n) | (hi << (32 - n))
    if n == 32:
        return lo, hi
    n -= 32
    return (lo >> n) | (hi << (32 - n)), (hi >> n) | (lo << (32 - n))


def _shr64(hi, lo, n):
    if n < 32:
        nlo = (lo >> n) | (hi << (32 - n)) if n else lo
        return hi >> n, nlo
    return jnp.zeros_like(hi), hi >> (n - 32)


def _xor3(a, b, c):
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _big_sigma0(hi, lo):
    return _xor3(_rotr64(hi, lo, 28), _rotr64(hi, lo, 34), _rotr64(hi, lo, 39))


def _big_sigma1(hi, lo):
    return _xor3(_rotr64(hi, lo, 14), _rotr64(hi, lo, 18), _rotr64(hi, lo, 41))


def _small_sigma0(hi, lo):
    return _xor3(_rotr64(hi, lo, 1), _rotr64(hi, lo, 8), _shr64(hi, lo, 7))


def _small_sigma1(hi, lo):
    return _xor3(_rotr64(hi, lo, 19), _rotr64(hi, lo, 61), _shr64(hi, lo, 6))


def _compress(state, block):
    """One SHA-512 compression. state: [..., 16] u32; block: [..., 32] u32."""
    batch_shape = block.shape[:-1]
    # message schedule: rolled recurrence over a [..., 80, 2] buffer
    w_init = jnp.zeros(batch_shape + (80, 2), jnp.uint32)
    msg = block.reshape(batch_shape + (16, 2))
    w_init = lax.dynamic_update_slice_in_dim(w_init, msg, 0, axis=-2)

    def sched_body(t, w):
        s0 = _small_sigma0(*_dyn(w, t - 15))
        s1 = _small_sigma1(*_dyn(w, t - 2))
        hi, lo = _add64_many(_dyn(w, t - 16), s0, _dyn(w, t - 7), s1)
        return _dyn_set(w, t, hi, lo)

    w = lax.fori_loop(16, 80, sched_body, w_init)

    khi = jnp.asarray(_KHI)
    klo = jnp.asarray(_KLO)

    def round_body(t, vs):
        a, b, c, d, e, f, g, h = [(vs[..., 2 * i], vs[..., 2 * i + 1]) for i in range(8)]
        ch = (e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1])
        maj = (
            (a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
            (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]),
        )
        kt = (khi[t], klo[t])
        t1 = _add64_many(h, _big_sigma1(*e), ch, kt, _dyn(w, t))
        t2 = _add64_many(_big_sigma0(*a), maj)
        ne = _add64(*d, *t1)
        na = _add64(*t1, *t2)
        return jnp.stack(
            [na[0], na[1], a[0], a[1], b[0], b[1], c[0], c[1],
             ne[0], ne[1], e[0], e[1], f[0], f[1], g[0], g[1]],
            axis=-1,
        )

    vs = lax.fori_loop(0, 80, round_body, state)
    out = []
    for i in range(8):
        hi, lo = _add64(state[..., 2 * i], state[..., 2 * i + 1], vs[..., 2 * i], vs[..., 2 * i + 1])
        out.extend([hi, lo])
    return jnp.stack(out, axis=-1)


def _dyn(w, t):
    """w: [..., 80, 2], dynamic index t -> (hi, lo) of shape [...]."""
    row = lax.dynamic_index_in_dim(w, t, axis=-2, keepdims=False)
    return row[..., 0], row[..., 1]


def _dyn_set(w, t, hi, lo):
    row = jnp.stack([hi, lo], axis=-1)[..., None, :]
    return lax.dynamic_update_slice_in_dim(w, row, t, axis=-2)


def sha512_blocks(blocks: jax.Array) -> jax.Array:
    """SHA-512 over pre-padded message blocks.

    blocks: [..., nblocks, 32] uint32 (16 BE 64-bit words per block as
    hi/lo pairs). Returns [..., 16] uint32 digest state (64 bytes).
    """
    state = jnp.broadcast_to(jnp.asarray(_IV32), blocks.shape[:-2] + (16,))
    nblocks = blocks.shape[-2]
    if nblocks <= 4:
        for i in range(nblocks):
            state = _compress(state, blocks[..., i, :])
    else:
        def body(i, st):
            return _compress(st, lax.dynamic_index_in_dim(blocks, i, axis=-2, keepdims=False))

        state = lax.fori_loop(0, nblocks, body, state)
    return state


def padded_block_count(length: int) -> int:
    """Number of 128-byte blocks after FIPS 180-4 padding."""
    return (length + 17 + 127) // 128


def pad_message_np(data: bytes) -> np.ndarray:
    """Host-side FIPS 180-4 padding -> [nblocks, 32] uint32 array."""
    length = len(data)
    padded = data + b"\x80"
    while (len(padded) + 16) % 128:
        padded += b"\x00"
    padded += (length * 8).to_bytes(16, "big")
    return np.frombuffer(padded, dtype=">u4").astype(np.uint32).reshape(-1, 32)


def pad_batch_np(messages: list[bytes]) -> np.ndarray:
    """Pad a batch of equal-block-count messages -> [B, nblocks, 32] u32."""
    arrs = [pad_message_np(m) for m in messages]
    n = {a.shape[0] for a in arrs}
    if len(n) != 1:
        raise ValueError("messages must pad to the same block count; bucket first")
    return np.stack(arrs)


def digest_to_bytes(state: np.ndarray) -> bytes:
    """[16] uint32 digest state -> 64 raw bytes."""
    return b"".join(int(w).to_bytes(4, "big") for w in np.asarray(state))


def sha512_half_batch(messages: list[bytes]) -> list[bytes]:
    """Convenience: batched SHA-512-half of same-block-count messages."""
    blocks = jnp.asarray(pad_batch_np(messages))
    out = np.asarray(jax.jit(sha512_blocks)(blocks))
    return [digest_to_bytes(out[i])[:32] for i in range(out.shape[0])]
