"""Device-resident SHAMap tree hashing — the replay/flush hot path.

Replaces per-level synchronous device calls (VERDICT r2 weak #3) with a
level-synchronous pipeline that never round-trips to the host between
levels (reference seam: SHAMapTreeNode::updateHash,
src/ripple_app/shamap/SHAMapTreeNode.cpp:253-295, driven by flushDirty):

- one device buffer holds every dirty node's digest (8 u32 words each);
- leaf levels hash with a MASKED multi-block SHA-512 kernel (mixed true
  block counts share one fixed-shape program);
- inner levels assemble their 516-byte payloads ON DEVICE: host builds a
  template with the prefix/known-child-hashes/FIPS-padding filled in, and
  the unknown child digests are scattered in from the digest buffer;
- every level is an async JAX dispatch; the host blocks ONCE at the end
  and reads all digests in a single transfer.

Shapes are quantized (node counts to powers of two, leaf block counts to
a small ladder) so the jit cache stays bounded across replays.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .sha512_jax import _IV32, _compress, pad_message_np, sha512_blocks

__all__ = [
    "sha512_blocks_masked",
    "leaf_level_kernel",
    "inner_level_kernel",
    "tree_leaf_body",
    "INNER_BLOCKS",
    "INNER_WORDS",
]

INNER_BLOCKS = 5  # 4-byte prefix + 16*32 child hashes = 516B -> 5 blocks
INNER_WORDS = INNER_BLOCKS * 32  # flattened u32 words per inner payload

# leaf padded-block-count ladder (oversized leaves hash on the host and
# enter the tree as known children)
LEAF_BLOCK_LADDER = (2, 4, 8, 16)


def sha512_blocks_masked(blocks: jax.Array, nblocks: jax.Array) -> jax.Array:
    """SHA-512 over [B, NB, 32] pre-padded blocks where row b only has
    nblocks[b] true blocks — compression is predicated per row, so mixed
    sizes share one program."""
    state = jnp.broadcast_to(jnp.asarray(_IV32), blocks.shape[:-2] + (16,))
    nb = blocks.shape[-2]

    def body(i, st):
        new = _compress(st, lax.dynamic_index_in_dim(blocks, i, axis=-2, keepdims=False))
        return jnp.where((i < nblocks)[..., None], new, st)

    return lax.fori_loop(0, nb, body, state)


def tree_leaf_body(buf, blocks, nblocks, offset):
    """Hash a (padded) batch of leaves and bank the 32-byte digests into
    the global digest buffer at `offset`. Un-jitted body: the sharded
    close pipeline re-jits it with mesh shardings and a DONATED buffer
    (parallel/mesh.py sharded_tree_kernels)."""
    st = sha512_blocks_masked(blocks, nblocks)  # [M, 16]
    return lax.dynamic_update_slice(buf, st[:, :8], (offset, 0))


leaf_level_kernel = jax.jit(tree_leaf_body)


@jax.jit
def inner_level_kernel(buf, template, rows, col_base, src_rows, offset, n_real):
    """Hash a (padded) batch of inner nodes.

    template: [N+1, INNER_WORDS] u32 — prefix, known child hashes and
      FIPS padding pre-filled; row N is the dummy-scatter scratch row.
    rows/col_base/src_rows: [K] scatter program — child digest src_rows
      of `buf` land at template[rows, col_base:col_base+8].
    """
    vals = buf[src_rows]  # [K, 8]
    cols = col_base[:, None] + jnp.arange(8, dtype=col_base.dtype)[None, :]
    t = template.at[rows[:, None], cols].set(vals)
    st = sha512_blocks(t.reshape(t.shape[0], INNER_BLOCKS, 32))  # [N+1, 16]
    return lax.dynamic_update_slice(buf, st[: t.shape[0] - 1, :8], (offset, 0))


def _pow2(n: int, lo: int = 8) -> int:
    size = lo
    while size < n:
        size *= 2
    return size


def pad_leaf_batch(payloads: list[bytes], ladder_nb: int) -> tuple[np.ndarray, np.ndarray]:
    """-> (blocks [Mpad, ladder_nb, 32], nblocks [Mpad]) host arrays."""
    m_pad = _pow2(len(payloads))
    blocks = np.zeros((m_pad, ladder_nb, 32), np.uint32)
    nblocks = np.zeros(m_pad, np.int32)
    for i, data in enumerate(payloads):
        b = pad_message_np(data)
        blocks[i, : b.shape[0]] = b
        nblocks[i] = b.shape[0]
    return blocks, nblocks


def build_inner_template(n_nodes: int, pow2_rows: bool = False) -> np.ndarray:
    """u32 template with the invariant parts of every 516-byte inner
    payload filled: the 0x80 terminator and the 16-byte big-endian bit
    length (the prefix + child hashes are per-node).

    Default layout is [Npad+1, INNER_WORDS] — row Npad is the dummy-
    scatter scratch row of the legacy ``inner_level_kernel``. With
    ``pow2_rows`` the layout is [Npad, INNER_WORDS] with NO scratch row
    (the sharded pipeline pads its scatter program by repeating a real
    entry — duplicate scatters of an identical value are well-defined —
    so every row count stays a power of two >= 8 and divides any mesh
    width up to 8)."""
    n_pad = _pow2(n_nodes)
    rows = n_pad if pow2_rows else n_pad + 1
    t = np.zeros((rows, INNER_WORDS), np.uint32)
    # byte 516 = 0x80 -> word 129, top byte
    t[:, 129] = 0x80000000
    # length trailer: last 16 bytes of block 5 = words 158..159 hold
    # 516*8 = 4128 bits (fits the final u32)
    t[:, 159] = 516 * 8
    return t
