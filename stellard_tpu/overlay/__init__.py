"""Overlay plane: the distributed communication backend.

Reference: src/ripple_overlay (peer sessions, flooding),
src/ripple/proto/ripple.proto (wire schema), src/ripple/testoverlay
(deterministic in-process network for consensus tests).

Two transports drive identical node logic (node.validator.ValidatorNode):

- `simnet` — deterministic discrete-time in-process network, the unit-test
  substrate (reference: testoverlay; SURVEY §4.2);
- `tcp` — length-prefixed frames over real sockets for the 4-validator
  private net on DCN (reference: PeerImp framing).
"""

from .simnet import SimNet, SimValidator
from .wire import MessageType, decode_message, encode_message

__all__ = [
    "MessageType",
    "SimNet",
    "SimValidator",
    "decode_message",
    "encode_message",
]
