"""Cascading follower trees: deterministic topology planning + child
subset selection.

Role: with a flat follower tier every follower dials the LEADER, so
the leader's egress per close is O(followers) — validation relays,
GetLedger replies, and segment serving all scale with the read tier.
A cascading tree bounds the leader's egress to its DIRECT children:
each follower names a follower (not the leader) as upstream via
``[node] upstream=`` and re-publishes the validated ledger stream +
segment ranges downstream (the existing relay/serve paths in
``overlay.tcp`` already run on followers; this module only decides
WHO dials WHOM).

Two deterministic pieces, shared by simnet scenarios, the depth-2
tree smoke, and the 100k-subscriber bench so every harness agrees on
the topology without negotiation:

- ``plan_tree(n_followers, branching)``: a breadth-first ``branching``-ary
  heap layout rooted at the leader. Follower ``j`` occupies heap slot
  ``j + 1`` (the leader is slot 0), so its parent is follower
  ``j // branching - 1`` — ``-1`` meaning the leader itself. The first
  ``branching`` followers are the leader's only dialers; everyone else
  hangs off a follower.

- ``select_children(...)``: when a tier over-subscribes (more dialers
  than a parent's child budget), the subset is chosen by the SAME
  rank function as overlay squelching (``squelch.relay_rank``) so any
  two processes agree on the child set for a given epoch without
  traffic, and the set rotates on the squelch epoch schedule so no
  fixed parent is a permanent point of failure.
"""

from __future__ import annotations

from typing import Callable, Iterable

from .squelch import SQUELCH_ROTATE, relay_rank

__all__ = [
    "plan_tree",
    "tier_of",
    "select_children",
    "tree_stats",
]

# domain separator: child-selection ranks must not collide with relay
# squelch ranks for the same (signer, epoch, peer) tuple
_TREE_SALT = b"followertree/v1"


def plan_tree(n_followers: int, branching: int) -> list[int]:
    """Parent index for each follower: ``-1`` = dial the leader,
    ``k >= 0`` = dial follower ``k``. Breadth-first heap layout, so
    the leader has at most ``branching`` direct children and depth is
    O(log_branching(n))."""
    b = max(1, int(branching))
    return [j // b - 1 for j in range(max(0, int(n_followers)))]


def tier_of(follower: int, branching: int) -> int:
    """1-based tree depth of a follower (1 = direct child of the
    leader) under the ``plan_tree`` layout."""
    b = max(1, int(branching))
    tier, j = 1, int(follower)
    while j // b - 1 >= 0:
        j = j // b - 1
        tier += 1
    return tier


def select_children(
    parent_id: bytes,
    seq: int,
    candidates: Iterable,
    key_fn: Callable[[object], bytes],
    size: int,
    rotate: int = SQUELCH_ROTATE,
) -> list:
    """Deterministic child subset for an over-subscribed parent: the
    ``size`` lowest-ranked candidates under the squelch rank function,
    salted so tree selection and relay squelching never share ranks.
    Pure function of (parent, epoch, candidate ids) — every process
    computes the same set; rotates every ``rotate`` ledgers."""
    cands = list(candidates)
    k = int(size)
    if k <= 0 or len(cands) <= k:
        return cands
    epoch = int(seq) // max(1, int(rotate))
    ranked = sorted(
        cands,
        key=lambda c: relay_rank(parent_id, epoch, _TREE_SALT, key_fn(c)),
    )
    return ranked[:k]


def tree_stats(parents: list[int], branching: int) -> dict:
    """Shape evidence for scorecards/provenance: leader child count,
    max depth, and max observed fan-out at any node."""
    children: dict[int, int] = {}
    for p in parents:
        children[p] = children.get(p, 0) + 1
    depth = max((tier_of(j, branching) for j in range(len(parents))),
                default=0)
    return {
        "n_followers": len(parents),
        "branching": max(1, int(branching)),
        "leader_children": children.get(-1, 0),
        "max_children": max(children.values(), default=0),
        "depth": depth,
    }
