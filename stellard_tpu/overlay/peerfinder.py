"""PeerFinder: endpoint discovery, ranking, and connect policy.

Role parity with the reference's PeerFinder subsystem
(/root/reference/src/ripple/peerfinder/impl/{PeerSlotLogic.h,Bootcache.h,
Livecache.h,Tuning.h}): the overlay should grow from one seed address to
a full mesh without manual configuration.

Three coordinated pieces:

- **Bootcache** — long-lived store of endpoints that ever accepted a
  connection, ranked by "valence" (net connect successes, clamped).
  Persisted as JSON lines under the node's data dir (the reference uses
  a sqlite table; the dataset is tiny — hundreds of rows — so a flat
  file keeps the dependency surface down and loads in one read).
- **Livecache** — endpoints heard via ENDPOINTS gossip recently, with a
  hop count; entries expire after ``LIVECACHE_TTL`` seconds. Fresh,
  low-hop entries are the preferred dial targets.
- **PeerFinder** — the connect policy: keeps ``out_desired`` outbound
  slots filled (fixed seeds first, then livecache by hops, then
  bootcache by valence), caps total peers, records outcomes, and
  assembles the periodic gossip sample (own endpoint at hop 0 plus a
  bounded re-share of fresh entries at hop+1, reference
  Tuning::numberOfEndpoints).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Iterable, Optional

__all__ = ["Bootcache", "Livecache", "PeerFinder"]

MAX_HOPS = 6
GOSSIP_MAX = 12  # numberOfEndpoints = 2 * maxHops
LIVECACHE_TTL = 30.0
GOSSIP_INTERVAL = 5.0  # reference secondsPerMessage
VALENCE_MAX = 10
# reconnect hygiene: first failure backs a target off REDIAL_BACKOFF
# seconds, consecutive failures double it up to REDIAL_BACKOFF_MAX, and
# a deterministic per-(address, failure-count) jitter of up to 25%
# decorrelates a fleet restarting against one dead seed. on_success
# resets the ladder. (reference: connection attempts ride timer ticks;
# the explicit ladder guarantees no tight redial spin against a
# refusing/dead address regardless of timer rate.)
REDIAL_BACKOFF = 15.0
REDIAL_BACKOFF_MAX = 300.0


class Bootcache:
    """Valence-ranked persistent endpoint store (Bootcache.h role)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self._lock = threading.Lock()
        self._valence: dict[tuple[str, int], int] = {}
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    for line in f:
                        rec = json.loads(line)
                        self._valence[(rec["host"], int(rec["port"]))] = int(
                            rec["valence"]
                        )
            except (OSError, ValueError, KeyError):
                self._valence = {}

    def insert(self, addr: tuple[str, int]) -> None:
        with self._lock:
            self._valence.setdefault(addr, 0)

    def on_success(self, addr: tuple[str, int]) -> None:
        with self._lock:
            v = self._valence.get(addr, 0)
            self._valence[addr] = min(VALENCE_MAX, v + 1 if v >= 0 else 1)

    def on_failure(self, addr: tuple[str, int]) -> None:
        with self._lock:
            v = self._valence.get(addr, 0)
            self._valence[addr] = max(-VALENCE_MAX, v - 1 if v <= 0 else -1)

    def ranked(self) -> list[tuple[str, int]]:
        """Addresses best-first (highest valence)."""
        with self._lock:
            return [
                a
                for a, _v in sorted(
                    self._valence.items(), key=lambda kv: -kv[1]
                )
            ]

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            items = list(self._valence.items())
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for (host, port), valence in items:
                f.write(json.dumps({"host": host, "port": port, "valence": valence}))
                f.write("\n")
        os.replace(tmp, self.path)

    def __len__(self) -> int:
        with self._lock:
            return len(self._valence)


class Livecache:
    """Hop-counted, expiring gossip endpoint cache (Livecache.h role)."""

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        # addr -> (hops, heard_at)
        self._entries: dict[tuple[str, int], tuple[int, float]] = {}

    def insert(self, addr: tuple[str, int], hops: int) -> None:
        if hops > MAX_HOPS:
            return
        now = self._clock()
        with self._lock:
            cur = self._entries.get(addr)
            # keep the lowest-hop, freshest sighting
            if cur is None or hops <= cur[0]:
                self._entries[addr] = (hops, now)

    def expire(self) -> None:
        now = self._clock()
        with self._lock:
            dead = [
                a for a, (_h, t) in self._entries.items() if now - t > LIVECACHE_TTL
            ]
            for a in dead:
                del self._entries[a]

    def sample(self, limit: int = GOSSIP_MAX) -> list[tuple[str, int, int]]:
        """(host, port, hops) entries, lowest-hop first."""
        self.expire()
        with self._lock:
            items = sorted(self._entries.items(), key=lambda kv: kv[1][0])
        return [(a[0], a[1], h) for a, (h, _t) in items[:limit]]

    def addrs(self) -> list[tuple[str, int]]:
        self.expire()
        with self._lock:
            return [
                a
                for a, (_h, _t) in sorted(
                    self._entries.items(), key=lambda kv: kv[1][0]
                )
            ]

    def __len__(self) -> int:
        self.expire()
        with self._lock:
            return len(self._entries)


class PeerFinder:
    """Connect policy + gossip assembly (PeerSlotLogic role)."""

    def __init__(
        self,
        fixed: Iterable[tuple[str, int]],
        out_desired: int = 4,
        max_peers: int = 21,  # reference defaultMaxPeers
        bootcache_path: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._clock = clock or time.monotonic
        self.fixed = list(fixed)
        self.out_desired = out_desired
        self.max_peers = max_peers
        self.bootcache = Bootcache(bootcache_path)
        self.livecache = Livecache(clock=self._clock)
        self._lock = threading.Lock()
        self._last_fail: dict[tuple[str, int], float] = {}
        self._fail_count: dict[tuple[str, int], int] = {}
        self.backoff_base = REDIAL_BACKOFF
        self.backoff_max = REDIAL_BACKOFF_MAX
        for a in self.fixed:
            self.bootcache.insert(a)

    # -- outcomes ---------------------------------------------------------

    def on_success(self, addr: tuple[str, int]) -> None:
        self.bootcache.on_success(addr)
        with self._lock:
            self._last_fail.pop(addr, None)
            self._fail_count.pop(addr, None)

    def on_failure(self, addr: tuple[str, int]) -> None:
        self.bootcache.on_failure(addr)
        with self._lock:
            self._last_fail[addr] = self._clock()
            self._fail_count[addr] = self._fail_count.get(addr, 0) + 1

    def backoff_delay(self, addr: tuple[str, int]) -> float:
        """Current redial delay for an address: exponential in its
        consecutive-failure count, capped, with deterministic jitter
        (pure function of address and count — testable, yet two nodes
        dialing one dead seed still spread out)."""
        import zlib

        with self._lock:
            n = self._fail_count.get(addr, 0)
        if n == 0:
            return 0.0
        delay = min(self.backoff_max, self.backoff_base * (2 ** (n - 1)))
        seed = zlib.crc32(f"{addr[0]}:{addr[1]}:{n}".encode())
        return delay * (1.0 + 0.25 * (seed % 1000) / 1000.0)

    # -- gossip -----------------------------------------------------------

    def on_endpoints(
        self, endpoints, sender: Optional[tuple] = None
    ) -> int:
        """Learn from a received ENDPOINTS message; returns #accepted,
        or -1 when the message itself is abusive (oversized).

        Only the first GOSSIP_MAX entries are processed — a well-behaved
        peer never sends more (reference Tuning::numberOfEndpointsMax),
        and an unbounded message must not flood the caches. Entries above
        MAX_HOPS are discarded (loop guard); hop-0 entries are rewritten
        to the sender's observed host, preventing a peer from advertising
        an arbitrary third-party address as itself (reference
        PeerSlotLogic endpoint checking)."""
        endpoints = list(endpoints)
        oversized = len(endpoints) > GOSSIP_MAX
        n = 0
        for host, port, hops in endpoints[:GOSSIP_MAX]:
            if hops > MAX_HOPS or not (0 < port < 65536):
                continue
            if hops == 0 and sender is not None:
                host = sender[0]
            addr = (str(host), int(port))
            self.livecache.insert(addr, int(hops))
            self.bootcache.insert(addr)
            n += 1
        return -1 if oversized else n

    def gossip_sample(
        self, own: Optional[tuple[str, int]]
    ) -> list[tuple[str, int, int]]:
        """Our periodic ENDPOINTS payload: self at hop 0 + fresh re-shares
        at hop+1."""
        out: list[tuple[str, int, int]] = []
        if own is not None:
            out.append((own[0], own[1], 0))
        for host, port, hops in self.livecache.sample(GOSSIP_MAX - len(out)):
            out.append((host, port, hops + 1))
        return out

    # -- connect policy ---------------------------------------------------

    def dial_targets(
        self,
        connected: set[tuple[str, int]],
        dialing: set[tuple[str, int]],
        out_count: int,
        total_count: int,
    ) -> list[tuple[str, int]]:
        """Addresses to dial now. Fixed seeds are always kept connected;
        discovered addresses fill the remaining outbound slots
        (livecache by hops, then bootcache by valence), observing the
        per-address failure backoff and the total peer cap."""
        now = self._clock()
        targets: list[tuple[str, int]] = []

        def eligible(a: tuple[str, int]) -> bool:
            if a in connected or a in dialing or a in targets:
                return False
            last = self._last_fail.get(a)
            return last is None or now - last >= self.backoff_delay(a)

        for a in self.fixed:
            if eligible(a):
                targets.append(a)
        want = self.out_desired - out_count - len(targets)
        if total_count + len(targets) >= self.max_peers:
            want = 0
        if want > 0:
            for a in self.livecache.addrs():
                if want <= 0:
                    break
                if eligible(a):
                    targets.append(a)
                    want -= 1
            for a in self.bootcache.ranked():
                if want <= 0:
                    break
                if eligible(a):
                    targets.append(a)
                    want -= 1
        return targets

    # -- slot accounting (reference: peerfinder/impl/Counts.h, Fixed.h) ----

    @property
    def max_in(self) -> int:
        """Inbound slot cap: whatever the total cap leaves after the
        outbound allotment (reference Counts::onConfig — maxPeers split
        into outDesired outbound + the rest inbound)."""
        return max(0, self.max_peers - self.out_desired)

    def can_accept_inbound(
        self, in_count: int, is_fixed_or_cluster: bool = False
    ) -> bool:
        """Admission check for a completed inbound handshake. Fixed and
        cluster peers have RESERVED slots and are always admitted
        (reference: Fixed.h fixed slots / cluster slots bypass the
        inbound cap); everyone else competes for max_in."""
        if is_fixed_or_cluster:
            return True
        return in_count < self.max_in

    def handout(
        self,
        exclude: set[tuple[str, int]],
        limit: int = GOSSIP_MAX,
    ) -> list[tuple[str, int]]:
        """Utility-ranked addresses to hand a peer we are refusing for
        lack of slots (reference ConnectHandouts.cpp: a full node
        REDIRECTS the connector to better targets instead of silently
        dropping it). Ranking: fresh low-hop livecache entries first,
        then bootcache by valence."""
        out: list[tuple[str, int]] = []
        for a in self.livecache.addrs():
            if len(out) >= limit:
                return out
            if a not in exclude and a not in out:
                out.append(a)
        for a in self.bootcache.ranked():
            if len(out) >= limit:
                break
            if a not in exclude and a not in out:
                out.append(a)
        return out

    def get_json(self) -> dict:
        return {
            "fixed": len(self.fixed),
            "bootcache": len(self.bootcache),
            "livecache": len(self.livecache),
            "max_in": self.max_in,
            "out_desired": self.out_desired,
            "max_peers": self.max_peers,
        }
