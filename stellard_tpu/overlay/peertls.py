"""TLS transport for validator peer links.

The reference encrypts EVERY peer connection with anonymous-cipher SSL
and proves node-key ownership by signing material bound to that specific
SSL session (PeerImp.h:88-90 async_handshake over beast MultiSocket; the
TMHello carries a node-key signature over the session fingerprint, so a
terminating man-in-the-middle is detected even though no certificate is
verified).

TPU-native equivalent, same trust model:

- Each node auto-generates a THROWAWAY self-signed cert (identity lives
  in the node keypair, not the X.509 subject) and peers use
  ``CERT_NONE`` — encryption without PKI, exactly the anonymous-cipher
  semantics.
- Links pin TLS 1.2 so the RFC 5929 ``tls-unique`` channel binding is
  available (CPython exposes no binding for TLS 1.3); the binding is
  mixed into the session hash each side signs with its node key in the
  hello. The binding differs on the two legs of any terminating MITM,
  so the hello signature check fails — the reference's session proof.
- Inbound sockets auto-detect TLS by peeking for the 0x16 handshake
  record (the reference's MultiSocket does the same SSL-or-plain
  autodetection), so a net can be upgraded node by node; ``required``
  refuses plaintext peers outright.
"""

from __future__ import annotations

import datetime
import os
import socket
import ssl
from typing import Optional

__all__ = ["PeerTLS", "ensure_node_cert", "make_door_ssl_context"]


def make_door_ssl_context(
    cert_path: str, key_path: str, state_dir: str
) -> ssl.SSLContext:
    """Server-side TLS context for the API doors (reference
    [rpc_secure]/[websocket_secure], Config.cpp:475-492). Empty paths
    auto-generate the node's self-signed transport cert — operators
    terminating with a real cert point [rpc_ssl_cert]/[rpc_ssl_key] at
    it, exactly the reference's config surface."""
    if not (cert_path and key_path):
        cert_path, key_path = ensure_node_cert(state_dir)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def _openssl_cli_cert(cert_path: str, key_path: str) -> tuple[str, str]:
    """Cert generation without the `cryptography` wheel: the ubiquitous
    openssl(1) binary emits the same throwaway self-signed EC transport
    cert. Only reached when the wheel is absent (see ensure_node_cert)."""
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        raise RuntimeError(
            "peer/door TLS needs a certificate but neither the "
            "`cryptography` wheel (pip install stellard-tpu[crypto]) nor "
            "an openssl(1) binary is available"
        )
    # 0o600 on the key from birth: pre-create it and have openssl write
    # into the existing file
    fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    os.close(fd)
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "ec",
            "-pkeyopt", "ec_paramgen_curve:prime256v1",
            "-keyout", key_path, "-out", cert_path,
            "-days", "3650", "-nodes",
            "-subj", "/CN=stellard-tpu-peer",
        ],
        check=True, capture_output=True,
    )
    return cert_path, key_path


def ensure_node_cert(state_dir: str) -> tuple[str, str]:
    """Return (cert_path, key_path), generating a self-signed EC cert on
    first use. The cert is a transport artifact only — peers never verify
    it — so its subject/lifetime carry no meaning."""
    os.makedirs(state_dir, exist_ok=True)
    cert_path = os.path.join(state_dir, "peer_tls_cert.pem")
    key_path = os.path.join(state_dir, "peer_tls_key.pem")
    if os.path.exists(cert_path) and os.path.exists(key_path):
        return cert_path, key_path

    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError:
        return _openssl_cli_cert(cert_path, key_path)

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "stellard-tpu-peer")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=3650))
        .sign(key, hashes.SHA256())
    )
    flags = os.O_WRONLY | os.O_CREAT | os.O_TRUNC
    with os.fdopen(os.open(key_path, flags, 0o600), "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            )
        )
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    return cert_path, key_path


class PeerTLS:
    """Per-overlay TLS wrapper: one server context (our throwaway cert)
    and one verification-free client context, both pinned to TLS 1.2 for
    the tls-unique session binding."""

    def __init__(self, cert_path: str, key_path: str, required: bool = False):
        self.required = required
        srv = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        srv.load_cert_chain(cert_path, key_path)
        cli = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        cli.check_hostname = False
        for ctx in (srv, cli):
            ctx.verify_mode = ssl.CERT_NONE
            ctx.minimum_version = ssl.TLSVersion.TLSv1_2
            ctx.maximum_version = ssl.TLSVersion.TLSv1_2
        self._server_ctx = srv
        self._client_ctx = cli

    @classmethod
    def from_state_dir(cls, state_dir: str, required: bool = False) -> "PeerTLS":
        cert, key = ensure_node_cert(state_dir)
        return cls(cert, key, required=required)

    def wrap_server(self, sock: socket.socket) -> ssl.SSLSocket:
        return self._server_ctx.wrap_socket(sock, server_side=True)

    def wrap_client(self, sock: socket.socket) -> ssl.SSLSocket:
        return self._client_ctx.wrap_socket(sock)

    @staticmethod
    def is_tls_client_hello(sock: socket.socket, timeout: float = 5.0) -> bool:
        """Peek the first byte without consuming it: 0x16 is the TLS
        handshake record type; anything else is our plaintext nonce
        exchange (reference: MultiSocket's SSL-or-plain autodetect)."""
        prev = sock.gettimeout()
        sock.settimeout(timeout)
        try:
            first = sock.recv(1, socket.MSG_PEEK)
        except OSError:
            return False
        finally:
            sock.settimeout(prev)
        return first == b"\x16"

    @staticmethod
    def channel_binding(sock) -> bytes:
        """RFC 5929 tls-unique of an established TLS session (b"" on a
        plaintext socket) — mixed into the signed session hash so the
        hello proof is bound to THIS encrypted channel."""
        get = getattr(sock, "get_channel_binding", None)
        if get is None:
            return b""
        try:
            return get("tls-unique") or b""
        except (ValueError, ssl.SSLError):
            return b""
