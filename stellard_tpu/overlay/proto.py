"""Minimal protobuf (proto2) wire-format codec, written from scratch.

The reference's overlay speaks protobuf messages defined in
src/ripple/proto/ripple.proto, framed by Message.cpp's 6-byte header.
SURVEY §5 names "same protobuf schema" as the wire-compatibility target,
so overlay.wire encodes its messages in genuine protobuf wire format
with ripple.proto's field numbers — via this ~150-line codec rather than
a vendored protobuf build (the reference vendors all of protobuf 2.x,
108k LoC, for exactly the subset implemented here: varint, 32/64-bit
and length-delimited fields, repeated fields, nested messages).

Encoding is a list of (field_number, wire_value) appends; decoding
parses a buffer into {field_number: [values]} with ints for varint /
fixed fields and bytes for length-delimited ones. Unknown fields are
skipped, which is what makes protobuf schemas forward-compatible.
"""

from __future__ import annotations

__all__ = [
    "Encoder",
    "parse",
    "first",
    "first_bytes",
    "first_int",
]

# wire types
WT_VARINT = 0
WT_FIXED64 = 1
WT_LEN = 2
WT_FIXED32 = 5


def _varint(n: int) -> bytes:
    if n < 0:
        # proto2 int32/int64 negatives encode as 10-byte two's complement
        n += 1 << 64
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Encoder:
    """Append-only protobuf message builder."""

    def __init__(self):
        self._parts: list[bytes] = []

    def _tag(self, field: int, wt: int) -> None:
        self._parts.append(_varint((field << 3) | wt))

    def varint(self, field: int, value: int) -> "Encoder":
        self._tag(field, WT_VARINT)
        self._parts.append(_varint(int(value)))
        return self

    def boolean(self, field: int, value: bool) -> "Encoder":
        return self.varint(field, 1 if value else 0)

    def blob(self, field: int, value: bytes) -> "Encoder":
        self._tag(field, WT_LEN)
        self._parts.append(_varint(len(value)))
        self._parts.append(bytes(value))
        return self

    def string(self, field: int, value: str) -> "Encoder":
        return self.blob(field, value.encode("utf-8"))

    def message(self, field: int, sub: "Encoder") -> "Encoder":
        return self.blob(field, sub.data())

    def fixed32(self, field: int, value: int) -> "Encoder":
        self._tag(field, WT_FIXED32)
        self._parts.append(int(value).to_bytes(4, "little"))
        return self

    def fixed64(self, field: int, value: int) -> "Encoder":
        self._tag(field, WT_FIXED64)
        self._parts.append(int(value).to_bytes(8, "little"))
        return self

    def data(self) -> bytes:
        return b"".join(self._parts)


def parse(buf: bytes) -> dict[int, list]:
    """Parse a protobuf message into {field: [values]} (ints / bytes).
    Raises ValueError on truncation or a malformed tag."""
    out: dict[int, list] = {}
    i = 0
    n = len(buf)
    while i < n:
        # tag varint
        tag = 0
        shift = 0
        while True:
            if i >= n:
                raise ValueError("truncated tag")
            b = buf[i]
            i += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                break
            if shift > 63:
                raise ValueError("tag varint overflow")
        field, wt = tag >> 3, tag & 7
        if field == 0:
            raise ValueError("field number 0")
        if wt == WT_VARINT:
            val = 0
            shift = 0
            while True:
                if i >= n:
                    raise ValueError("truncated varint")
                b = buf[i]
                i += 1
                val |= (b & 0x7F) << shift
                shift += 7
                if not (b & 0x80):
                    break
                if shift > 70:
                    raise ValueError("varint overflow")
        elif wt == WT_FIXED64:
            if i + 8 > n:
                raise ValueError("truncated fixed64")
            val = int.from_bytes(buf[i : i + 8], "little")
            i += 8
        elif wt == WT_LEN:
            ln = 0
            shift = 0
            while True:
                if i >= n:
                    raise ValueError("truncated length")
                b = buf[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not (b & 0x80):
                    break
                if shift > 35:
                    raise ValueError("length overflow")
            if i + ln > n:
                raise ValueError("truncated length-delimited field")
            val = bytes(buf[i : i + ln])
            i += ln
        elif wt == WT_FIXED32:
            if i + 4 > n:
                raise ValueError("truncated fixed32")
            val = int.from_bytes(buf[i : i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(field, []).append(val)
    return out


def first(fields: dict[int, list], field: int, default=None):
    vals = fields.get(field)
    return vals[0] if vals else default


def first_bytes(fields: dict[int, list], field: int, default: bytes = b"") -> bytes:
    v = first(fields, field, default)
    if not isinstance(v, (bytes, bytearray)):
        raise ValueError(f"field {field}: expected bytes")
    return bytes(v)


def first_int(fields: dict[int, list], field: int, default: int = 0) -> int:
    v = first(fields, field, default)
    if not isinstance(v, int):
        raise ValueError(f"field {field}: expected int")
    return v
