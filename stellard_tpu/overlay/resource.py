"""Resource consumption accounting — per-endpoint DoS defense.

Role parity with the reference's Resource::Manager / Consumer / Charge
plane (/root/reference/src/ripple/resource/api/Consumer.h:63,
impl/Logic.h:422-509, impl/Fees.cpp, impl/Tuning.h): every abusive or
costly action by a remote endpoint charges a fee against an exponentially
decaying balance; crossing `WARNING_THRESHOLD` flags the endpoint,
crossing `DROP_THRESHOLD` tells the overlay to disconnect (and keep
rejecting reconnects until the balance decays back under the line).

The decay here is an explicit exponential-moving-average over elapsed
seconds rather than the reference's power-of-two DecayingSample bucket
trick — same observable behavior (halving roughly every
``DECAY_WINDOW_SECONDS``), simpler math for a host runtime that is not
counting cycles.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "Charge",
    "Disposition",
    "ResourceManager",
    "FEE_INVALID_REQUEST",
    "FEE_REQUEST_NO_REPLY",
    "FEE_INVALID_SIGNATURE",
    "FEE_UNWANTED_DATA",
    "FEE_BAD_DATA",
    "FEE_GARBAGE_SEGMENT",
    "FEE_INVALID_RPC",
    "FEE_REFERENCE_RPC",
    "FEE_EXCEPTION_RPC",
    "FEE_LIGHT_RPC",
    "FEE_LOW_BURDEN_RPC",
    "FEE_MEDIUM_BURDEN_RPC",
    "FEE_HIGH_BURDEN_RPC",
    "FEE_PATH_FIND",
    "FEE_PATH_FIND_UPDATE",
    "FEE_NEW_VALID_TX",
    "FEE_SATISFIED_REQUEST",
    "WARNING_THRESHOLD",
    "DROP_THRESHOLD",
]


@dataclass(frozen=True)
class Charge:
    cost: int
    label: str

    def __str__(self) -> str:  # pragma: no cover - debug aid
        return f"{self.label}({self.cost})"


# Fee schedule (same costs as the reference's Fees.cpp so operator
# intuition transfers).
FEE_INVALID_REQUEST = Charge(10, "malformed request")
FEE_REQUEST_NO_REPLY = Charge(1, "unsatisfiable request")
FEE_INVALID_SIGNATURE = Charge(100, "invalid signature")
FEE_UNWANTED_DATA = Charge(5, "useless data")
FEE_BAD_DATA = Charge(20, "invalid data")
FEE_INVALID_RPC = Charge(10, "malformed RPC")
FEE_REFERENCE_RPC = Charge(2, "reference RPC")
FEE_EXCEPTION_RPC = Charge(10, "exceptioned RPC")
FEE_LIGHT_RPC = Charge(5, "light RPC")
FEE_LOW_BURDEN_RPC = Charge(20, "low RPC")
FEE_MEDIUM_BURDEN_RPC = Charge(40, "medium RPC")
FEE_HIGH_BURDEN_RPC = Charge(300, "heavy RPC")
# the pathfinding surfaces get their own class ABOVE heavy RPC: one
# path_find is a full candidate search + trial execution, the reference's
# notorious validator-killer — two back-to-back requests put a
# non-admin endpoint over the WARNING line (ISSUE 17 satellite)
FEE_PATH_FIND = Charge(400, "path find")
FEE_PATH_FIND_UPDATE = Charge(100, "path update")
FEE_NEW_VALID_TX = Charge(10, "valid tx")
FEE_SATISFIED_REQUEST = Charge(10, "needed data")
# FEE_BAD_DATA-class condemnation for a peer that served a garbage
# segment transfer (SegmentCatchup's per-peer fallback): one condemned
# transfer lands the endpoint PAST the warning line — relay/catch-up
# demotion — and a second pushes it over the DROP line, so the catch-up
# scorer and the overlay's drop gate act on ONE unified balance
FEE_GARBAGE_SEGMENT = Charge(800, "garbage segment transfer")

WARNING_THRESHOLD = 500
DROP_THRESHOLD = 1500
DECAY_WINDOW_SECONDS = 32.0
SECONDS_UNTIL_EXPIRATION = 300.0


class Disposition:
    OK = "ok"
    WARN = "warn"
    DROP = "drop"


class _Entry:
    __slots__ = ("balance", "stamp", "warned")

    def __init__(self, now: float):
        self.balance = 0.0
        self.stamp = now
        self.warned = False

    def decayed(self, now: float) -> float:
        dt = max(0.0, now - self.stamp)
        if dt:
            self.balance *= math.exp(-dt * (math.log(2.0) / DECAY_WINDOW_SECONDS))
            self.stamp = now
        return self.balance


class ResourceManager:
    """Tracks one decaying charge balance per endpoint key.

    ``key_fn`` maps a (host, port) remote address to the accounting key —
    by default the host only, matching the reference's by-IP inbound
    accounting; tests on loopback can inject host:port granularity.
    """

    def __init__(
        self,
        key_fn: Optional[Callable[[tuple], str]] = None,
        clock: Optional[Callable[[], float]] = None,
        admin: Optional[set[str]] = None,
    ):
        self._key_fn = key_fn or (lambda addr: addr[0])
        self._clock = clock or time.monotonic
        self._entries: dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self.admin = admin or set()
        self.dropped = 0      # charges that crossed the DROP line
        self.charged = 0
        self.warned = 0       # charges that crossed the WARN line
        self.refused = 0      # admissions refused (note_refused)
        self.throttled = 0    # inbound messages shed at WARN (note_throttled)
        self.disconnects = 0  # sessions torn down on DROP (note_disconnect)

    def key(self, addr: tuple) -> str:
        return self._key_fn(addr)

    def charge(self, addr: tuple, fee: Charge) -> str:
        """Charge the endpoint; returns a Disposition."""
        k = self.key(addr)
        if k in self.admin:
            return Disposition.OK
        now = self._clock()
        with self._lock:
            e = self._entries.get(k)
            if e is None:
                e = self._entries[k] = _Entry(now)
            bal = e.decayed(now) + fee.cost
            e.balance = bal
            self.charged += 1
            if bal >= DROP_THRESHOLD:
                self.dropped += 1
                return Disposition.DROP
            if bal >= WARNING_THRESHOLD:
                if not e.warned:  # count CROSSINGS, not charges-at-WARN
                    e.warned = True
                    self.warned += 1
                return Disposition.WARN
            e.warned = False  # decayed under the line: re-arm the crossing
            return Disposition.OK

    def balance(self, addr: tuple) -> float:
        with self._lock:
            e = self._entries.get(self.key(addr))
            return e.decayed(self._clock()) if e else 0.0

    def status(self, addr: tuple) -> str:
        """Current Disposition from the decayed balance, charging nothing."""
        if self.key(addr) in self.admin:
            return Disposition.OK
        bal = self.balance(addr)
        if bal >= DROP_THRESHOLD:
            return Disposition.DROP
        if bal >= WARNING_THRESHOLD:
            return Disposition.WARN
        return Disposition.OK

    def is_throttled(self, addr: tuple) -> bool:
        """WARN-or-worse: the overlay sheds this endpoint's non-essential
        inbound (tx gossip, endpoint gossip, bulk serving) until the
        balance decays back under the warning line."""
        return (
            self.key(addr) not in self.admin
            and self.balance(addr) >= WARNING_THRESHOLD
        )

    def should_admit(self, addr: tuple) -> bool:
        """Admission gate for new inbound connections: a dropped endpoint
        stays rejected until its balance decays under the drop line."""
        return (
            self.key(addr) in self.admin
            or self.balance(addr) < DROP_THRESHOLD
        )

    def note_refused(self, addr: tuple) -> None:
        self.refused += 1

    def note_throttled(self, n: int = 1) -> None:
        self.throttled += n

    def note_disconnect(self) -> None:
        self.disconnects += 1

    def aggregate_pressure(self) -> float:
        """Network-wide abuse pressure: the sum of all decayed balances
        relative to the warning threshold. ~0 on a healthy net; >= 1.0
        means the combined charge inflow equals one endpoint pinned at
        WARN. The overlay maps this onto LoadFeeTrack so local fees rise
        while the whole peer set misbehaves (reference: Logic::periodic
        feeding the load fee from importers)."""
        now = self._clock()
        with self._lock:
            total = sum(e.decayed(now) for e in self._entries.values())
        return total / float(WARNING_THRESHOLD)

    def sweep(self) -> None:
        """Expire idle entries (reference secondsUntilExpiration)."""
        now = self._clock()
        with self._lock:
            dead = [
                k
                for k, e in self._entries.items()
                if now - e.stamp > SECONDS_UNTIL_EXPIRATION or e.decayed(now) < 1.0
            ]
            for k in dead:
                del self._entries[k]

    def get_json(self) -> dict:
        now = self._clock()
        with self._lock:
            # bound the reported table: at 1000-peer fan-in the full
            # entry dict would dominate every get_counts payload
            items = sorted(
                ((k, e.decayed(now)) for k, e in self._entries.items()),
                key=lambda kv: -kv[1],
            )
            return {
                "entries": {k: round(bal, 1) for k, bal in items[:64]},
                "entry_count": len(items),
                "charged": self.charged,
                "warned": self.warned,
                "dropped": self.dropped,
                "refused": self.refused,
                "throttled": self.throttled,
                "disconnects": self.disconnects,
            }
