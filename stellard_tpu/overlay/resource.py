"""Resource consumption accounting — per-endpoint DoS defense.

Role parity with the reference's Resource::Manager / Consumer / Charge
plane (/root/reference/src/ripple/resource/api/Consumer.h:63,
impl/Logic.h:422-509, impl/Fees.cpp, impl/Tuning.h): every abusive or
costly action by a remote endpoint charges a fee against an exponentially
decaying balance; crossing `WARNING_THRESHOLD` flags the endpoint,
crossing `DROP_THRESHOLD` tells the overlay to disconnect (and keep
rejecting reconnects until the balance decays back under the line).

The decay here is an explicit exponential-moving-average over elapsed
seconds rather than the reference's power-of-two DecayingSample bucket
trick — same observable behavior (halving roughly every
``DECAY_WINDOW_SECONDS``), simpler math for a host runtime that is not
counting cycles.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "Charge",
    "Disposition",
    "ResourceManager",
    "FEE_INVALID_REQUEST",
    "FEE_REQUEST_NO_REPLY",
    "FEE_INVALID_SIGNATURE",
    "FEE_UNWANTED_DATA",
    "FEE_BAD_DATA",
    "FEE_INVALID_RPC",
    "FEE_REFERENCE_RPC",
    "FEE_EXCEPTION_RPC",
    "FEE_LIGHT_RPC",
    "FEE_LOW_BURDEN_RPC",
    "FEE_MEDIUM_BURDEN_RPC",
    "FEE_HIGH_BURDEN_RPC",
    "FEE_PATH_FIND_UPDATE",
    "FEE_NEW_VALID_TX",
    "FEE_SATISFIED_REQUEST",
    "WARNING_THRESHOLD",
    "DROP_THRESHOLD",
]


@dataclass(frozen=True)
class Charge:
    cost: int
    label: str

    def __str__(self) -> str:  # pragma: no cover - debug aid
        return f"{self.label}({self.cost})"


# Fee schedule (same costs as the reference's Fees.cpp so operator
# intuition transfers).
FEE_INVALID_REQUEST = Charge(10, "malformed request")
FEE_REQUEST_NO_REPLY = Charge(1, "unsatisfiable request")
FEE_INVALID_SIGNATURE = Charge(100, "invalid signature")
FEE_UNWANTED_DATA = Charge(5, "useless data")
FEE_BAD_DATA = Charge(20, "invalid data")
FEE_INVALID_RPC = Charge(10, "malformed RPC")
FEE_REFERENCE_RPC = Charge(2, "reference RPC")
FEE_EXCEPTION_RPC = Charge(10, "exceptioned RPC")
FEE_LIGHT_RPC = Charge(5, "light RPC")
FEE_LOW_BURDEN_RPC = Charge(20, "low RPC")
FEE_MEDIUM_BURDEN_RPC = Charge(40, "medium RPC")
FEE_HIGH_BURDEN_RPC = Charge(300, "heavy RPC")
FEE_PATH_FIND_UPDATE = Charge(100, "path update")
FEE_NEW_VALID_TX = Charge(10, "valid tx")
FEE_SATISFIED_REQUEST = Charge(10, "needed data")

WARNING_THRESHOLD = 500
DROP_THRESHOLD = 1500
DECAY_WINDOW_SECONDS = 32.0
SECONDS_UNTIL_EXPIRATION = 300.0


class Disposition:
    OK = "ok"
    WARN = "warn"
    DROP = "drop"


class _Entry:
    __slots__ = ("balance", "stamp", "warned")

    def __init__(self, now: float):
        self.balance = 0.0
        self.stamp = now
        self.warned = False

    def decayed(self, now: float) -> float:
        dt = max(0.0, now - self.stamp)
        if dt:
            self.balance *= math.exp(-dt * (math.log(2.0) / DECAY_WINDOW_SECONDS))
            self.stamp = now
        return self.balance


class ResourceManager:
    """Tracks one decaying charge balance per endpoint key.

    ``key_fn`` maps a (host, port) remote address to the accounting key —
    by default the host only, matching the reference's by-IP inbound
    accounting; tests on loopback can inject host:port granularity.
    """

    def __init__(
        self,
        key_fn: Optional[Callable[[tuple], str]] = None,
        clock: Optional[Callable[[], float]] = None,
        admin: Optional[set[str]] = None,
    ):
        self._key_fn = key_fn or (lambda addr: addr[0])
        self._clock = clock or time.monotonic
        self._entries: dict[str, _Entry] = {}
        self._lock = threading.Lock()
        self.admin = admin or set()
        self.dropped = 0
        self.charged = 0

    def key(self, addr: tuple) -> str:
        return self._key_fn(addr)

    def charge(self, addr: tuple, fee: Charge) -> str:
        """Charge the endpoint; returns a Disposition."""
        k = self.key(addr)
        if k in self.admin:
            return Disposition.OK
        now = self._clock()
        with self._lock:
            e = self._entries.get(k)
            if e is None:
                e = self._entries[k] = _Entry(now)
            bal = e.decayed(now) + fee.cost
            e.balance = bal
            self.charged += 1
            if bal >= DROP_THRESHOLD:
                self.dropped += 1
                return Disposition.DROP
            if bal >= WARNING_THRESHOLD:
                e.warned = True
                return Disposition.WARN
            return Disposition.OK

    def balance(self, addr: tuple) -> float:
        with self._lock:
            e = self._entries.get(self.key(addr))
            return e.decayed(self._clock()) if e else 0.0

    def should_admit(self, addr: tuple) -> bool:
        """Admission gate for new inbound connections: a dropped endpoint
        stays rejected until its balance decays under the drop line."""
        return self.balance(addr) < DROP_THRESHOLD

    def sweep(self) -> None:
        """Expire idle entries (reference secondsUntilExpiration)."""
        now = self._clock()
        with self._lock:
            dead = [
                k
                for k, e in self._entries.items()
                if now - e.stamp > SECONDS_UNTIL_EXPIRATION or e.decayed(now) < 1.0
            ]
            for k in dead:
                del self._entries[k]

    def get_json(self) -> dict:
        now = self._clock()
        with self._lock:
            return {
                "entries": {
                    k: round(e.decayed(now), 1) for k, e in self._entries.items()
                },
                "charged": self.charged,
                "dropped": self.dropped,
            }
