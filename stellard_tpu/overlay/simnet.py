"""SimNet: a deterministic, discrete-time, in-process validator network.

Reference: src/ripple/testoverlay (templated in-memory P2P net with
discrete time steps; SURVEY §4.2) and the peerfinder sim — the way the
reference tests multi-node behavior without sockets. Messages travel as
real wire frames (overlay.wire), so the codec and the consensus logic are
exercised together; only the transport is simulated.

Topology is a full mesh by default; links can be cut (partitions) and
given per-step latency. Time advances only via `step()`, so every run is
bit-for-bit reproducible.

Fault plane (the testkit scenario runner drives these): per-link
drop/duplicate/extra-delay/jitter probabilities from ONE seeded RNG
(`seed=` — identical seed, identical fault pattern), validator
kill/revive (a down node neither sends, receives, nor ticks — in-flight
messages to it are discarded at delivery), and per-SOURCE frame readers
so one peer's malformed bytes can never desync another peer's framing
(the TCP overlay gets this isolation from per-session sockets; the
simulated transport must provide it explicitly).
"""

from __future__ import annotations

import heapq
import itertools
import random
import struct
from typing import Callable, Optional

from ..consensus.consensus import ConsensusAdapter
from ..consensus.txset import TxSet
from ..consensus.validation import STValidation
from ..node.validator import ValidatorNode
from ..protocol.keys import KeyPair
from ..protocol.sttx import SerializedTransaction
from ..state.ledger import Ledger
from ..utils.hashes import sha512_half
from .resource import (
    FEE_BAD_DATA,
    FEE_INVALID_REQUEST,
    FEE_UNWANTED_DATA,
    Disposition,
    ResourceManager,
)
from .followertree import plan_tree, tree_stats
from .squelch import SQUELCH_ROTATE, SquelchPolicy
from .wire import (
    FrameReader,
    GetLedger,
    GetSegments,
    LedgerData,
    ProposeSet,
    SegmentData,
    TraceContext,
    TxMessage,
    TxSetData,
    ValidationMessage,
    frame,
)

__all__ = ["SimNet", "SimValidator", "RelayPeer"]

# network-epoch start time for simulations (seconds since 2000)
SIM_START_NTIME = 10_000_000


class SimValidator(ConsensusAdapter):
    """One simulated validator: a real ValidatorNode wired to the SimNet
    through the ConsensusAdapter seam."""

    def __init__(
        self,
        net: "SimNet",
        nid: int,
        key: KeyPair,
        unl: set[bytes],
        quorum: int,
        idle_interval: int,
        proposing: bool = True,
        voting=None,
        follower: bool = False,
    ):
        self.net = net
        self.nid = nid
        # one reader per SOURCE: a byzantine peer's garbage must desync
        # only its own stream, exactly like a per-session TCP socket
        self.readers: dict[int, FrameReader] = {}
        # enforced resource pricing (set by the net when enabled): one
        # decaying charge balance per SOURCE nid; DROP refuses further
        # deliveries until the balance decays (disconnect + gated
        # readmission, collapsed onto the simulated transport)
        self.resources: Optional[ResourceManager] = None
        # squelch policy (set by the net when squelch_size > 0)
        self.squelch: Optional[SquelchPolicy] = None
        # cascading follower tree: nid of the preferred upstream for
        # ledger acquisition (None = anycast over the validator core,
        # the flat-tier behavior); set by the net from plan_tree()
        self.upstream: Optional[int] = None
        self.node = ValidatorNode(
            key=key,
            unl=unl,
            adapter=self,
            quorum=quorum,
            network_time=net.network_time,
            clock=net.clock,
            idle_interval=idle_interval,
            proposing=proposing,
            voting=voting,
            follower=follower,
        )

    # -- cross-node trace propagation (no-ops while the module tracer
    # keeps propagate=0, the simnet default — wire bytes and scorecards
    # stay bit-identical) --------------------------------------------------

    def _trace_stamp(self, msg, txid=None, seq=None) -> None:
        ctx = self.node.lm.tracer.wire_context(txid=txid, seq=seq)
        if ctx is not None:
            msg.trace_ctx = TraceContext(*ctx)

    def _trace_adopt(self, msg) -> None:
        ctx = getattr(msg, "trace_ctx", None)
        if ctx is None:
            return
        tracer = self.node.lm.tracer
        if not (tracer.enabled and tracer.propagate):
            msg.trace_ctx = None  # re-relays stay legacy bytes
            return
        if ctx.sampled:
            tracer.adopt_context(tracer.trace_key(ctx.trace), ctx.parent)

    # -- ConsensusAdapter -------------------------------------------------

    def propose(self, proposal) -> None:
        msg = ProposeSet.from_proposal(proposal)
        if self.node.round is not None:
            self._trace_stamp(msg, seq=getattr(self.node.round, "seq", None))
        data = frame(msg)
        if self.squelch is not None:
            self.net.relay_validator(
                self.nid, proposal.node_public or self.node.key.public,
                data, self.squelch, kind="relay_proposal",
            )
        else:
            self.net.broadcast(self.nid, data)

    def share_tx_set(self, txset: TxSet) -> None:
        blobs = [blob for _txid, blob in txset.blobs()]
        self.net.broadcast(self.nid, frame(TxSetData(txset.hash(), blobs)))

    def acquire_tx_set(self, set_hash: bytes) -> Optional[TxSet]:
        return self.node.txset_cache.get(set_hash)

    def send_validation(self, val: STValidation) -> None:
        vmsg = ValidationMessage(val.serialize())
        self._trace_stamp(vmsg, seq=val.ledger_seq)
        data = frame(vmsg)
        if self.squelch is not None:
            self.net.relay_validator(
                self.nid, val.signer or self.node.key.public, data,
                self.squelch, kind="relay_validation",
            )
        else:
            self.net.broadcast(self.nid, data)

    def relay_disputed_tx(self, blob: bytes) -> None:
        self.net.broadcast(self.nid, frame(TxMessage(blob)))

    def request_ledger_data(self, msg: GetLedger) -> None:
        # cascading follower tree: a follower with a named upstream
        # acquires ledgers from THAT follower (leader egress stays
        # O(direct children)); when every ancestor is dead the net
        # resolves None and we re-home onto the validator anycast
        if self.upstream is not None:
            dst = self.net.upstream_for(self.nid)
            if dst is not None:
                self.net.send(self.nid, dst, frame(msg))
                return
        # anycast to one peer, rotating (reference: PeerSet picks a peer
        # per request); broadcasting would multiply reply waves by N-1
        self._acq_rr = getattr(self, "_acq_rr", 0) + 1
        n = len(self.net.validators)
        for step in range(1, n):
            dst = (self.nid + self._acq_rr + step) % n
            if dst != self.nid:
                self.net.send(self.nid, dst, frame(msg))
                return

    def on_accepted(self, ledger: Ledger, round_ms: int) -> None:
        self.net.on_ledger_accepted(self.nid, ledger)
        self.node.round_accepted(ledger, round_ms)

    # -- client side ------------------------------------------------------

    def submit_client_tx(self, tx: SerializedTransaction) -> None:
        """Client submission: apply locally, flood to peers
        (reference: NetworkOPs::processTransaction relay tail)."""
        self.node.submit(tx)
        msg = TxMessage(tx.serialize())
        self._trace_stamp(msg, txid=tx.txid())
        self.net.broadcast(self.nid, frame(msg))

    # -- delivery ---------------------------------------------------------

    def deliver(self, src: int, data: bytes) -> None:
        if self.resources is not None and not self.resources.should_admit(
            (src,)
        ):
            # endpoint above the DROP line: the session analog is a
            # disconnect + refused readmission until the balance decays
            self.resources.note_refused((src,))
            self.net.note_refusal(self.nid, src)
            return
        reader = self.readers.setdefault(src, FrameReader())
        try:
            msgs = list(reader.feed(data))
        except ValueError:
            # malformed frame / out-of-schema type: drop THIS source's
            # stream state (a real session would disconnect), count the
            # offense, keep every other peer's framing intact
            self.readers[src] = FrameReader()
            self.node.note_byzantine("malformed_frame", peer_nid=src)
            self._charge(src, FEE_INVALID_REQUEST)
            return
        if self.resources is not None and msgs and self.resources.is_throttled(
            (src,)
        ):
            # WARN throttling: shed the endpoint's tx gossip before any
            # parse/verify work; consensus traffic still flows. Shed
            # traffic still pays, so a sustained flood walks past WARN
            # to DROP instead of parking at the throttle forever.
            kept = [m for m in msgs if not isinstance(m, TxMessage)]
            if len(kept) != len(msgs):
                from .resource import Charge

                n_shed = len(msgs) - len(kept)
                self.resources.note_throttled(n_shed)
                self._charge(src, Charge(
                    FEE_UNWANTED_DATA.cost * n_shed, "throttled flood"
                ))
                msgs = kept
        # one delivery often carries several relayed txs: parse each
        # once and batch their signature verification through the plane
        # before dispatching. An unparseable tx drops only ITSELF —
        # the rest of the delivery still dispatches.
        parsed: dict[int, SerializedTransaction] = {}
        for i, m in enumerate(msgs):
            if isinstance(m, TxMessage):
                try:
                    parsed[i] = SerializedTransaction.from_bytes(m.blob)
                except Exception:  # noqa: BLE001 — malformed relay
                    self._charge(src, FEE_BAD_DATA)
        if len(parsed) > 1:
            try:
                self.node.prefetch_tx_sigs(list(parsed.values()))
            except Exception:  # noqa: BLE001 — prefetch is an
                pass           # optimization; per-tx paths re-verify
        for i, msg in enumerate(msgs):
            if isinstance(msg, TxMessage):
                if i in parsed:
                    self._trace_adopt(msg)
                    self.node.handle_tx(parsed[i])
            else:
                self._dispatch(src, msg)

    def _charge(self, src: int, fee) -> None:
        if self.resources is None:
            return
        if self.resources.charge((src,), fee) == Disposition.DROP:
            self.resources.note_disconnect()

    def _dispatch(self, src: int, msg) -> None:
        node = self.node
        self._trace_adopt(msg)
        # TxMessages are handled (parse-once + batched sig prefetch) in
        # deliver(), the only caller
        if isinstance(msg, ProposeSet):
            if self.squelch is not None:
                data = frame(msg)
                is_new, dup = node.router.note_peer(sha512_half(data), src)
                if dup:
                    self._charge(src, FEE_UNWANTED_DATA)
                if is_new and node.handle_proposal(msg.to_proposal()):
                    self.net.relay_validator(
                        self.nid, msg.node_public, data, self.squelch,
                        exclude=(src,), kind="relay_proposal",
                    )
            else:
                node.handle_proposal(msg.to_proposal())
        elif isinstance(msg, ValidationMessage):
            if self.squelch is not None:
                data = frame(msg)
                is_new, dup = node.router.note_peer(sha512_half(data), src)
                if dup:
                    self._charge(src, FEE_UNWANTED_DATA)
                if is_new:
                    val = STValidation.from_bytes(msg.blob)
                    if node.handle_validation(val):
                        self.net.relay_validator(
                            self.nid, val.signer or b"", data, self.squelch,
                            exclude=(src,), kind="relay_validation",
                        )
            else:
                node.handle_validation(STValidation.from_bytes(msg.blob))
        elif isinstance(msg, TxSetData):
            from ..consensus.txset import MAX_TXSET_BLOBS

            if len(msg.tx_blobs) > MAX_TXSET_BLOBS:
                # oversized candidate set: refuse before parsing a single
                # blob (a byzantine peer must not buy O(huge) parse work)
                node.note_byzantine("oversized_txset", peer_nid=src)
                self._charge(src, FEE_BAD_DATA)
                return
            ts = TxSet(node.hash_batch)
            intact = True
            for blob in msg.tx_blobs:
                try:
                    tx = SerializedTransaction.from_bytes(blob)
                except Exception:  # noqa: BLE001 — hostile blob
                    intact = False
                    break
                ts.add(tx.txid(), blob)
            if intact and ts.hash() == msg.set_hash:  # recomputed root
                node.handle_txset(ts)
            else:
                node.note_byzantine("txset_mismatch", peer_nid=src)
                self._charge(src, FEE_BAD_DATA)
        elif isinstance(msg, GetSegments):
            reply = node.serve_get_segments(msg)
            if reply is not None:
                if msg.trace_ctx is not None:
                    reply.trace_ctx = msg.trace_ctx
                self.net.send(self.nid, src, frame(reply))
        elif isinstance(msg, SegmentData):
            node.handle_segment_data(src, msg)
        elif isinstance(msg, GetLedger):
            reply = node.serve_get_ledger(msg)
            if reply is not None:
                self.net.send(self.nid, src, frame(reply))
        elif isinstance(msg, LedgerData):
            node.handle_ledger_data(msg)


class RelayPeer:
    """A lightweight non-validator overlay node for production-fan-in
    scenarios: it parses wire frames, dedups, enforces resource pricing
    on its sources, and re-relays validator messages through squelch
    subsets — WITHOUT running consensus or verifying signatures. This is
    what makes 500-1000-node simnets tractable: the validator core stays
    full ValidatorNodes, the fan-in tier costs a frame parse + k sends
    per message. Client txs are NOT re-relayed (the injection path
    already floods them to every node), so the relay tier's traffic is
    exactly the squelched proposal/validation gossip the scenario
    measures."""

    SEEN_CAP = 8192

    def __init__(self, net: "SimNet", nid: int):
        self.net = net
        self.nid = nid
        self.readers: dict[int, FrameReader] = {}
        # message hash -> set of sources that delivered it (bounded,
        # insertion-ordered eviction) — the HashRouter role
        self.seen: dict[bytes, set[int]] = {}
        self.resources: Optional[ResourceManager] = None
        self.squelch: Optional[SquelchPolicy] = None
        self.malformed = 0

    def _charge(self, src: int, fee) -> None:
        if self.resources is not None:
            self.resources.charge((src,), fee)

    def _note_seen(self, h: bytes, src: int) -> tuple[bool, bool]:
        sources = self.seen.get(h)
        if sources is None:
            if len(self.seen) >= self.SEEN_CAP:
                self.seen.pop(next(iter(self.seen)))
            self.seen[h] = {src}
            return True, False
        dup = src in sources
        sources.add(src)
        return False, dup

    def deliver(self, src: int, data: bytes) -> None:
        if self.resources is not None and not self.resources.should_admit(
            (src,)
        ):
            self.resources.note_refused((src,))
            self.net.note_refusal(self.nid, src)
            return
        reader = self.readers.setdefault(src, FrameReader())
        try:
            msgs = list(reader.feed(data))
        except ValueError:
            self.readers[src] = FrameReader()
            self.malformed += 1
            self._charge(src, FEE_INVALID_REQUEST)
            return
        throttled = (
            self.resources is not None
            and bool(msgs)
            and self.resources.is_throttled((src,))
        )
        for msg in msgs:
            if isinstance(msg, ProposeSet):
                self._relay(src, msg, msg.node_public)
            elif isinstance(msg, ValidationMessage):
                try:
                    signer = STValidation.from_bytes(msg.blob).signer or b""
                except Exception:  # noqa: BLE001 — hostile blob
                    self._charge(src, FEE_BAD_DATA)
                    continue
                self._relay(src, msg, signer)
            elif isinstance(msg, TxMessage) and throttled:
                self.resources.note_throttled()
                self._charge(src, FEE_UNWANTED_DATA)  # shed traffic pays

    def _relay(self, src: int, msg, signer: bytes) -> None:
        data = frame(msg)
        is_new, dup = self._note_seen(sha512_half(data), src)
        if dup:
            self._charge(src, FEE_UNWANTED_DATA)
        if is_new and self.squelch is not None:
            kind = (
                "relay_proposal" if isinstance(msg, ProposeSet)
                else "relay_validation"
            )
            self.net.relay_validator(
                self.nid, signer, data, self.squelch,
                exclude=(src,), kind=kind,
            )


class SimNet:
    def __init__(
        self,
        n_validators: int = 4,
        quorum: Optional[int] = None,
        latency_steps: int = 1,
        step_ms: int = 1000,
        idle_interval: int = 4,
        genesis_account: Optional[bytes] = None,
        voting_factory=None,
        seed: int = 0,
        n_peers: int = 0,
        squelch_size: int = 0,
        squelch_rotate: int = SQUELCH_ROTATE,
        resources: bool = False,
        n_followers: int = 0,
        follower_branching: int = 0,
    ):
        self.step_ms = step_ms
        self.latency_ms = latency_steps * step_ms
        self.time_ms = 0
        self._seq = itertools.count()
        # (deliver_at_ms, seq, dst, bytes)
        self._queue: list = []
        self._links_down: set[tuple[int, int]] = set()
        # fault plane: ONE seeded stream drives every probabilistic
        # fault, so a given seed replays the identical fault pattern
        self.seed = seed
        self.rng = random.Random(0x5EED ^ seed)
        # (src, dst) -> {"drop": p, "dup": p, "delay_steps": n,
        #               "jitter_steps": n} (directional)
        self._link_faults: dict[tuple[int, int], dict] = {}
        self._down: set[int] = set()
        self.net_stats = {
            "sent": 0, "dropped_link": 0, "dropped_fault": 0,
            "dropped_down": 0, "duplicated": 0, "delayed": 0,
        }
        # src nid -> set of dsts that refused its deliveries (DROP gate)
        self.refusals: dict[int, set[int]] = {}
        # src nid -> virtual ms of the FIRST refusal (drop latency: how
        # long a flooder ran before the first honest node shut the door)
        self.first_refusal_ms: dict[int, int] = {}
        self.accept_log: list[tuple[int, int, bytes]] = []  # (nid, seq, hash)

        self.keys = [
            KeyPair.from_passphrase(f"sim-validator-{i}")
            for i in range(n_validators)
        ]
        unl = {k.public for k in self.keys}
        self.unl = unl
        self.idle_interval = idle_interval
        q = quorum if quorum is not None else (n_validators * 3 + 3) // 4
        self.validators = [
            SimValidator(
                self,
                i,
                self.keys[i],
                unl,
                q,
                idle_interval,
                voting=voting_factory(i) if voting_factory else None,
            )
            for i in range(n_validators)
        ]
        # production fan-in shape: a small trusted validator core plus a
        # relay-peer tier (nids n_validators..n_validators+n_peers-1)
        self.peers = [
            RelayPeer(self, n_validators + j) for j in range(n_peers)
        ]
        # follower tier ([node] mode=follower, the PR 9 read plane):
        # non-consensus full nodes (nids after the relay tier) whose
        # chains advance ONLY by ingesting trusted validations and
        # acquiring the validated ledgers — scenarios partition/kill
        # them like any node and assert they end on the honest chain
        self.followers = [
            SimValidator(
                self, n_validators + n_peers + j,
                KeyPair.from_passphrase(f"sim-follower-{j}"),
                unl, q, idle_interval, follower=True,
            )
            for j in range(n_followers)
        ]
        self.nodes: list = (
            list(self.validators) + list(self.peers) + list(self.followers)
        )
        # cascading follower tree (0 = flat tier, every follower
        # anycasts to the validator core — byte-for-byte the pre-tree
        # behavior): plan_tree assigns each follower a parent; tier-1
        # followers (parent -1) keep upstream=None (they ARE the
        # leader's direct children), deeper tiers prefer their parent
        # follower for ledger acquisition and re-home upward on kill
        self.follower_branching = int(follower_branching)
        self.tree_parents: list[int] = []
        if follower_branching > 0 and n_followers > 0:
            self.tree_parents = plan_tree(n_followers, follower_branching)
            base = n_validators + n_peers
            for j, p in enumerate(self.tree_parents):
                if p >= 0:
                    self.followers[j].upstream = base + p
            # materialized only for tree nets: legacy scorecards keep
            # their exact net_stats shape
            self.net_stats["rehomed"] = 0
        # validator-message squelching (0 = full flood, byte-for-byte
        # today's behavior — the [overlay] squelch=0 kill-switch)
        self.squelch_size = squelch_size
        self.squelch_rotate = squelch_rotate
        self.resources_enabled = resources
        if squelch_size > 0 or resources:
            # fan-out / defense evidence (only materialized when the
            # defense plane is on, so legacy scorecards stay identical)
            self.net_stats.update({
                "relay_proposal": 0, "relay_validation": 0,
                "relay_fanout_max": 0, "refused": 0,
            })
        for node in self.nodes:
            if squelch_size > 0:
                node.squelch = SquelchPolicy(
                    size=squelch_size, rotate=squelch_rotate,
                    relayer_id=struct.pack(">I", node.nid),
                )
            if resources:
                node.resources = ResourceManager(
                    key_fn=lambda a: a[0], clock=self.clock,
                )
        self.genesis_account = genesis_account

    # -- clocks -----------------------------------------------------------

    def clock(self) -> float:
        return self.time_ms / 1000.0

    def network_time(self) -> int:
        return SIM_START_NTIME + self.time_ms // 1000

    # -- topology ---------------------------------------------------------

    def cut_link(self, a: int, b: int) -> None:
        self._links_down.add((a, b))
        self._links_down.add((b, a))

    def heal_link(self, a: int, b: int) -> None:
        self._links_down.discard((a, b))
        self._links_down.discard((b, a))

    def partition(self, group_a: set[int], group_b: set[int]) -> None:
        for a in group_a:
            for b in group_b:
                self.cut_link(a, b)

    def set_link_fault(
        self,
        a: int,
        b: int,
        drop: float = 0.0,
        dup: float = 0.0,
        delay_steps: int = 0,
        jitter_steps: int = 0,
        bidirectional: bool = True,
    ) -> None:
        """Degrade a link: `drop`/`dup` are per-message probabilities,
        `delay_steps` adds fixed latency, `jitter_steps` adds a uniform
        random extra delay (which also REORDERS messages relative to the
        base-latency ones — heapq delivery is by arrival time)."""
        fault = {
            "drop": drop, "dup": dup,
            "delay_steps": delay_steps, "jitter_steps": jitter_steps,
        }
        self._link_faults[(a, b)] = fault
        if bidirectional:
            self._link_faults[(b, a)] = dict(fault)

    def clear_link_fault(self, a: int, b: int) -> None:
        self._link_faults.pop((a, b), None)
        self._link_faults.pop((b, a), None)

    # -- follower tree ----------------------------------------------------

    def upstream_for(self, nid: int) -> Optional[int]:
        """Resolve a tree follower's LIVE upstream: its parent if up,
        else walk up the ancestor chain (re-home onto the grandparent,
        then great-grandparent, ... then the leader). Returns None for
        non-tree nodes or when the walk reaches the leader tier — the
        caller falls back to the validator anycast, which IS the
        leader re-home."""
        if not self.tree_parents:
            return None
        base = len(self.validators) + len(self.peers)
        j = nid - base
        if not (0 <= j < len(self.tree_parents)):
            return None
        p = self.tree_parents[j]
        hops = 0
        while p >= 0:
            dst = base + p
            if dst not in self._down:
                if hops:
                    self.net_stats["rehomed"] += 1
                return dst
            hops += 1
            p = self.tree_parents[p]
        if hops:
            self.net_stats["rehomed"] += 1
        return None

    def tree_json(self) -> dict:
        """Tree-shape + re-home evidence for the scenario scorecard."""
        out = tree_stats(self.tree_parents, self.follower_branching)
        out["rehomed"] = self.net_stats.get("rehomed", 0)
        return out

    # -- validator kill/revive --------------------------------------------

    def kill(self, nid: int) -> None:
        """Silence a validator: no sends, no deliveries, no timer ticks.
        In-flight messages TO it are discarded at delivery time (a dead
        process loses its socket buffers)."""
        self._down.add(nid)

    def revive(self, nid: int) -> None:
        self._down.discard(nid)

    def is_down(self, nid: int) -> bool:
        return nid in self._down

    # -- transport --------------------------------------------------------

    def broadcast(self, src: int, data: bytes) -> None:
        for dst in range(len(self.nodes)):
            if dst != src:
                self.send(src, dst, data)

    def sim_seq(self) -> int:
        """Approximate ledger cadence for the squelch epoch clock: the
        deterministic virtual-time analog of 'rotate every N ledgers'."""
        return self.time_ms // max(1, self.step_ms * self.idle_interval)

    def relay_validator(
        self, src: int, signer: bytes, data: bytes, policy: SquelchPolicy,
        exclude: tuple = (), kind: str = "relay_proposal",
    ) -> None:
        """Squelched fan-out of one validator message: the deterministic
        rotating subset for (signer, epoch, relayer) plus the validator
        core; untrusted signers demoted. Fan-out evidence rides
        net_stats so scenarios can assert the bound. The subset ranks
        over ALL other nodes and the message's source is filtered from
        the RESULT (excluding it from the ranking input would alias the
        policy memo across sources — same candidate count, different
        members — echoing relays back to their sender for an epoch)."""
        n_val = len(self.validators)
        cands = [i for i in range(len(self.nodes)) if i != src]
        subset = policy.subset(
            signer, self.sim_seq(), cands,
            key_fn=lambda i: struct.pack(">I", i),
            trusted=lambda i: i < n_val,
            demoted=bool(signer) and signer not in self.unl,
        )
        targets = [dst for dst in subset if dst not in exclude]
        for dst in targets:
            self.send(src, dst, data)
        self.net_stats[kind] += 1
        if len(targets) > self.net_stats["relay_fanout_max"]:
            self.net_stats["relay_fanout_max"] = len(targets)

    def note_refusal(self, dst: int, src: int) -> None:
        self.net_stats["refused"] = self.net_stats.get("refused", 0) + 1
        self.refusals.setdefault(src, set()).add(dst)
        self.first_refusal_ms.setdefault(src, self.time_ms)

    def resource_json(self) -> dict:
        """`resource.*` evidence aggregated over every enforcing node —
        the counter block flood scenarios assert on (charges paid, WARN
        crossings, DROP crossings, shed messages, refused deliveries)."""
        agg = {
            "charged": 0, "warned": 0, "dropped": 0,
            "refused": 0, "throttled": 0,
        }
        for node in self.nodes:
            rm = node.resources
            if rm is None:
                continue
            agg["charged"] += rm.charged
            agg["warned"] += rm.warned
            agg["dropped"] += rm.dropped
            agg["refused"] += rm.refused
            agg["throttled"] += rm.throttled
        agg["refusing_nodes"] = {
            src: len(dsts) for src, dsts in sorted(self.refusals.items())
        }
        return agg

    def send(self, src: int, dst: int, data: bytes) -> None:
        if src in self._down or dst in self._down:
            self.net_stats["dropped_down"] += 1
            return
        if (src, dst) in self._links_down:
            self.net_stats["dropped_link"] += 1
            return
        self.net_stats["sent"] += 1
        delay_ms = self.latency_ms
        fault = self._link_faults.get((src, dst))
        copies = 1
        if fault is not None:
            # exposure evidence for the scenario plane's anti-vacuity
            # check: the fault was ARMED on live traffic (whether any
            # message then dropped/duplicated is probabilistic — a
            # lucky streak must not read as a silently-dead fault).
            # Key materializes lazily so legacy nets keep their shape.
            self.net_stats["fault_exposed"] = (
                self.net_stats.get("fault_exposed", 0) + 1
            )
            if fault["drop"] and self.rng.random() < fault["drop"]:
                self.net_stats["dropped_fault"] += 1
                return
            if fault["dup"] and self.rng.random() < fault["dup"]:
                copies = 2
                self.net_stats["duplicated"] += 1
            extra = fault["delay_steps"]
            if fault["jitter_steps"]:
                extra += self.rng.randrange(fault["jitter_steps"] + 1)
            if extra:
                delay_ms += extra * self.step_ms
                self.net_stats["delayed"] += 1
        for _ in range(copies):
            heapq.heappush(
                self._queue,
                (self.time_ms + delay_ms, next(self._seq), dst, src, data),
            )

    def on_ledger_accepted(self, nid: int, ledger: Ledger) -> None:
        self.accept_log.append((nid, ledger.seq, ledger.hash()))

    # -- simulation loop --------------------------------------------------

    def start(self) -> None:
        if self.genesis_account is None:
            # the well-known test genesis account (node.MASTER_PASSPHRASE)
            self.genesis_account = KeyPair.from_passphrase(
                "masterpassphrase"
            ).account_id
        root = self.genesis_account
        for v in self.validators:
            v.node.start(root, close_time=self.network_time())
        for f in self.followers:
            f.node.start(root, close_time=self.network_time())

    def step(self, n: int = 1) -> None:
        for _ in range(n):
            self.time_ms += self.step_ms
            while self._queue and self._queue[0][0] <= self.time_ms:
                _at, _seq, dst, src, data = heapq.heappop(self._queue)
                if dst in self._down:
                    # a dead process loses its socket buffers; messages
                    # already in flight FROM a freshly-killed node still
                    # arrive (they left its kernel before the crash)
                    self.net_stats["dropped_down"] += 1
                    continue
                self.nodes[dst].deliver(src, data)
            for v in self.validators:
                if v.nid not in self._down:
                    v.node.on_timer()
            for f in self.followers:
                if f.nid not in self._down:
                    f.node.on_timer()

    def run_until(
        self, pred: Callable[[], bool], max_steps: int = 200
    ) -> bool:
        for _ in range(max_steps):
            if pred():
                return True
            self.step()
        return pred()

    # -- assertions helpers ----------------------------------------------

    def validated_seqs(self) -> list[int]:
        return [
            v.node.lm.validated.seq if v.node.lm.validated else 0
            for v in self.validators
        ]

    def validated_hashes_at(self, seq: int) -> set[bytes]:
        out = set()
        for v in self.validators:
            h = v.node.lm.ledger_history.get(seq)
            if h is not None:
                out.add(h)
        return out

    def all_validated_at_least(self, seq: int) -> bool:
        return all(s >= seq for s in self.validated_seqs())
