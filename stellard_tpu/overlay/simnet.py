"""SimNet: a deterministic, discrete-time, in-process validator network.

Reference: src/ripple/testoverlay (templated in-memory P2P net with
discrete time steps; SURVEY §4.2) and the peerfinder sim — the way the
reference tests multi-node behavior without sockets. Messages travel as
real wire frames (overlay.wire), so the codec and the consensus logic are
exercised together; only the transport is simulated.

Topology is a full mesh by default; links can be cut (partitions) and
given per-step latency. Time advances only via `step()`, so every run is
bit-for-bit reproducible.

Fault plane (the testkit scenario runner drives these): per-link
drop/duplicate/extra-delay/jitter probabilities from ONE seeded RNG
(`seed=` — identical seed, identical fault pattern), validator
kill/revive (a down node neither sends, receives, nor ticks — in-flight
messages to it are discarded at delivery), and per-SOURCE frame readers
so one peer's malformed bytes can never desync another peer's framing
(the TCP overlay gets this isolation from per-session sockets; the
simulated transport must provide it explicitly).
"""

from __future__ import annotations

import heapq
import itertools
import random
from typing import Callable, Optional

from ..consensus.consensus import ConsensusAdapter
from ..consensus.txset import TxSet
from ..consensus.validation import STValidation
from ..node.validator import ValidatorNode
from ..protocol.keys import KeyPair
from ..protocol.sttx import SerializedTransaction
from ..state.ledger import Ledger
from .wire import (
    FrameReader,
    GetLedger,
    GetSegments,
    LedgerData,
    ProposeSet,
    SegmentData,
    TxMessage,
    TxSetData,
    ValidationMessage,
    frame,
)

__all__ = ["SimNet", "SimValidator"]

# network-epoch start time for simulations (seconds since 2000)
SIM_START_NTIME = 10_000_000


class SimValidator(ConsensusAdapter):
    """One simulated validator: a real ValidatorNode wired to the SimNet
    through the ConsensusAdapter seam."""

    def __init__(
        self,
        net: "SimNet",
        nid: int,
        key: KeyPair,
        unl: set[bytes],
        quorum: int,
        idle_interval: int,
        proposing: bool = True,
        voting=None,
    ):
        self.net = net
        self.nid = nid
        # one reader per SOURCE: a byzantine peer's garbage must desync
        # only its own stream, exactly like a per-session TCP socket
        self.readers: dict[int, FrameReader] = {}
        self.node = ValidatorNode(
            key=key,
            unl=unl,
            adapter=self,
            quorum=quorum,
            network_time=net.network_time,
            clock=net.clock,
            idle_interval=idle_interval,
            proposing=proposing,
            voting=voting,
        )

    # -- ConsensusAdapter -------------------------------------------------

    def propose(self, proposal) -> None:
        self.net.broadcast(self.nid, frame(ProposeSet.from_proposal(proposal)))

    def share_tx_set(self, txset: TxSet) -> None:
        blobs = [blob for _txid, blob in txset.blobs()]
        self.net.broadcast(self.nid, frame(TxSetData(txset.hash(), blobs)))

    def acquire_tx_set(self, set_hash: bytes) -> Optional[TxSet]:
        return self.node.txset_cache.get(set_hash)

    def send_validation(self, val: STValidation) -> None:
        self.net.broadcast(self.nid, frame(ValidationMessage(val.serialize())))

    def relay_disputed_tx(self, blob: bytes) -> None:
        self.net.broadcast(self.nid, frame(TxMessage(blob)))

    def request_ledger_data(self, msg: GetLedger) -> None:
        # anycast to one peer, rotating (reference: PeerSet picks a peer
        # per request); broadcasting would multiply reply waves by N-1
        self._acq_rr = getattr(self, "_acq_rr", 0) + 1
        n = len(self.net.validators)
        for step in range(1, n):
            dst = (self.nid + self._acq_rr + step) % n
            if dst != self.nid:
                self.net.send(self.nid, dst, frame(msg))
                return

    def on_accepted(self, ledger: Ledger, round_ms: int) -> None:
        self.net.on_ledger_accepted(self.nid, ledger)
        self.node.round_accepted(ledger, round_ms)

    # -- client side ------------------------------------------------------

    def submit_client_tx(self, tx: SerializedTransaction) -> None:
        """Client submission: apply locally, flood to peers
        (reference: NetworkOPs::processTransaction relay tail)."""
        self.node.submit(tx)
        self.net.broadcast(self.nid, frame(TxMessage(tx.serialize())))

    # -- delivery ---------------------------------------------------------

    def deliver(self, src: int, data: bytes) -> None:
        reader = self.readers.setdefault(src, FrameReader())
        try:
            msgs = list(reader.feed(data))
        except ValueError:
            # malformed frame / out-of-schema type: drop THIS source's
            # stream state (a real session would disconnect), count the
            # offense, keep every other peer's framing intact
            self.readers[src] = FrameReader()
            self.node.note_byzantine("malformed_frame", peer_nid=src)
            return
        # one delivery often carries several relayed txs: parse each
        # once and batch their signature verification through the plane
        # before dispatching. An unparseable tx drops only ITSELF —
        # the rest of the delivery still dispatches.
        parsed: dict[int, SerializedTransaction] = {}
        for i, m in enumerate(msgs):
            if isinstance(m, TxMessage):
                try:
                    parsed[i] = SerializedTransaction.from_bytes(m.blob)
                except Exception:  # noqa: BLE001 — malformed relay
                    pass
        if len(parsed) > 1:
            try:
                self.node.prefetch_tx_sigs(list(parsed.values()))
            except Exception:  # noqa: BLE001 — prefetch is an
                pass           # optimization; per-tx paths re-verify
        for i, msg in enumerate(msgs):
            if isinstance(msg, TxMessage):
                if i in parsed:
                    self.node.handle_tx(parsed[i])
            else:
                self._dispatch(src, msg)

    def _dispatch(self, src: int, msg) -> None:
        node = self.node
        # TxMessages are handled (parse-once + batched sig prefetch) in
        # deliver(), the only caller
        if isinstance(msg, ProposeSet):
            node.handle_proposal(msg.to_proposal())
        elif isinstance(msg, ValidationMessage):
            node.handle_validation(STValidation.from_bytes(msg.blob))
        elif isinstance(msg, TxSetData):
            from ..consensus.txset import MAX_TXSET_BLOBS

            if len(msg.tx_blobs) > MAX_TXSET_BLOBS:
                # oversized candidate set: refuse before parsing a single
                # blob (a byzantine peer must not buy O(huge) parse work)
                node.note_byzantine("oversized_txset", peer_nid=src)
                return
            ts = TxSet(node.hash_batch)
            intact = True
            for blob in msg.tx_blobs:
                try:
                    tx = SerializedTransaction.from_bytes(blob)
                except Exception:  # noqa: BLE001 — hostile blob
                    intact = False
                    break
                ts.add(tx.txid(), blob)
            if intact and ts.hash() == msg.set_hash:  # recomputed root
                node.handle_txset(ts)
            else:
                node.note_byzantine("txset_mismatch", peer_nid=src)
        elif isinstance(msg, GetSegments):
            reply = node.serve_get_segments(msg)
            if reply is not None:
                self.net.send(self.nid, src, frame(reply))
        elif isinstance(msg, SegmentData):
            node.handle_segment_data(src, msg)
        elif isinstance(msg, GetLedger):
            reply = node.serve_get_ledger(msg)
            if reply is not None:
                self.net.send(self.nid, src, frame(reply))
        elif isinstance(msg, LedgerData):
            node.handle_ledger_data(msg)


class SimNet:
    def __init__(
        self,
        n_validators: int = 4,
        quorum: Optional[int] = None,
        latency_steps: int = 1,
        step_ms: int = 1000,
        idle_interval: int = 4,
        genesis_account: Optional[bytes] = None,
        voting_factory=None,
        seed: int = 0,
    ):
        self.step_ms = step_ms
        self.latency_ms = latency_steps * step_ms
        self.time_ms = 0
        self._seq = itertools.count()
        # (deliver_at_ms, seq, dst, bytes)
        self._queue: list = []
        self._links_down: set[tuple[int, int]] = set()
        # fault plane: ONE seeded stream drives every probabilistic
        # fault, so a given seed replays the identical fault pattern
        self.seed = seed
        self.rng = random.Random(0x5EED ^ seed)
        # (src, dst) -> {"drop": p, "dup": p, "delay_steps": n,
        #               "jitter_steps": n} (directional)
        self._link_faults: dict[tuple[int, int], dict] = {}
        self._down: set[int] = set()
        self.net_stats = {
            "sent": 0, "dropped_link": 0, "dropped_fault": 0,
            "dropped_down": 0, "duplicated": 0, "delayed": 0,
        }
        self.accept_log: list[tuple[int, int, bytes]] = []  # (nid, seq, hash)

        self.keys = [
            KeyPair.from_passphrase(f"sim-validator-{i}")
            for i in range(n_validators)
        ]
        unl = {k.public for k in self.keys}
        q = quorum if quorum is not None else (n_validators * 3 + 3) // 4
        self.validators = [
            SimValidator(
                self,
                i,
                self.keys[i],
                unl,
                q,
                idle_interval,
                voting=voting_factory(i) if voting_factory else None,
            )
            for i in range(n_validators)
        ]
        self.genesis_account = genesis_account

    # -- clocks -----------------------------------------------------------

    def clock(self) -> float:
        return self.time_ms / 1000.0

    def network_time(self) -> int:
        return SIM_START_NTIME + self.time_ms // 1000

    # -- topology ---------------------------------------------------------

    def cut_link(self, a: int, b: int) -> None:
        self._links_down.add((a, b))
        self._links_down.add((b, a))

    def heal_link(self, a: int, b: int) -> None:
        self._links_down.discard((a, b))
        self._links_down.discard((b, a))

    def partition(self, group_a: set[int], group_b: set[int]) -> None:
        for a in group_a:
            for b in group_b:
                self.cut_link(a, b)

    def set_link_fault(
        self,
        a: int,
        b: int,
        drop: float = 0.0,
        dup: float = 0.0,
        delay_steps: int = 0,
        jitter_steps: int = 0,
        bidirectional: bool = True,
    ) -> None:
        """Degrade a link: `drop`/`dup` are per-message probabilities,
        `delay_steps` adds fixed latency, `jitter_steps` adds a uniform
        random extra delay (which also REORDERS messages relative to the
        base-latency ones — heapq delivery is by arrival time)."""
        fault = {
            "drop": drop, "dup": dup,
            "delay_steps": delay_steps, "jitter_steps": jitter_steps,
        }
        self._link_faults[(a, b)] = fault
        if bidirectional:
            self._link_faults[(b, a)] = dict(fault)

    def clear_link_fault(self, a: int, b: int) -> None:
        self._link_faults.pop((a, b), None)
        self._link_faults.pop((b, a), None)

    # -- validator kill/revive --------------------------------------------

    def kill(self, nid: int) -> None:
        """Silence a validator: no sends, no deliveries, no timer ticks.
        In-flight messages TO it are discarded at delivery time (a dead
        process loses its socket buffers)."""
        self._down.add(nid)

    def revive(self, nid: int) -> None:
        self._down.discard(nid)

    def is_down(self, nid: int) -> bool:
        return nid in self._down

    # -- transport --------------------------------------------------------

    def broadcast(self, src: int, data: bytes) -> None:
        for dst in range(len(self.validators)):
            if dst != src:
                self.send(src, dst, data)

    def send(self, src: int, dst: int, data: bytes) -> None:
        if src in self._down or dst in self._down:
            self.net_stats["dropped_down"] += 1
            return
        if (src, dst) in self._links_down:
            self.net_stats["dropped_link"] += 1
            return
        self.net_stats["sent"] += 1
        delay_ms = self.latency_ms
        fault = self._link_faults.get((src, dst))
        copies = 1
        if fault is not None:
            if fault["drop"] and self.rng.random() < fault["drop"]:
                self.net_stats["dropped_fault"] += 1
                return
            if fault["dup"] and self.rng.random() < fault["dup"]:
                copies = 2
                self.net_stats["duplicated"] += 1
            extra = fault["delay_steps"]
            if fault["jitter_steps"]:
                extra += self.rng.randrange(fault["jitter_steps"] + 1)
            if extra:
                delay_ms += extra * self.step_ms
                self.net_stats["delayed"] += 1
        for _ in range(copies):
            heapq.heappush(
                self._queue,
                (self.time_ms + delay_ms, next(self._seq), dst, src, data),
            )

    def on_ledger_accepted(self, nid: int, ledger: Ledger) -> None:
        self.accept_log.append((nid, ledger.seq, ledger.hash()))

    # -- simulation loop --------------------------------------------------

    def start(self) -> None:
        if self.genesis_account is None:
            # the well-known test genesis account (node.MASTER_PASSPHRASE)
            self.genesis_account = KeyPair.from_passphrase(
                "masterpassphrase"
            ).account_id
        root = self.genesis_account
        for v in self.validators:
            v.node.start(root, close_time=self.network_time())

    def step(self, n: int = 1) -> None:
        for _ in range(n):
            self.time_ms += self.step_ms
            while self._queue and self._queue[0][0] <= self.time_ms:
                _at, _seq, dst, src, data = heapq.heappop(self._queue)
                if dst in self._down:
                    # a dead process loses its socket buffers; messages
                    # already in flight FROM a freshly-killed node still
                    # arrive (they left its kernel before the crash)
                    self.net_stats["dropped_down"] += 1
                    continue
                self.validators[dst].deliver(src, data)
            for v in self.validators:
                if v.nid not in self._down:
                    v.node.on_timer()

    def run_until(
        self, pred: Callable[[], bool], max_steps: int = 200
    ) -> bool:
        for _ in range(max_steps):
            if pred():
                return True
            self.step()
        return pred()

    # -- assertions helpers ----------------------------------------------

    def validated_seqs(self) -> list[int]:
        return [
            v.node.lm.validated.seq if v.node.lm.validated else 0
            for v in self.validators
        ]

    def validated_hashes_at(self, seq: int) -> set[bytes]:
        out = set()
        for v in self.validators:
            h = v.node.lm.ledger_history.get(seq)
            if h is not None:
                out.add(h)
        return out

    def all_validated_at_least(self, seq: int) -> bool:
        return all(s >= seq for s in self.validated_seqs())
