"""Validator-message squelching: deterministic rotating relay subsets.

Role parity with the reference overlay's squelching ("reduce-relay"):
at production fan-in, relaying every validator's proposals and
validations to EVERY peer costs O(peers) sends per node per message —
the dominant overlay traffic at 1000 peers. Squelching bounds each
node's relay fan-out for a given validator to a small subset of its
peers, rotated on an epoch schedule so no fixed set of relayers is a
permanent censorship point.

The reference negotiates squelches dynamically (receivers tell senders
to stop); this reproduction derives the subset DETERMINISTICALLY so the
deterministic simnet replays bit-identically and any two processes
agree on the subset without negotiation traffic:

    rank(candidate) = sha512_half(signer || epoch || relayer || candidate)

and the relay set is the ``size`` lowest-ranked candidates. Properties:

- pure function of (signer, epoch, relayer id, candidate ids): the same
  UNL + seq yields the same subset in every process (pinned by test);
- rotation: the epoch advances every ``rotate`` ledgers, re-randomizing
  every subset; peer churn re-ranks immediately (the subset is always
  computed over the CURRENT candidate set);
- per-relayer diversity: the relayer's own id salts the rank, so the
  union of all nodes' subsets forms a k-out gossip digraph (connected
  with overwhelming probability for size >= 2) rather than one global
  k-subset that would strand messages;
- trusted-validator peers are ALWAYS included (consensus-critical
  traffic is never squelched away from the quorum), so the fan-out
  bound is ``size + |UNL peers|`` — independent of peer count;
- untrusted-source demotion: messages signed by keys outside the UNL
  relay to ``max(1, size // demote_factor)`` peers with NO forced
  validator inclusion — correctly-signed-but-untrusted chatter cannot
  buy full fan-out.

``size=0`` is the kill-switch: full flood, byte-for-byte the
pre-squelch behavior (pinned by test).
"""

from __future__ import annotations

import struct
from typing import Callable, Iterable, Optional

from ..utils.hashes import sha512_half

__all__ = ["SQUELCH_SIZE", "SQUELCH_ROTATE", "relay_rank", "SquelchPolicy"]

# default relay-subset size per (validator, epoch); the reference keeps
# a similar single-digit squelch set per validator
SQUELCH_SIZE = 8
# ledgers per squelch epoch: long enough that a subset amortizes, short
# enough that a bad relayer set rotates away within a minute
SQUELCH_ROTATE = 16


def relay_rank(
    signer: bytes, epoch: int, relayer: bytes, candidate: bytes
) -> bytes:
    """The deterministic ranking key (lowest ranks win a relay slot)."""
    return sha512_half(
        signer + struct.pack(">Q", epoch & 0xFFFFFFFFFFFFFFFF)
        + relayer + candidate
    )


class SquelchPolicy:
    """Subset computation + a one-epoch memo.

    The memo matters at scale: ranking is O(candidates) hashes, and at
    1000 peers a validator's proposal triggers a relay decision on every
    node it reaches — caching per (signer, epoch) makes the steady-state
    cost O(size) sends. The cache is invalidated by epoch advance or by
    ``bump()`` (peer churn).
    """

    def __init__(
        self,
        size: int = SQUELCH_SIZE,
        rotate: int = SQUELCH_ROTATE,
        demote_factor: int = 4,
        relayer_id: bytes = b"",
    ):
        self.size = int(size)
        self.rotate = max(1, int(rotate))
        self.demote_factor = max(1, int(demote_factor))
        self.relayer_id = relayer_id
        self._cache: dict[tuple, list] = {}
        self._version = 0  # bumped on peer churn

    @property
    def enabled(self) -> bool:
        return self.size > 0

    @property
    def demoted_size(self) -> int:
        return max(1, self.size // self.demote_factor)

    def epoch(self, seq: int) -> int:
        return int(seq) // self.rotate

    def bump(self) -> None:
        """Candidate set changed (peer churn): drop every memoized
        subset so the next relay re-ranks over the current peers."""
        self._version += 1
        self._cache.clear()

    def subset(
        self,
        signer: bytes,
        seq: int,
        candidates: Iterable,
        key_fn: Callable[[object], bytes],
        trusted: Optional[Callable[[object], bool]] = None,
        demoted: bool = False,
    ) -> list:
        """Relay targets for one validator's message at ledger ``seq``.

        ``candidates`` is the relayer's current peer set (any objects),
        ``key_fn`` maps a candidate to its stable wire identity bytes,
        ``trusted`` marks always-include candidates (UNL peers),
        ``demoted=True`` applies the untrusted-source demotion.
        """
        cands = list(candidates)
        if not self.enabled:
            return cands
        k = self.demoted_size if demoted else self.size
        if len(cands) <= k:
            return cands
        ep = self.epoch(seq)
        memo_key = (signer, ep, demoted, self._version, len(cands))
        hit = self._cache.get(memo_key)
        if hit is not None:
            return hit
        ranked = sorted(
            cands,
            key=lambda c: relay_rank(signer, ep, self.relayer_id, key_fn(c)),
        )
        picked = ranked[:k]
        if not demoted and trusted is not None:
            chosen = {id(c) for c in picked}
            picked = picked + [
                c for c in cands
                if trusted(c) and id(c) not in chosen
            ]
        if len(self._cache) > 256:  # one-epoch working set is tiny
            self._cache.clear()
        self._cache[memo_key] = picked
        return picked

    def get_json(self) -> dict:
        return {
            "size": self.size,
            "rotate": self.rotate,
            "demoted_size": self.demoted_size if self.enabled else 0,
            "enabled": self.enabled,
        }
