"""TCP overlay: real-socket peer sessions for a validator private net.

Reference: src/ripple_overlay/impl/{OverlayImpl,PeerImp}.cpp — inbound
door + outbound dials, per-peer handshake proving node-key ownership,
length-prefixed message framing, flood relay with HashRouter
suppression. The reference handshakes over anonymous SSL and signs the
SSL session fingerprint (PeerImp hello proof); without a vendored TLS
stack we exchange fresh random nonces and sign the hash of both, which
gives the same session-binding property on a trusted LAN/DCN. Validator
traffic rides this overlay (DCN); the TPU batch work stays on ICI
(SURVEY §2.9 mapping #3).

Threading model: one reader thread per peer plus a shared heartbeat
thread driving the consensus timer — the asio/JobQueue shape collapsed
onto the ValidatorNode's internal locking.
"""

from __future__ import annotations

import itertools
import logging
import os
import socket
import struct
import threading
import time
from typing import Callable, Optional

from ..consensus.consensus import ConsensusAdapter
from ..consensus.txset import TxSet
from ..consensus.validation import STValidation
from ..node.hashrouter import SF_RELAYED
from ..node.validator import ValidatorNode
from ..protocol.keys import KeyPair, verify_signature
from ..protocol.sttx import SerializedTransaction
from ..state.ledger import Ledger
from ..utils.hashes import prefix_hash
from .peerfinder import GOSSIP_INTERVAL, PeerFinder
from .resource import (
    Disposition,
    FEE_BAD_DATA,
    FEE_INVALID_REQUEST,
    FEE_INVALID_SIGNATURE,
    FEE_REQUEST_NO_REPLY,
    FEE_UNWANTED_DATA,
    ResourceManager,
)
from .squelch import SQUELCH_ROTATE, SQUELCH_SIZE, SquelchPolicy
from .wire import (
    ClusterStatus,
    ClusterUpdate,
    Endpoints,
    FrameReader,
    GetLedger,
    GetSegments,
    GetTxSet,
    Hello,
    LedgerData,
    Ping,
    ProposeSet,
    SegmentData,
    TraceContext,
    TxMessage,
    TxSetData,
    ValidationMessage,
    frame,
)

__all__ = ["TcpOverlay"]

log = logging.getLogger("stellard.overlay")

PROTO_VERSION = 1
# domain prefix for the session-binding signature ("SSN\0")
HP_SESSION = (ord("S") << 24) | (ord("S") << 16) | (ord("N") << 8)


class _Peer:
    # bounded outbound queue: a peer that stops reading sheds here
    # instead of blocking the caller (consensus timer / relay threads
    # must NEVER wait on a socket — reference: PeerImp's async writes)
    SENDQ_DEPTH = 256
    # graceful degradation (the infosub sendq discipline applied to the
    # overlay): overflow drops the OLDEST queued frame — a slow reader
    # sees a gap its acquisition machinery repairs, never a stale
    # stream — and this many CONSECUTIVE overflow events evicts the
    # peer outright (it is wedged, not slow)
    EVICT_DROPS = 64
    # writer coalescing: drain up to this many queued bytes into ONE
    # sendall — a relay burst of small frames becomes one size-bounded
    # batch write instead of a syscall per frame
    WRITE_COALESCE = 256 * 1024

    # never-recycled session ids for HashRouter suppression sets (id()
    # can be reused by a later peer object within the router's 300s hold,
    # which would wrongly exclude a fresh peer from relays)
    _NEXT_UID = itertools.count(1)

    def __init__(self, sock: socket.socket, inbound: bool,
                 addr: Optional[tuple[str, int]] = None,
                 sendq_depth: Optional[int] = None,
                 evict_drops: Optional[int] = None):
        import queue

        if sendq_depth:
            self.SENDQ_DEPTH = int(sendq_depth)  # instance override
        if evict_drops:
            self.EVICT_DROPS = int(evict_drops)
        self.uid = next(_Peer._NEXT_UID)
        # serializes SSL_read/SSL_write on a TLS socket: one OpenSSL SSL*
        # must not run concurrent operations from two threads (the writer
        # thread sends while the session thread recvs). Plain sockets
        # don't take it — the kernel allows full-duplex concurrency.
        self.io_lock = threading.Lock()
        self.is_tls = False
        # slot accounting (reference Counts.h): reserved = fixed/cluster
        self.slot_reserved = False
        # real-clock establishment stamp (0.0 = never registered) and a
        # flag marking closes that must NOT trigger dial backoff
        self.established_mono = 0.0
        self.benign_close = False
        # acquisition scoring (reference: PeerSet peer selection): how
        # many ledger-data requests we routed here and how many replies
        # came back — the reply rate drives future routing
        self.acq_requests = 0
        self.acq_replies = 0
        self.sock = sock
        self.inbound = inbound
        self.addr = addr  # configured dial address (outbound only)
        self.reader = FrameReader()
        self.node_public: bytes = b""
        self.send_lock = threading.Lock()
        self.sendq: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=self.SENDQ_DEPTH
        )
        # sendq shedding evidence (aggregated into the overlay's
        # `resource`/`squelch` observability blocks)
        self.sendq_dropped = 0
        self._consec_drops = 0
        self.evicted = False
        self._writer: Optional[threading.Thread] = None
        self.alive = True
        self.established_at = 0.0
        # real wall-clock (not the node's virtual clock): socket liveness
        self.last_recv = time.monotonic()
        try:
            self.remote: tuple[str, int] = sock.getpeername()[:2]
        except OSError:
            self.remote = ("?", 0)
        # (remote_ip, their_listen_port) once the hello arrives — the
        # dialable identity of this peer for discovery
        self.advertised: Optional[tuple[str, int]] = None

    def send(self, data: bytes) -> None:
        """Non-blocking enqueue; the per-peer writer thread drains. A
        full queue sheds the OLDEST queued frame (never the sender's
        thread — the master lock may be held here); EVICT_DROPS
        consecutive overflows means the reader is wedged, not slow, and
        the peer is evicted so one dead peer can never hold a sendq's
        worth of every relay wave forever."""
        import queue

        if not self.alive:
            return
        if self._writer is None:
            with self.send_lock:
                if self._writer is None:
                    t = threading.Thread(
                        target=self._write_loop, name="peer-writer", daemon=True
                    )
                    self._writer = t
                    t.start()
        try:
            self.sendq.put_nowait(data)
        except queue.Full:
            self.sendq_dropped += 1
            self._consec_drops += 1
            if self._consec_drops >= self.EVICT_DROPS:
                self.evicted = True
                self.close()
                return
            try:
                self.sendq.get_nowait()  # drop-OLDEST
            except queue.Empty:
                pass
            try:
                self.sendq.put_nowait(data)
            except queue.Full:
                pass  # racing senders refilled it: this frame sheds
        else:
            self._consec_drops = 0

    def _write_loop(self) -> None:
        import queue

        while True:
            data = self.sendq.get()
            if data is None or not self.alive:
                return
            # coalesce a backlog burst into one bounded write: frames
            # are self-delimiting, so concatenation is free batching
            if len(data) < self.WRITE_COALESCE:
                chunks = [data]
                size = len(data)
                while size < self.WRITE_COALESCE:
                    try:
                        nxt = self.sendq.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is None:  # close sentinel: flush then exit
                        self._flush(b"".join(chunks))
                        return
                    chunks.append(nxt)
                    size += len(nxt)
                data = b"".join(chunks) if len(chunks) > 1 else data
            if not self._flush(data):
                return

    def _flush(self, data: bytes) -> bool:
        try:
            if self.is_tls:
                with self.io_lock:
                    self.sock.sendall(data)
            else:
                self.sock.sendall(data)  # SO_SNDTIMEO bounds each write
            return True
        except OSError:
            self.alive = False
            return False

    def recv_locked(self, bufsize: int = 65536) -> Optional[bytes]:
        """One recv honoring the TLS serialization rule. Returns None on
        a poll timeout (TLS path polls so the writer can interleave),
        b\"\" on EOF, data otherwise. Raises OSError on a dead socket."""
        if not self.is_tls:
            return self.sock.recv(bufsize)
        import ssl as _ssl

        try:
            with self.io_lock:
                return self.sock.recv(bufsize)
        except (TimeoutError, socket.timeout, _ssl.SSLWantReadError):
            return None

    def close(self) -> None:
        self.alive = False
        try:
            self.sendq.put_nowait(None)  # wake the writer
        except Exception:  # noqa: BLE001 — full queue: shutdown below aborts it
            pass
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _acq_score(p) -> tuple:
    """Ordering key for acquisition routing: better reply rate first,
    then fewer outstanding requests (min() picks the best)."""
    rate = (p.acq_replies + 1) / (p.acq_requests + 1)
    outstanding = p.acq_requests - p.acq_replies
    return (-rate, outstanding)


class TcpOverlay(ConsensusAdapter):
    """Peer-connection manager + the node's ConsensusAdapter."""

    def __init__(
        self,
        key: KeyPair,
        unl: set[bytes],
        quorum: int,
        port: int,
        peer_addrs: list[tuple[str, int]],
        network_time: Optional[Callable[[], int]] = None,
        clock: Optional[Callable[[], float]] = None,
        timer_interval: float = 1.0,
        idle_interval: int = 15,
        hash_batch: Optional[Callable] = None,
        peer_idle_ping: float = 9.0,
        peer_idle_drop: float = 30.0,
        out_desired: int = 8,
        max_peers: int = 21,
        bootcache_path: Optional[str] = None,
        resource_key_fn: Optional[Callable] = None,
        gossip_interval: float = GOSSIP_INTERVAL,
        unl_store=None,
        cluster: Optional[set[bytes]] = None,
        fee_track=None,
        verify_many: Optional[Callable] = None,
        proposing: bool = True,
        router=None,
        job_dispatch: Optional[Callable[[str, Callable], None]] = None,
        peer_tls=None,
        follower: bool = False,
        pinned_upstream: bool = False,
        squelch_size: int = SQUELCH_SIZE,
        squelch_rotate: int = SQUELCH_ROTATE,
        sendq_cap: int = 0,
        sendq_evict_drops: int = 0,
    ):
        self.key = key
        self.port = port
        self.peer_addrs = peer_addrs
        self.timer_interval = timer_interval
        self.peer_idle_ping = peer_idle_ping
        self.peer_idle_drop = peer_idle_drop
        self._clock = clock or time.monotonic
        self._ntime = network_time or (lambda: int(time.time()) - 946_684_800)
        self.node = ValidatorNode(
            key=key,
            unl=unl,
            adapter=self,
            quorum=quorum,
            network_time=self._ntime,
            clock=self._clock,
            idle_interval=idle_interval,
            hash_batch=hash_batch,
            verify_many=verify_many,
            proposing=proposing,
            router=router,
            follower=follower,
        )
        if unl_store is not None:
            # per-validator misbehavior bookkeeping: defense events with
            # an identified trusted signer land on its UNL row
            def _note_unl(kind: str, peer_pub: bytes) -> None:
                if peer_pub in unl_store:
                    unl_store.on_byzantine(peer_pub, kind)

            self.node.on_byzantine = _note_unl
        self.peers: dict[bytes, _Peer] = {}  # node pubkey -> session
        self._dialing: set[tuple[str, int]] = set()  # dials in flight
        # cascading follower tree ([node] upstream=): a pinned follower
        # dials ONLY its named upstreams — fixed seeds are always kept
        # connected, but out_desired=0 disables discovery dialing, so
        # gossip-learned endpoints (including the leader's) can never
        # re-flatten the tree; inbound children still attach freely
        self.pinned_upstream = bool(pinned_upstream)
        self.peerfinder = PeerFinder(
            fixed=peer_addrs,
            out_desired=0 if pinned_upstream else out_desired,
            max_peers=max_peers,
            bootcache_path=bootcache_path,
        )
        self.resources = ResourceManager(key_fn=resource_key_fn)
        # validator-message squelching ([overlay] squelch=): every relay
        # (and origin send) of a proposal/validation goes to the
        # deterministic rotating subset for its SIGNER instead of the
        # whole peer set; squelch_size=0 is the full-flood kill-switch
        self.squelch = SquelchPolicy(
            size=squelch_size, rotate=squelch_rotate,
            relayer_id=key.public,
        )
        self.sendq_cap = int(sendq_cap)
        self.sendq_evict_drops = int(sendq_evict_drops)
        # overlay defense evidence (`resource.*`/`squelch.*` naming,
        # doc/observability.md): relay fan-outs, throttled/dup sheds,
        # sendq drops/evictions — the counters scenario gates assert on
        from ..node.metrics import AtomicCounters

        self.overlay_stats = AtomicCounters(
            "relay_proposal", "relay_validation", "relay_fanout_max",
            "throttled_msgs", "dup_charges", "sendq_dropped",
            "sendq_evicted", "squelch_demoted",
        )
        self.unl_store = unl_store  # node.unl.UniqueNodeList or None
        # same-operator cluster (reference mtCLUSTER): members share their
        # load fee so the whole cluster escalates together
        self.cluster = cluster or set()
        self.fee_track = fee_track  # node.loadmgr.LoadFeeTrack or None
        # peer-message scheduler seam: when the application container
        # wires its JobQueue here, proposal/validation handling becomes
        # jtPROPOSAL_t/jtVALIDATION_t jobs (latency-tracked, sheddable);
        # bare overlays handle inline
        self.job_dispatch = job_dispatch
        # transport encryption (overlay/peertls.py). None = plaintext
        # (reference parity requires TLS: every reference peer link is
        # anonymous SSL, PeerImp.h:88-90); when set, outbound dials speak
        # TLS, inbound autodetects, and `peer_tls.required` refuses
        # plaintext peers
        self.peer_tls = peer_tls
        self.gossip_interval = gossip_interval
        self._last_gossip = 0.0
        self._peers_lock = threading.Lock()
        # our own addresses as learned from self-connects via gossiped
        # endpoints: never handed out, never redialed
        self._self_addrs: set[tuple[str, int]] = set()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._threads_lock = threading.Lock()
        self._listener: Optional[socket.socket] = None

    # -- lifecycle --------------------------------------------------------

    def start(self, genesis_account: bytes, close_time: int = 0) -> None:
        self.node.start(genesis_account, close_time or self._ntime())
        self.start_network()

    def start_network(self) -> None:
        """Open the listener + dial/timer loops WITHOUT (re)creating the
        genesis ledger — the path for an application container whose
        LedgerMaster was already set up (fresh or loaded) by Node.setup."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", self.port))
        self._listener.listen(16)
        self._spawn(self._accept_loop)
        self._spawn(self._connect_loop)
        self._spawn(self._timer_loop)

    def stop(self) -> None:
        self._stop.set()
        try:
            self.peerfinder.bootcache.save()
        except OSError:
            pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._peers_lock:
            for p in list(self.peers.values()):
                p.close()
        with self._threads_lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)

    def _spawn(self, fn, *args) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True)
        t.start()
        with self._threads_lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # -- session establishment -------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            self._spawn(self._session, sock, True)

    def _connect_loop(self) -> None:
        """Fill outbound slots from the PeerFinder's connect policy
        (reference: OverlayImpl autoconnect via PeerFinder::autoconnect):
        fixed seeds always, then gossip-discovered endpoints. Addresses
        with a live session (or a dial in flight) are skipped so an
        established connection is never churned by the redial timer."""
        while not self._stop.is_set():
            with self._peers_lock:
                connected = {
                    a
                    for p in self.peers.values()
                    if p.alive
                    for a in (p.addr, p.advertised)
                    if a is not None
                }
                dialing = set(self._dialing)
                out_count = sum(
                    1 for p in self.peers.values() if not p.inbound and p.alive
                )
                total = len(self.peers)
            # never dial ourselves (our own gossiped hop-0 endpoint,
            # plus any address a past self-connect proved is us)
            connected.add(("127.0.0.1", self.port))
            with self._peers_lock:
                connected |= self._self_addrs
            targets = self.peerfinder.dial_targets(
                connected, dialing, out_count, total
            )
            for addr in targets:
                with self._peers_lock:
                    if addr in self._dialing:
                        continue
                    self._dialing.add(addr)
                self._spawn(self._dial, addr)
            self._stop.wait(2.0)

    def _dial(self, addr: tuple[str, int]) -> None:
        try:
            sock = socket.create_connection(addr, timeout=2.0)
        except OSError:
            self.peerfinder.on_failure(addr)
            with self._peers_lock:
                self._dialing.discard(addr)
            return
        if self.peer_tls is not None:
            import ssl as _ssl

            sock.settimeout(5.0)
            try:
                sock = self.peer_tls.wrap_client(sock)
            except (OSError, _ssl.SSLError):
                try:
                    sock.close()
                except OSError:
                    pass
                if self.peer_tls.required:
                    self.peerfinder.on_failure(addr)
                    with self._peers_lock:
                        self._dialing.discard(addr)
                    return
                # allow mode: the remote may be a plaintext node that ate
                # our ClientHello as garbage — redial in the clear
                # (opportunistic encryption, mixed-net upgrades)
                try:
                    sock = socket.create_connection(addr, timeout=2.0)
                except OSError:
                    self.peerfinder.on_failure(addr)
                    with self._peers_lock:
                        self._dialing.discard(addr)
                    return
                self._session(sock, False, addr)
                return
            self._session(sock, False, addr, tls=True)
            return
        self._session(sock, False, addr)

    def _session(
        self,
        sock: socket.socket,
        inbound: bool,
        addr: Optional[tuple[str, int]] = None,
        tls: bool = False,
    ) -> None:
        """Nonce exchange → signed hello → message pump
        (reference: PeerImp::onHandshake/recvHello). Outbound TLS wrapping
        happens in _dial (where a failed handshake can fall back to a
        plaintext redial); inbound autodetects here."""
        peer = _Peer(sock, inbound, addr,
                     sendq_depth=self.sendq_cap,
                     evict_drops=self.sendq_evict_drops)
        peer.is_tls = tls
        try:
            if inbound and not self.resources.should_admit(peer.remote):
                # endpoint balance still above the drop line: refuse
                # reconnects until it decays (reference Logic::newInboundEndpoint)
                self.resources.note_refused(peer.remote)
                peer.close()
                return
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            sock.settimeout(5.0)
            if self.peer_tls is not None and inbound:
                # SSL-or-plain autodetect (reference: MultiSocket)
                if self.peer_tls.is_tls_client_hello(sock):
                    sock = self.peer_tls.wrap_server(sock)
                    peer.sock = sock  # writer/pump/close use the TLS socket
                    peer.is_tls = True
                elif self.peer_tls.required:
                    peer.close()  # plaintext peer refused
                    return
            if peer.is_tls:
                # from here on the writer thread (hello send onward) and
                # this session thread share one SSL object: reads poll on
                # a short timeout so the io_lock is released regularly
                sock.settimeout(0.05)
            # first nonce byte must not collide with the TLS handshake
            # record type (0x16) or the remote's autodetect would
            # misclassify this plaintext session
            nonce = os.urandom(32)
            while nonce[0] == 0x16:
                nonce = os.urandom(32)
            sock.sendall(nonce)
            their_nonce = self._read_exact(sock, 32)
            # session binding the hello signature proves: both nonces
            # plus (when encrypted) the RFC 5929 tls-unique value of THIS
            # TLS session — a terminating MITM's two legs have different
            # bindings, so its spliced hellos fail verification
            # (reference: node-key proof of the SSL session fingerprint)
            binding = (
                self.peer_tls.channel_binding(sock)
                if (self.peer_tls is not None and peer.is_tls)
                else b""
            )
            session_hash = prefix_hash(
                HP_SESSION,
                min(nonce, their_nonce) + max(nonce, their_nonce) + binding,
            )
            lcl = self.node.lm.closed_ledger()
            hello = Hello(
                PROTO_VERSION,
                self._ntime(),
                self.key.public,
                self.key.sign(session_hash),
                lcl.seq,
                lcl.hash(),
                self.port,
            )
            peer.send(frame(hello))
            their_hello = self._read_hello(sock, peer)
            if their_hello is None:
                peer.close()
                return
            if not verify_signature(
                their_hello.node_public, session_hash, their_hello.session_sig
            ):
                self._charge(peer, FEE_INVALID_SIGNATURE)
                peer.close()
                return
            if their_hello.proto_version != PROTO_VERSION:
                # protocol version skew: refuse cleanly (reference: TMHello
                # version gate in PeerImp::recvHello)
                peer.close()
                return
            if their_hello.node_public == self.key.public:
                # connected to ourselves via a gossiped address: drop,
                # blacklist in the bootcache, and remember it as a SELF
                # address so it is never handed out or redialed
                if addr is not None:
                    self.peerfinder.on_failure(addr)
                    with self._peers_lock:
                        self._self_addrs.add(addr)
                peer.close()
                return
            peer.node_public = their_hello.node_public
            if 0 < their_hello.listen_port < 65536:
                peer.advertised = (peer.remote[0], their_hello.listen_port)
                self.peerfinder.bootcache.insert(peer.advertised)
            if not inbound and addr is not None:
                self.peerfinder.on_success(addr)
            now = self._clock()
            refused = False
            with self._peers_lock:
                if inbound:
                    # slot admission in the SAME critical section as the
                    # registration below, so concurrent handshakes cannot
                    # all see a free slot (reference: peerfinder Counts.h
                    # accounting). Reserved (fixed/cluster) peers bypass
                    # the cap and are excluded from in_count, so they
                    # never starve the ordinary inbound budget.
                    fixed = set(map(tuple, self.peerfinder.fixed))
                    reserved = (
                        peer.node_public in self.cluster
                        or (
                            peer.advertised is not None
                            and peer.advertised in fixed
                        )
                    )
                    in_count = sum(
                        1
                        for pub, p in self.peers.items()
                        if p.inbound
                        and p.alive
                        and not p.slot_reserved
                        and pub != peer.node_public
                    )
                    if not self.peerfinder.can_accept_inbound(
                        in_count, reserved
                    ):
                        refused = True
                    else:
                        peer.slot_reserved = reserved
                if not refused:
                    existing = self.peers.get(peer.node_public)
                    if existing is not None:
                        young = (
                            existing.alive
                            and now - existing.established_at <= 5.0
                        )
                        fresh = (
                            existing.alive
                            and time.monotonic() - existing.last_recv
                            <= self.peer_idle_ping
                        )
                        if young:
                            # simultaneous-connect race: the smaller key's
                            # dial wins, deterministically on both sides
                            if (self.key.public < peer.node_public) == inbound:
                                if existing.addr is None:
                                    existing.addr = peer.addr
                                peer.benign_close = True
                                peer.close()
                                return
                        elif fresh:
                            # existing session demonstrably alive (recent
                            # recv): keep it; learn the dial addr so
                            # _connect_loop stops redialing an
                            # inbound-only pair
                            if existing.addr is None:
                                existing.addr = peer.addr
                            peer.benign_close = True
                            peer.close()
                            return
                        # else: existing is likely half-open (crashed
                        # peer) — the fresh authenticated session
                        # displaces it; worst case a restarted peer waits
                        # one idle-ping window
                        if peer.addr is None:
                            peer.addr = existing.addr
                        existing.close()
                    peer.established_at = now
                    peer.established_mono = time.monotonic()
                    self.peers[peer.node_public] = peer
                    self.squelch.bump()  # peer churn re-ranks subsets
                exclude = set(self._self_addrs)
            if refused:
                # inbound slots exhausted: REDIRECT the connector to
                # better targets instead of silently dropping it
                # (reference ConnectHandouts.cpp / doRedirect), then
                # close. Never hand out our own addresses or the
                # connector's own.
                exclude.add(("127.0.0.1", self.port))
                if peer.advertised is not None:
                    exclude.add(peer.advertised)
                sample = self.peerfinder.handout(exclude=exclude)
                if sample:
                    data = frame(
                        Endpoints([(h, pt, 1) for h, pt in sample])
                    )
                    try:
                        if peer.is_tls:
                            with peer.io_lock:
                                sock.sendall(data)
                        else:
                            sock.sendall(data)
                    except OSError:
                        pass
                peer.close()
                return
            if not peer.is_tls:
                sock.settimeout(None)  # TLS keeps its 0.05s poll timeout
            # bounded sends only (SO_SNDTIMEO applies to send, not recv):
            # a stalled peer with a full kernel buffer must never block the
            # heartbeat/relay threads forever — sendall times out, send()
            # marks the peer dead, the session cleans up
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", 10, 0),
            )
            self._pump(peer)
        except OSError:
            pass
        except ValueError:
            # malformed frame / unknown message type (version skew): charge
            # and close this peer cleanly instead of killing the reader
            # thread (reference: PeerImp charge(feeInvalidRequest))
            self.node.note_byzantine(
                "malformed_frame", peer=peer.node_public or None
            )
            self._charge(peer, FEE_INVALID_REQUEST)
        finally:
            with self._peers_lock:
                if self.peers.get(peer.node_public) is peer:
                    del self.peers[peer.node_public]
                    self.squelch.bump()
                if peer.addr is not None:
                    self._dialing.discard(peer.addr)
            if peer.sendq_dropped or peer.evicted:
                self.overlay_stats.add_many(
                    sendq_dropped=peer.sendq_dropped,
                    sendq_evicted=1 if peer.evicted else 0,
                )
            peer.close()
            # a dial whose session never established (refused handshake,
            # slot redirect) or died within seconds must BACK OFF instead
            # of re-handshaking every connect-loop tick; benign closes
            # (duplicate-session handling) are exempt
            if (
                not inbound
                and addr is not None
                and not peer.benign_close
                and not self._stop.is_set()
                and (
                    peer.established_mono == 0.0
                    or time.monotonic() - peer.established_mono < 3.0
                )
            ):
                self.peerfinder.on_failure(addr)

    def slots_json(self) -> dict:
        """Slot accounting for the peers RPC (reference: Counts in the
        peerfinder section of the peers response)."""
        with self._peers_lock:
            in_use = sum(1 for p in self.peers.values() if p.inbound and p.alive)
            out_use = sum(
                1 for p in self.peers.values() if not p.inbound and p.alive
            )
            cluster = sum(
                1
                for pub, p in self.peers.items()
                if p.alive and pub in self.cluster
            )
        d = self.peerfinder.get_json()
        d.update({"in_use": in_use, "out_use": out_use, "cluster_use": cluster})
        return d

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes:
        """Handshake-phase read (single-threaded: the writer thread is
        not live yet, so no io_lock needed). Poll timeouts retry up to a
        10s deadline; a dead peer raises OSError."""
        import ssl as _ssl

        deadline = time.monotonic() + 10.0
        buf = b""
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except (TimeoutError, socket.timeout, _ssl.SSLWantReadError):
                if time.monotonic() > deadline:
                    raise OSError("handshake read timed out")
                continue
            if not chunk:
                raise OSError("peer closed")
            buf += chunk
        return buf

    def _read_hello(self, sock: socket.socket, peer: _Peer) -> Optional[Hello]:
        # the writer thread is live from our own hello send onward, so
        # reads go through the TLS-serializing recv; bounded overall
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            data = peer.recv_locked()
            if data is None:
                continue  # TLS poll timeout
            if not data:
                return None
            msgs = peer.reader.feed(data)
            if msgs:
                return msgs[0] if isinstance(msgs[0], Hello) else None
        return None

    # -- message pump -----------------------------------------------------

    def _pump(self, peer: _Peer) -> None:
        while not self._stop.is_set() and peer.alive:
            try:
                data = peer.recv_locked()
            except OSError:
                return
            if data is None:
                continue  # TLS poll timeout — let the writer in
            if not data:
                return
            peer.last_recv = time.monotonic()
            msgs = list(peer.reader.feed(data))
            # WARN throttling (enforced resource pricing): while this
            # endpoint's balance sits above the warning line its
            # NON-ESSENTIAL inbound is shed before any parse/verify work
            # — tx gossip, endpoint gossip, and bulk-serving requests.
            # Consensus messages (proposals/validations/acquisition
            # replies) still flow: throttling a warned-but-honest peer
            # must degrade its gossip, never the network's liveness.
            if msgs and self.resources.is_throttled(peer.remote):
                kept = [
                    m for m in msgs
                    if not isinstance(m, (TxMessage, Endpoints, GetSegments,
                                          GetLedger))
                ]
                if len(kept) != len(msgs):
                    n_shed = len(msgs) - len(kept)
                    self.resources.note_throttled(n_shed)
                    self.overlay_stats.add("throttled_msgs", n_shed)
                    msgs = kept
                    # shed traffic still pays (reference: discarded
                    # data is charged feeUnwantedData): a flooder that
                    # keeps sending through its WARN throttle walks on
                    # to DROP instead of parking at WARN forever
                    from .resource import Charge

                    self._charge(peer, Charge(
                        FEE_UNWANTED_DATA.cost * n_shed, "throttled flood"
                    ))
            # a single read often carries a burst of relayed txs: parse
            # each ONCE and verify their signatures in one plane call
            # before dispatching (an unparseable tx stays None here and
            # raises inside _dispatch, where the sender is charged)
            parsed_txs: dict[int, SerializedTransaction] = {}
            if sum(1 for m in msgs if isinstance(m, TxMessage)) > 1:
                for i, m in enumerate(msgs):
                    if isinstance(m, TxMessage):
                        try:
                            parsed_txs[i] = (
                                SerializedTransaction.from_bytes(m.blob)
                            )
                        except Exception:  # noqa: BLE001 — charged below
                            pass
                try:
                    self.node.prefetch_tx_sigs(list(parsed_txs.values()))
                except Exception:  # noqa: BLE001 — prefetch is an
                    pass           # optimization; per-tx paths re-verify
            for i, msg in enumerate(msgs):
                try:
                    self._dispatch(peer, msg, parsed_tx=parsed_txs.get(i))
                except Exception:  # noqa: BLE001 — a malformed message
                    # (unparseable blob, absurd nesting, handler bug)
                    # must charge the SENDER, never kill our own pump
                    # thread (reference: PeerImp catches per message and
                    # charges feeBadData)
                    log.exception(
                        "peer %s: dispatch failed for %s",
                        peer.remote, type(msg).__name__,
                    )
                    self._charge(peer, FEE_BAD_DATA)

    def _charge(self, peer: _Peer, fee) -> None:
        """Charge the peer's endpoint; disconnect on DROP (reference:
        PeerImp.cpp:129-131 charge(feeInvalidSignature) → Logic drop).
        The dropped endpoint then stays refused at inbound admission
        (should_admit in _session) until its balance decays."""
        if self.resources.charge(peer.remote, fee) == Disposition.DROP:
            self.resources.note_disconnect()
            peer.close()

    def _charge_if_bad(self, peer: _Peer, suppression_id: bytes) -> None:
        """After a handler rejected a message: if the HashRouter marked it
        SF_BAD the signature was invalid (not merely duplicate) — that is
        the chargeable offense."""
        from ..node.hashrouter import SF_BAD

        if self.node.router.get_flags(suppression_id) & SF_BAD:
            self._charge(peer, FEE_INVALID_SIGNATURE)

    def _adopt_ctx(self, msg) -> None:
        """Inbound trace-context handling (Dapper propagation): when the
        extension is present and propagation is on, register the sender's
        span as the foreign parent for that trace so every local span
        joins the sender's causal tree; when propagation is off, STRIP
        the extension so any re-relayed frame is byte-identical to the
        legacy wire."""
        ctx = getattr(msg, "trace_ctx", None)
        if ctx is None:
            return
        tracer = self.node.lm.tracer
        if not (tracer.enabled and tracer.propagate):
            msg.trace_ctx = None
            return
        if ctx.sampled:
            tracer.adopt_context(tracer.trace_key(ctx.trace), ctx.parent)

    def _stamp_ctx(self, msg, txid=None, seq=None) -> None:
        """Stamp an ORIGIN frame with this node's trace context. Relayed
        frames are never restamped — every flooded copy of a message must
        stay byte-identical so content-hash dedup keeps working."""
        ctx = self.node.lm.tracer.wire_context(txid=txid, seq=seq)
        if ctx is not None:
            msg.trace_ctx = TraceContext(*ctx)

    def _dispatch(self, peer: _Peer, msg, parsed_tx=None) -> None:
        """reference: PeerImp message switch (PeerImp.cpp:1459-1738) —
        verify → apply → relay-if-new, charging abusive senders."""
        node = self.node
        self._adopt_ctx(msg)
        if isinstance(msg, TxMessage):
            tx = (parsed_tx if parsed_tx is not None
                  else SerializedTransaction.from_bytes(msg.blob))
            txid = tx.txid()
            if self._first_seen(txid, peer):
                # trace root for an overlay-relayed tx: the first sighting
                # on this node (the local-submit root is NetworkOPs')
                node.lm.tracer.instant(
                    "overlay.tx_in", "submit", txid=txid,
                    peer=peer.remote[0] if peer.remote else None,
                )
                if node.handle_tx(tx):
                    self._relay(msg, except_peer=peer)
                else:
                    self._charge_if_bad(peer, txid)
        elif isinstance(msg, ProposeSet):
            prop = msg.to_proposal()
            pid = prop.suppression_id()
            if self._first_seen(pid, peer):
                # handling (sig check + round routing) rides a
                # jtPROPOSAL_t job when a scheduler is wired (reference:
                # PeerImp::recvPropose queues checkPropose); inline
                # otherwise (bare-overlay tests)
                def do_proposal(prop=prop, pid=pid, peer=peer, msg=msg):
                    if node.handle_proposal(prop):
                        self._relay_validator_msg(
                            msg, prop.node_public, except_peer=peer,
                            kind="relay_proposal",
                        )
                    else:
                        self._charge_if_bad(peer, pid)

                self._schedule("proposal", do_proposal)
        elif isinstance(msg, ValidationMessage):
            val = STValidation.from_bytes(msg.blob)
            vid = val.validation_id()
            if self._first_seen(vid, peer):
                # jtVALIDATION_t job when scheduled (reference:
                # PeerImp::recvValidation → checkValidation job)
                def do_validation(val=val, vid=vid, peer=peer, msg=msg):
                    if node.handle_validation(val):
                        if (
                            self.unl_store is not None
                            and val.signer in self.unl_store
                        ):
                            # observed-validation bookkeeping (the modern
                            # unl_score: UniqueNodeList.on_validation)
                            self.unl_store.on_validation(
                                val.signer, val.ledger_seq
                            )
                        self._relay_validator_msg(
                            msg, val.signer or b"", except_peer=peer,
                            kind="relay_validation",
                        )
                    else:
                        self._charge_if_bad(peer, vid)

                self._schedule("validation", do_validation)
        elif isinstance(msg, ClusterUpdate):
            # TMCluster carries one entry per cluster node the sender
            # knows; we accept only reports about cluster members, and
            # the sender's own entry must come from the sender itself
            if self.fee_track is not None and peer.node_public in self.cluster:
                for st in msg.nodes:
                    # never ingest a relayed report about OURSELVES as a
                    # "remote" fee — that self-echo would ratchet
                    # local_fee's own report back onto us forever
                    if (
                        st.node_public in self.cluster
                        and st.node_public != self.key.public
                    ):
                        self.fee_track.set_remote_fee(
                            st.load_fee,
                            source=st.node_public,
                            report_time=st.report_time,
                        )
        elif isinstance(msg, Endpoints):
            accepted = self.peerfinder.on_endpoints(
                msg.endpoints, sender=peer.remote
            )
            if accepted <= 0:  # oversized (-1) or all-garbage (0)
                self._charge(peer, FEE_UNWANTED_DATA)
        elif isinstance(msg, TxSetData):
            from ..consensus.txset import MAX_TXSET_BLOBS

            if len(msg.tx_blobs) > MAX_TXSET_BLOBS:
                # oversized candidate set: refused before parsing a
                # single blob — one message must not buy O(huge) work
                node.note_byzantine(
                    "oversized_txset", peer=peer.node_public or None
                )
                self._charge(peer, FEE_BAD_DATA)
                return
            ts = TxSet(node.hash_batch)
            intact = True
            for blob in msg.tx_blobs:
                try:
                    tx = SerializedTransaction.from_bytes(blob)
                except Exception:  # noqa: BLE001 — hostile blob
                    intact = False
                    break
                ts.add(tx.txid(), blob)
            if intact and ts.hash() == msg.set_hash:
                node.handle_txset(ts)
            else:
                node.note_byzantine(
                    "txset_mismatch", peer=peer.node_public or None
                )
                self._charge(peer, FEE_BAD_DATA)
        elif isinstance(msg, GetTxSet):
            ts = node.txset_cache.get(msg.set_hash)
            if ts is None and node.round is not None:
                ts = node.round.acquired.get(msg.set_hash)
            if ts is not None:
                blobs = [blob for _t, blob in ts.blobs()]
                peer.send(frame(TxSetData(msg.set_hash, blobs)))
            else:
                # unsatisfiable request: a tiny charge an honest prober
                # never notices but a request-hammer accumulates
                # (reference: charge(feeRequestNoReply))
                self._charge(peer, FEE_REQUEST_NO_REPLY)
        elif isinstance(msg, GetLedger):
            reply = node.serve_get_ledger(msg)
            if reply is not None:
                peer.send(frame(reply))
            else:
                self._charge(peer, FEE_REQUEST_NO_REPLY)
        elif isinstance(msg, GetSegments):
            reply = node.serve_get_segments(msg)
            if reply is not None:
                if msg.trace_ctx is not None:
                    # reply joins the requester's tree (its ctx survived
                    # _adopt_ctx only when propagation is on here)
                    reply.trace_ctx = msg.trace_ctx
                peer.send(frame(reply))
            else:
                self._charge(peer, FEE_REQUEST_NO_REPLY)
        elif isinstance(msg, SegmentData):
            node.handle_segment_data(peer.node_public, msg)
        elif isinstance(msg, LedgerData):
            # only replies that actually advanced an acquisition score —
            # unsolicited LedgerData must not buy routing preference.
            # Duplicates for LIVE acquisitions are legitimate (we fan
            # out); data for unknown hashes earns a small charge
            if node.handle_ledger_data(msg):
                peer.acq_replies += 1
            elif not node.has_acquisition(msg.ledger_hash):
                self._charge(peer, FEE_UNWANTED_DATA)
        elif isinstance(msg, Ping) and not msg.is_pong:
            peer.send(frame(Ping(True, msg.seq)))

    def _first_seen(self, h: bytes, peer: _Peer) -> bool:
        """HashRouter relay suppression (reference: addSuppressionPeer)
        with re-send pricing: an honest mesh delivers each hash at most
        once per neighbor, so the SAME peer re-sending a suppressed hash
        is the duplicate-flood signature and takes FEE_UNWANTED_DATA
        (cross-peer duplicates — normal flood overlap — stay free)."""
        is_new, same_peer_dup = self.node.router.note_peer(h, peer.uid)
        if same_peer_dup:
            self.overlay_stats.add("dup_charges")
            self._charge(peer, FEE_UNWANTED_DATA)
        return is_new

    def _schedule(self, kind: str, thunk: Callable) -> None:
        if self.job_dispatch is not None:
            self.job_dispatch(kind, thunk)
        else:
            thunk()

    def _relay(self, msg, except_peer: Optional[_Peer] = None) -> None:
        data = frame(msg)
        with self._peers_lock:
            targets = [
                p for p in self.peers.values() if p is not except_peer
            ]
        for p in targets:
            p.send(data)

    def _broadcast(self, msg) -> None:
        self._relay(msg, None)

    def _squelch_targets(
        self, signer: bytes, except_peer: Optional[_Peer] = None
    ) -> list:
        """Relay targets for one validator's message: the deterministic
        rotating subset for (signer, epoch) plus every trusted-validator
        peer; untrusted signers are demoted (smaller subset, no forced
        validator inclusion). squelch off → all peers (full flood).

        The subset is computed over the FULL peer set and the sending
        peer filtered from the RESULT — excluding it from the ranking
        input would alias the subset memo across different senders
        (same candidate count, different members), relaying messages
        back to their own sender for a whole epoch."""
        with self._peers_lock:
            peers = [p for p in self.peers.values() if p.alive]
        if not self.squelch.enabled:
            return [p for p in peers if p is not except_peer]
        unl = self.node.unl
        demoted = bool(signer) and signer not in unl
        if demoted:
            self.overlay_stats.add("squelch_demoted")
        seq = self.node.lm.closed_ledger().seq
        subset = self.squelch.subset(
            signer, seq, peers,
            key_fn=lambda p: p.node_public,
            trusted=lambda p: p.node_public in unl,
            demoted=demoted,
        )
        return [p for p in subset if p is not except_peer]

    def _relay_validator_msg(
        self, msg, signer: bytes,
        except_peer: Optional[_Peer] = None,
        kind: str = "relay_proposal",
    ) -> None:
        """Squelched relay of a proposal/validation (reference overlay
        squelching role): fan-out bounded by the squelch subset size
        plus the UNL peer count, never by the peer count."""
        targets = self._squelch_targets(signer, except_peer)
        if not targets:
            return
        data = frame(msg)
        for p in targets:
            p.send(data)
        stats = self.overlay_stats
        stats.add(kind)
        if len(targets) > stats.get("relay_fanout_max"):
            stats.set("relay_fanout_max", len(targets))

    # -- timer ------------------------------------------------------------

    def _timer_loop(self) -> None:
        ping_seq = 0
        while not self._stop.wait(self.timer_interval):
            self.node.on_timer()
            # ENDPOINTS gossip: advertise our own listener (hop 0, host
            # rewritten to the observed IP by the receiver) plus a bounded
            # re-share of fresh livecache entries (reference mtENDPOINTS,
            # PeerSlotLogic::sendEndpoints)
            mono = time.monotonic()
            if mono - self._last_gossip >= self.gossip_interval:
                self._last_gossip = mono
                # a pinned-upstream follower never advertises its own
                # listener: its children find it via explicit upstream=
                # config, and an advertised endpoint would invite the
                # wider net (the leader included) to dial down into the
                # tree, un-bounding the very egress the tree bounds
                own = (
                    None if self.pinned_upstream
                    else ("0.0.0.0", self.port)
                )
                sample = self.peerfinder.gossip_sample(own)
                if sample:
                    self._broadcast(Endpoints(sample))
                if self.fee_track is not None and self.cluster:
                    # our own entry plus every unexpired report we hold —
                    # cluster members relay the full picture (reference:
                    # TMCluster carries all known ClusterNodeStatus rows)
                    now_nt = self._ntime()
                    nodes = [ClusterStatus(
                        self.key.public, self.fee_track.local_fee, now_nt,
                    )]
                    # relay stored reports with their ORIGINAL report_time
                    # (re-stamping would let two members refresh each
                    # other's stale entries forever — reference TMCluster
                    # carries the reporter's own reportTime)
                    for src, fee, rtime in self.fee_track.remote_reports():
                        if src in self.cluster and src != self.key.public:
                            nodes.append(ClusterStatus(src, fee, rtime))
                    status = frame(ClusterUpdate(nodes))
                    with self._peers_lock:
                        members = [
                            p for p in self.peers.values()
                            if p.node_public in self.cluster
                        ]
                    for p in members:
                        p.send(status)
                self.resources.sweep()
            if self.fee_track is not None:
                # aggregate peer pressure → local fee: while the peer
                # set as a whole is paying charges, the open-ledger
                # price rises (NORMAL_FEE x pressure, pressure = total
                # balance / WARN threshold) and decays with the
                # balances — network-wide abuse costs the abusers
                from ..node.loadmgr import NORMAL_FEE

                pressure = self.resources.aggregate_pressure()
                self.fee_track.set_network_pressure(
                    int(NORMAL_FEE * max(1.0, pressure))
                )
            # Half-open detection: a crashed peer (no FIN/RST) leaves our
            # reader blocked in recv with alive=True forever, which would
            # also suppress redials. Ping idle peers; drop ones silent past
            # the real-time threshold so the session cleans up and the
            # connect loop can redial (reference: PeerImp NO_PING timeout).
            now = time.monotonic()
            with self._peers_lock:
                peers = list(self.peers.values())
            for p in peers:
                idle = now - p.last_recv
                if idle > self.peer_idle_drop:
                    p.close()
                elif idle > self.peer_idle_ping:
                    ping_seq += 1
                    p.send(frame(Ping(False, ping_seq)))

    # -- ConsensusAdapter -------------------------------------------------

    def propose(self, proposal) -> None:
        # own proposals ride the same squelched fan-out as relays: at
        # production peer counts a validator's origin broadcast is the
        # other O(peers) send path, and the gossip subsets carry the
        # message the rest of the way
        msg = ProposeSet.from_proposal(proposal)
        rnd = self.node.round
        if rnd is not None:
            self._stamp_ctx(msg, seq=getattr(rnd, "seq", None))
        self._relay_validator_msg(
            msg, self.key.public, kind="relay_proposal",
        )

    def share_tx_set(self, txset: TxSet) -> None:
        blobs = [blob for _t, blob in txset.blobs()]
        self._broadcast(TxSetData(txset.hash(), blobs))

    def acquire_tx_set(self, set_hash: bytes) -> Optional[TxSet]:
        ts = self.node.txset_cache.get(set_hash)
        if ts is None:
            self._broadcast(GetTxSet(set_hash))  # async acquisition
        return ts

    def send_validation(self, val: STValidation) -> None:
        self.node.router.set_flag(val.validation_id(), SF_RELAYED)
        msg = ValidationMessage(val.serialize())
        self._stamp_ctx(msg, seq=val.ledger_seq)
        self._relay_validator_msg(
            msg, self.key.public, kind="relay_validation",
        )

    def relay_disputed_tx(self, blob: bytes) -> None:
        msg = TxMessage(blob)
        if self.node.lm.tracer.propagate:
            try:
                self._stamp_ctx(
                    msg, txid=SerializedTransaction.from_bytes(blob).txid()
                )
            except Exception:  # noqa: BLE001 — tracing never blocks a relay
                pass
        self._broadcast(msg)

    def request_ledger_data(self, msg: GetLedger) -> None:
        """Anycast to the best-scoring connected peer (reference:
        PeerSet's peer selection): highest observed reply rate, fewest
        outstanding requests; every 8th request explores round-robin so
        fresh peers earn a score and a decayed one can recover."""
        with self._peers_lock:
            peers = [p for _k, p in sorted(self.peers.items()) if p.alive]
        if not peers:
            return
        self._acq_rr = getattr(self, "_acq_rr", 0) + 1
        if self._acq_rr % 8 == 0:
            target = peers[(self._acq_rr // 8) % len(peers)]
        else:
            target = min(peers, key=_acq_score)
        target.acq_requests += 1
        target.send(frame(msg))

    # segment catch-up transport hooks (node/inbound.SegmentCatchup)

    def segment_peers(self) -> list[bytes]:
        """Stable-ordered candidate peers for bulk segment transfer.
        Unified scoring: an endpoint at WARN or worse (charged for
        garbage, floods, or a condemned transfer) loses the catch-up
        privilege along with its relay/admission standing."""
        with self._peers_lock:
            cands = [
                (pub, self.peers[pub].remote)
                for pub in sorted(self.peers)
                if self.peers[pub].alive
            ]
        return [
            pub for pub, remote in cands
            if not self.resources.is_throttled(remote)
        ]

    def charge_peer(self, peer_pub: bytes, fee) -> str:
        """Charge a peer identified by node key (the SegmentCatchup
        condemnation seam): returns the Disposition; DROP disconnects,
        and the endpoint stays refused at inbound admission until its
        balance decays."""
        with self._peers_lock:
            p = self.peers.get(peer_pub)
        if p is None:
            return Disposition.OK
        disp = self.resources.charge(p.remote, fee)
        if disp == Disposition.DROP:
            self.resources.note_disconnect()
            p.close()
        return disp

    def send_segments_request(self, peer_pub: bytes, msg) -> None:
        with self._peers_lock:
            p = self.peers.get(peer_pub)
        if p is None or not p.alive:
            raise OSError("segment peer gone")
        if getattr(msg, "trace_ctx", None) is None:
            # best-effort: the catch-up trace is this node's ledger line
            self._stamp_ctx(msg, seq=self.node.lm.closed_ledger().seq)
        p.acq_requests += 1
        p.send(frame(msg))

    def on_accepted(self, ledger: Ledger, round_ms: int) -> None:
        self.node.round_accepted(ledger, round_ms)

    @property
    def accepted_hooks(self) -> list:
        """Ledger hooks live on the ValidatorNode (fired for consensus
        closes AND catch-up adoptions); exposed here for the container."""
        return self.node.on_ledger

    # -- client entry -----------------------------------------------------

    def submit_client_tx(self, tx: SerializedTransaction) -> None:
        self.node.submit(tx)
        msg = TxMessage(tx.serialize())
        self._stamp_ctx(msg, txid=tx.txid())
        self._broadcast(msg)

    def broadcast_tx(self, tx: SerializedTransaction, except_ids=None) -> None:
        """Relay an already-applied client tx (the NetworkOPs relay seam).
        `except_ids` is the HashRouter suppression peer-id set — peers the
        tx already arrived FROM are excluded from the fan-out (reference:
        the swapSet peer set drives exactly this exclusion)."""
        msg = TxMessage(tx.serialize())
        self._stamp_ctx(msg, txid=tx.txid())
        data = frame(msg)
        with self._peers_lock:
            targets = [
                p
                for p in self.peers.values()
                if not except_ids or p.uid not in except_ids
            ]
        for p in targets:
            p.send(data)

    def peer_count(self) -> int:
        with self._peers_lock:
            return len(self.peers)

    def squelch_json(self) -> dict:
        """`squelch.*` observability block: policy + relay fan-out
        evidence + sendq shedding (live peers' counts folded in)."""
        out = self.squelch.get_json()
        out.update(self.overlay_stats.snapshot())
        with self._peers_lock:
            live_drops = sum(p.sendq_dropped for p in self.peers.values())
        out["sendq_dropped"] += live_drops
        return out

    def peers_json(self) -> list[dict]:
        """reference: OverlayImpl::json / handlers/Peers.cpp row shape."""
        from ..protocol.keys import encode_node_public

        with self._peers_lock:
            peers = list(self.peers.items())
        out = []
        for pub, p in peers:
            out.append(
                {
                    "public_key": encode_node_public(pub),
                    "address": f"{p.addr[0]}:{p.addr[1]}" if p.addr else "",
                    "inbound": bool(p.inbound),
                    "alive": bool(p.alive),
                }
            )
        return out
