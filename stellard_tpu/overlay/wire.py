"""Wire schema for peer-to-peer messages.

Reference: src/ripple/proto/ripple.proto (TM* messages over a 6-byte
length+type header, framed in ripple_overlay/impl/Message.cpp). Same
semantics, different encoding: rather than vendoring protobuf we reuse
the protocol plane's canonical Serializer (VL fields), which the node
already has hot paths for, under the same header layout:

    4 bytes big-endian payload length | 2 bytes big-endian message type

Payloads are field-lists; every field is a VL blob or fixed-width int,
so the schema stays self-describing enough for version skew while
avoiding a second serialization stack.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Optional

from ..consensus.proposal import LedgerProposal
from ..protocol.serializer import BinaryParser, Serializer

__all__ = [
    "MessageType",
    "Hello",
    "Ping",
    "TxMessage",
    "ProposeSet",
    "ValidationMessage",
    "HaveTxSet",
    "GetTxSet",
    "TxSetData",
    "GetLedger",
    "LedgerData",
    "StatusChange",
    "Endpoints",
    "ClusterStatus",
    "GetObjects",
    "ObjectsData",
    "encode_message",
    "decode_message",
    "frame",
    "FrameReader",
]

HEADER_LEN = 6
MAX_FRAME = 64 * 1024 * 1024


class MessageType(IntEnum):
    """Wire ids (role-parity with ripple.proto MessageType:3-39)."""

    HELLO = 1
    PING = 2
    TRANSACTION = 10
    PROPOSE_SET = 11
    VALIDATION = 12
    HAVE_TX_SET = 13
    GET_TX_SET = 14
    TX_SET_DATA = 15
    GET_LEDGER = 20
    LEDGER_DATA = 21
    STATUS_CHANGE = 22
    ENDPOINTS = 30
    CLUSTER = 31
    GET_OBJECTS = 40
    OBJECTS_DATA = 41


@dataclass
class Hello:
    """Session handshake: protocol version, our node key, a signature of
    the session's shared fingerprint proving key ownership, our chain
    tip, and the port our own listener accepts on — inbound sessions
    arrive from an ephemeral port, so discovery (PeerFinder) needs the
    listen port advertised explicitly (reference: TMHello ipv4Port)."""

    proto_version: int
    net_time: int
    node_public: bytes
    session_sig: bytes
    ledger_seq: int
    closed_ledger: bytes
    listen_port: int = 0


@dataclass
class Ping:
    is_pong: bool
    seq: int


@dataclass
class TxMessage:
    blob: bytes  # serialized STTx


@dataclass
class ProposeSet:
    propose_seq: int
    close_time: int
    prev_ledger: bytes
    tx_set_hash: bytes
    node_public: bytes
    signature: bytes

    @classmethod
    def from_proposal(cls, p: LedgerProposal) -> "ProposeSet":
        return cls(
            p.propose_seq,
            p.close_time,
            p.prev_ledger,
            p.tx_set_hash,
            p.node_public,
            p.signature,
        )

    def to_proposal(self) -> LedgerProposal:
        return LedgerProposal(
            self.prev_ledger,
            self.propose_seq,
            self.tx_set_hash,
            self.close_time,
            self.node_public,
            self.signature,
        )


@dataclass
class ValidationMessage:
    blob: bytes  # serialized STValidation


@dataclass
class HaveTxSet:
    set_hash: bytes


@dataclass
class GetTxSet:
    set_hash: bytes


@dataclass
class TxSetData:
    set_hash: bytes
    tx_blobs: list = field(default_factory=list)


@dataclass
class GetLedger:
    ledger_hash: bytes
    ledger_seq: int  # 0 = by hash
    what: int  # 0=base header, 1=tx tree, 2=state tree
    node_ids: list = field(default_factory=list)  # wire node-id blobs


@dataclass
class LedgerData:
    ledger_hash: bytes
    ledger_seq: int
    what: int
    nodes: list = field(default_factory=list)  # (node_id, node_blob)


@dataclass
class StatusChange:
    status: int  # OperatingMode value
    ledger_seq: int
    ledger_hash: bytes
    network_time: int


@dataclass
class Endpoints:
    endpoints: list = field(default_factory=list)  # (host, port, hops)


@dataclass
class ClusterStatus:
    """Same-operator load report (reference: mtCLUSTER /
    ClusterNodeStatus.h): cluster members share their load fee so every
    member escalates together."""

    node_public: bytes
    load_fee: int
    report_time: int


@dataclass
class GetObjects:
    hashes: list = field(default_factory=list)


@dataclass
class ObjectsData:
    objects: list = field(default_factory=list)  # (hash, blob)


# -- encoding -------------------------------------------------------------


def _enc_hello(s: Serializer, m: Hello):
    s.add32(m.proto_version)
    s.add32(m.net_time)
    s.add_vl(m.node_public)
    s.add_vl(m.session_sig)
    s.add32(m.ledger_seq)
    s.add_raw(m.closed_ledger)
    s.add16(m.listen_port)


def _dec_hello(p: BinaryParser) -> Hello:
    return Hello(
        p.read32(),
        p.read32(),
        p.read_vl(),
        p.read_vl(),
        p.read32(),
        p.read(32),
        p.read16(),
    )


def _enc_ping(s: Serializer, m: Ping):
    s.add8(1 if m.is_pong else 0)
    s.add32(m.seq)


def _dec_ping(p: BinaryParser) -> Ping:
    return Ping(p.read8() == 1, p.read32())


def _enc_tx(s: Serializer, m: TxMessage):
    s.add_vl(m.blob)


def _dec_tx(p: BinaryParser) -> TxMessage:
    return TxMessage(p.read_vl())


def _enc_propose(s: Serializer, m: ProposeSet):
    s.add32(m.propose_seq)
    s.add32(m.close_time)
    s.add_raw(m.prev_ledger)
    s.add_raw(m.tx_set_hash)
    s.add_vl(m.node_public)
    s.add_vl(m.signature)


def _dec_propose(p: BinaryParser) -> ProposeSet:
    return ProposeSet(
        p.read32(), p.read32(), p.read(32), p.read(32), p.read_vl(), p.read_vl()
    )


def _enc_validation(s: Serializer, m: ValidationMessage):
    s.add_vl(m.blob)


def _dec_validation(p: BinaryParser) -> ValidationMessage:
    return ValidationMessage(p.read_vl())


def _enc_have_set(s: Serializer, m: HaveTxSet):
    s.add_raw(m.set_hash)


def _dec_have_set(p: BinaryParser) -> HaveTxSet:
    return HaveTxSet(p.read(32))


def _enc_get_set(s: Serializer, m: GetTxSet):
    s.add_raw(m.set_hash)


def _dec_get_set(p: BinaryParser) -> GetTxSet:
    return GetTxSet(p.read(32))


def _enc_set_data(s: Serializer, m: TxSetData):
    s.add_raw(m.set_hash)
    s.add32(len(m.tx_blobs))
    for blob in m.tx_blobs:
        s.add_vl(blob)


def _dec_set_data(p: BinaryParser) -> TxSetData:
    h = p.read(32)
    n = p.read32()
    return TxSetData(h, [p.read_vl() for _ in range(n)])


def _enc_get_ledger(s: Serializer, m: GetLedger):
    s.add_raw(m.ledger_hash)
    s.add32(m.ledger_seq)
    s.add8(m.what)
    s.add32(len(m.node_ids))
    for nid in m.node_ids:
        s.add_vl(nid)


def _dec_get_ledger(p: BinaryParser) -> GetLedger:
    h = p.read(32)
    seq = p.read32()
    what = p.read8()
    n = p.read32()
    return GetLedger(h, seq, what, [p.read_vl() for _ in range(n)])


def _enc_ledger_data(s: Serializer, m: LedgerData):
    s.add_raw(m.ledger_hash)
    s.add32(m.ledger_seq)
    s.add8(m.what)
    s.add32(len(m.nodes))
    for nid, blob in m.nodes:
        s.add_vl(nid)
        s.add_vl(blob)


def _dec_ledger_data(p: BinaryParser) -> LedgerData:
    h = p.read(32)
    seq = p.read32()
    what = p.read8()
    n = p.read32()
    return LedgerData(h, seq, what, [(p.read_vl(), p.read_vl()) for _ in range(n)])


def _enc_status(s: Serializer, m: StatusChange):
    s.add8(m.status)
    s.add32(m.ledger_seq)
    s.add_raw(m.ledger_hash)
    s.add32(m.network_time)


def _dec_status(p: BinaryParser) -> StatusChange:
    return StatusChange(p.read8(), p.read32(), p.read(32), p.read32())


def _enc_cluster(s: Serializer, m: ClusterStatus):
    s.add_vl(m.node_public)
    s.add32(m.load_fee)
    s.add32(m.report_time)


def _dec_cluster(p: BinaryParser) -> ClusterStatus:
    return ClusterStatus(p.read_vl(), p.read32(), p.read32())


def _enc_endpoints(s: Serializer, m: Endpoints):
    s.add32(len(m.endpoints))
    for host, port, hops in m.endpoints:
        s.add_vl(host.encode())
        s.add16(port)
        s.add8(hops)


def _dec_endpoints(p: BinaryParser) -> Endpoints:
    n = p.read32()
    return Endpoints(
        [(p.read_vl().decode(), p.read16(), p.read8()) for _ in range(n)]
    )


def _enc_get_objects(s: Serializer, m: GetObjects):
    s.add32(len(m.hashes))
    for h in m.hashes:
        s.add_raw(h)


def _dec_get_objects(p: BinaryParser) -> GetObjects:
    return GetObjects([p.read(32) for _ in range(p.read32())])


def _enc_objects_data(s: Serializer, m: ObjectsData):
    s.add32(len(m.objects))
    for h, blob in m.objects:
        s.add_raw(h)
        s.add_vl(blob)


def _dec_objects_data(p: BinaryParser) -> ObjectsData:
    return ObjectsData([(p.read(32), p.read_vl()) for _ in range(p.read32())])


_CODECS = {
    MessageType.HELLO: (Hello, _enc_hello, _dec_hello),
    MessageType.PING: (Ping, _enc_ping, _dec_ping),
    MessageType.TRANSACTION: (TxMessage, _enc_tx, _dec_tx),
    MessageType.PROPOSE_SET: (ProposeSet, _enc_propose, _dec_propose),
    MessageType.VALIDATION: (ValidationMessage, _enc_validation, _dec_validation),
    MessageType.HAVE_TX_SET: (HaveTxSet, _enc_have_set, _dec_have_set),
    MessageType.GET_TX_SET: (GetTxSet, _enc_get_set, _dec_get_set),
    MessageType.TX_SET_DATA: (TxSetData, _enc_set_data, _dec_set_data),
    MessageType.GET_LEDGER: (GetLedger, _enc_get_ledger, _dec_get_ledger),
    MessageType.LEDGER_DATA: (LedgerData, _enc_ledger_data, _dec_ledger_data),
    MessageType.STATUS_CHANGE: (StatusChange, _enc_status, _dec_status),
    MessageType.ENDPOINTS: (Endpoints, _enc_endpoints, _dec_endpoints),
    MessageType.CLUSTER: (ClusterStatus, _enc_cluster, _dec_cluster),
    MessageType.GET_OBJECTS: (GetObjects, _enc_get_objects, _dec_get_objects),
    MessageType.OBJECTS_DATA: (ObjectsData, _enc_objects_data, _dec_objects_data),
}

_TYPE_OF = {cls: mt for mt, (cls, _e, _d) in _CODECS.items()}


def encode_message(msg) -> bytes:
    """Payload bytes (no frame header)."""
    mt = _TYPE_OF[type(msg)]
    s = Serializer()
    _CODECS[mt][1](s, msg)
    return s.data()


def decode_message(mt: int, payload: bytes):
    cls, _enc, dec = _CODECS[MessageType(mt)]
    return dec(BinaryParser(payload))


def frame(msg) -> bytes:
    """Full wire frame: 4-byte length + 2-byte type + payload
    (reference: Message.cpp 6-byte header)."""
    payload = encode_message(msg)
    mt = _TYPE_OF[type(msg)]
    return len(payload).to_bytes(4, "big") + int(mt).to_bytes(2, "big") + payload


class FrameReader:
    """Incremental frame decoder for a TCP byte stream."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> list:
        """Append stream bytes; return completed messages."""
        self._buf.extend(data)
        out = []
        while len(self._buf) >= HEADER_LEN:
            length = int.from_bytes(self._buf[:4], "big")
            if length > MAX_FRAME:
                raise ValueError("oversized frame")
            if len(self._buf) < HEADER_LEN + length:
                break
            mt = int.from_bytes(self._buf[4:6], "big")
            payload = bytes(self._buf[HEADER_LEN : HEADER_LEN + length])
            del self._buf[: HEADER_LEN + length]
            out.append(decode_message(mt, payload))
        return out
