"""Wire schema for peer-to-peer messages — protobuf-compatible.

Reference: src/ripple/proto/ripple.proto (TM* messages) framed by the
6-byte header of ripple_overlay/impl/Message.cpp:

    4 bytes big-endian payload length | 2 bytes big-endian message type

Payloads are genuine protobuf (proto2) wire format with ripple.proto's
message-type numbers and field numbers — SURVEY §5's "same protobuf
schema" compatibility target — encoded by overlay.proto (a from-scratch
~150-line codec standing in for the reference's vendored 108k-LoC
protobuf build). The Python-facing message classes below keep their
framework-internal shape; only their byte encoding follows ripple.proto:

    Hello          <-> TMHello            (mt 1)
    Ping           <-> TMPing             (mt 3)
    ClusterStatus  <-> TMCluster          (mt 5)
    Endpoints      <-> TMEndpoints        (mt 15)
    TxMessage      <-> TMTransaction      (mt 30)
    GetLedger      <-> TMGetLedger        (mt 31)
    GetTxSet       <-> TMGetLedger        (mt 31, itype liTS_CANDIDATE —
                                           the reference acquires candidate
                                           tx sets through TMGetLedger)
    LedgerData     <-> TMLedgerData       (mt 32)
    TxSetData      <-> TMLedgerData       (mt 32, liTS_CANDIDATE)
    ProposeSet     <-> TMProposeSet       (mt 33)
    StatusChange   <-> TMStatusChange     (mt 34)
    HaveTxSet      <-> TMHaveTransactionSet (mt 35)
    ValidationMessage <-> TMValidation    (mt 41)
    GetObjects     <-> TMGetObjectByHash  (mt 42, query=true)
    ObjectsData    <-> TMGetObjectByHash  (mt 42, query=false)

Two EXTENSION messages (mt 54/55, outside ripple.proto — both ends of a
stellard-tpu private net speak them; a reference peer would reject them
as out-of-schema, which is why the segment catch-up plane only engages
against peers that answered a manifest request):

    GetSegments    (mt 54)  segment-granular catch-up: manifest request
                            (seg_id < 0) or one chunk of one segment
    SegmentData    (mt 55)  manifest reply or a verified-by-content
                            chunk of a store segment (nodestore/segstore
                            ``fetch_segment`` read door)

One EXTENSION FIELD (outside ripple.proto, Dapper-style): TxMessage,
ProposeSet, ValidationMessage, GetSegments and SegmentData may carry a
nested ``TraceContext`` submessage at field 60 (trace id + parent span
token + flags) so spans on different nodes join one causal tree. proto2
parsers skip unknown fields, so a reference peer ignores it; when
``[trace] propagate=0`` the field is never emitted and every frame is
byte-identical to the legacy wire.
"""

from __future__ import annotations

import socket as _socket
from dataclasses import dataclass, field
from enum import IntEnum

from ..consensus.proposal import LedgerProposal
from .proto import Encoder, first, first_bytes, first_int, parse

__all__ = [
    "MessageType",
    "TraceContext",
    "TRACE_CTX_FIELD",
    "Hello",
    "Ping",
    "TxMessage",
    "ProposeSet",
    "ValidationMessage",
    "HaveTxSet",
    "GetTxSet",
    "TxSetData",
    "GetLedger",
    "LedgerData",
    "StatusChange",
    "Endpoints",
    "ClusterStatus",
    "ClusterUpdate",
    "GetObjects",
    "ObjectsData",
    "GetSegments",
    "SegmentData",
    "SEGMENT_CHUNK",
    "encode_message",
    "decode_message",
    "frame",
    "FrameReader",
]

HEADER_LEN = 6
MAX_FRAME = 64 * 1024 * 1024

# ripple.proto TMLedgerInfoType
LI_BASE = 0
LI_TX_NODE = 1
LI_AS_NODE = 2
LI_TS_CANDIDATE = 3

# ripple.proto TransactionStatus / TxSetStatus
TS_CURRENT = 2
TXSET_HAVE = 1


# field number of the TraceContext extension submessage — high enough to
# clear every ripple.proto field on the five messages that carry it
TRACE_CTX_FIELD = 60


@dataclass
class TraceContext:
    """Cross-node trace propagation extension (Dapper-style): the trace
    id (raw 32-byte txid or a utf-8 trace string), the sender's span id
    as the receiver's parent token, and a flags varint (bit0 = sampled).
    Stamped ONCE at the origin and never restamped on relay, so every
    relayed copy of a frame stays byte-identical (content-hash dedup)."""

    trace: bytes = b""
    parent: int = 0
    sampled: bool = True


def _enc_trace_ctx(e: Encoder, ctx: "TraceContext | None") -> None:
    if ctx is None:
        return
    sub = Encoder().blob(1, ctx.trace).varint(2, ctx.parent)
    sub.varint(3, 1 if ctx.sampled else 0)
    e.message(TRACE_CTX_FIELD, sub)


def _dec_trace_ctx(f: dict) -> "TraceContext | None":
    raw = first(f, TRACE_CTX_FIELD)
    if not isinstance(raw, (bytes, bytearray)):
        return None
    try:
        cf = parse(bytes(raw))
        return TraceContext(
            trace=first_bytes(cf, 1),
            parent=first_int(cf, 2),
            sampled=bool(first_int(cf, 3)),
        )
    except ValueError:
        return None  # malformed extension never drops the message


class MessageType(IntEnum):
    """ripple.proto MessageType numbers (the wire ids)."""

    HELLO = 1
    PING = 3
    CLUSTER = 5
    ENDPOINTS = 15
    TRANSACTION = 30
    GET_LEDGER = 31
    LEDGER_DATA = 32
    PROPOSE_SET = 33
    STATUS_CHANGE = 34
    HAVE_TX_SET = 35
    VALIDATION = 41
    GET_OBJECTS = 42
    # stellard-tpu extensions (outside ripple.proto)
    GET_SEGMENTS = 54
    SEGMENT_DATA = 55


@dataclass
class Hello:
    """Session handshake: protocol version, our node key, a signature of
    the session's shared fingerprint proving key ownership, our chain
    tip, and the port our own listener accepts on — inbound sessions
    arrive from an ephemeral port, so discovery (PeerFinder) needs the
    listen port advertised explicitly (reference: TMHello ipv4Port)."""

    proto_version: int
    net_time: int
    node_public: bytes
    session_sig: bytes
    ledger_seq: int
    closed_ledger: bytes
    listen_port: int = 0


@dataclass
class Ping:
    is_pong: bool
    seq: int


@dataclass
class TxMessage:
    blob: bytes  # serialized STTx
    trace_ctx: "TraceContext | None" = None


@dataclass
class ProposeSet:
    propose_seq: int
    close_time: int
    prev_ledger: bytes
    tx_set_hash: bytes
    node_public: bytes
    signature: bytes
    trace_ctx: "TraceContext | None" = None

    @classmethod
    def from_proposal(cls, p: LedgerProposal) -> "ProposeSet":
        return cls(
            p.propose_seq,
            p.close_time,
            p.prev_ledger,
            p.tx_set_hash,
            p.node_public,
            p.signature,
        )

    def to_proposal(self) -> LedgerProposal:
        return LedgerProposal(
            self.prev_ledger,
            self.propose_seq,
            self.tx_set_hash,
            self.close_time,
            self.node_public,
            self.signature,
        )


@dataclass
class ValidationMessage:
    blob: bytes  # serialized STValidation
    trace_ctx: "TraceContext | None" = None


@dataclass
class HaveTxSet:
    set_hash: bytes


@dataclass
class GetTxSet:
    set_hash: bytes


@dataclass
class TxSetData:
    set_hash: bytes
    tx_blobs: list = field(default_factory=list)


@dataclass
class GetLedger:
    ledger_hash: bytes
    ledger_seq: int  # 0 = by hash
    what: int  # 0=base header, 1=tx tree, 2=state tree (liBASE/TX/AS)
    node_ids: list = field(default_factory=list)  # wire node-id blobs


@dataclass
class LedgerData:
    ledger_hash: bytes
    ledger_seq: int
    what: int
    nodes: list = field(default_factory=list)  # (node_id, node_blob)


@dataclass
class StatusChange:
    status: int  # OperatingMode value
    ledger_seq: int
    ledger_hash: bytes
    network_time: int


@dataclass
class Endpoints:
    endpoints: list = field(default_factory=list)  # (host, port, hops)


@dataclass
class ClusterStatus:
    """Same-operator load report (reference: mtCLUSTER /
    ClusterNodeStatus.h): cluster members share their load fee so every
    member escalates together."""

    node_public: bytes
    load_fee: int
    report_time: int


@dataclass
class ClusterUpdate:
    """Decoded TMCluster: every clusterNodes entry (the field is
    `repeated` — a member reports all cluster nodes it knows)."""

    nodes: list = field(default_factory=list)  # [ClusterStatus, ...]


# one SegmentData chunk's payload budget: large enough that a few round
# trips move a whole segment, small enough that one request's timeout
# clock covers a bounded transfer
SEGMENT_CHUNK = 1 << 20


@dataclass
class GetSegments:
    """Segment-granular catch-up request: ``seg_id < 0`` asks for the
    peer's segment manifest; otherwise one chunk of segment ``seg_id``
    starting at ``offset``."""

    seg_id: int = -1
    offset: int = 0
    # snapshot handoff (doc/follower.md): the epoch the fetcher is
    # pinned to — 0 = don't-care (manifest requests, pre-epoch peers).
    # proto2 unknown-field skip keeps old peers wire-compatible.
    snap_epoch: int = 0
    trace_ctx: "TraceContext | None" = None


@dataclass
class SegmentData:
    """Manifest reply (``seg_id < 0``, ``segments`` rows) or one chunk of
    one segment: ``total`` is the full segment size so the fetcher knows
    when it holds the whole byte range."""

    seg_id: int = -1
    total: int = 0
    offset: int = 0
    data: bytes = b""
    # manifest rows: (id, size, live, active[, lo, hi, file_bytes]).
    # lo/hi advertise a sealed shard's ledger-seq range and file_bytes
    # its full on-disk size (the SHARD_FILE door serves whole files);
    # all three ride nonzero-only so legacy rows stay byte-identical.
    segments: list = field(default_factory=list)
    # snapshot handoff: the serving peer's sealed-set epoch + validated
    # seq at reply time (0 = a pre-epoch peer; fetchers treat as
    # don't-care). An epoch that MOVES mid-transfer means the source
    # rotated/compacted under the fetcher → restart from the manifest.
    snap_epoch: int = 0
    snap_seq: int = 0
    trace_ctx: "TraceContext | None" = None


@dataclass
class GetObjects:
    hashes: list = field(default_factory=list)


@dataclass
class ObjectsData:
    objects: list = field(default_factory=list)  # (hash, blob)


# -- encoding: dataclass -> ripple.proto wire shape ------------------------


def _enc_hello(m: Hello) -> bytes:
    e = Encoder()
    e.varint(1, m.proto_version)  # protoVersion
    e.varint(2, m.proto_version)  # protoVersionMin
    e.blob(3, m.node_public)  # nodePublic
    e.blob(4, m.session_sig)  # nodeProof
    e.varint(6, m.net_time)  # netTime
    e.varint(7, m.listen_port)  # ipv4Port
    e.varint(8, m.ledger_seq)  # ledgerIndex
    e.blob(9, m.closed_ledger)  # ledgerClosed
    return e.data()


def _dec_hello(buf: bytes) -> Hello:
    f = parse(buf)
    return Hello(
        proto_version=first_int(f, 1),
        net_time=first_int(f, 6),
        node_public=first_bytes(f, 3),
        session_sig=first_bytes(f, 4),
        ledger_seq=first_int(f, 8),
        closed_ledger=first_bytes(f, 9, b"\x00" * 32),
        listen_port=first_int(f, 7),
    )


def _enc_ping(m: Ping) -> bytes:
    return Encoder().varint(1, 1 if m.is_pong else 0).varint(2, m.seq).data()


def _dec_ping(buf: bytes) -> Ping:
    f = parse(buf)
    return Ping(first_int(f, 1) == 1, first_int(f, 2))


def _enc_tx(m: TxMessage) -> bytes:
    e = Encoder().blob(1, m.blob).varint(2, TS_CURRENT)
    _enc_trace_ctx(e, m.trace_ctx)
    return e.data()


def _dec_tx(buf: bytes) -> TxMessage:
    f = parse(buf)
    return TxMessage(first_bytes(f, 1), trace_ctx=_dec_trace_ctx(f))


def _enc_propose(m: ProposeSet) -> bytes:
    e = Encoder()
    e.varint(1, m.propose_seq)  # proposeSeq
    e.blob(2, m.tx_set_hash)  # currentTxHash
    e.blob(3, m.node_public)  # nodePubKey
    e.varint(4, m.close_time)  # closeTime
    e.blob(5, m.signature)  # signature
    e.blob(6, m.prev_ledger)  # previousledger
    _enc_trace_ctx(e, m.trace_ctx)
    return e.data()


def _dec_propose(buf: bytes) -> ProposeSet:
    f = parse(buf)
    return ProposeSet(
        propose_seq=first_int(f, 1),
        close_time=first_int(f, 4),
        prev_ledger=first_bytes(f, 6, b"\x00" * 32),
        tx_set_hash=first_bytes(f, 2),
        node_public=first_bytes(f, 3),
        signature=first_bytes(f, 5),
        trace_ctx=_dec_trace_ctx(f),
    )


def _enc_validation(m: ValidationMessage) -> bytes:
    e = Encoder().blob(1, m.blob)
    _enc_trace_ctx(e, m.trace_ctx)
    return e.data()


def _dec_validation(buf: bytes) -> ValidationMessage:
    f = parse(buf)
    return ValidationMessage(first_bytes(f, 1), trace_ctx=_dec_trace_ctx(f))


def _enc_have_set(m: HaveTxSet) -> bytes:
    return Encoder().varint(1, TXSET_HAVE).blob(2, m.set_hash).data()


def _dec_have_set(buf: bytes) -> HaveTxSet:
    return HaveTxSet(first_bytes(parse(buf), 2))


def _enc_get_set(m: GetTxSet) -> bytes:
    # reference: candidate tx sets acquire via TMGetLedger liTS_CANDIDATE
    return Encoder().varint(1, LI_TS_CANDIDATE).blob(3, m.set_hash).data()


def _enc_get_ledger(m: GetLedger) -> bytes:
    e = Encoder()
    e.varint(1, m.what)  # itype: liBASE/liTX_NODE/liAS_NODE
    e.blob(3, m.ledger_hash)  # ledgerHash
    if m.ledger_seq:
        e.varint(4, m.ledger_seq)  # ledgerSeq
    for nid in m.node_ids:
        e.blob(5, nid)  # nodeIDs
    return e.data()


def _dec_get_ledger(buf: bytes):
    f = parse(buf)
    itype = first_int(f, 1)
    if itype == LI_TS_CANDIDATE:
        return GetTxSet(first_bytes(f, 3))
    return GetLedger(
        ledger_hash=first_bytes(f, 3),
        ledger_seq=first_int(f, 4),
        what=itype,
        node_ids=[bytes(v) for v in f.get(5, [])],
    )


def _ledger_node(nodedata: bytes, nodeid: bytes | None = None) -> Encoder:
    sub = Encoder().blob(1, nodedata)
    if nodeid is not None:
        sub.blob(2, nodeid)
    return sub


def _enc_set_data(m: TxSetData) -> bytes:
    e = Encoder()
    e.blob(1, m.set_hash)  # ledgerHash (the tx-set hash here)
    e.varint(2, 0)  # ledgerSeq (none for a candidate set)
    e.varint(3, LI_TS_CANDIDATE)  # type
    for blob in m.tx_blobs:
        e.message(4, _ledger_node(blob))  # nodes: nodedata only
    return e.data()


def _enc_ledger_data(m: LedgerData) -> bytes:
    e = Encoder()
    e.blob(1, m.ledger_hash)
    e.varint(2, m.ledger_seq)
    e.varint(3, m.what)
    for nid, blob in m.nodes:
        e.message(4, _ledger_node(blob, nid))
    return e.data()


def _dec_ledger_data(buf: bytes):
    f = parse(buf)
    itype = first_int(f, 3)
    nodes = [parse(sub) for sub in f.get(4, [])]
    if itype == LI_TS_CANDIDATE:
        return TxSetData(
            first_bytes(f, 1), [first_bytes(nf, 1) for nf in nodes]
        )
    return LedgerData(
        ledger_hash=first_bytes(f, 1),
        ledger_seq=first_int(f, 2),
        what=itype,
        nodes=[(first_bytes(nf, 2), first_bytes(nf, 1)) for nf in nodes],
    )


def _enc_status(m: StatusChange) -> bytes:
    e = Encoder()
    # NodeStatus is 1-based (nsCONNECTING=1..); OperatingMode is 0-based
    e.varint(1, m.status + 1)  # newStatus
    e.varint(3, m.ledger_seq)  # ledgerSeq
    e.blob(4, m.ledger_hash)  # ledgerHash
    e.varint(6, m.network_time)  # networkTime
    return e.data()


def _dec_status(buf: bytes) -> StatusChange:
    f = parse(buf)
    return StatusChange(
        status=max(first_int(f, 1) - 1, 0),
        ledger_seq=first_int(f, 3),
        ledger_hash=first_bytes(f, 4, b"\x00" * 32),
        network_time=first_int(f, 6),
    )


def _cluster_node(m: ClusterStatus) -> Encoder:
    from ..protocol.keys import encode_node_public

    node = Encoder()
    node.string(1, encode_node_public(m.node_public))  # publicKey (base58)
    node.varint(2, m.report_time)  # reportTime
    node.varint(3, m.load_fee)  # nodeLoad
    return node


def _enc_cluster(m: ClusterStatus) -> bytes:
    return Encoder().message(1, _cluster_node(m)).data()


def _enc_cluster_update(m: "ClusterUpdate") -> bytes:
    e = Encoder()
    for node in m.nodes:
        e.message(1, _cluster_node(node))
    return e.data()


def _dec_cluster(buf: bytes) -> "ClusterUpdate":
    """TMCluster.clusterNodes is `repeated`: a member may report every
    cluster node it knows (or none — loadSources only). All entries
    decode; malformed public keys skip their entry, never the message."""
    from ..protocol.keys import decode_node_public

    f = parse(buf)
    nodes = []
    for sub in f.get(1, []):
        nf = parse(sub)
        try:
            pub = decode_node_public(first_bytes(nf, 1).decode("utf-8"))
        except Exception:  # noqa: BLE001 — skip one bad entry, keep the rest
            continue
        nodes.append(
            ClusterStatus(
                node_public=pub,
                load_fee=first_int(nf, 3),
                report_time=first_int(nf, 2),
            )
        )
    return ClusterUpdate(nodes)


def _enc_endpoints(m: Endpoints) -> bytes:
    e = Encoder()
    e.varint(1, 1)  # version
    for host, port, hops in m.endpoints:
        try:
            ipv4 = int.from_bytes(_socket.inet_aton(host), "big")
        except OSError:
            continue  # TMIPv4Endpoint cannot carry non-IPv4 hosts
        ip = Encoder().varint(1, ipv4).varint(2, port)
        ep = Encoder().message(1, ip).varint(2, hops)
        e.message(2, ep)
    return e.data()


def _dec_endpoints(buf: bytes) -> Endpoints:
    f = parse(buf)
    out = []
    for sub in f.get(2, []):
        ef = parse(sub)
        ipf = parse(first_bytes(ef, 1))
        host = _socket.inet_ntoa(first_int(ipf, 1).to_bytes(4, "big"))
        out.append((host, first_int(ipf, 2), first_int(ef, 2)))
    return Endpoints(out)


def _enc_get_segments(m: GetSegments) -> bytes:
    # seg_id rides +1 so the manifest sentinel (-1) stays a valid varint
    e = Encoder().varint(1, m.seg_id + 1).varint(2, m.offset)
    if m.snap_epoch:
        e.varint(3, m.snap_epoch)
    _enc_trace_ctx(e, m.trace_ctx)
    return e.data()


def _dec_get_segments(buf: bytes) -> GetSegments:
    f = parse(buf)
    return GetSegments(
        seg_id=first_int(f, 1) - 1,
        offset=first_int(f, 2),
        snap_epoch=first_int(f, 3),
        trace_ctx=_dec_trace_ctx(f),
    )


def _enc_segment_data(m: SegmentData) -> bytes:
    e = Encoder()
    e.varint(1, m.seg_id + 1)
    e.varint(2, m.total)
    e.varint(3, m.offset)
    if m.data:
        e.blob(4, m.data)
    for seg in m.segments:
        sid, size, live, active = seg[0], seg[1], seg[2], seg[3]
        row = (
            Encoder().varint(1, sid + 1).varint(2, size)
            .varint(3, live).varint(4, 1 if active else 0)
        )
        # sealed-shard range advertisement (nonzero-only: a legacy
        # 4-tuple row and a zero-extended 7-tuple encode identically)
        lo = seg[4] if len(seg) > 4 else 0
        hi = seg[5] if len(seg) > 5 else 0
        fbytes = seg[6] if len(seg) > 6 else 0
        if lo:
            row.varint(5, lo)
        if hi:
            row.varint(6, hi)
        if fbytes:
            row.varint(7, fbytes)
        e.message(5, row)
    if m.snap_epoch:
        e.varint(6, m.snap_epoch)
    if m.snap_seq:
        e.varint(7, m.snap_seq)
    _enc_trace_ctx(e, m.trace_ctx)
    return e.data()


def _dec_segment_data(buf: bytes) -> SegmentData:
    f = parse(buf)
    segments = []
    for sub in f.get(5, []):
        rf = parse(sub)
        segments.append((
            first_int(rf, 1) - 1,
            first_int(rf, 2),
            first_int(rf, 3),
            bool(first_int(rf, 4)),
            first_int(rf, 5),
            first_int(rf, 6),
            first_int(rf, 7),
        ))
    return SegmentData(
        seg_id=first_int(f, 1) - 1,
        total=first_int(f, 2),
        offset=first_int(f, 3),
        data=first_bytes(f, 4, b""),
        segments=segments,
        snap_epoch=first_int(f, 6),
        snap_seq=first_int(f, 7),
        trace_ctx=_dec_trace_ctx(f),
    )


def _enc_get_objects(m: GetObjects) -> bytes:
    e = Encoder()
    e.varint(1, 0)  # type otUNKNOWN
    e.boolean(2, True)  # query
    for h in m.hashes:
        e.message(6, Encoder().blob(1, h))
    return e.data()


def _enc_objects_data(m: ObjectsData) -> bytes:
    e = Encoder()
    e.varint(1, 0)
    e.boolean(2, False)  # reply
    for h, blob in m.objects:
        e.message(6, Encoder().blob(1, h).blob(4, blob))
    return e.data()


def _dec_get_objects(buf: bytes):
    f = parse(buf)
    objs = [parse(sub) for sub in f.get(6, [])]
    if first_int(f, 2):
        return GetObjects([first_bytes(of, 1) for of in objs])
    return ObjectsData(
        [(first_bytes(of, 1), first_bytes(of, 4)) for of in objs]
    )


# class -> (message type, encoder); one mt may decode to several classes
_ENCODERS = {
    Hello: (MessageType.HELLO, _enc_hello),
    Ping: (MessageType.PING, _enc_ping),
    ClusterStatus: (MessageType.CLUSTER, _enc_cluster),
    ClusterUpdate: (MessageType.CLUSTER, _enc_cluster_update),
    Endpoints: (MessageType.ENDPOINTS, _enc_endpoints),
    TxMessage: (MessageType.TRANSACTION, _enc_tx),
    GetLedger: (MessageType.GET_LEDGER, _enc_get_ledger),
    GetTxSet: (MessageType.GET_LEDGER, _enc_get_set),
    LedgerData: (MessageType.LEDGER_DATA, _enc_ledger_data),
    TxSetData: (MessageType.LEDGER_DATA, _enc_set_data),
    ProposeSet: (MessageType.PROPOSE_SET, _enc_propose),
    StatusChange: (MessageType.STATUS_CHANGE, _enc_status),
    HaveTxSet: (MessageType.HAVE_TX_SET, _enc_have_set),
    ValidationMessage: (MessageType.VALIDATION, _enc_validation),
    GetObjects: (MessageType.GET_OBJECTS, _enc_get_objects),
    ObjectsData: (MessageType.GET_OBJECTS, _enc_objects_data),
    GetSegments: (MessageType.GET_SEGMENTS, _enc_get_segments),
    SegmentData: (MessageType.SEGMENT_DATA, _enc_segment_data),
}

_DECODERS = {
    MessageType.HELLO: _dec_hello,
    MessageType.PING: _dec_ping,
    MessageType.CLUSTER: _dec_cluster,
    MessageType.ENDPOINTS: _dec_endpoints,
    MessageType.TRANSACTION: _dec_tx,
    MessageType.GET_LEDGER: _dec_get_ledger,
    MessageType.LEDGER_DATA: _dec_ledger_data,
    MessageType.PROPOSE_SET: _dec_propose,
    MessageType.STATUS_CHANGE: _dec_status,
    MessageType.HAVE_TX_SET: _dec_have_set,
    MessageType.VALIDATION: _dec_validation,
    MessageType.GET_OBJECTS: _dec_get_objects,
    MessageType.GET_SEGMENTS: _dec_get_segments,
    MessageType.SEGMENT_DATA: _dec_segment_data,
}


def encode_message(msg) -> bytes:
    """Payload bytes (no frame header)."""
    _mt, enc = _ENCODERS[type(msg)]
    return enc(msg)


# ripple.proto MessageType values we know of but do not implement:
# mtERROR_MSG, mtPROOFOFWORK(wire), presence/discovery legacy
# (mtGET_CONTACTS..mtUNUSED_FIELD), small-node ops
# (mtSEARCH_TRANSACTION..mtACCOUNT), mtGET_VALIDATIONS
_KNOWN_UNIMPLEMENTED = frozenset({2, 4, 10, 11, 12, 13, 14, 20, 21, 22, 40})


def decode_message(mt: int, payload: bytes):
    """Decode one payload. Schema-known message types outside our subset
    return None (skipped — a full-ripple.proto peer routinely sends
    them, and protobuf compatibility means never erroring on them); a
    type outside the schema entirely is a protocol violation and raises,
    so the resource plane can charge the sender (reference: PeerImp's
    invalid-message fee)."""
    if mt in _KNOWN_UNIMPLEMENTED:
        return None
    try:
        typ = MessageType(mt)
    except ValueError:
        raise ValueError(f"message type {mt} outside the wire schema") from None
    return _DECODERS[typ](payload)


def frame(msg) -> bytes:
    """Full wire frame: 4-byte length + 2-byte type + payload
    (reference: Message.cpp 6-byte header)."""
    mt, enc = _ENCODERS[type(msg)]
    payload = enc(msg)
    return len(payload).to_bytes(4, "big") + int(mt).to_bytes(2, "big") + payload


class FrameReader:
    """Incremental frame decoder for a TCP byte stream."""

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> list:
        """Append stream bytes; return completed messages."""
        self._buf.extend(data)
        out = []
        while len(self._buf) >= HEADER_LEN:
            length = int.from_bytes(self._buf[:4], "big")
            if length > MAX_FRAME:
                raise ValueError("oversized frame")
            if len(self._buf) < HEADER_LEN + length:
                break
            mt = int.from_bytes(self._buf[4:6], "big")
            payload = bytes(self._buf[HEADER_LEN : HEADER_LEN + length])
            del self._buf[: HEADER_LEN + length]
            msg = decode_message(mt, payload)
            if msg is not None:  # unknown type: skipped, stream continues
                out.append(msg)
        return out
