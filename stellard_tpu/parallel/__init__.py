from .mesh import (
    make_mesh,
    sharded_verify_kernel,
    sharded_sha512_blocks,
    verify_and_count,
)
