"""Multi-chip sharding of the crypto plane.

The reference has no collectives (its 'distributed backend' is the TCP
overlay between validators — SURVEY.md §2.9); chips within one validator
host are the new, TPU-idiomatic parallel axis. The batch dimension of the
verify/hash kernels shards data-parallel over ICI via a 1-D
``jax.sharding.Mesh``; cross-chip aggregation (e.g. "did every signature
in the consensus set verify") is an ICI collective (psum), not host code.

Validator-to-validator traffic stays on the overlay (DCN/TCP): the mesh is
intra-node only, matching SURVEY.md §5's "overlay inter-node, ICI
intra-node" design.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ed25519_jax import verify_kernel
from ..ops.sha512_jax import sha512_blocks

BATCH_AXIS = "batch"


def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """Version-portable shard_map: `jax.shard_map` (with `check_vma`)
    landed well after the jax this image pins — older versions expose
    `jax.experimental.shard_map.shard_map` with the same semantics under
    the pre-rename `check_rep` keyword."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = {} if check_vma is None else {"check_vma": check_vma}
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
    from jax.experimental.shard_map import shard_map as esm

    kwargs = {} if check_vma is None else {"check_rep": check_vma}
    return esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kwargs)


def make_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (BATCH_AXIS,))


def _batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(BATCH_AXIS))


def sharded_verify_kernel(mesh: Mesh):
    """jit of the batched Ed25519 verify with the batch dim sharded over the
    mesh. XLA partitions the whole point-arithmetic pipeline; no host-side
    scatter/gather is involved beyond the initial device_put."""
    shard = _batch_sharding(mesh)
    return jax.jit(
        verify_kernel,
        in_shardings=(shard, shard, shard, shard, shard),
        out_shardings=shard,
    )


def sharded_verify_kernel_pallas(mesh: Mesh):
    """shard_map of the Pallas whole-verify-in-VMEM kernel: each chip
    runs the grid over its local batch shard (a pallas_call is a custom
    call XLA cannot auto-partition, so the data-parallel split is
    explicit shard_map, unlike sharded_verify_kernel's jit+shardings).
    Public layout identical to verify_kernel's; each shard pads itself
    to its block multiple internally."""
    from ..ops.ed25519_pallas import verify_kernel_pallas

    pspec = P(BATCH_AXIS)
    return jax.jit(
        _shard_map(
            verify_kernel_pallas,
            mesh=mesh,
            in_specs=(pspec,) * 5,
            out_specs=pspec,
            # a pallas_call's out_shape carries no varying-mesh-axes
            # annotation, so the vma consistency check cannot apply
            check_vma=False,
        )
    )


def sharded_sha512_blocks(mesh: Mesh):
    shard = _batch_sharding(mesh)
    return jax.jit(sha512_blocks, in_shardings=(shard,), out_shardings=shard)


def sharded_masked_sha512(mesh: Mesh):
    """jit of the masked mixed-block-count SHA-512 kernel (the tree
    hasher's leaf/flat-batch workhorse) with the row dim sharded over the
    mesh — the hashing twin of sharded_verify_kernel."""
    from ..ops.treehash_jax import sha512_blocks_masked

    shard = _batch_sharding(mesh)
    return jax.jit(
        sha512_blocks_masked, in_shardings=(shard, shard), out_shardings=shard
    )


def sharded_path_quality(mesh: Mesh):
    """jit of the Q16.16 path-quality fold with the candidate batch dim
    sharded over the mesh — the liquidity plane's flat kernel arm,
    shaped exactly like sharded_masked_sha512 (callers pad the batch to
    a width multiple before dispatch)."""
    from ..ops.pathq_jax import path_quality_kernel

    shard = _batch_sharding(mesh)
    return jax.jit(
        path_quality_kernel, in_shardings=(shard,), out_shardings=shard
    )


def sharded_tree_kernels(mesh: Mesh):
    """-> (leaf_kernel, inner_kernel): the fused close's level-chained
    tree-hash programs, sharded over the mesh with the digest buffer
    DONATED so the whole chain stays device-resident at any width.

    The digest buffer rides every level replicated and is re-donated
    call to call (``donate_argnums=0`` — the pjit idiom from the
    SNIPPETS exemplars): XLA reuses the same device allocation across
    the chain instead of materializing a fresh buffer per level, and
    the host reads it back ONCE after the last level. Leaf batches and
    the assembled inner payloads shard row-wise (every row count is a
    power of two >= 8, so any width up to 8 divides them); the inner
    scatter assembles replicated, then ``with_sharding_constraint``
    splits the 5-block compression — the expensive part — across the
    mesh. Width 1 is a one-device mesh of the SAME programs, not a
    separate code path."""
    from ..ops.treehash_jax import INNER_BLOCKS, tree_leaf_body

    shard = _batch_sharding(mesh)
    rep = NamedSharding(mesh, P())

    leaf = jax.jit(
        tree_leaf_body,
        in_shardings=(rep, shard, shard, None),
        out_shardings=rep,
        donate_argnums=0,
    )

    def inner_body(buf, template, rows, col_base, src_rows, offset):
        vals = buf[src_rows]  # [K, 8]
        cols = col_base[:, None] + jnp.arange(8, dtype=col_base.dtype)[None, :]
        t = template.at[rows[:, None], cols].set(vals)
        t = jax.lax.with_sharding_constraint(t, shard)
        st = sha512_blocks(t.reshape(t.shape[0], INNER_BLOCKS, 32))
        return jax.lax.dynamic_update_slice(buf, st[:, :8], (offset, 0))

    inner = jax.jit(
        inner_body,
        in_shardings=(rep, rep, rep, rep, rep, None),
        out_shardings=rep,
        donate_argnums=0,
    )
    return leaf, inner


def verify_and_count(mesh: Mesh):
    """shard_map pipeline: verify local shard, psum the per-chip valid
    counts over ICI -> (flags [B], total_valid scalar replicated).

    This is the consensus-path shape: 'all validations in this quorum batch
    verified' is a cross-chip reduction, kept on-device.
    """

    def local(a_words, r_words, s_windows, h_digits, s_canonical):
        flags = verify_kernel(a_words, r_words, s_windows, h_digits, s_canonical)
        total = jax.lax.psum(jnp.sum(flags.astype(jnp.int32)), BATCH_AXIS)
        return flags, total

    pspec = P(BATCH_AXIS)
    return jax.jit(
        _shard_map(
            local,
            mesh=mesh,
            in_specs=(pspec, pspec, pspec, pspec, pspec),
            out_specs=(pspec, P()),
        )
    )
