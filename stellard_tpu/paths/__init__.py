"""Payment paths: multi-hop cross-currency execution and path search.

Reference: src/ripple_app/paths/ — RippleCalc.cpp (path execution,
2863 LoC), Pathfinder.cpp (path search, 937 LoC), PathState.cpp.

The TPU build replaces the reference's entangled per-node
calcNodeRev/Fwd state machine with a strand model: a path is compiled
into a list of hops (trust-line hops and order-book hops), executed
forward over a sandboxed LedgerEntrySet with exact output targets, and
multi-path payments repeatedly take the best-quality strand — same
semantics, separable pieces.
"""

from .flow import PathError, flow, plan_strand
from .orderbook import Book, LiveBookIndex, OrderBookDB
from .pathfinder import find_paths

__all__ = [
    "Book",
    "LiveBookIndex",
    "OrderBookDB",
    "PathError",
    "find_paths",
    "flow",
    "plan_strand",
]
