"""Path execution: compile an STPath into hops, execute on a sandbox,
combine strands by quality.

Reference: src/ripple_app/paths/RippleCalc.cpp — rippleCalc multi-path
loop (best-quality path per iteration, partial-payment rules),
calcNodeAccountRev/Fwd (trust-line hops: capacity = balance + limit,
issuer transfer fees, NoRipple pair rule), calcNodeDeliverRev/Fwd
(order-book hops, owner-funds limits).

Execution model: every strand runs FORWARD over a duplicated
LedgerEntrySet with an exact output target per hop; book hops consume
real offers via the same taker loop OfferCreate uses (engine.offers.
cross_offers), so a path payment and an offer crossing move money
through identical code.

Recorded design bound: trust-line QualityIn/QualityOut rates
(calcNodeRipple's uQualityIn/uQualityOut scaling, RippleCalc.cpp:
1253-1340) are stored and reported (TrustSet/account_lines) but NOT
applied to path delivery — faithful quality math requires the
reference's per-node redeem-vs-issue split (quality scales only the
ISSUE portion, calcNodeAccountFwd:1996-2010), which this engine's
single-amount-per-edge model deliberately folds together. Lines with
default (unset) quality — the overwhelming norm — behave identically.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Optional, Union

from ..engine import views
from ..engine.flags import lsfHighNoRipple, lsfLowNoRipple
from ..engine.offers import (
    Amounts,
    CURRENCY_ONE as _CUR_ONE,
    PERMISSIVE_RATE,
    _scale_to_out,
    cross_offers,
)
from ..protocol.sfields import (
    sfAccount,
    sfFlags,
    sfHighLimit,
    sfLowLimit,
    sfTakerGets,
    sfTakerPays,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..engine.views import ACCOUNT_ONE
from ..protocol.stobject import PathElement
from ..protocol.ter import TER
from ..state import indexes
from ..state.entryset import LedgerEntrySet

__all__ = ["flow", "plan_strand", "PathError", "AccountHop", "BookHop"]

CURRENCY_XRP = b"\x00" * 20


class PathError(Exception):
    def __init__(self, ter: TER, why: str = ""):
        super().__init__(why or ter.name)
        self.ter = ter


@dataclass
class AccountHop:
    """Move value from `src` to `dst` across their mutual trust line in
    `currency` (reference: account node, calcNodeAccountRev/Fwd)."""

    src: bytes
    dst: bytes
    currency: bytes


@dataclass
class BookHop:
    """Convert via the order book (reference: offer node)."""

    in_currency: bytes
    in_issuer: bytes
    out_currency: bytes
    out_issuer: bytes


Hop = Union[AccountHop, BookHop]


def _asset(currency: bytes, issuer: bytes) -> STAmount:
    if currency == CURRENCY_XRP:
        return STAmount.from_drops(0)
    return STAmount.zero_like(currency, issuer)


def plan_strand(
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    src_currency: bytes,
    src_issuer: bytes,
    path: list[PathElement],
) -> list[Hop]:
    """Compile src + path elements + dst into hops, inserting the implied
    nodes the reference's PathState::expandPath inserts (first/last
    account, books on currency switch).
    """
    hops: list[Hop] = []
    cur_acct = src
    cur_currency = src_currency
    cur_issuer = src_issuer if src_currency != CURRENCY_XRP else ACCOUNT_ZERO

    def push_account(acct: bytes) -> None:
        nonlocal cur_acct, cur_issuer
        if acct == cur_acct:
            return
        if cur_currency == CURRENCY_XRP:
            raise PathError(TER.temBAD_PATH, "STR cannot ripple")
        if (
            not hops
            and cur_acct == src
            and cur_issuer not in (src, acct)
        ):
            # implied head: a spend of an externally-issued asset enters
            # the network through its issuer (reference: expandPath
            # inserts the SendMax issuer node after the source), so
            # src -> [G1] -> M for a USD/G1 spend
            hops.append(AccountHop(src, cur_issuer, cur_currency))
            cur_acct = cur_issuer
        hops.append(AccountHop(cur_acct, acct, cur_currency))
        cur_acct = acct
        # an account node becomes the issuer context of the leg it
        # forwards (reference: PathState::pushNode account nodes carry
        # issuer = account) — without this a cross-gateway chain like
        # src -> G1 -> M -> G2 -> dst sprouts a spurious book hop
        cur_issuer = acct

    for el in path:
        if el.account is not None:
            push_account(el.account)
        elif el.currency is not None or el.issuer is not None:
            new_currency = el.currency if el.currency is not None else cur_currency
            if new_currency == CURRENCY_XRP:
                new_issuer = ACCOUNT_ZERO
            elif el.issuer is not None:
                new_issuer = el.issuer
            else:
                new_issuer = cur_issuer
            if new_currency == cur_currency and new_issuer == cur_issuer:
                raise PathError(TER.temBAD_PATH, "no-op book element")
            hops.append(
                BookHop(cur_currency, cur_issuer, new_currency, new_issuer)
            )
            cur_currency, cur_issuer = new_currency, new_issuer
        else:
            raise PathError(TER.temBAD_PATH, "empty path element")

    # implied tail (reference: expandPath appends dst / final book).
    # `cur_issuer == cur_acct` is the no-SendMax placeholder (the sender
    # stands in as issuer of its own spend) — same-currency delivery from
    # there needs no book, just the issuer ripple below.
    # An IOU dst_amount whose issuer IS the destination account means
    # "any issuer the destination accepts" (reference: STAmount
    # issuer-of-self convention) — whatever issuer the strand carries is
    # deliverable, so no issuer-correcting book is implied.
    flexible = (
        dst_amount.currency != CURRENCY_XRP and dst_amount.issuer == dst
    )
    if cur_currency != dst_amount.currency or (
        cur_currency != CURRENCY_XRP
        and dst_amount.currency != CURRENCY_XRP
        and not flexible
        and cur_issuer != dst_amount.issuer
        and cur_issuer != cur_acct
        and cur_acct != dst
        and cur_issuer != dst
    ):
        out_iss = (
            ACCOUNT_ZERO
            if dst_amount.currency == CURRENCY_XRP
            else dst_amount.issuer
        )
        hops.append(
            BookHop(cur_currency, cur_issuer, dst_amount.currency, out_iss)
        )
        cur_currency, cur_issuer = dst_amount.currency, out_iss
    if cur_acct != dst:
        if cur_currency == CURRENCY_XRP:
            hops.append(AccountHop(cur_acct, dst, CURRENCY_XRP))
        else:
            # deliver through the issuer when src/dst share no line
            # (reference: implied issuer node for the default path).
            # Flexible delivery routes through the issuer the strand
            # actually carries.
            issuer = (
                cur_issuer
                if (flexible and cur_issuer != cur_acct)
                else dst_amount.issuer
            )
            if cur_acct != issuer and dst != issuer:
                hops.append(AccountHop(cur_acct, issuer, cur_currency))
                cur_acct = issuer
            hops.append(AccountHop(cur_acct, dst, cur_currency))
    return hops


# -- capacity / quotes ----------------------------------------------------


def line_capacity(
    les: LedgerEntrySet, src: bytes, dst: bytes, currency: bytes
) -> Optional[STAmount]:
    """How much `src` can move to `dst` over their line: src's balance
    (redeeming dst's IOU) plus dst's trust limit for src (issuing src's
    own IOU) (reference: calcNodeAccountRev limit math). None = no line.
    """
    idx = indexes.ripple_state_index(src, dst, currency)
    line = les.peek(idx)
    if line is None:
        return None
    bal = views.ripple_balance(les, src, dst, currency)
    # dst's limit lives on dst's side of the line (dst is high iff
    # src < dst, since the low account sorts first)
    dst_limit = line.get(sfHighLimit if src < dst else sfLowLimit)
    if dst_limit is None:
        dst_limit = STAmount.zero_like(currency, dst)
    return bal + STAmount.from_iou(
        currency, ACCOUNT_ONE, dst_limit.mantissa, dst_limit.offset,
        dst_limit.negative,
    )


def no_ripple_blocked(
    les: LedgerEntrySet, mid: bytes, prev: bytes, nxt: bytes, currency: bytes
) -> bool:
    """The NoRipple pair rule: rippling through `mid` between its lines
    with `prev` and `nxt` is blocked when mid set NoRipple on BOTH
    (reference: calcNodeRipple NoRipple enforcement)."""

    def mid_no_ripple(other: bytes) -> bool:
        line = les.peek(indexes.ripple_state_index(mid, other, currency))
        if line is None:
            return False
        flags = line.get(sfFlags, 0)
        mid_is_low = mid < other
        return bool(flags & (lsfLowNoRipple if mid_is_low else lsfHighNoRipple))

    return mid_no_ripple(prev) and mid_no_ripple(nxt)


def book_quote(
    les: LedgerEntrySet,
    in_currency: bytes,
    in_issuer: bytes,
    out_need: STAmount,
    in_cap: Optional[STAmount] = None,
) -> tuple[STAmount, STAmount]:
    """Read-only estimate: walking the book best-quality-first, what
    input buys `out_need` (owner-funds-limited)? -> (in_needed,
    out_available). With `in_cap`, also stop when the input budget is
    exhausted — the quote for "how much does my budget buy".
    reference: calcNodeDeliverRev."""
    from ..engine.offers import _scale_to_in

    in_total = _asset(in_currency, in_issuer)
    out_total = _zero_of(out_need)

    book_base = indexes.book_base(
        in_currency, in_issuer, out_need.currency,
        ACCOUNT_ZERO if out_need.is_native else out_need.issuer,
    )
    book_end = indexes.quality_next(book_base)
    cursor = book_base
    while out_total < out_need:
        item = les.ledger.state_map.succ(cursor)
        if item is None or item.tag >= book_end:
            break
        cursor = item.tag
        if les.peek(item.tag) is None:
            continue
        for offer_idx in list(les.dir_entries(item.tag)):
            offer = les.peek(offer_idx)
            if offer is None:
                continue
            rest = Amounts(offer[sfTakerPays], offer[sfTakerGets])
            funds = views.account_funds(les, offer[sfAccount], rest.o)
            if funds.signum() <= 0 or rest.o.signum() <= 0:
                continue
            flow_amts = _scale_to_out(rest, funds)
            remaining = out_need - out_total
            flow_amts = _scale_to_out(flow_amts, remaining)
            if in_cap is not None:
                in_left = in_cap - in_total
                if in_left.signum() <= 0:
                    return in_total, out_total
                flow_amts = _scale_to_in(flow_amts, in_left)
            if flow_amts.o.signum() <= 0:
                continue
            in_total = in_total + flow_amts.i
            out_total = out_total + flow_amts.o
            if out_total >= out_need:
                break
    return in_total, out_total


def _node_qualities(
    les: LedgerEntrySet, hops: list, i: int, src: bytes
) -> tuple[int, int]:
    """(qualityIn, qualityOut) at the node SENDING hop i (an interior
    AccountHop), 1e9 = parity (reference: calcNodeAccountRev's
    rippleQualityIn/Out lookups, RippleCalc.cpp:1419-1424). qualityIn
    covers the line the value arrived over — defined only when the
    previous hop is an account-to-account ripple (the reference's
    account-adjacent-to-account node shape; book boundaries carry no
    line quality); qualityOut covers the line to this hop's receiver."""
    hop = hops[i]
    prev = hops[i - 1] if i > 0 else None
    if not isinstance(prev, AccountHop) or hop.src == src:
        return views.QUALITY_ONE, views.QUALITY_ONE
    qin = views.ripple_quality(
        les, hop.src, prev.src, hop.currency, inbound=True
    )
    qout = views.ripple_quality(
        les, hop.src, hop.dst, hop.currency, inbound=False
    )
    return qin, qout


# -- forward execution ----------------------------------------------------


def execute_strand(
    les: LedgerEntrySet,
    src: bytes,
    hops: list[Hop],
    out_target: STAmount,
    in_budget: STAmount,
    parent_close_time: int,
) -> tuple[STAmount, STAmount]:
    """Run the strand forward on `les` (callers pass a duplicate); returns
    (spent_at_src, delivered_at_dst). Raises PathError on a dry/broken
    strand. Output is targeted exactly: every hop knows what the rest of
    the strand still needs (reference: calcNode*Fwd with the rev-pass
    requests folded in)."""
    if not hops:
        raise PathError(TER.tecPATH_DRY, "empty strand")
    # REVERSE pass (reference: calcNodeAccountRev / calcNodeDeliverRev):
    # per-hop output targets computed backwards, clamped by what each hop
    # can actually move — a capacity-limited line downstream shrinks the
    # request upstream, so a budget-limited book hop never buys input the
    # rest of the strand cannot deliver (over-buying both wastes sendmax
    # and degrades the strand's measured quality)
    targets: list[STAmount] = [None] * len(hops)  # type: ignore[list-item]
    need = out_target
    for i in range(len(hops) - 1, -1, -1):
        hop = hops[i]
        if isinstance(hop, AccountHop):
            # the clamp is valid only where upstream execution cannot
            # raise this hop's capacity: an account hop directly after a
            # book hop moves value over the very line the book crossing
            # just credited, so its pre-execution capacity understates
            # (reference: calcNodeAccountRev computes caps against the
            # previous node's deliverable, not the static line state)
            after_book = i > 0 and isinstance(hops[i - 1], BookHop)
            if hop.currency != CURRENCY_XRP and not after_book:
                cap = line_capacity(les, hop.src, hop.dst, hop.currency)
                if cap is None or cap.signum() <= 0:
                    raise PathError(
                        TER.tecPATH_DRY, "no line capacity (rev pass)"
                    )
                if cap < need:
                    need = STAmount.from_iou(
                        need.currency, need.issuer,
                        cap.mantissa, cap.offset, cap.negative,
                    )
            targets[i] = need
            # the hop's source must first RECEIVE need*rate when it is an
            # intermediary gateway (reference: rippleTransferFee)
            if hop.src != src and hop.currency != CURRENCY_XRP:
                rate = views.ripple_transfer_rate(les, hop.src)
                if rate != views.QUALITY_ONE:
                    need = STAmount.multiply(
                        need,
                        STAmount.from_iou(_CUR_ONE, ACCOUNT_ONE, rate, -9),
                        need.currency,
                        need.issuer,
                    )
                # line-quality fee at the interior node (reference:
                # calcNodeRipple — in = out * qualityOut/qualityIn when
                # qualityIn < qualityOut, never a bonus): the node rates
                # inbound IOUs from the previous account by ITS OWN
                # QualityIn on that line, and its forwarding to the next
                # by its QualityOut
                qin, qout = _node_qualities(les, hops, i, src)
                if qin < qout:
                    need = STAmount.multiply(
                        STAmount.divide(
                            need,
                            STAmount.from_iou(_CUR_ONE, ACCOUNT_ONE, qin, -9),
                            need.currency, need.issuer,
                        ),
                        STAmount.from_iou(_CUR_ONE, ACCOUNT_ONE, qout, -9),
                        need.currency,
                        need.issuer,
                    )
        else:
            # the requirement carried backward may still be denominated
            # in the FINAL delivery issuer (e.g. flexible issuer-of-dst
            # amounts); this book produces hop.out_issuer's IOUs — quote
            # and target in that denomination
            if not need.is_native and need.issuer != hop.out_issuer:
                need = STAmount.from_iou(
                    hop.out_currency, hop.out_issuer,
                    need.mantissa, need.offset, need.negative,
                )
            targets[i] = need
            # book input requirement discovered by quote
            in_needed, out_avail = book_quote(
                les, hop.in_currency, hop.in_issuer, need
            )
            if out_avail.signum() <= 0:
                raise PathError(TER.tecPATH_DRY, "empty book")
            need = in_needed

    holder = src
    carried = in_budget  # value available entering the next hop
    spent: Optional[STAmount] = None
    for i, hop in enumerate(hops):
        want_out = targets[i]
        if isinstance(hop, AccountHop):
            # NoRipple pair rule: an intermediary that set NoRipple on
            # both adjacent lines has opted out of rippling through it
            if (
                hop.src != src
                and i > 0
                and isinstance(hops[i - 1], AccountHop)
                and no_ripple_blocked(
                    les, hop.src, hops[i - 1].src, hop.dst, hop.currency
                )
            ):
                raise PathError(TER.tecPATH_DRY, "NoRipple blocks this hop")
            if hop.currency == CURRENCY_XRP:
                amount = min(carried, want_out)
                if amount.signum() <= 0:
                    raise PathError(TER.tecPATH_DRY, "no STR to deliver")
                ter = views.account_send(les, hop.src, hop.dst, amount)
                if ter != TER.tesSUCCESS:
                    raise PathError(ter, "STR delivery failed")
                if spent is None:
                    spent = amount
                carried = amount
                holder = hop.dst
                continue
            cap = line_capacity(les, hop.src, hop.dst, hop.currency)
            if cap is None:
                raise PathError(TER.tecPATH_DRY, "no trust line")
            deliver = want_out
            # fee at an intermediary gateway: it forwards what it
            # received net of its transfer rate
            if hop.src != src:
                rate = views.ripple_transfer_rate(les, hop.src)
                usable = carried
                if rate != views.QUALITY_ONE:
                    usable = STAmount.divide(
                        carried,
                        STAmount.from_iou(_CUR_ONE, ACCOUNT_ONE, rate, -9),
                        carried.currency,
                        carried.issuer,
                    )
                # line-quality fee (mirror of the reverse pass): the
                # node forwards in * qualityIn/qualityOut of what
                # arrived when qualityIn < qualityOut
                qin, qout = _node_qualities(les, hops, i, src)
                if qin < qout:
                    usable = STAmount.divide(
                        STAmount.multiply(
                            usable,
                            STAmount.from_iou(_CUR_ONE, ACCOUNT_ONE, qin, -9),
                            usable.currency, usable.issuer,
                        ),
                        STAmount.from_iou(_CUR_ONE, ACCOUNT_ONE, qout, -9),
                        usable.currency,
                        usable.issuer,
                    )
                deliver = min(deliver, usable)
            else:
                # strand source: limited by its own budget if same asset
                if not carried.is_native and carried.currency == hop.currency:
                    deliver = min(deliver, carried)
            deliver = min(deliver, cap)
            deliver = STAmount.from_iou(
                hop.currency,
                hop.dst,
                deliver.mantissa,
                deliver.offset,
                deliver.negative,
            )
            if deliver.signum() <= 0:
                raise PathError(TER.tecPATH_DRY, "line capacity exhausted")
            ter = views.ripple_credit(les, hop.src, hop.dst, deliver)
            if ter != TER.tesSUCCESS:
                raise PathError(ter, "ripple credit failed")
            if spent is None:
                # at the strand source: cost = what src sent, plus the
                # downstream fees are already embedded in later hops
                spent = deliver
            carried = deliver
            holder = hop.dst
        else:
            in_cap = carried if (
                carried.currency == hop.in_currency
            ) else views.account_holds(
                les, holder, hop.in_currency, hop.in_issuer
            )
            if in_cap.signum() <= 0:
                raise PathError(TER.tecPATH_DRY, "no input for book")
            # quote-then-cross, iterated: the quote's midpoint roundings
            # (reference STAmount +7/+5 rounding) can price the need a
            # drop short, and a multi-level fill then under-delivers by
            # a rounding quantum; a follow-up pass buys the remainder.
            # Budget-limited throughout: the quote finds what the budget
            # actually buys (cross_offers caps both sides exactly).
            total_paid: Optional[STAmount] = None
            total_got: Optional[STAmount] = None
            for _round in range(4):
                still = (want_out if total_got is None
                         else want_out - total_got)
                if still.signum() <= 0:
                    break
                cap_left = (in_cap if total_paid is None
                            else in_cap - total_paid)
                if cap_left.signum() <= 0:
                    break
                _, est_out = book_quote(
                    les, hop.in_currency, hop.in_issuer, still, cap_left
                )
                if est_out.signum() <= 0:
                    if total_got is None:
                        raise PathError(
                            TER.tecPATH_DRY, "book too expensive or dry"
                        )
                    break
                ter, paid, got = cross_offers(
                    les,
                    holder,
                    # the full remaining budget, not est_in: the quote's
                    # midpoint roundings can price the fill a drop short
                    # and starve the marginal offer's input; the exact
                    # est_out cap is what terminates the fill, so input
                    # headroom cannot overshoot the out target
                    cap_left,
                    est_out,
                    sell=False,
                    passive=False,
                    parent_close_time=parent_close_time,
                    # a payment's book node has NO taker quality limit
                    # (reference: calcNodeDeliverFwd consumes offers at
                    # their own prices until the need is met; only
                    # tfLimitQuality imposes one). The default in/out
                    # threshold is the AVERAGE price of the quote, which
                    # wrongly rejects the marginal offer of a multi-
                    # level fill; est_in/est_out still cap both sides.
                    threshold_rate=PERMISSIVE_RATE,
                )
                if ter != TER.tesSUCCESS:
                    if total_got is None:
                        raise PathError(ter, "book crossing failed")
                    break  # keep the earlier rounds' successful fill
                if got.signum() <= 0:
                    break
                total_paid = paid if total_paid is None else total_paid + paid
                total_got = got if total_got is None else total_got + got
            if total_got is None or total_got.signum() <= 0:
                raise PathError(TER.tecPATH_DRY, "book gave nothing")
            if spent is None:
                spent = total_paid
            carried = total_got
    assert spent is not None
    return spent, carried


# -- multi-path combiner --------------------------------------------------


def _ratio(delivered: STAmount, cost: STAmount) -> Fraction:
    """Quality for ranking strands (higher = cheaper), as an exact rational
    so edge-rate limit-quality comparisons match the reference's exact
    STAmount::getRate arithmetic (no float precision boundary)."""
    c_m = cost.mantissa
    c_off = 0 if cost.is_native else cost.offset
    d_m = delivered.mantissa
    d_off = 0 if delivered.is_native else delivered.offset
    if c_m <= 0:
        return Fraction(0)
    num, den = d_m, c_m
    e = d_off - c_off
    if e >= 0:
        num *= 10**e
    else:
        den *= 10 ** (-e)
    return Fraction(num, den)


def flow(
    les: LedgerEntrySet,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: STAmount,
    paths: list[list[PathElement]],
    partial: bool,
    parent_close_time: int,
    max_iterations: int = 30,
    limit_quality: Optional[Fraction] = None,
) -> tuple[TER, STAmount, STAmount]:
    """Deliver `dst_amount` to dst using the given strands, best quality
    first, spending at most `send_max` (reference: rippleCalc multi-path
    loop). Returns (ter, actually_spent, actually_delivered); mutations
    land in `les` only for the committed strands."""
    src_currency = send_max.currency
    src_issuer = (
        ACCOUNT_ZERO if send_max.is_native else send_max.issuer
    )
    strands: list[list[Hop]] = []
    for path in paths:
        try:
            strands.append(
                plan_strand(src, dst, dst_amount, src_currency, src_issuer, path)
            )
        except PathError as e:
            if -299 <= int(e.ter) <= -200:  # tem*: the tx is malformed
                return e.ter, _zero_of(send_max), _zero_of(dst_amount)
            continue
    if not strands:
        return TER.tecPATH_DRY, _zero_of(send_max), _zero_of(dst_amount)

    remaining = dst_amount
    budget = send_max
    total_spent = _zero_of(send_max)
    total_delivered = _zero_of(dst_amount)

    for _ in range(max_iterations):
        if remaining.signum() <= 0 or budget.signum() <= 0:
            break
        best = None  # (ratio, sandbox, spent, delivered)
        for hops in strands:
            sandbox = les.duplicate()
            try:
                spent, delivered = execute_strand(
                    sandbox, src, hops, remaining, budget, parent_close_time
                )
            except PathError:
                continue
            if delivered.signum() <= 0 or spent.signum() <= 0:
                continue
            if spent > budget:
                continue
            r = _ratio(delivered, spent)
            if limit_quality is not None and r < limit_quality:
                continue  # tfLimitQuality: refuse worse-than-stated rates
            if best is None or r > best[0]:
                best = (r, sandbox, spent, delivered)
        if best is None:
            break
        _r, sandbox, spent, delivered = best
        les.swap_with(sandbox)
        total_spent = total_spent + spent
        total_delivered = total_delivered + delivered
        remaining = remaining - delivered
        budget = budget - spent

    if remaining.signum() <= 0:
        return TER.tesSUCCESS, total_spent, total_delivered
    if partial and total_delivered.signum() > 0:
        return TER.tesSUCCESS, total_spent, total_delivered
    if total_delivered.signum() > 0:
        return TER.tecPATH_PARTIAL, total_spent, total_delivered
    return TER.tecPATH_DRY, total_spent, total_delivered


def _zero_of(a: STAmount) -> STAmount:
    if a.is_native:
        return STAmount.from_drops(0)
    return STAmount.zero_like(a.currency, a.issuer)
