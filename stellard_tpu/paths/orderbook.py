"""OrderBookDB: index of the order books that exist in a ledger.

Reference: src/ripple_app/ledger/OrderBookDB.cpp (326 LoC) — rebuilt on
ledger switch (jtOB_SETUP), consulted by the Pathfinder for which
currency conversions are available, and by book subscriptions.

LiveBookIndex is this repo's incremental twin: instead of rescanning
every ltOFFER per ledger switch, it carries an offer count per Book
forward across closes and applies only the close's own write set —
the Created/Deleted ltOFFER nodes in each transaction's metadata.
A close that touches no books carries the previous index forward
without a single state read (pinned by the `state_offers_scanned` /
`book_rereads` counters); any discontinuity (gap, fork, missing
metadata, count underflow) falls back to the full scan, which the
`incremental=False` kill-switch forces unconditionally.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import (
    sfAffectedNodes,
    sfCreatedNode,
    sfDeletedNode,
    sfFinalFields,
    sfLedgerEntryType,
    sfNewFields,
    sfTakerGets,
    sfTakerPays,
)
from ..protocol.stamount import ACCOUNT_ZERO
from ..protocol.stobject import STObject
from ..state.ledger import Ledger

__all__ = ["Book", "OrderBookDB", "LiveBookIndex", "book_of"]

CURRENCY_XRP = b"\x00" * 20


@dataclass(frozen=True)
class Book:
    """One direction of one market (reference: OrderBook)."""

    in_currency: bytes  # what the taker pays (book's TakerPays)
    in_issuer: bytes
    out_currency: bytes  # what the taker gets (book's TakerGets)
    out_issuer: bytes


class OrderBookDB:
    # (ledger seq, state root) -> OrderBookDB; tiny LRU so repeated
    # pathfinding against the same ledger doesn't rescan the state map
    # (reference: rebuilt once per ledger switch on jtOB_SETUP)
    _cache: dict[tuple[int, bytes], "OrderBookDB"] = {}
    _CACHE_MAX = 4

    def __init__(self):
        self.books: set[Book] = set()
        # in-asset -> books consuming it (the pathfinder's fan-out edge)
        self.by_in: dict[tuple[bytes, bytes], set[Book]] = {}
        self.by_out: dict[tuple[bytes, bytes], set[Book]] = {}

    @classmethod
    def for_ledger(cls, ledger: Ledger) -> "OrderBookDB":
        key = (ledger.seq, ledger.state_map.get_hash())
        db = cls._cache.get(key)
        if db is None:
            db = cls().setup(ledger)
            cls._cache[key] = db
            while len(cls._cache) > cls._CACHE_MAX:
                cls._cache.pop(next(iter(cls._cache)))
        return db

    def setup(self, ledger: Ledger) -> "OrderBookDB":
        """Scan the state map's offers (reference: OrderBookDB::setup
        walks ltOFFER entries)."""
        self.books.clear()
        self.by_in.clear()
        self.by_out.clear()
        for item in ledger.state_map.items():
            sle = STObject.from_bytes(item.data)
            if sle.get(sfLedgerEntryType) != int(LedgerEntryType.ltOFFER):
                continue
            self.add(book_of(sle[sfTakerPays], sle[sfTakerGets]))
        return self

    def add(self, book: Book) -> None:
        if book not in self.books:
            self.books.add(book)
            self.by_in.setdefault(
                (book.in_currency, book.in_issuer), set()
            ).add(book)
            self.by_out.setdefault(
                (book.out_currency, book.out_issuer), set()
            ).add(book)

    def books_taking(self, currency: bytes, issuer: bytes) -> set[Book]:
        return self.by_in.get((currency, issuer), set())

    def books_delivering(self, currency: bytes, issuer: bytes) -> set[Book]:
        return self.by_out.get((currency, issuer), set())

    def __len__(self) -> int:
        return len(self.books)


def book_of(pays, gets) -> Book:
    """The Book an offer with these TakerPays/TakerGets lives in."""
    return Book(
        pays.currency,
        ACCOUNT_ZERO if pays.is_native else pays.issuer,
        gets.currency,
        ACCOUNT_ZERO if gets.is_native else gets.issuer,
    )


class LiveBookIndex:
    """Per-close incremental OrderBookDB (reference: OrderBookDB is
    rebuilt from scratch on every ledger switch; here only the books in
    the close's write set are touched).

    The source of truth for membership deltas is transaction metadata:
    a CreatedNode for an ltOFFER adds one offer to its book (TakerPays/
    TakerGets live in NewFields), a DeletedNode removes one (FinalFields).
    ModifiedNode never moves an offer between books — partial fills
    change amounts, never the currency/issuer pair — so it is ignored.

    Identity contract: after advance(ledger), the book set equals what
    OrderBookDB().setup(ledger) would compute, for every ledger — pinned
    by tests and the pathsmoke gate against the kill-switch.
    """

    def __init__(self, incremental: bool = True):
        import threading

        self.incremental = incremental
        # the close hook (persist/publish thread) and the jtUPDATE_PF
        # publisher race to advance the same close; one coarse lock
        # keeps the count/continuity state consistent (the second
        # caller returns the memoized view)
        self._advance_lock = threading.RLock()
        self._counts: dict[Book, int] = {}
        self._db: OrderBookDB | None = None
        self._seq: int | None = None
        self._hash: bytes | None = None
        # observability (doc/observability.md `paths.index.*`)
        self.full_rebuilds = 0
        self.incremental_advances = 0
        self.carries = 0
        self.book_rereads = 0  # books touched by incremental deltas
        self.state_offers_scanned = 0  # offers read by full scans

    @property
    def seq(self) -> int | None:
        return self._seq

    def counters(self) -> dict:
        return {
            "incremental": bool(self.incremental),
            "seq": self._seq,
            "books": len(self._counts),
            "full_rebuilds": self.full_rebuilds,
            "incremental_advances": self.incremental_advances,
            "carries": self.carries,
            "book_rereads": self.book_rereads,
            "state_offers_scanned": self.state_offers_scanned,
        }

    def books_if_current(self, ledger: Ledger) -> OrderBookDB | None:
        """The live view if it already reflects `ledger`, else None —
        never mutates (RPC against historical ledgers must not wreck
        the close-to-close continuity)."""
        with self._advance_lock:
            if self._db is not None and self._seq == ledger.seq \
                    and self._hash == ledger.hash():
                return self._db
            return None

    def advance(self, ledger: Ledger) -> OrderBookDB:
        """Bring the index to `ledger` and return its OrderBookDB view.

        Incremental when `ledger` is the direct successor of the last
        advanced ledger (parent-hash continuity); a zero-delta close
        carries the previous view forward untouched. Everything else —
        first use, gaps, forks, a tx without metadata, the kill-switch —
        is a full rebuild.
        """
        with self._advance_lock:
            h = ledger.hash()
            if self._db is not None and self._seq == ledger.seq \
                    and self._hash == h:
                return self._db
            if (
                not self.incremental
                or self._db is None
                or ledger.parent_hash != self._hash
                or ledger.seq != (self._seq or 0) + 1
            ):
                return self._rebuild(ledger, h)
            deltas = self._meta_deltas(ledger)
            if deltas is None:  # metadata missing somewhere: rebuild
                return self._rebuild(ledger, h)
            if not any(deltas.values()):
                self.carries += 1
                self._seq, self._hash = ledger.seq, h
                return self._db
            counts = self._counts
            for book, d in deltas.items():
                if d == 0:
                    continue
                self.book_rereads += 1
                c = counts.get(book, 0) + d
                if c < 0:  # underflow: our view disagrees with the chain
                    return self._rebuild(ledger, h)
                if c == 0:
                    counts.pop(book, None)
                else:
                    counts[book] = c
            self.incremental_advances += 1
            self._db = self._db_from_counts()
            self._seq, self._hash = ledger.seq, h
            return self._db

    # -- internals --------------------------------------------------------

    @staticmethod
    def _meta_deltas(ledger: Ledger) -> dict[Book, int] | None:
        """Net per-book offer-count deltas from the close's tx metadata,
        or None when any tx lacks metadata."""
        lt_offer = int(LedgerEntryType.ltOFFER)
        deltas: dict[Book, int] = {}
        parsed = getattr(ledger, "parsed_metas", None) or {}
        for txid, _blob, meta_blob in ledger.tx_entries():
            if not meta_blob:
                return None
            # leader closes memoize the parsed meta (record_transaction);
            # only follower-ingested ledgers pay the deserialization
            meta = parsed.get(txid)
            if meta is None:
                meta = STObject.from_bytes(meta_blob)
            affected = meta.get(sfAffectedNodes)
            if affected is None:
                return None
            for field, node in affected:
                if node.get(sfLedgerEntryType) != lt_offer:
                    continue
                if field == sfCreatedNode:
                    inner, d = node.get(sfNewFields), 1
                elif field == sfDeletedNode:
                    inner, d = node.get(sfFinalFields), -1
                else:
                    continue  # ModifiedNode: amounts only, same book
                if inner is None:
                    return None
                pays = inner.get(sfTakerPays)
                gets = inner.get(sfTakerGets)
                if pays is None or gets is None:
                    return None
                book = book_of(pays, gets)
                deltas[book] = deltas.get(book, 0) + d
        return deltas

    def _rebuild(self, ledger: Ledger, h: bytes) -> OrderBookDB:
        self.full_rebuilds += 1
        lt_offer = int(LedgerEntryType.ltOFFER)
        counts: dict[Book, int] = {}
        scanned = 0
        for item in ledger.state_map.items():
            sle = STObject.from_bytes(item.data)
            if sle.get(sfLedgerEntryType) != lt_offer:
                continue
            scanned += 1
            book = book_of(sle[sfTakerPays], sle[sfTakerGets])
            counts[book] = counts.get(book, 0) + 1
        self.state_offers_scanned += scanned
        self._counts = counts
        self._db = self._db_from_counts()
        self._seq, self._hash = ledger.seq, h
        return self._db

    def _db_from_counts(self) -> OrderBookDB:
        db = OrderBookDB()
        for book in self._counts:
            db.add(book)
        return db
