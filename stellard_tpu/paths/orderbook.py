"""OrderBookDB: index of the order books that exist in a ledger.

Reference: src/ripple_app/ledger/OrderBookDB.cpp (326 LoC) — rebuilt on
ledger switch (jtOB_SETUP), consulted by the Pathfinder for which
currency conversions are available, and by book subscriptions.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import sfLedgerEntryType, sfTakerGets, sfTakerPays
from ..protocol.stamount import ACCOUNT_ZERO
from ..protocol.stobject import STObject
from ..state.ledger import Ledger

__all__ = ["Book", "OrderBookDB"]

CURRENCY_XRP = b"\x00" * 20


@dataclass(frozen=True)
class Book:
    """One direction of one market (reference: OrderBook)."""

    in_currency: bytes  # what the taker pays (book's TakerPays)
    in_issuer: bytes
    out_currency: bytes  # what the taker gets (book's TakerGets)
    out_issuer: bytes


class OrderBookDB:
    # (ledger seq, state root) -> OrderBookDB; tiny LRU so repeated
    # pathfinding against the same ledger doesn't rescan the state map
    # (reference: rebuilt once per ledger switch on jtOB_SETUP)
    _cache: dict[tuple[int, bytes], "OrderBookDB"] = {}
    _CACHE_MAX = 4

    def __init__(self):
        self.books: set[Book] = set()
        # in-asset -> books consuming it (the pathfinder's fan-out edge)
        self.by_in: dict[tuple[bytes, bytes], set[Book]] = {}
        self.by_out: dict[tuple[bytes, bytes], set[Book]] = {}

    @classmethod
    def for_ledger(cls, ledger: Ledger) -> "OrderBookDB":
        key = (ledger.seq, ledger.state_map.get_hash())
        db = cls._cache.get(key)
        if db is None:
            db = cls().setup(ledger)
            cls._cache[key] = db
            while len(cls._cache) > cls._CACHE_MAX:
                cls._cache.pop(next(iter(cls._cache)))
        return db

    def setup(self, ledger: Ledger) -> "OrderBookDB":
        """Scan the state map's offers (reference: OrderBookDB::setup
        walks ltOFFER entries)."""
        self.books.clear()
        self.by_in.clear()
        self.by_out.clear()
        for item in ledger.state_map.items():
            sle = STObject.from_bytes(item.data)
            if sle.get(sfLedgerEntryType) != int(LedgerEntryType.ltOFFER):
                continue
            pays = sle[sfTakerPays]  # offer owner receives this = taker in
            gets = sle[sfTakerGets]  # offer owner gives this = taker out
            book = Book(
                pays.currency,
                ACCOUNT_ZERO if pays.is_native else pays.issuer,
                gets.currency,
                ACCOUNT_ZERO if gets.is_native else gets.issuer,
            )
            self.add(book)
        return self

    def add(self, book: Book) -> None:
        if book not in self.books:
            self.books.add(book)
            self.by_in.setdefault(
                (book.in_currency, book.in_issuer), set()
            ).add(book)
            self.by_out.setdefault(
                (book.out_currency, book.out_issuer), set()
            ).add(book)

    def books_taking(self, currency: bytes, issuer: bytes) -> set[Book]:
        return self.by_in.get((currency, issuer), set())

    def books_delivering(self, currency: bytes, issuer: bytes) -> set[Book]:
        return self.by_out.get((currency, issuer), set())

    def __len__(self) -> int:
        return len(self.books)
