"""Pathfinder: search for viable payment paths.

Reference: src/ripple_app/paths/Pathfinder.cpp (937 LoC) — candidate
generation from fixed path patterns (direct, through gateways, through
order books, XRP-bridged), then liquidity-checked and quality-ranked.
The TPU build generates the same pattern families and validates each
candidate by actually trial-executing its strand on a sandboxed
LedgerEntrySet (the flow engine is its own liquidity oracle), which
replaces the reference's separate path-state liquidity estimation.
"""

from __future__ import annotations

from typing import Optional

from ..engine.flags import lsfHighNoRipple, lsfLowNoRipple
from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import (
    sfBalance,
    sfFlags,
    sfHighLimit,
    sfLedgerEntryType,
    sfLowLimit,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..protocol.stobject import PathElement
from ..state import indexes
from ..state.entryset import LedgerEntrySet
from .flow import CURRENCY_XRP, PathError, execute_strand, plan_strand
from .orderbook import OrderBookDB

__all__ = ["find_paths", "build_path_set", "account_lines_of"]

MAX_GATEWAY_FANOUT = 16


def account_lines_of(
    les: LedgerEntrySet, account_id: bytes, currency: Optional[bytes] = None
) -> list[dict]:
    """[{peer, currency, balance(signed, our perspective), our_limit,
    peer_limit, no_ripple(peer side)}] from the owner directory."""
    out = []
    for entry_idx in les.dir_entries(indexes.owner_dir_index(account_id)):
        sle = les.peek(entry_idx)
        if sle is None or sle.get(sfLedgerEntryType) != int(
            LedgerEntryType.ltRIPPLE_STATE
        ):
            continue
        low = sle[sfLowLimit]
        high = sle[sfHighLimit]
        if currency is not None and low.currency != currency:
            continue
        is_low = low.issuer == account_id
        peer = high.issuer if is_low else low.issuer
        balance = sle[sfBalance]
        bal = balance if is_low else -balance
        flags = sle.get(sfFlags, 0)
        peer_no_ripple = bool(
            flags & (lsfHighNoRipple if is_low else lsfLowNoRipple)
        )
        out.append(
            {
                "peer": peer,
                "currency": low.currency,
                "balance": bal,
                "our_limit": low if is_low else high,
                "peer_limit": high if is_low else low,
                "peer_no_ripple": peer_no_ripple,
            }
        )
    return out


def _source_assets(
    les: LedgerEntrySet, src: bytes, send_max: Optional[STAmount]
) -> list[tuple[bytes, bytes]]:
    """(currency, issuer) pairs the source can spend. A SendMax pins the
    spendable asset (reference: Pathfinder only considers the SendMax
    currency when present)."""
    if send_max is not None:
        if send_max.is_native:
            return [(CURRENCY_XRP, ACCOUNT_ZERO)]
        if send_max.issuer != src:
            return [(send_max.currency, send_max.issuer)]
        # SendMax issuer == source account: "any of my <currency>" —
        # every line the source holds in that currency is spendable
        # (reference: STAmount issuer-of-self convention in RippleCalc)
        out = [
            (line["currency"], line["peer"])
            for line in account_lines_of(les, src, send_max.currency)
            if line["balance"].signum() > 0 or line["peer_limit"].signum() > 0
        ]
        return out or [(send_max.currency, src)]
    assets: list[tuple[bytes, bytes]] = [(CURRENCY_XRP, ACCOUNT_ZERO)]
    for line in account_lines_of(les, src):
        if line["balance"].signum() > 0 or line["peer_limit"].signum() > 0:
            assets.append((line["currency"], line["peer"]))
    return assets


def _candidate_paths(
    les: LedgerEntrySet,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: Optional[STAmount],
    books: OrderBookDB,
) -> list[list[PathElement]]:
    """Pattern families (reference: Pathfinder's mPathTable):
    same-currency: [], [G], [G1,G2]; cross-currency: [book],
    [XRP-bridge], each with implied issuer delivery."""
    c_d = dst_amount.currency
    i_d = ACCOUNT_ZERO if dst_amount.is_native else dst_amount.issuer
    # delivery issuers dst accepts: an IOU amount whose issuer is the
    # destination itself means "any issuer dst trusts" (reference:
    # STAmount issuer-of-self convention in Pathfinder/RippleCalc)
    if dst_amount.is_native:
        dst_issuers = {ACCOUNT_ZERO}
    elif i_d == dst:
        dst_issuers = {
            l["peer"] for l in account_lines_of(les, dst, c_d)
        } | {dst}
    else:
        dst_issuers = {i_d}
    candidates: list[list[PathElement]] = []

    src_assets = _source_assets(les, src, send_max)
    same_currency = any(c == c_d for c, _ in src_assets)

    if same_currency and c_d != CURRENCY_XRP:
        # default path (src → [issuer] → dst) is the empty path
        candidates.append([])
        # one-gateway paths: src --line--> G --line--> dst
        src_peers = {
            l["peer"]
            for l in account_lines_of(les, src, c_d)
            if l["balance"].signum() > 0 or l["peer_limit"].signum() > 0
        }
        dst_peers = {l["peer"] for l in account_lines_of(les, dst, c_d)}
        for g in sorted(src_peers & dst_peers)[:MAX_GATEWAY_FANOUT]:
            if g not in (src, dst, i_d):
                candidates.append([PathElement(account=g)])
        # two-gateway chains: src → G1 → G2 → dst, and connector chains
        # src → G1 → M → G2 → dst (a market maker holding lines at both
        # gateways — the reference's longer mPathTable patterns)
        for g1 in sorted(src_peers)[:MAX_GATEWAY_FANOUT]:
            if g1 in (src, dst):
                continue
            for l2 in account_lines_of(les, g1, c_d)[:MAX_GATEWAY_FANOUT]:
                g2 = l2["peer"]
                if g2 in (src, dst, g1):
                    continue
                if g2 in dst_peers:
                    candidates.append(
                        [PathElement(account=g1), PathElement(account=g2)]
                    )
                    continue
                for l3 in account_lines_of(les, g2, c_d)[:MAX_GATEWAY_FANOUT]:
                    g3 = l3["peer"]
                    if g3 in (src, dst, g1, g2):
                        continue
                    if g3 in dst_peers:
                        candidates.append(
                            [
                                PathElement(account=g1),
                                PathElement(account=g2),
                                PathElement(account=g3),
                            ]
                        )

    # cross-currency: convert some source asset through a book, then
    # (when the book's out-issuer is not directly acceptable) ripple the
    # proceeds through an account chain to one the destination trusts
    if c_d == CURRENCY_XRP:
        dst_line_peers: set[bytes] = set()
    elif i_d == dst:
        dst_line_peers = dst_issuers - {dst}  # computed above, same walk
    else:
        dst_line_peers = {l["peer"] for l in account_lines_of(les, dst, c_d)}
    for c_s, i_s in src_assets:
        if c_s == c_d and (c_s == CURRENCY_XRP or i_s == i_d):
            continue
        for b in books.books_taking(c_s, i_s):
            if b.out_currency != c_d:
                continue
            g = b.out_issuer
            if dst_amount.is_native:
                candidates.append([PathElement(currency=c_d, issuer=None)])
                continue
            if g in dst_issuers:
                candidates.append([PathElement(currency=c_d, issuer=g)])
                continue
            # book lands on issuer g the destination does not trust:
            # extend through a connector m holding lines at both ends
            # (reference: Pathfinder's book + account continuations)
            for l2 in account_lines_of(les, g, c_d)[:MAX_GATEWAY_FANOUT]:
                m = l2["peer"]
                if m in (src, dst, g):
                    continue
                if m in dst_issuers or m in dst_line_peers:
                    candidates.append([
                        PathElement(currency=c_d, issuer=g),
                        PathElement(account=g),
                        PathElement(account=m),
                    ])
        # XRP bridge: (c_s → XRP) then (XRP → c_d)
        if c_s != CURRENCY_XRP and c_d != CURRENCY_XRP:
            leg1 = any(
                b.out_currency == CURRENCY_XRP
                for b in books.books_taking(c_s, i_s)
            )
            leg2_issuers = {
                b.out_issuer
                for b in books.books_taking(CURRENCY_XRP, ACCOUNT_ZERO)
                if b.out_currency == c_d and b.out_issuer in dst_issuers
            }
            if leg1:
                for g in sorted(leg2_issuers):
                    candidates.append(
                        [
                            PathElement(currency=CURRENCY_XRP),
                            PathElement(currency=c_d, issuer=g),
                        ]
                    )

    # dedup, preserving order
    seen: set[tuple] = set()
    out = []
    for p in candidates:
        key = tuple(
            (e.account, e.currency, e.issuer) for e in p
        )
        if key not in seen:
            seen.add(key)
            out.append(p)
    return out


def find_paths(
    ledger,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: Optional[STAmount] = None,
    max_paths: int = 4,
    books: Optional[OrderBookDB] = None,
    include_partial: bool = False,
) -> list[dict]:
    """Liquidity-checked alternatives, best quality first:
    [{"paths": [path], "source_amount": STAmount, "delivered": STAmount}]
    (the shape `ripple_path_find` renders; reference:
    Pathfinder::findPaths + getJson). With include_partial, strands that
    deliver only part of the target are appended after the full
    alternatives (for build_path payment construction)."""
    les = LedgerEntrySet(ledger)
    if books is None:
        books = OrderBookDB.for_ledger(ledger)
    candidates = _candidate_paths(les, src, dst, dst_amount, send_max, books)

    if send_max is not None:
        # _source_assets resolves the issuer-of-self convention (SendMax
        # issuer == src means "any of my <currency>")
        probe_assets = _source_assets(les, src, send_max)
    else:
        probe_assets = None

    results = []
    partials = []
    for path in candidates:
        if probe_assets is not None:
            assets = probe_assets
        elif path and path[0].currency is not None:
            # book-first path: source asset inferred per-asset; probe all
            assets = _source_assets(les, src, None)
        else:
            assets = [(
                dst_amount.currency,
                ACCOUNT_ZERO if dst_amount.is_native else dst_amount.issuer,
            )]
        for a_c, a_i in assets:
            try:
                hops = plan_strand(src, dst, dst_amount, a_c, a_i, path)
            except PathError:
                continue
            sandbox = les.duplicate()
            budget = (
                STAmount.from_drops(2**62)
                if a_c == CURRENCY_XRP
                else STAmount.from_iou(a_c, a_i, 10**17, 60)
            )
            try:
                spent, delivered = execute_strand(
                    sandbox, src, hops, dst_amount, budget,
                    ledger.parent_close_time,
                )
            except PathError:
                continue
            if delivered < dst_amount:
                if delivered.signum() > 0:
                    # single strand covers only part of the target: not
                    # an RPC "alternative", but a payment combining
                    # several such strands may still succeed — kept for
                    # build_path_set (reference: Pathfinder keeps
                    # partial-liquidity paths for build_path payments)
                    partials.append({
                        "paths": [path],
                        "source_amount": spent,
                        "delivered": delivered,
                    })
                continue
            results.append(
                {"paths": [path], "source_amount": spent,
                 "delivered": delivered}
            )
            break

    def cost_key(r):
        """Exact-rational cost ordering (float rounding must never flip
        two near-equal alternatives — the reference compares exact
        STAmount rates)."""
        from fractions import Fraction

        a = r["source_amount"]
        if a.is_native:
            return Fraction(a.mantissa)
        return Fraction(a.mantissa) * Fraction(10) ** a.offset

    results.sort(key=cost_key)
    if include_partial:
        def quality_key(r):
            """Partials rank primarily by how much of the TARGET they
            cover (delivered is always in the dst denomination, so it is
            comparable across strands); delivered-per-spent breaks ties,
            with native spends scaled from drops to whole-STR units so
            an XRP-spending strand is not penalized 10^6x against an
            IOU-spending one (spend-asset values remain a heuristic —
            there is no universal exchange rate to rank with)."""
            from fractions import Fraction

            d, s = r["delivered"], r["source_amount"]
            dv = Fraction(d.mantissa) * Fraction(10) ** (0 if d.is_native else d.offset)
            sv = Fraction(s.mantissa) * Fraction(10) ** (-6 if s.is_native else s.offset)
            return (-dv, -(dv / sv) if sv else Fraction(0))

        partials.sort(key=quality_key)
        # one entry per path SHAPE (the same path probed with several
        # source assets yields duplicates; keep its best-quality probe)
        seen_shapes: set[tuple] = set()
        uniq = []
        for r in partials:
            key = tuple(
                (e.account, e.currency, e.issuer)
                for p in r["paths"]
                for e in p
            )
            if key not in seen_shapes:
                seen_shapes.add(key)
                uniq.append(r)
        head = results[:max_paths]
        return head + uniq[: max_paths - len(head)]
    return results[:max_paths]


def build_path_set(
    ledger,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: Optional[STAmount] = None,
    max_paths: int = 4,
) -> list[list[PathElement]]:
    """Paths to ATTACH to a payment (the JS client's build_path /
    reference Pathfinder usage from TransactionSign): full-liquidity
    alternatives first, then partial-liquidity strands the flow engine
    can combine with the default path to split a delivery no single
    strand covers. The empty default path is excluded — the Payment
    transactor always adds it (unless tfNoDirectRipple)."""
    alts = find_paths(
        ledger, src, dst, dst_amount, send_max=send_max,
        max_paths=max_paths, include_partial=True,
    )
    out: list[list[PathElement]] = []
    seen: set[tuple] = set()
    for alt in alts:
        for path in alt["paths"]:
            if not path:
                continue  # default path: transactor's job
            key = tuple((e.account, e.currency, e.issuer) for e in path)
            if key not in seen:
                seen.add(key)
                out.append(path)
    return out[:max_paths]
