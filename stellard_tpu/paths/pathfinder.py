"""Pathfinder: search for viable payment paths.

Reference: src/ripple_app/paths/Pathfinder.cpp (937 LoC). Search is
driven by the cost-ranked path-class table (`initPathTable`,
Pathfinder.cpp:872): every payment classifies into one of five types by
its source/destination currencies, and each type owns an ordered list
of (cost, shape) entries where a shape is a node-class string — s =
source, a = account hop, b = any order book, x = book to XRP, f = book
into the destination currency, d = destination. Shapes whose cost
exceeds the caller's search level are skipped (PATH_SEARCH knobs,
ripple_core/functional/Config.h:62-65), which is how the reference
scales search effort under load. Shape expansion mirrors
`Pathfinder::addLink` (Pathfinder.cpp:631+): account hops are gated on
line credit / authorization / no-ripple pairs and ranked by the
`getPathsOut` utility count with the 10-per-node (50 from the source)
candidate caps; book hops never revisit an (currency, issuer) node and
append the book issuer's account node.

Candidates found by the shape search are then validated by actually
trial-executing each strand on a sandboxed LedgerEntrySet — the flow
engine is its own liquidity oracle, which replaces the reference's
separate PathState liquidity estimation.
"""

from __future__ import annotations

from typing import Optional

from ..engine.flags import (
    lsfHighAuth,
    lsfHighNoRipple,
    lsfLowAuth,
    lsfLowNoRipple,
    lsfRequireAuth,
)
from ..protocol.formats import LedgerEntryType
from ..protocol.sfields import (
    sfBalance,
    sfFlags,
    sfHighLimit,
    sfLedgerEntryType,
    sfLowLimit,
)
from ..protocol.stamount import ACCOUNT_ZERO, STAmount
from ..protocol.stobject import PathElement
from ..state import indexes
from ..state.entryset import LedgerEntrySet
from .flow import CURRENCY_XRP, PathError, execute_strand, plan_strand
from .orderbook import OrderBookDB

__all__ = [
    "find_paths",
    "build_path_set",
    "account_lines_of",
    "PATH_SEARCH_DEFAULT",
    "PATH_SEARCH_FAST",
    "PATH_SEARCH_MAX",
]

# Search-level knobs (reference: Config.h:62-65 DEFAULT_PATH_SEARCH*).
PATH_SEARCH_FAST = 2
PATH_SEARCH_DEFAULT = 7
PATH_SEARCH_MAX = 10

# The path-class table (reference: Pathfinder::initPathTable,
# Pathfinder.cpp:872-934). Keys are payment types (classified from the
# source asset and destination amount); rows are (cost, shape).
_PATH_TABLE: dict[str, list[tuple[int, str]]] = {
    "xrp_to_xrp": [],  # default path only
    "xrp_to_iou": [
        (1, "sfd"), (3, "sfad"), (5, "sfaad"), (6, "sbfd"),
        (8, "sbafd"), (9, "sbfad"), (10, "sbafad"),
    ],
    "iou_to_xrp": [
        (1, "sxd"), (2, "saxd"), (6, "saaxd"), (7, "sbxd"),
        (8, "sabxd"), (9, "sabaxd"),
    ],
    "iou_to_same": [
        (1, "sad"), (1, "sfd"), (4, "safd"), (4, "sfad"), (5, "saad"),
        (5, "sxfd"), (6, "sxfad"), (6, "safad"), (6, "saxfd"),
        (6, "saxfad"), (7, "saaad"),
    ],
    "iou_to_iou": [
        (1, "sfad"), (1, "safd"), (3, "safad"), (4, "sxfd"),
        (5, "saxfd"), (5, "sxfad"), (6, "saxfad"), (7, "saafd"),
        (8, "saafad"), (9, "safaad"),
    ],
}

# Candidate caps per expansion node (reference: Pathfinder::addLink
# count clamp — 10 per interior node, 50 fanning out of the source).
_MAX_CANDIDATES = 10
_MAX_CANDIDATES_SOURCE = 50
# Global safety bounds: the trial-execution liquidity check costs a
# sandboxed strand run per candidate, so the complete set and the live
# partial frontier are both capped (the reference bounds its cheaper
# PathState estimation with filterPaths instead).
_MAX_COMPLETE = 128
_MAX_PARTIALS = 512


def account_lines_of(
    les: LedgerEntrySet, account_id: bytes, currency: Optional[bytes] = None
) -> list[dict]:
    """[{peer, currency, balance(signed, our perspective), our_limit,
    peer_limit, no_ripple(peer side)}] from the owner directory."""
    out = []
    for entry_idx in les.dir_entries(indexes.owner_dir_index(account_id)):
        sle = les.peek(entry_idx)
        if sle is None or sle.get(sfLedgerEntryType) != int(
            LedgerEntryType.ltRIPPLE_STATE
        ):
            continue
        low = sle[sfLowLimit]
        high = sle[sfHighLimit]
        if currency is not None and low.currency != currency:
            continue
        is_low = low.issuer == account_id
        peer = high.issuer if is_low else low.issuer
        balance = sle[sfBalance]
        bal = balance if is_low else -balance
        flags = sle.get(sfFlags, 0)
        peer_no_ripple = bool(
            flags & (lsfHighNoRipple if is_low else lsfLowNoRipple)
        )
        our_no_ripple = bool(
            flags & (lsfLowNoRipple if is_low else lsfHighNoRipple)
        )
        # Has the enumerated account authorized the peer to hold its
        # issuances? (relevant when the enumerated account is an
        # lsfRequireAuth issuer; reference: RippleState::getAuth via the
        # addLink credit gate)
        auth_by_us = bool(flags & (lsfLowAuth if is_low else lsfHighAuth))
        out.append(
            {
                "peer": peer,
                "currency": low.currency,
                "balance": bal,
                "our_limit": low if is_low else high,
                "peer_limit": high if is_low else low,
                "peer_no_ripple": peer_no_ripple,
                "our_no_ripple": our_no_ripple,
                "auth_by_us": auth_by_us,
            }
        )
    return out


def _source_assets(
    les: LedgerEntrySet, src: bytes, send_max: Optional[STAmount]
) -> list[tuple[bytes, bytes]]:
    """(currency, issuer) pairs the source can spend. A SendMax pins the
    spendable asset (reference: Pathfinder only considers the SendMax
    currency when present)."""
    if send_max is not None:
        if send_max.is_native:
            return [(CURRENCY_XRP, ACCOUNT_ZERO)]
        if send_max.issuer != src:
            return [(send_max.currency, send_max.issuer)]
        # SendMax issuer == source account: "any of my <currency>" —
        # every line the source holds in that currency is spendable
        # (reference: STAmount issuer-of-self convention in RippleCalc)
        out = [
            (line["currency"], line["peer"])
            for line in account_lines_of(les, src, send_max.currency)
            if line["balance"].signum() > 0 or line["peer_limit"].signum() > 0
        ]
        return out or [(send_max.currency, src)]
    assets: list[tuple[bytes, bytes]] = [(CURRENCY_XRP, ACCOUNT_ZERO)]
    for line in account_lines_of(les, src):
        if line["balance"].signum() > 0 or line["peer_limit"].signum() > 0:
            assets.append((line["currency"], line["peer"]))
    return assets


class _Partial:
    """One incomplete path during shape expansion: the elements emitted
    so far plus the node the path currently ends on (reference: the
    STPath + pathEnd pair addLink works from)."""

    __slots__ = (
        "elems", "end_acct", "end_cur", "end_iss", "no_ripple_in", "seen",
    )

    def __init__(self, elems, end_acct, end_cur, end_iss, no_ripple_in,
                 seen):
        self.elems: tuple[PathElement, ...] = elems
        self.end_acct = end_acct
        self.end_cur = end_cur
        self.end_iss = end_iss
        # did the account we're standing on set NoRipple on the link we
        # entered through? (reference: Pathfinder::isNoRippleOut pairs
        # this with the out-link's flag)
        self.no_ripple_in = no_ripple_in
        # (account, currency, issuer) triples of visited path nodes
        # (reference: STPath::hasSeen) — the same ACCOUNT may be
        # revisited in a different currency, which is what lets a path
        # continue THROUGH the destination in the wrong currency and
        # still complete later
        self.seen: frozenset = seen


class _Search:
    """Shape-table expansion over one ledger (reference:
    Pathfinder::getPaths / addLink / getPathsOut). One instance per
    find_paths call; caches line walks, paths-out counts, and expanded
    shape prefixes (the reference's mPaths memo) across shapes."""

    def __init__(self, les, books, src, dst, dst_amount):
        self.les = les
        self.books = books
        self.src = src
        self.dst = dst
        self.c_d = dst_amount.currency
        self.dst_native = dst_amount.is_native
        self._lines: dict[bytes, list[dict]] = {}
        self._po: dict[tuple[bytes, bytes], int] = {}
        self._auth: dict[bytes, bool] = {}
        self._prefix: dict[tuple, list[_Partial]] = {}
        # path key -> (elements, source asset) — uniqued completes
        # (reference: mCompletePaths.addUniquePath)
        self.complete: dict[tuple, tuple[list[PathElement], tuple]] = {}

    # -- caches ---------------------------------------------------------

    def lines_of(self, acct: bytes, currency: bytes) -> list[dict]:
        all_lines = self._lines.get(acct)
        if all_lines is None:
            all_lines = account_lines_of(self.les, acct)
            self._lines[acct] = all_lines
        return [l for l in all_lines if l["currency"] == currency]

    def _requires_auth(self, acct: bytes) -> bool:
        cached = self._auth.get(acct)
        if cached is None:
            sle = self.les.peek(indexes.account_root_index(acct))
            cached = bool(
                sle is not None and sle.get(sfFlags, 0) & lsfRequireAuth
            )
            self._auth[acct] = cached
        return cached

    @staticmethod
    def _has_credit(line: dict, require_auth: bool) -> bool:
        """Can value ripple from the enumerated account to this peer?
        (reference: addLink's 'path has no credit' gate)"""
        bal = line["balance"]
        if bal.signum() > 0:
            return True
        peer_limit = line["peer_limit"]
        if peer_limit.signum() <= 0:
            return False
        if (-bal) >= peer_limit:
            return False
        if require_auth and not line["auth_by_us"]:
            return False
        return True

    def paths_out(self, currency: bytes, acct: bytes) -> int:
        """Utility rank for candidate account hops (reference:
        Pathfinder::getPathsOut — viable out-line count, destination
        lines in the destination currency weighted 10000)."""
        key = (currency, acct)
        cached = self._po.get(key)
        if cached is not None:
            return cached
        if self.les.peek(indexes.account_root_index(acct)) is None:
            self._po[key] = 0
            return 0
        require_auth = self._requires_auth(acct)
        count = 0
        for line in self.lines_of(acct, currency):
            if not self._has_credit(line, require_auth):
                continue
            if currency == self.c_d and line["peer"] == self.dst:
                count += 10000
            elif line["peer_no_ripple"]:
                pass  # not a useful path out
            else:
                count += 1
        self._po[key] = count
        return count

    # -- completion -----------------------------------------------------

    def _add_complete(self, elems: tuple, asset: tuple) -> None:
        if len(self.complete) >= _MAX_COMPLETE:
            return
        key = (
            tuple((e.account, e.currency, e.issuer) for e in elems),
            asset,
        )
        if key not in self.complete and elems:
            self.complete[key] = (list(elems), asset)

    # -- expansion steps ------------------------------------------------

    def _add_accounts(
        self, partials: list[_Partial], asset: tuple, last: bool
    ) -> list[_Partial]:
        out: list[_Partial] = []
        for p in partials:
            if p.end_cur == CURRENCY_XRP:
                # an account step on XRP can only be the destination
                # (reference: addLink afADD_ACCOUNTS bOnSTR branch)
                if self.dst_native and p.elems:
                    self._add_complete(p.elems, asset)
                continue
            require_auth = self._requires_auth(p.end_acct)
            cands: list[tuple[int, bytes, dict]] = []
            for line in self.lines_of(p.end_acct, p.end_cur):
                peer = line["peer"]
                if (peer, p.end_cur, peer) in p.seen:
                    continue
                if not self._has_credit(line, require_auth):
                    continue
                if p.no_ripple_in and line["our_no_ripple"]:
                    continue  # can't ripple through a NoRipple pair
                if peer == self.dst:
                    if p.end_cur == self.c_d:
                        if p.elems:
                            self._add_complete(p.elems, asset)
                    elif not last:
                        # destination in the wrong currency: always
                        # worth continuing through (reference: the
                        # 100000-priority candidate)
                        cands.append((100000, peer, line))
                elif peer == self.src:
                    continue  # going back to the source is bad
                elif not last:
                    rank = self.paths_out(p.end_cur, peer)
                    if rank:
                        cands.append((rank, peer, line))
            if last or not cands:
                continue
            cands.sort(key=lambda c: (-c[0], c[1]))
            cap = (
                _MAX_CANDIDATES_SOURCE
                if p.end_acct == self.src
                else _MAX_CANDIDATES
            )
            for _, peer, line in cands[:cap]:
                out.append(
                    _Partial(
                        p.elems + (PathElement(account=peer),),
                        peer,
                        p.end_cur,
                        peer,
                        line["peer_no_ripple"],
                        p.seen | {(peer, p.end_cur, peer)},
                    )
                )
        return out

    def _add_books(
        self,
        partials: list[_Partial],
        asset: tuple,
        to_xrp: bool,
        dest_only: bool,
    ) -> list[_Partial]:
        out: list[_Partial] = []
        for p in partials:
            for b in sorted(
                self.books.books_taking(p.end_cur, p.end_iss),
                key=lambda b: (b.out_currency, b.out_issuer),
            ):
                if to_xrp and b.out_currency != CURRENCY_XRP:
                    continue
                if dest_only and b.out_currency != self.c_d:
                    continue
                if (b.out_currency, b.out_issuer) == asset:
                    continue  # matchesOrigin: don't convert back
                if b.out_currency == CURRENCY_XRP:
                    xrp_key = (ACCOUNT_ZERO, CURRENCY_XRP, ACCOUNT_ZERO)
                    if xrp_key in p.seen:
                        continue
                    elems = p.elems + (PathElement(currency=CURRENCY_XRP),)
                    if self.dst_native:
                        self._add_complete(elems, asset)
                    else:
                        out.append(
                            _Partial(
                                elems, ACCOUNT_ZERO, CURRENCY_XRP,
                                ACCOUNT_ZERO, False, p.seen | {xrp_key},
                            )
                        )
                    continue
                iss_key = (b.out_issuer, b.out_currency, b.out_issuer)
                if iss_key in p.seen:
                    continue  # already seen this issuer node
                book_el = PathElement(
                    currency=b.out_currency, issuer=b.out_issuer
                )
                if b.out_issuer == self.dst and b.out_currency == self.c_d:
                    self._add_complete(p.elems + (book_el,), asset)
                    continue
                # append the book and its out-issuer's account node
                # (reference: addLink's assembleAdd of the issuer)
                out.append(
                    _Partial(
                        p.elems
                        + (book_el, PathElement(account=b.out_issuer)),
                        b.out_issuer,
                        b.out_currency,
                        b.out_issuer,
                        False,
                        p.seen | {iss_key},
                    )
                )
        return out

    # -- shape driver ---------------------------------------------------

    def run_shape(self, shape: str, asset: tuple) -> None:
        """Expand one shape string left to right, memoizing prefixes so
        'saxfd' reuses the 'saxf' work 'saxfad' did (reference: the
        mPaths map in Pathfinder::getPaths)."""
        c_s, i_s = asset
        for end in range(1, len(shape) + 1):
            prefix = shape[:end]
            memo_key = (asset, prefix)
            if memo_key in self._prefix:
                continue
            cls = prefix[-1]
            if cls == "s":
                # the source node: path expansion starts on the source
                # account for native/self-issued assets, else on the
                # issuer (reference: mSource construction,
                # Pathfinder.cpp:120-125)
                if c_s == CURRENCY_XRP or i_s == self.src:
                    start_acct = self.src
                else:
                    start_acct = i_s
                # seed the seen-set with the start node's triple so the
                # search never loops back through the start issuer in
                # the SAME currency; the currency-aware triple still
                # lets it reappear as a book's out-issuer in another
                # currency (reference: STPath::hasSeen semantics)
                partials = [
                    _Partial(
                        (), start_acct, c_s,
                        i_s if c_s != CURRENCY_XRP else ACCOUNT_ZERO,
                        False, frozenset({(start_acct, c_s, start_acct)}),
                    )
                ]
            else:
                parents = self._prefix[(asset, prefix[:-1])]
                if cls == "a":
                    partials = self._add_accounts(parents, asset, False)
                elif cls == "d":
                    partials = self._add_accounts(parents, asset, True)
                elif cls == "b":
                    partials = self._add_books(parents, asset, False, False)
                elif cls == "x":
                    partials = self._add_books(parents, asset, True, False)
                elif cls == "f":
                    partials = self._add_books(parents, asset, False, True)
                else:
                    raise ValueError(f"unknown path node class {cls!r}")
            # frontier bound: a hostile trust-line graph must not make
            # one RPC call expand without limit
            self._prefix[memo_key] = partials[:_MAX_PARTIALS]


def _payment_type(c_s: bytes, c_d: bytes) -> str:
    if c_s == CURRENCY_XRP and c_d == CURRENCY_XRP:
        return "xrp_to_xrp"
    if c_s == CURRENCY_XRP:
        return "xrp_to_iou"
    if c_d == CURRENCY_XRP:
        return "iou_to_xrp"
    if c_s == c_d:
        return "iou_to_same"
    return "iou_to_iou"


def _candidate_paths(
    les: LedgerEntrySet,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: Optional[STAmount],
    books: OrderBookDB,
    level: int = PATH_SEARCH_DEFAULT,
) -> list[tuple[list[PathElement], tuple[bytes, bytes]]]:
    """(path, source asset) candidates from the cost-ranked shape table
    (reference: Pathfinder::findPaths' mPathTable walk gated on
    iLevel)."""
    c_d = dst_amount.currency
    search = _Search(les, books, src, dst, dst_amount)
    candidates: list[tuple[list[PathElement], tuple[bytes, bytes]]] = []
    seen: set[tuple] = set()

    # Shape search starts from the SOURCE ACCOUNT with the issuer-of-
    # self placeholder unless a SendMax pins a foreign issuer
    # (reference: mSource construction, Pathfinder.cpp:120-125) — the
    # 'a' step's line walk is what discovers explicit gateway hops.
    if send_max is None:
        search_assets = [(CURRENCY_XRP, ACCOUNT_ZERO)] + sorted(
            {
                (line["currency"], src)
                for line in account_lines_of(les, src)
                if line["balance"].signum() > 0
                or line["peer_limit"].signum() > 0
            }
        )
    elif send_max.is_native:
        search_assets = [(CURRENCY_XRP, ACCOUNT_ZERO)]
    else:
        search_assets = [(send_max.currency, send_max.issuer)]

    for c_s, i_s in search_assets:
        ptype = _payment_type(c_s, c_d)
        for cost, shape in _PATH_TABLE[ptype]:
            if cost > level:
                continue
            search.run_shape(shape, (c_s, i_s))

    # the default path (src → [issuer] → dst) rides along as the empty
    # candidate, probed per concrete holding so the issuer ripple is
    # exact (reference: RippleCalc always tries default paths)
    for c_s, i_s in _source_assets(les, src, send_max):
        if _payment_type(c_s, c_d) == "iou_to_same":
            key = ((), (c_s, i_s))
            if key not in seen:
                seen.add(key)
                candidates.append(([], (c_s, i_s)))

    for elems, asset in search.complete.values():
        key = (
            tuple((e.account, e.currency, e.issuer) for e in elems),
            asset,
        )
        if key not in seen:
            seen.add(key)
            candidates.append((elems, asset))
    return candidates


def find_paths(
    ledger,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: Optional[STAmount] = None,
    max_paths: int = 4,
    books: Optional[OrderBookDB] = None,
    include_partial: bool = False,
    level: int = PATH_SEARCH_DEFAULT,
    pre_rank=None,
) -> list[dict]:
    """Liquidity-checked alternatives, best quality first:
    [{"paths": [path], "source_amount": STAmount, "delivered": STAmount}]
    (the shape `ripple_path_find` renders; reference:
    Pathfinder::findPaths + getJson). With include_partial, strands that
    deliver only part of the target are appended after the full
    alternatives (for build_path payment construction). `level` bounds
    which shape-table rows are searched (reference: iLevel vs
    CostedPath cost; PATH_SEARCH_FAST for quick answers under load,
    PATH_SEARCH_DEFAULT normally)."""
    les = LedgerEntrySet(ledger)
    # source account must exist; a missing destination only works for a
    # funding-size native delivery (reference: findPaths' sleSrc/sleDest
    # guards, Pathfinder.cpp:149-155)
    if les.peek(indexes.account_root_index(src)) is None:
        return []
    if les.peek(indexes.account_root_index(dst)) is None and not (
        dst_amount.is_native
    ):
        return []
    if books is None:
        books = OrderBookDB.for_ledger(ledger)
    level = max(1, min(int(level), PATH_SEARCH_MAX))
    candidates = _candidate_paths(
        les, src, dst, dst_amount, send_max, books, level=level
    )
    # liquidity-plane hook (paths/plane.py): an estimated-quality
    # pre-pass over the candidate set BEFORE the expensive per-candidate
    # trial executions. Pure reordering never changes output (results
    # re-sort by exact cost below); pruning is the hook's contract to
    # apply only above its floor.
    if pre_rank is not None and candidates:
        candidates = pre_rank(les, candidates)

    results = []
    partials = []
    for path, (a_c, a_i) in candidates:
        try:
            hops = plan_strand(src, dst, dst_amount, a_c, a_i, path)
        except PathError:
            continue
        sandbox = les.duplicate()
        budget = (
            STAmount.from_drops(2**62)
            if a_c == CURRENCY_XRP
            else STAmount.from_iou(a_c, a_i, 10**17, 60)
        )
        try:
            spent, delivered = execute_strand(
                sandbox, src, hops, dst_amount, budget,
                ledger.parent_close_time,
            )
        except PathError:
            continue
        if delivered < dst_amount:
            if delivered.signum() > 0:
                # single strand covers only part of the target: not
                # an RPC "alternative", but a payment combining
                # several such strands may still succeed — kept for
                # build_path_set (reference: Pathfinder keeps
                # partial-liquidity paths for build_path payments)
                partials.append({
                    "paths": [path],
                    "source_amount": spent,
                    "delivered": delivered,
                })
            continue
        results.append(
            {"paths": [path], "source_amount": spent,
             "delivered": delivered, "_currency": a_c}
        )

    def cost_key(r):
        """Exact-rational cost ordering (float rounding must never flip
        two near-equal alternatives — the reference compares exact
        STAmount rates)."""
        from fractions import Fraction

        a = r["source_amount"]
        if a.is_native:
            return Fraction(a.mantissa)
        return Fraction(a.mantissa) * Fraction(10) ** a.offset

    results.sort(key=cost_key)
    # one alternative per source currency, carrying the path SET
    # (reference: RipplePathFind runs findPaths once per source currency
    # and renders one alternative with up to max_paths paths_computed);
    # first-in-cost-order is the alternative's headline source_amount.
    # The DEFAULT path is never rendered (the payment engine always tries
    # it unless tfNoRippleDirect — Payment.do_apply inserts it; reference
    # Pathfinder drops bDefaultPath from paths_computed) but it still
    # anchors the alternative's existence and source_amount quote.
    by_currency: dict[bytes, dict] = {}
    for r in results:
        cur = r.pop("_currency")
        r["paths"] = [p for p in r["paths"] if p]
        g = by_currency.get(cur)
        if g is None:
            by_currency[cur] = r
        elif len(g["paths"]) < max_paths:
            g["paths"].extend(
                p for p in r["paths"] if p not in g["paths"]
            )
    results = list(by_currency.values())
    if include_partial:
        def quality_key(r):
            """Partials rank primarily by how much of the TARGET they
            cover (delivered is always in the dst denomination, so it is
            comparable across strands); delivered-per-spent breaks ties,
            with native spends scaled from drops to whole-STR units so
            an XRP-spending strand is not penalized 10^6x against an
            IOU-spending one (spend-asset values remain a heuristic —
            there is no universal exchange rate to rank with)."""
            from fractions import Fraction

            d, s = r["delivered"], r["source_amount"]
            dv = Fraction(d.mantissa) * Fraction(10) ** (0 if d.is_native else d.offset)
            sv = Fraction(s.mantissa) * Fraction(10) ** (-6 if s.is_native else s.offset)
            return (-dv, -(dv / sv) if sv else Fraction(0))

        partials.sort(key=quality_key)
        # one entry per path SHAPE (the same path probed with several
        # source assets yields duplicates; keep its best-quality probe)
        seen_shapes: set[tuple] = set()
        uniq = []
        for r in partials:
            key = tuple(
                (e.account, e.currency, e.issuer)
                for p in r["paths"]
                for e in p
            )
            if key not in seen_shapes:
                seen_shapes.add(key)
                uniq.append(r)
        head = results[:max_paths]
        return head + uniq[: max_paths - len(head)]
    return results[:max_paths]


def build_path_set(
    ledger,
    src: bytes,
    dst: bytes,
    dst_amount: STAmount,
    send_max: Optional[STAmount] = None,
    max_paths: int = 4,
    level: int = PATH_SEARCH_DEFAULT,
) -> list[list[PathElement]]:
    """Paths to ATTACH to a payment (the JS client's build_path /
    reference Pathfinder usage from TransactionSign): full-liquidity
    alternatives first, then partial-liquidity strands the flow engine
    can combine with the default path to split a delivery no single
    strand covers. The empty default path is excluded — the Payment
    transactor always adds it (unless tfNoDirectRipple)."""
    alts = find_paths(
        ledger, src, dst, dst_amount, send_max=send_max,
        max_paths=max_paths, include_partial=True, level=level,
    )
    out: list[list[PathElement]] = []
    seen: set[tuple] = set()
    for alt in alts:
        for path in alt["paths"]:
            if not path:
                continue  # default path: transactor's job
            key = tuple((e.account, e.currency, e.issuer) for e in path)
            if key not in seen:
                seen.add(key)
                out.append(path)
    return out[:max_paths]
