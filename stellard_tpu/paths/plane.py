"""PathPlane: the liquidity read plane (ISSUE 17 tentpole).

One object owns the three legs that turn `paths/` from an on-demand
library into production serving:

* the incremental per-close book index (`LiveBookIndex`) — advanced
  once per validated close from the close's own write set, shared by
  the subscription publisher and the RPC door;
* per-subscription staleness + bounded per-close update budget — the
  sharded fanout re-ranks the stalest subscriptions first and SHEDS
  (rather than queues) the rest, so a path-spam client cannot stall
  the close (SEDA stance; charged through the overlay resource plane);
* the routed device evaluator (`crypto.backend.PathQualityEvaluator`)
  — oversized candidate sets are flattened to Q16.16 rate matrices and
  pre-ranked on the measured-cost host/1-chip/N-chip arms before the
  expensive trial executions.

Everything is observable under `paths.*` (doc/observability.md) via
``get_json``.
"""

from __future__ import annotations

import threading
from typing import Optional

from .orderbook import LiveBookIndex, OrderBookDB

__all__ = ["PathPlane"]

# keep this floor above every unit-test-sized candidate set: pre-rank
# pruning must be a no-op until a search is genuinely oversized, so the
# device plane can never change small-search results
DEFAULT_PRUNE_FLOOR = 64
DEFAULT_PRUNE_KEEP = 32
DEFAULT_UPDATE_BUDGET = 256


class PathPlane:
    def __init__(
        self,
        *,
        incremental: bool = True,
        evaluator=None,
        device_prune: bool = True,
        prune_floor: int = DEFAULT_PRUNE_FLOOR,
        prune_keep: int = DEFAULT_PRUNE_KEEP,
        max_updates_per_close: int = DEFAULT_UPDATE_BUDGET,
        resources=None,
        update_charge=None,
    ):
        self.index = LiveBookIndex(incremental=incremental)
        self.evaluator = evaluator
        self.device_prune = bool(device_prune)
        self.prune_floor = max(1, int(prune_floor))
        self.prune_keep = max(1, int(prune_keep))
        self.max_updates_per_close = max(1, int(max_updates_per_close))
        self.resources = resources
        if update_charge is None:
            from ..overlay.resource import FEE_PATH_FIND_UPDATE

            update_charge = FEE_PATH_FIND_UPDATE
        self.update_charge = update_charge
        self._lock = threading.Lock()
        # (sub id, request id) -> last seq this subscription was ranked at
        self._last_ranked: dict[tuple, int] = {}
        # staleness-in-ledgers histogram (small ints; p99 from the dict)
        self._stale_hist: dict[int, int] = {}
        self._budget_left = self.max_updates_per_close
        # `paths.*` counters
        self.closes = 0
        self.reranked = 0
        self.shed_budget = 0
        self.shed_throttled = 0
        self.pruned_candidates = 0
        self.prune_batches = 0
        self.staleness_max = 0

    # -- book index -------------------------------------------------------

    def note_close(self, ledger) -> None:
        """Per-validated-close hook (ops.on_ledger_closed): advance the
        incremental index so continuity never breaks between closes."""
        self.index.advance(ledger)

    def books_for(self, ledger) -> OrderBookDB:
        return self.index.advance(ledger)

    def books_if_current(self, ledger) -> Optional[OrderBookDB]:
        return self.index.books_if_current(ledger)

    # -- device pre-ranking ----------------------------------------------

    def make_pre_rank(self, ledger):
        """A find_paths pre_rank hook, or None when device pruning is
        off. Reorders candidates best-estimated-first and prunes ONLY
        when the set exceeds the floor (small searches byte-unchanged —
        find_paths re-sorts trial results anyway, so pure reordering
        can never alter output). Empty (default) paths always survive:
        they anchor the alternative's source_amount quote."""
        ev = self.evaluator
        if ev is None or not self.device_prune:
            return None

        def pre_rank(les, candidates):
            if len(candidates) <= self.prune_floor:
                return candidates
            import numpy as np

            from .quality import build_rate_matrix

            rates = build_rate_matrix(ledger, candidates)
            composite = ev.evaluate(rates)
            order = np.argsort(composite, kind="stable")
            keep = set(int(i) for i in order[: self.prune_keep])
            keep |= {i for i, (path, _a) in enumerate(candidates)
                     if not path}
            out = [c for i, c in enumerate(candidates) if i in keep]
            with self._lock:
                self.prune_batches += 1
                self.pruned_candidates += len(candidates) - len(out)
            return out

        return pre_rank

    # -- per-close update scheduling --------------------------------------

    def begin_close(self, seq: int) -> None:
        with self._lock:
            self.closes += 1
            self._budget_left = self.max_updates_per_close

    def note_created(self, key: tuple, seq: int) -> None:
        """A subscription was created and answered at `seq`."""
        with self._lock:
            self._last_ranked.setdefault(key, seq)

    def order_keys(self, keys, seq: int):
        """Stalest-first update order (ties: stable by key) — under a
        budget, the subscriptions that waited longest go first, which
        bounds worst-case staleness at budget ratio × reranking period."""
        with self._lock:
            last = self._last_ranked
            return sorted(keys, key=lambda k: (last.get(k, -1), k))

    def claim_update(self, key: tuple, seq: int, endpoint=None) -> bool:
        """One subscription asks to re-rank at `seq`. False = shed this
        close (budget exhausted, or the endpoint is throttled by the
        resource plane); its staleness keeps growing until a later
        close picks it (stalest-first)."""
        rm = self.resources
        if rm is not None and endpoint is not None:
            if rm.is_throttled(endpoint):
                with self._lock:
                    self.shed_throttled += 1
                return False
        with self._lock:
            if self._budget_left <= 0:
                self.shed_budget += 1
                return False
            self._budget_left -= 1
        if rm is not None and endpoint is not None:
            rm.charge(endpoint, self.update_charge)
        return True

    def note_ranked(self, key: tuple, seq: int) -> None:
        with self._lock:
            prev = self._last_ranked.get(key)
            if prev is not None:
                stale = max(0, seq - prev)
                self._stale_hist[stale] = self._stale_hist.get(stale, 0) + 1
                if stale > self.staleness_max:
                    self.staleness_max = stale
            self._last_ranked[key] = seq
            self.reranked += 1

    def sync_live(self, keys) -> None:
        """Drop staleness state for closed subscriptions (the publisher
        passes the live key set each close)."""
        live = set(keys)
        with self._lock:
            for k in [k for k in self._last_ranked if k not in live]:
                del self._last_ranked[k]

    # -- observability ----------------------------------------------------

    def staleness_quantile(self, q: float) -> int:
        with self._lock:
            total = sum(self._stale_hist.values())
            if not total:
                return 0
            want = q * total
            seen = 0
            for stale in sorted(self._stale_hist):
                seen += self._stale_hist[stale]
                if seen >= want:
                    return stale
            return max(self._stale_hist)

    def get_json(self) -> dict:
        with self._lock:
            out = {
                "subs": len(self._last_ranked),
                "closes": self.closes,
                "reranked": self.reranked,
                "shed_budget": self.shed_budget,
                "shed_throttled": self.shed_throttled,
                "max_updates_per_close": self.max_updates_per_close,
                "staleness_max": self.staleness_max,
                "pruned_candidates": self.pruned_candidates,
                "prune_batches": self.prune_batches,
                "device_prune": self.device_prune,
                "prune_floor": self.prune_floor,
                "prune_keep": self.prune_keep,
            }
        out["staleness_p99"] = self.staleness_quantile(0.99)
        out["index"] = self.index.counters()
        if self.evaluator is not None:
            out["evaluator"] = self.evaluator.get_json()
        return out
