"""Flattening candidate paths into fixed-shape Q16.16 rate matrices.

The device plane (crypto.backend.PathQualityEvaluator) ranks thousands
of candidate paths per close by composing per-hop rates. This module is
the host-side flattener: each candidate becomes one row of MAX_HOPS
uint32 Q16.16 rates, padded with the identity rate —

* a book hop's rate is the book's best-tier directory quality (the
  64-bit STAmount rate encoded in the directory key — reference:
  Ledger::getQuality on the page getBookBase points at), i.e. what one
  unit out costs in units in at the tip of the book;
* an account hop's rate is the hop account's TransferRate (1e9 =
  parity), the fee a gateway charges for rippling through it.

Lower composite = cheaper path. This is a *ranking pre-pass* feeding
candidate pruning, not execution: exact liquidity still comes from the
flow engine's trial execution of whatever survives the cut.
"""

from __future__ import annotations

import numpy as np

from ..ops.pathq_jax import Q16_MAX, Q16_ONE
from ..protocol.sfields import sfTransferRate
from ..protocol.stamount import ACCOUNT_ZERO
from ..state import indexes
from .orderbook import CURRENCY_XRP, Book

__all__ = [
    "MAX_HOPS",
    "book_quality_q16",
    "build_rate_matrix",
    "rate_u64_to_q16",
]

MAX_HOPS = 8  # matches the pathfinder's deepest shape

_QUALITY_ONE_PPB = 1_000_000_000  # TransferRate parity


DROPS_PER_XRP = 1_000_000


def rate_u64_to_q16(q: int, num: int = 1, den: int = 1) -> int:
    """Decode a directory-key 64-bit rate ((offset+100)<<56 | mantissa,
    value = mantissa * 10^offset) into saturated Q16.16, rescaled by
    num/den (exact integer math — the rescale must not round before
    the final fixed-point truncation)."""
    if q == 0:
        return Q16_ONE
    exp = (q >> 56) - 100
    mantissa = q & ((1 << 56) - 1)
    if exp >= 0:
        v = (mantissa << 16) * (10 ** exp) * num // den
    else:
        v = (mantissa << 16) * num // ((10 ** (-exp)) * den)
    return max(1, min(Q16_MAX, v))


def book_quality_q16(ledger, book: Book) -> int:
    """Best-tier quality of `book` in Q16.16 from the first populated
    page of its directory — one ordered-successor probe, no offer
    reads. An empty book rates Q16_MAX (prune-worthy, not an error).

    Directory qualities price XRP in DROPS (an XRP/IOU book's raw rate
    is ~1e6, far past Q16.16's 65535 ceiling), so XRP legs rescale to
    natural units: rates stay O(1) and comparable across book kinds."""
    base = indexes.book_base(
        book.in_currency, book.in_issuer,
        book.out_currency, book.out_issuer,
    )
    end = indexes.quality_next(base)
    item = ledger.state_map.succ(base)
    if item is None or item.tag >= end:
        return Q16_MAX
    num = DROPS_PER_XRP if book.out_currency == CURRENCY_XRP else 1
    den = DROPS_PER_XRP if book.in_currency == CURRENCY_XRP else 1
    return rate_u64_to_q16(indexes.get_quality(item.tag), num, den)


def _transfer_q16(ledger, account: bytes, memo: dict) -> int:
    q = memo.get(account)
    if q is None:
        acct = ledger.read_entry(indexes.account_root_index(account))
        ppb = acct.get(sfTransferRate, 0) if acct is not None else 0
        ppb = ppb or _QUALITY_ONE_PPB
        q = max(1, min(Q16_MAX, (ppb << 16) // _QUALITY_ONE_PPB))
        memo[account] = q
    return q


def build_rate_matrix(ledger, candidates) -> np.ndarray:
    """[B, MAX_HOPS] uint32 rate matrix for `candidates`, the
    pathfinder's [(path_elems, (src_currency, src_issuer))] list. Hops
    beyond MAX_HOPS saturate the row (over-deep paths rank last rather
    than rank wrong); unused columns pad with the identity rate."""
    books_memo: dict[Book, int] = {}
    xfer_memo: dict[bytes, int] = {}
    rows = np.full((len(candidates), MAX_HOPS), Q16_ONE, dtype=np.uint32)
    for r, (path, (src_c, src_i)) in enumerate(candidates):
        cur_c, cur_i = src_c, src_i
        col = 0
        for el in path:
            if el.currency is not None:
                new_c = el.currency
                new_i = (
                    ACCOUNT_ZERO if new_c == CURRENCY_XRP
                    else (el.issuer if el.issuer is not None else cur_i)
                )
                book = Book(cur_c, cur_i, new_c, new_i)
                q = books_memo.get(book)
                if q is None:
                    q = book_quality_q16(ledger, book)
                    books_memo[book] = q
                cur_c, cur_i = new_c, new_i
            elif el.account is not None:
                q = _transfer_q16(ledger, el.account, xfer_memo)
            else:
                continue
            if col >= MAX_HOPS:
                rows[r, :] = Q16_MAX
                break
            rows[r, col] = q
            col += 1
    return rows
