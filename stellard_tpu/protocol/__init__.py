from .serializer import Serializer, BinaryParser, encode_vl_length
from .sfields import SField, STI, FIELDS, field_by_code, field_by_name
from .stamount import STAmount, currency_from_iso, iso_from_currency, CURRENCY_STR
from .stobject import STObject, STArray, STPathSet, PathElement
from .formats import (
    TX_FORMATS,
    TX_FORMATS_BY_NAME,
    LEDGER_FORMATS,
    LEDGER_FORMATS_BY_NAME,
    TxType,
    LedgerEntryType,
    SOE,
    validate_against,
)
from .ter import TER
from .keys import (
    KeyPair,
    verify_signature,
    signature_is_canonical,
    encode_account_id,
    decode_account_id,
    encode_seed,
    decode_seed,
    passphrase_to_seed,
)
