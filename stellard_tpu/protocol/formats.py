"""Transaction and ledger-entry format tables.

Protocol constants shared with the reference
(src/ripple_data/protocol/TxFormats.{h,cpp},
LedgerFormats.{h,cpp}): each format names its type code and the
required/optional field template (SOTemplate).
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import IntEnum

from . import sfields as sf
from .sfields import SField


class SOE(IntEnum):
    """Field presence classes (SerializedObjectTemplate.h:29-34)."""

    REQUIRED = 0
    OPTIONAL = 1
    DEFAULT = 2  # optional; if present must not hold the default value


class TxType(IntEnum):
    """Transaction type codes (reference TxFormats.h:33-53)."""

    ttPAYMENT = 0
    ttINFLATION = 1
    ttWALLET_ADD = 2
    ttACCOUNT_SET = 3
    ttACCOUNT_MERGE = 4
    ttREGULAR_KEY_SET = 5
    ttNICKNAME_SET = 6
    ttOFFER_CREATE = 7
    ttOFFER_CANCEL = 8
    ttCONTRACT = 9
    ttCONTRACT_REMOVE = 10
    ttTRUST_SET = 20
    ttAMENDMENT = 100
    ttFEE = 101


class LedgerEntryType(IntEnum):
    """Ledger entry type codes (reference LedgerFormats.h:38-72)."""

    ltACCOUNT_ROOT = ord("a")
    ltDIR_NODE = ord("d")
    ltGENERATOR_MAP = ord("g")
    ltNICKNAME = ord("n")
    ltRIPPLE_STATE = ord("r")
    ltOFFER = ord("o")
    ltCONTRACT = ord("c")
    ltLEDGER_HASHES = ord("h")
    ltAMENDMENTS = ord("f")
    ltFEE_SETTINGS = ord("s")


@dataclass(frozen=True)
class Format:
    name: str
    type_code: int
    template: tuple[tuple[SField, SOE], ...]

    # built once per (immutable) format: these sit on the per-tx apply
    # hot path via validate_against, where a rebuilt set per call was
    # measurable at flood rates
    def known_fields(self) -> frozenset[SField]:
        cached = self.__dict__.get("_known")
        if cached is None:
            cached = frozenset(f for f, _ in self.template)
            object.__setattr__(self, "_known", cached)
        return cached

    def required_fields(self) -> frozenset[SField]:
        cached = self.__dict__.get("_required")
        if cached is None:
            cached = frozenset(
                f for f, soe in self.template if soe == SOE.REQUIRED
            )
            object.__setattr__(self, "_required", cached)
        return cached


def _fmt(name: str, code: int, elems: list[tuple[SField, SOE]]) -> Format:
    return Format(name, code, tuple(elems))


# Common fields present on every transaction (reference
# TxFormats::addCommonFields, TxFormats.cpp:97-115).
TX_COMMON_FIELDS: list[tuple[SField, SOE]] = [
    (sf.sfTransactionType, SOE.REQUIRED),
    (sf.sfFlags, SOE.OPTIONAL),
    (sf.sfSourceTag, SOE.OPTIONAL),
    (sf.sfAccount, SOE.REQUIRED),
    (sf.sfSequence, SOE.REQUIRED),
    (sf.sfPreviousTxnID, SOE.OPTIONAL),  # deprecated
    (sf.sfLastLedgerSequence, SOE.OPTIONAL),
    (sf.sfAccountTxnID, SOE.OPTIONAL),
    (sf.sfFee, SOE.REQUIRED),
    (sf.sfOperationLimit, SOE.OPTIONAL),
    (sf.sfMemos, SOE.OPTIONAL),
    (sf.sfSigningPubKey, SOE.REQUIRED),
    (sf.sfTxnSignature, SOE.OPTIONAL),
]


def _tx(name: str, code: TxType, elems: list[tuple[SField, SOE]]) -> Format:
    return _fmt(name, int(code), TX_COMMON_FIELDS + elems)


# Transaction formats (reference TxFormats.cpp:22-95).
TX_FORMATS: dict[int, Format] = {
    f.type_code: f
    for f in [
        _tx("AccountSet", TxType.ttACCOUNT_SET, [
            (sf.sfTransferRate, SOE.OPTIONAL),
            (sf.sfSetFlag, SOE.OPTIONAL),
            (sf.sfClearFlag, SOE.OPTIONAL),
            (sf.sfInflationDest, SOE.OPTIONAL),
            (sf.sfSetAuthKey, SOE.OPTIONAL),
        ]),
        _tx("AccountMerge", TxType.ttACCOUNT_MERGE, [
            (sf.sfDestination, SOE.REQUIRED),
            (sf.sfDestinationTag, SOE.OPTIONAL),
        ]),
        _tx("TrustSet", TxType.ttTRUST_SET, [
            (sf.sfLimitAmount, SOE.OPTIONAL),
            (sf.sfQualityIn, SOE.OPTIONAL),
            (sf.sfQualityOut, SOE.OPTIONAL),
        ]),
        _tx("OfferCreate", TxType.ttOFFER_CREATE, [
            (sf.sfTakerPays, SOE.REQUIRED),
            (sf.sfTakerGets, SOE.REQUIRED),
            (sf.sfExpiration, SOE.OPTIONAL),
            (sf.sfOfferSequence, SOE.OPTIONAL),
        ]),
        _tx("OfferCancel", TxType.ttOFFER_CANCEL, [
            (sf.sfOfferSequence, SOE.REQUIRED),
        ]),
        _tx("SetRegularKey", TxType.ttREGULAR_KEY_SET, [
            (sf.sfRegularKey, SOE.OPTIONAL),
        ]),
        _tx("Payment", TxType.ttPAYMENT, [
            (sf.sfDestination, SOE.REQUIRED),
            (sf.sfAmount, SOE.REQUIRED),
            (sf.sfSendMax, SOE.OPTIONAL),
            (sf.sfPaths, SOE.DEFAULT),
            (sf.sfInvoiceID, SOE.OPTIONAL),
            (sf.sfDestinationTag, SOE.OPTIONAL),
        ]),
        _tx("Inflation", TxType.ttINFLATION, [
            (sf.sfInflateSeq, SOE.REQUIRED),
        ]),
        _tx("EnableAmendment", TxType.ttAMENDMENT, [
            (sf.sfAmendment, SOE.REQUIRED),
        ]),
        _tx("SetFee", TxType.ttFEE, [
            (sf.sfBaseFee, SOE.REQUIRED),
            (sf.sfReferenceFeeUnits, SOE.REQUIRED),
            (sf.sfReserveBase, SOE.REQUIRED),
            (sf.sfReserveIncrement, SOE.REQUIRED),
        ]),
    ]
}

TX_FORMATS_BY_NAME: dict[str, Format] = {f.name: f for f in TX_FORMATS.values()}

# Common fields on every ledger entry (reference
# LedgerFormats::addCommonFields: LedgerEntryType + Flags).
LE_COMMON_FIELDS: list[tuple[SField, SOE]] = [
    (sf.sfLedgerEntryType, SOE.REQUIRED),
    (sf.sfFlags, SOE.REQUIRED),
]


def _le(name: str, code: LedgerEntryType, elems: list[tuple[SField, SOE]]) -> Format:
    return _fmt(name, int(code), LE_COMMON_FIELDS + elems)


# Ledger entry formats (reference LedgerFormats.cpp:22-120).
LEDGER_FORMATS: dict[int, Format] = {
    f.type_code: f
    for f in [
        _le("AccountRoot", LedgerEntryType.ltACCOUNT_ROOT, [
            (sf.sfAccount, SOE.REQUIRED),
            (sf.sfSequence, SOE.REQUIRED),
            (sf.sfBalance, SOE.REQUIRED),
            (sf.sfOwnerCount, SOE.REQUIRED),
            (sf.sfPreviousTxnID, SOE.REQUIRED),
            (sf.sfPreviousTxnLgrSeq, SOE.REQUIRED),
            (sf.sfAccountTxnID, SOE.OPTIONAL),
            (sf.sfRegularKey, SOE.OPTIONAL),
            (sf.sfTransferRate, SOE.OPTIONAL),
            (sf.sfDomain, SOE.OPTIONAL),
            (sf.sfInflationDest, SOE.OPTIONAL),
            (sf.sfSetAuthKey, SOE.OPTIONAL),
        ]),
        _le("DirectoryNode", LedgerEntryType.ltDIR_NODE, [
            (sf.sfOwner, SOE.OPTIONAL),
            (sf.sfTakerPaysCurrency, SOE.OPTIONAL),
            (sf.sfTakerPaysIssuer, SOE.OPTIONAL),
            (sf.sfTakerGetsCurrency, SOE.OPTIONAL),
            (sf.sfTakerGetsIssuer, SOE.OPTIONAL),
            (sf.sfExchangeRate, SOE.OPTIONAL),
            (sf.sfIndexes, SOE.REQUIRED),
            (sf.sfRootIndex, SOE.REQUIRED),
            (sf.sfIndexNext, SOE.OPTIONAL),
            (sf.sfIndexPrevious, SOE.OPTIONAL),
        ]),
        _le("Offer", LedgerEntryType.ltOFFER, [
            (sf.sfAccount, SOE.REQUIRED),
            (sf.sfSequence, SOE.REQUIRED),
            (sf.sfTakerPays, SOE.REQUIRED),
            (sf.sfTakerGets, SOE.REQUIRED),
            (sf.sfBookDirectory, SOE.REQUIRED),
            (sf.sfBookNode, SOE.REQUIRED),
            (sf.sfOwnerNode, SOE.REQUIRED),
            (sf.sfPreviousTxnID, SOE.REQUIRED),
            (sf.sfPreviousTxnLgrSeq, SOE.REQUIRED),
            (sf.sfExpiration, SOE.OPTIONAL),
        ]),
        _le("RippleState", LedgerEntryType.ltRIPPLE_STATE, [
            (sf.sfBalance, SOE.REQUIRED),
            (sf.sfLowLimit, SOE.REQUIRED),
            (sf.sfHighLimit, SOE.REQUIRED),
            (sf.sfPreviousTxnID, SOE.REQUIRED),
            (sf.sfPreviousTxnLgrSeq, SOE.REQUIRED),
            (sf.sfLowNode, SOE.OPTIONAL),
            (sf.sfLowQualityIn, SOE.OPTIONAL),
            (sf.sfLowQualityOut, SOE.OPTIONAL),
            (sf.sfHighNode, SOE.OPTIONAL),
            (sf.sfHighQualityIn, SOE.OPTIONAL),
            (sf.sfHighQualityOut, SOE.OPTIONAL),
        ]),
        _le("LedgerHashes", LedgerEntryType.ltLEDGER_HASHES, [
            (sf.sfLastLedgerSequence, SOE.OPTIONAL),
            (sf.sfHashes, SOE.REQUIRED),
        ]),
        _le("EnabledAmendments", LedgerEntryType.ltAMENDMENTS, [
            (sf.sfAmendments, SOE.REQUIRED),
        ]),
        _le("FeeSettings", LedgerEntryType.ltFEE_SETTINGS, [
            (sf.sfBaseFee, SOE.REQUIRED),
            (sf.sfReferenceFeeUnits, SOE.REQUIRED),
            (sf.sfReserveBase, SOE.REQUIRED),
            (sf.sfReserveIncrement, SOE.REQUIRED),
        ]),
    ]
}

LEDGER_FORMATS_BY_NAME: dict[str, Format] = {f.name: f for f in LEDGER_FORMATS.values()}


def validate_against(obj, fmt: Format) -> list[str]:
    """Template check: required fields present, no unknown fields.
    Returns a list of problems (empty = valid)."""
    problems = []
    known = fmt.known_fields()
    present = {f for f, _ in obj.fields()}
    for f in fmt.required_fields():
        if f not in present:
            problems.append(f"missing required field {f.name}")
    for f in present:
        if f not in known:
            problems.append(f"unknown field {f.name} for {fmt.name}")
    return problems
