"""Identities: seeds, Ed25519 keypairs, and Base58Check encodings.

Reference semantics (src/ripple_data/protocol/RippleAddress.cpp,
src/ripple_data/crypto/EdKeyPair.cpp, StellarPublicKey.cpp):

- a **seed** is 32 bytes (base58check version 33, renders s...); a
  passphrase maps to a seed via SHA-512-half (EdKeyPair::passPhraseToKey)
- an account/node keypair is the libsodium ``crypto_sign_seed_keypair`` of
  the seed; public keys are raw 32-byte Ed25519 points
- the **account ID** is RIPEMD160(SHA256(pubkey)) (version 0, renders g...)
- signatures are Ed25519 over the 32-byte signing hash, and verification
  additionally enforces the canonical-S rule S < l
  (RippleAddress.cpp:226-252 signatureIsCanonical)
"""

from __future__ import annotations

import os
from dataclasses import dataclass

# `cryptography` is an OPTIONAL accelerator: when the wheel is absent the
# same operations run on the pure-Python RFC 8032 reference
# (ops/ed25519_ref — byte-identical keys and signatures) with single-sig
# verification preferring the native C++ batch kernel when the toolchain
# can build it. Nothing in the protocol plane may hard-require the wheel:
# it is an extra in pyproject ("crypto"), not a dependency.
try:  # pragma: no cover - exercised by whichever env runs the suite
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    HAVE_CRYPTOGRAPHY = True
except ImportError:  # pragma: no cover
    HAVE_CRYPTOGRAPHY = False

from ..utils.base58 import b58check_decode, b58check_encode
from ..utils.hashes import hash160, sha512_half

# Base58Check version bytes (reference RippleAddress.h:50-57)
VER_NODE_PUBLIC = 122  # n...
VER_NODE_PRIVATE = 102  # h...
VER_ACCOUNT_ID = 0  # g...
VER_ACCOUNT_PUBLIC = 67  # p...
VER_ACCOUNT_PRIVATE = 101  # h...
VER_SEED = 33  # s...

# Ed25519 group order l = 2^252 + 27742317777372353535851937790883648493;
# the canonical-S rule rejects sigs with S >= l (RippleAddress.cpp:226-252).
ED25519_L = (1 << 252) + 27742317777372353535851937790883648493


def encode_account_id(account_id: bytes) -> str:
    return b58check_encode(VER_ACCOUNT_ID, account_id)


def decode_account_id(s: str) -> bytes:
    _, payload = b58check_decode(s, VER_ACCOUNT_ID)
    if len(payload) != 20:
        raise ValueError("account ID must be 20 bytes")
    return payload


def encode_seed(seed: bytes) -> str:
    return b58check_encode(VER_SEED, seed)


def decode_seed(s: str) -> bytes:
    _, payload = b58check_decode(s, VER_SEED)
    if len(payload) != 32:
        raise ValueError("seed must be 32 bytes")
    return payload


def encode_node_public(pubkey: bytes) -> str:
    return b58check_encode(VER_NODE_PUBLIC, pubkey)


def decode_node_public(s: str) -> bytes:
    _, payload = b58check_decode(s, VER_NODE_PUBLIC)
    return payload


def encode_account_public(pubkey: bytes) -> str:
    return b58check_encode(VER_ACCOUNT_PUBLIC, pubkey)


def decode_account_public(s: str) -> bytes:
    _, payload = b58check_decode(s, VER_ACCOUNT_PUBLIC)
    return payload


def passphrase_to_seed(passphrase: str) -> bytes:
    """SHA-512-half of the passphrase bytes (EdKeyPair::passPhraseToKey)."""
    return sha512_half(passphrase.encode("utf-8"))


def signature_is_canonical(sig: bytes) -> bool:
    if len(sig) != 64:
        return False
    return int.from_bytes(sig[32:], "little") < ED25519_L


# -- pure-Python fallback plumbing (no `cryptography` wheel) ----------------

_FALLBACK_VERIFY = None  # resolved once: native batch kernel or ref.verify


def _fallback_verify_fn():
    """Single-signature verifier for the no-wheel path: the native C++
    batch kernel when the toolchain is present (a batch of one), else
    the pure-Python reference. Resolved once per process."""
    global _FALLBACK_VERIFY
    if _FALLBACK_VERIFY is None:
        try:
            from ..native import Ed25519NativeVerify

            impl = Ed25519NativeVerify()

            def _native_one(public, msg, sig):
                return bool(impl.verify_batch([public], [msg], [sig])[0])

            _FALLBACK_VERIFY = _native_one
        except Exception:  # noqa: BLE001 — toolchain-less box: pure Python
            from ..ops import ed25519_ref

            _FALLBACK_VERIFY = ed25519_ref.verify
    return _FALLBACK_VERIFY


@dataclass(frozen=True)
class KeyPair:
    """Ed25519 seed keypair."""

    seed: bytes
    public: bytes  # 32-byte raw public key

    @classmethod
    def from_seed(cls, seed: bytes) -> "KeyPair":
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        if HAVE_CRYPTOGRAPHY:
            priv = Ed25519PrivateKey.from_private_bytes(seed)
            pub = priv.public_key().public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw
            )
        else:
            from ..ops.ed25519_ref import derive_public

            pub = derive_public(seed)
        return cls(seed, pub)

    @classmethod
    def from_passphrase(cls, passphrase: str) -> "KeyPair":
        return cls.from_seed(passphrase_to_seed(passphrase))

    @classmethod
    def random(cls) -> "KeyPair":
        return cls.from_seed(os.urandom(32))

    @property
    def account_id(self) -> bytes:
        return hash160(self.public)

    @property
    def human_account_id(self) -> str:
        return encode_account_id(self.account_id)

    @property
    def human_seed(self) -> str:
        return encode_seed(self.seed)

    @property
    def human_account_public(self) -> str:
        return encode_account_public(self.public)

    @property
    def human_node_public(self) -> str:
        return encode_node_public(self.public)

    def sign(self, signing_hash: bytes) -> bytes:
        """Detached Ed25519 signature over the 32-byte signing hash
        (reference RippleAddress::sign -> crypto_sign_detached)."""
        if len(signing_hash) != 32:
            raise ValueError("signing hash must be 32 bytes")
        if HAVE_CRYPTOGRAPHY:
            return Ed25519PrivateKey.from_private_bytes(self.seed).sign(
                signing_hash
            )
        from ..ops.ed25519_ref import sign as ref_sign

        return ref_sign(self.seed, self.public, signing_hash)


def verify_signature(public: bytes, signing_hash: bytes, sig: bytes) -> bool:
    """CPU-path single verification with the canonical-S rule
    (StellarPublicKey::verifySignature)."""
    if len(public) != 32 or len(sig) != 64 or len(signing_hash) != 32:
        return False
    if not signature_is_canonical(sig):
        return False
    if not HAVE_CRYPTOGRAPHY:
        return bool(_fallback_verify_fn()(public, signing_hash, sig))
    try:
        Ed25519PublicKey.from_public_bytes(public).verify(sig, signing_hash)
        return True
    except (InvalidSignature, ValueError):
        return False
