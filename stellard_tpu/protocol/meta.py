"""Transaction-metadata helpers.

Reference: src/ripple_data/protocol/TransactionMeta.cpp —
getAffectedAccounts walks every field of the affected nodes collecting
account IDs (including IOU issuers), which feeds both the
AccountTransactions SQL index and account-subscription pub/sub routing.
"""

from __future__ import annotations

from .sfields import STI
from .stamount import ACCOUNT_ZERO, STAmount
from .stobject import STArray, STObject

__all__ = ["affected_accounts"]


def affected_accounts(meta_blob: "bytes | STObject") -> list[bytes]:
    # accepts the already-parsed meta object when the caller has one
    # in hand (the close path builds it; re-parsing per tx at persist
    # was ~8% of the flood apply path)
    meta = (meta_blob if isinstance(meta_blob, STObject)
            else STObject.from_bytes(meta_blob))
    out: set[bytes] = set()

    def walk(obj: STObject) -> None:
        for f, v in obj.fields():
            if f.type_id == STI.ACCOUNT:
                out.add(v)
            elif isinstance(v, STAmount) and not v.is_native:
                if v.issuer != ACCOUNT_ZERO:
                    out.add(v.issuer)
            elif isinstance(v, STObject):
                walk(v)
            elif isinstance(v, STArray):
                for _, inner in v:
                    walk(inner)

    walk(meta)
    return sorted(out)
