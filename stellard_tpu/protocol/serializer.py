"""Canonical binary serialization.

Byte-compatible with the reference Serializer
(src/ripple_data/protocol/Serializer.cpp): big-endian integers,
variable-length blobs with the 1/2/3-byte length prefix (Serializer.cpp
addEncoded/encodeLengthLength), field headers packed by (type, name)
commonness (Serializer.cpp:193-223, addFieldID).
"""

from __future__ import annotations

from ..utils.hashes import prefix_hash, sha512_half

_VL1_MAX = 192
_VL2_MAX = 12480
_VL3_MAX = 918744


def encode_vl_length(length: int) -> bytes:
    if length <= _VL1_MAX:
        return bytes([length])
    if length <= _VL2_MAX:
        length -= _VL1_MAX + 1
        return bytes([193 + (length >> 8), length & 0xFF])
    if length <= _VL3_MAX:
        length -= _VL2_MAX + 1
        return bytes([241 + (length >> 16), (length >> 8) & 0xFF, length & 0xFF])
    raise ValueError(f"VL length {length} too long")


class Serializer:
    """Append-only canonical byte builder."""

    __slots__ = ("_buf",)

    def __init__(self, data: bytes = b""):
        self._buf = bytearray(data)

    def __len__(self) -> int:
        return len(self._buf)

    def data(self) -> bytes:
        return bytes(self._buf)

    def add8(self, v: int) -> None:
        self._buf.append(v & 0xFF)

    def add16(self, v: int) -> None:
        self._buf += (v & 0xFFFF).to_bytes(2, "big")

    def add32(self, v: int) -> None:
        self._buf += (v & 0xFFFFFFFF).to_bytes(4, "big")

    def add64(self, v: int) -> None:
        self._buf += (v & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big")

    def add_raw(self, data: bytes) -> None:
        self._buf += data

    def add_bits(self, data: bytes, nbytes: int) -> None:
        """Fixed-width big-endian byte string (uint128/160/256)."""
        if len(data) != nbytes:
            raise ValueError(f"expected {nbytes} bytes, got {len(data)}")
        self._buf += data

    def add_vl(self, data: bytes) -> None:
        self._buf += encode_vl_length(len(data))
        self._buf += data

    def add_field_id(self, type_id: int, name: int) -> None:
        # single source of truth for the field-id encoding: the same
        # function that precomputes SField.header (sfields._field_header)
        from .sfields import _field_header

        self._buf += _field_header(type_id, name)

    def sha512_half(self) -> bytes:
        return sha512_half(bytes(self._buf))

    def prefix_hash(self, prefix: int) -> bytes:
        return prefix_hash(prefix, bytes(self._buf))


class BinaryParser:
    """Sequential reader over canonical bytes."""

    __slots__ = ("_data", "_pos")

    def __init__(self, data: bytes):
        self._data = data
        self._pos = 0

    def empty(self) -> bool:
        return self._pos >= len(self._data)

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def read(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise ValueError("parser underflow")
        out = self._data[self._pos : self._pos + n]
        self._pos += n
        return out

    def read8(self) -> int:
        return self.read(1)[0]

    def read16(self) -> int:
        return int.from_bytes(self.read(2), "big")

    def read32(self) -> int:
        return int.from_bytes(self.read(4), "big")

    def read64(self) -> int:
        return int.from_bytes(self.read(8), "big")

    def read_vl(self) -> bytes:
        b1 = self.read8()
        if b1 <= _VL1_MAX:
            length = b1
        elif b1 <= 240:
            b2 = self.read8()
            length = _VL1_MAX + 1 + ((b1 - 193) << 8) + b2
        elif b1 <= 254:
            b2, b3 = self.read8(), self.read8()
            length = _VL2_MAX + 1 + ((b1 - 241) << 16) + (b2 << 8) + b3
        else:
            raise ValueError("invalid VL length byte")
        return self.read(length)

    def read_field_id(self) -> tuple[int, int]:
        b1 = self.read8()
        type_id = b1 >> 4
        name = b1 & 0x0F
        if type_id == 0:
            type_id = self.read8()
            if type_id == 0 or type_id < 16:
                raise ValueError("invalid field id encoding")
            if name == 0:
                name = self.read8()
                if name == 0 or name < 16:
                    raise ValueError("invalid field id encoding")
        elif name == 0:
            name = self.read8()
            if name == 0 or name < 16:
                raise ValueError("invalid field id encoding")
        return type_id, name
