"""Typed field registry (SField equivalent).

Field codes are protocol constants shared with the reference wire format
(src/ripple_data/protocol/SerializeDeclarations.h). A field is identified by
(type id, field value); canonical serialization orders fields by that pair
(src/ripple_data/protocol/FieldNames.cpp SField::compare).
"""

from __future__ import annotations

from dataclasses import dataclass, field as _dc_field
from enum import IntEnum


class STI(IntEnum):
    """Serialized type ids (reference: SerializeDeclarations.h:33-49)."""

    NOTPRESENT = 0
    UINT16 = 1
    UINT32 = 2
    UINT64 = 3
    HASH128 = 4
    HASH256 = 5
    AMOUNT = 6
    VL = 7
    ACCOUNT = 8
    OBJECT = 14
    ARRAY = 15
    UINT8 = 16
    HASH160 = 17
    PATHSET = 18
    VECTOR256 = 19
    # high-level (never wire-encoded as field headers)
    TRANSACTION = 10001
    LEDGERENTRY = 10002
    VALIDATION = 10003


# encode-kind tags for the (de)serialization hot loops: integer compares
# instead of enum identity tests, precomputed once per registry field
K_UINT8, K_UINT16, K_UINT32, K_UINT64 = 0, 1, 2, 3
K_HASH, K_AMOUNT, K_VL, K_ACCOUNT = 4, 5, 6, 7
K_OBJECT, K_ARRAY, K_PATHSET, K_VECTOR256 = 8, 9, 10, 11

_KIND_OF = {
    STI.UINT8: K_UINT8, STI.UINT16: K_UINT16, STI.UINT32: K_UINT32,
    STI.UINT64: K_UINT64,
    STI.HASH128: K_HASH, STI.HASH160: K_HASH, STI.HASH256: K_HASH,
    STI.AMOUNT: K_AMOUNT, STI.VL: K_VL, STI.ACCOUNT: K_ACCOUNT,
    STI.OBJECT: K_OBJECT, STI.ARRAY: K_ARRAY, STI.PATHSET: K_PATHSET,
    STI.VECTOR256: K_VECTOR256,
}
_HASH_WIDTH_OF = {STI.HASH128: 16, STI.HASH160: 20, STI.HASH256: 32}
_INT_WIDTH_OF = {STI.UINT8: 1, STI.UINT16: 2, STI.UINT32: 4, STI.UINT64: 8}


def _field_header(type_id: int, value: int) -> bytes:
    """The constant field-id prefix (reference Serializer::addFieldID)."""
    if not (0 < type_id < 256 and 0 < value < 256):
        raise ValueError(f"bad field id ({type_id}, {value})")
    if type_id < 16:
        if value < 16:
            return bytes([(type_id << 4) | value])
        return bytes([type_id << 4, value])
    if value < 16:
        return bytes([value, type_id])
    return bytes([0, type_id, value])


@dataclass(frozen=True, eq=False)
class SField:
    """eq=False: fields are registry singletons, so identity equality /
    hashing is correct and keeps the per-field dict operations on the
    hot (de)serialization paths at object-id speed (the generated
    frozen-dataclass __hash__ tuples all four members per lookup)."""

    name: str
    type_id: STI
    value: int
    signing: bool = True  # excluded from signing serialization when False
    # wire constants for the hot paths, derived in __post_init__:
    header: bytes = b""  # the encoded field id (empty for non-wire types)
    kind: int = -1  # K_* tag, -1 for non-wire types
    width: int = 0  # fixed byte width for K_UINT*/K_HASH kinds
    cid: int = -1  # dense registry index (the native serializer's key)

    def __post_init__(self):
        k = _KIND_OF.get(self.type_id, -1)
        object.__setattr__(self, "kind", k)
        if k >= 0:
            object.__setattr__(
                self, "header", _field_header(int(self.type_id), self.value)
            )
        w = (_INT_WIDTH_OF.get(self.type_id, 0)
             or _HASH_WIDTH_OF.get(self.type_id, 0))
        object.__setattr__(self, "width", w)

    @property
    def code(self) -> int:
        return (int(self.type_id) << 16) | self.value

    def __repr__(self) -> str:
        return f"sf{self.name}"


_REGISTRY_BY_CODE: dict[int, SField] = {}
_REGISTRY_BY_NAME: dict[str, SField] = {}


def _f(name: str, type_id: STI, value: int, signing: bool = True) -> SField:
    f = SField(name, type_id, value, signing, cid=len(_REGISTRY_BY_CODE))
    _REGISTRY_BY_CODE[f.code] = f
    _REGISTRY_BY_NAME[name] = f
    return f


def all_fields():
    """Registry snapshot (the native serializer registers constants per
    field at load)."""
    return list(_REGISTRY_BY_CODE.values())


# --- 8-bit ---------------------------------------------------------------
sfCloseResolution = _f("CloseResolution", STI.UINT8, 1)
sfTemplateEntryType = _f("TemplateEntryType", STI.UINT8, 2)
sfTransactionResult = _f("TransactionResult", STI.UINT8, 3)

# --- 16-bit --------------------------------------------------------------
sfLedgerEntryType = _f("LedgerEntryType", STI.UINT16, 1)
sfTransactionType = _f("TransactionType", STI.UINT16, 2)

# --- 32-bit (common) -----------------------------------------------------
sfFlags = _f("Flags", STI.UINT32, 2)
sfSourceTag = _f("SourceTag", STI.UINT32, 3)
sfSequence = _f("Sequence", STI.UINT32, 4)
sfPreviousTxnLgrSeq = _f("PreviousTxnLgrSeq", STI.UINT32, 5)
sfLedgerSequence = _f("LedgerSequence", STI.UINT32, 6)
sfCloseTime = _f("CloseTime", STI.UINT32, 7)
sfParentCloseTime = _f("ParentCloseTime", STI.UINT32, 8)
sfSigningTime = _f("SigningTime", STI.UINT32, 9)
sfExpiration = _f("Expiration", STI.UINT32, 10)
sfTransferRate = _f("TransferRate", STI.UINT32, 11)
sfWalletSize = _f("WalletSize", STI.UINT32, 12)
sfOwnerCount = _f("OwnerCount", STI.UINT32, 13)
sfDestinationTag = _f("DestinationTag", STI.UINT32, 14)
# --- 32-bit (uncommon) ---------------------------------------------------
sfHighQualityIn = _f("HighQualityIn", STI.UINT32, 16)
sfHighQualityOut = _f("HighQualityOut", STI.UINT32, 17)
sfLowQualityIn = _f("LowQualityIn", STI.UINT32, 18)
sfLowQualityOut = _f("LowQualityOut", STI.UINT32, 19)
sfQualityIn = _f("QualityIn", STI.UINT32, 20)
sfQualityOut = _f("QualityOut", STI.UINT32, 21)
sfStampEscrow = _f("StampEscrow", STI.UINT32, 22)
sfBondAmount = _f("BondAmount", STI.UINT32, 23)
sfLoadFee = _f("LoadFee", STI.UINT32, 24)
sfOfferSequence = _f("OfferSequence", STI.UINT32, 25)
sfInflateSeq = _f("InflateSeq", STI.UINT32, 26)
sfLastLedgerSequence = _f("LastLedgerSequence", STI.UINT32, 27)
sfTransactionIndex = _f("TransactionIndex", STI.UINT32, 28)
sfOperationLimit = _f("OperationLimit", STI.UINT32, 29)
sfReferenceFeeUnits = _f("ReferenceFeeUnits", STI.UINT32, 30)
sfReserveBase = _f("ReserveBase", STI.UINT32, 31)
sfReserveIncrement = _f("ReserveIncrement", STI.UINT32, 32)
sfSetFlag = _f("SetFlag", STI.UINT32, 33)
sfClearFlag = _f("ClearFlag", STI.UINT32, 34)

# --- 64-bit --------------------------------------------------------------
sfIndexNext = _f("IndexNext", STI.UINT64, 1)
sfIndexPrevious = _f("IndexPrevious", STI.UINT64, 2)
sfBookNode = _f("BookNode", STI.UINT64, 3)
sfOwnerNode = _f("OwnerNode", STI.UINT64, 4)
sfBaseFee = _f("BaseFee", STI.UINT64, 5)
sfExchangeRate = _f("ExchangeRate", STI.UINT64, 6)
sfLowNode = _f("LowNode", STI.UINT64, 7)
sfHighNode = _f("HighNode", STI.UINT64, 8)

# --- 128-bit -------------------------------------------------------------
sfEmailHash = _f("EmailHash", STI.HASH128, 1)

# --- 256-bit (common) ----------------------------------------------------
sfLedgerHash = _f("LedgerHash", STI.HASH256, 1)
sfParentHash = _f("ParentHash", STI.HASH256, 2)
sfTransactionHash = _f("TransactionHash", STI.HASH256, 3)
sfAccountHash = _f("AccountHash", STI.HASH256, 4)
sfPreviousTxnID = _f("PreviousTxnID", STI.HASH256, 5)
sfLedgerIndex = _f("LedgerIndex", STI.HASH256, 6)
sfWalletLocator = _f("WalletLocator", STI.HASH256, 7)
sfRootIndex = _f("RootIndex", STI.HASH256, 8)
sfAccountTxnID = _f("AccountTxnID", STI.HASH256, 9)
# --- 256-bit (uncommon) --------------------------------------------------
sfBookDirectory = _f("BookDirectory", STI.HASH256, 16)
sfInvoiceID = _f("InvoiceID", STI.HASH256, 17)
sfNickname = _f("Nickname", STI.HASH256, 18)
sfAmendment = _f("Amendment", STI.HASH256, 19)

# --- 160-bit -------------------------------------------------------------
sfTakerPaysCurrency = _f("TakerPaysCurrency", STI.HASH160, 1)
sfTakerPaysIssuer = _f("TakerPaysIssuer", STI.HASH160, 2)
sfTakerGetsCurrency = _f("TakerGetsCurrency", STI.HASH160, 3)
sfTakerGetsIssuer = _f("TakerGetsIssuer", STI.HASH160, 4)

# --- amounts (common) ----------------------------------------------------
sfAmount = _f("Amount", STI.AMOUNT, 1)
sfBalance = _f("Balance", STI.AMOUNT, 2)
sfLimitAmount = _f("LimitAmount", STI.AMOUNT, 3)
sfTakerPays = _f("TakerPays", STI.AMOUNT, 4)
sfTakerGets = _f("TakerGets", STI.AMOUNT, 5)
sfLowLimit = _f("LowLimit", STI.AMOUNT, 6)
sfHighLimit = _f("HighLimit", STI.AMOUNT, 7)
sfFee = _f("Fee", STI.AMOUNT, 8)
sfSendMax = _f("SendMax", STI.AMOUNT, 9)
# --- amounts (uncommon) --------------------------------------------------
sfMinimumOffer = _f("MinimumOffer", STI.AMOUNT, 16)
sfRippleEscrow = _f("RippleEscrow", STI.AMOUNT, 17)
sfDeliveredAmount = _f("DeliveredAmount", STI.AMOUNT, 18)

# --- variable length -----------------------------------------------------
sfPublicKey = _f("PublicKey", STI.VL, 1)
sfMessageKey = _f("MessageKey", STI.VL, 2)
sfSigningPubKey = _f("SigningPubKey", STI.VL, 3)
sfTxnSignature = _f("TxnSignature", STI.VL, 4, signing=False)
sfGenerator = _f("Generator", STI.VL, 5)
sfSignature = _f("Signature", STI.VL, 6, signing=False)
sfDomain = _f("Domain", STI.VL, 7)
sfFundCode = _f("FundCode", STI.VL, 8)
sfRemoveCode = _f("RemoveCode", STI.VL, 9)
sfExpireCode = _f("ExpireCode", STI.VL, 10)
sfCreateCode = _f("CreateCode", STI.VL, 11)
sfMemoType = _f("MemoType", STI.VL, 12)
sfMemoData = _f("MemoData", STI.VL, 13)

# --- account -------------------------------------------------------------
sfAccount = _f("Account", STI.ACCOUNT, 1)
sfOwner = _f("Owner", STI.ACCOUNT, 2)
sfDestination = _f("Destination", STI.ACCOUNT, 3)
sfIssuer = _f("Issuer", STI.ACCOUNT, 4)
sfTarget = _f("Target", STI.ACCOUNT, 7)
sfRegularKey = _f("RegularKey", STI.ACCOUNT, 8)
sfInflationDest = _f("InflationDest", STI.ACCOUNT, 9)
sfSetAuthKey = _f("SetAuthKey", STI.ACCOUNT, 10)

# --- path set ------------------------------------------------------------
sfPaths = _f("Paths", STI.PATHSET, 1)

# --- vector256 -----------------------------------------------------------
sfIndexes = _f("Indexes", STI.VECTOR256, 1)
sfHashes = _f("Hashes", STI.VECTOR256, 2)
sfAmendments = _f("Amendments", STI.VECTOR256, 3)

# --- inner objects (OBJECT/1 reserved: end-of-object) --------------------
sfTransactionMetaData = _f("TransactionMetaData", STI.OBJECT, 2)
sfCreatedNode = _f("CreatedNode", STI.OBJECT, 3)
sfDeletedNode = _f("DeletedNode", STI.OBJECT, 4)
sfModifiedNode = _f("ModifiedNode", STI.OBJECT, 5)
sfPreviousFields = _f("PreviousFields", STI.OBJECT, 6)
sfFinalFields = _f("FinalFields", STI.OBJECT, 7)
sfNewFields = _f("NewFields", STI.OBJECT, 8)
sfTemplateEntry = _f("TemplateEntry", STI.OBJECT, 9)
sfMemo = _f("Memo", STI.OBJECT, 10)

# --- arrays (ARRAY/1 reserved: end-of-array) -----------------------------
sfSigningAccounts = _f("SigningAccounts", STI.ARRAY, 2)
sfTxnSignatures = _f("TxnSignatures", STI.ARRAY, 3)
sfSignatures = _f("Signatures", STI.ARRAY, 4)
sfTemplate = _f("Template", STI.ARRAY, 5)
sfNecessary = _f("Necessary", STI.ARRAY, 6)
sfSufficient = _f("Sufficient", STI.ARRAY, 7)
sfAffectedNodes = _f("AffectedNodes", STI.ARRAY, 8)
sfMemos = _f("Memos", STI.ARRAY, 9)

FIELDS: dict[str, SField] = dict(_REGISTRY_BY_NAME)


def field_by_code(type_id: int, value: int) -> SField | None:
    return _REGISTRY_BY_CODE.get((type_id << 16) | value)


def field_by_name(name: str) -> SField:
    return _REGISTRY_BY_NAME[name]


def sort_key(f: SField) -> tuple[int, int]:
    """Canonical serialization order (reference SField::compare)."""
    return (int(f.type_id), f.value)
