"""STAmount — the protocol's decimal amount type.

Two regimes, byte-compatible with the reference
(src/ripple_data/protocol/STAmount.cpp, SerializedTypes.h:450-458):

- **native** (STR, drops): 62-bit integer magnitude + sign; wire encoding is
  a single uint64 whose bit 62 marks "positive", bit 63 clear marks native.
- **issued** (IOU): decimal mantissa in [1e15, 1e16) with exponent in
  [-96, 80], plus 160-bit currency and issuer; wire encoding packs
  [1, sign, exponent+97] into the top 10 bits over a 54-bit mantissa,
  followed by currency and issuer (STAmount.cpp:470-489).

Arithmetic reproduces the reference's exact rounding:
multiply = (m1*m2)/10^14 + 7 (STAmount.cpp multiply), divide =
(num*10^17)/den + 5 (STAmount.cpp divide) — consensus splits on any
divergence, so these are bit-for-bit.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from .serializer import Serializer, BinaryParser

CURRENCY_STR = b"\x00" * 20  # native currency id (all-zero uint160)
ACCOUNT_ZERO = b"\x00" * 20

MIN_VALUE = 10**15
MAX_VALUE = 10**16 - 1
MIN_OFFSET = -96
MAX_OFFSET = 80
MAX_NATIVE = 9_000_000_000_000_000_000
MAX_NATIVE_NETWORK = 100_000_000_000_000_000
NOT_NATIVE = 0x8000000000000000
POS_NATIVE = 0x4000000000000000

SYSTEM_CURRENCY_CODE = "STR"
SYSTEM_CURRENCY_PRECISION = 6
SYSTEM_CURRENCY_PARTS = 10**SYSTEM_CURRENCY_PRECISION


def currency_from_iso(iso: str) -> bytes:
    """3-letter ISO code -> 160-bit currency (ASCII at bytes 12..14,
    reference STAmount.cpp currencyFromString). Empty/'STR' -> zero."""
    if iso == "" or iso == SYSTEM_CURRENCY_CODE:
        return CURRENCY_STR
    if len(iso) != 3:
        raise ValueError(f"bad currency code {iso!r}")
    out = bytearray(20)
    out[12:15] = iso.upper().encode("ascii")
    return bytes(out)


def iso_from_currency(currency: bytes) -> str:
    if currency == CURRENCY_STR:
        return SYSTEM_CURRENCY_CODE
    body = currency[12:15]
    if currency[:12] == b"\x00" * 12 and currency[15:] == b"\x00" * 5:
        try:
            return body.decode("ascii")
        except UnicodeDecodeError:
            pass
    return currency.hex().upper()


_VALUE_RE = re.compile(r"^([-+]?)(\d*)(\.(\d*))?([eE]([+-]?)(\d+))?$")


@dataclass
class STAmount:
    """Value semantics; always canonicalized after construction."""

    currency: bytes = CURRENCY_STR
    issuer: bytes = ACCOUNT_ZERO
    mantissa: int = 0  # magnitude (drops when native)
    offset: int = 0
    negative: bool = False

    def __post_init__(self):
        self._canonicalize()

    # -- constructors -----------------------------------------------------

    @classmethod
    def from_drops(cls, drops: int) -> "STAmount":
        return cls(CURRENCY_STR, ACCOUNT_ZERO, abs(drops), 0, drops < 0)

    @classmethod
    def zero_like(cls, currency: bytes, issuer: bytes) -> "STAmount":
        return cls(currency, issuer, 0, 0, False)

    @classmethod
    def from_iou(cls, currency: bytes, issuer: bytes, mantissa: int, offset: int,
                 negative: bool = False) -> "STAmount":
        return cls(currency, issuer, mantissa, offset, negative)

    @classmethod
    def from_json(cls, j) -> "STAmount":
        """Parse the client JSON forms: a string of drops for native, or
        {value, currency, issuer} for IOUs (reference STAmount.cpp:150-230)."""
        if isinstance(j, (int,)):
            return cls.from_drops(j)
        if isinstance(j, str):
            neg, mant, off = _parse_decimal(j)
            # bare string = native, expressed in drops; normalize the
            # exponent away (reference setValue walks offset back to 0)
            while off > 0:
                mant *= 10
                off -= 1
            while off < 0 and mant % 10 == 0:
                mant //= 10
                off += 1
            if off != 0:
                raise ValueError("native amount must be integral drops")
            return cls(CURRENCY_STR, ACCOUNT_ZERO, mant, 0, neg)
        if isinstance(j, dict):
            iso = j.get("currency", "")
            currency = (
                bytes.fromhex(iso) if len(iso) == 40 else currency_from_iso(iso)
            )
            issuer = ACCOUNT_ZERO
            if j.get("issuer"):
                from .keys import decode_account_id

                issuer = decode_account_id(j["issuer"])
            value = j.get("value", "0")
            if isinstance(value, (int, float)):
                value = repr(value)
            neg, mant, off = _parse_decimal(value)
            if currency == CURRENCY_STR:
                # native passed in object form: value is in STR units
                return cls(CURRENCY_STR, ACCOUNT_ZERO, mant, off + SYSTEM_CURRENCY_PRECISION, neg)
            return cls(currency, issuer, mant, off, neg)
        raise ValueError(f"cannot parse amount from {j!r}")

    # -- predicates -------------------------------------------------------

    @property
    def is_native(self) -> bool:
        return self.currency == CURRENCY_STR

    def is_zero(self) -> bool:
        return self.mantissa == 0

    def __bool__(self) -> bool:
        return self.mantissa != 0

    def signum(self) -> int:
        if self.mantissa == 0:
            return 0
        return -1 if self.negative else 1

    # -- canonical form (reference STAmount::canonicalize) ---------------

    def _canonicalize(self) -> None:
        if not isinstance(self.currency, bytes) or len(self.currency) != 20:
            raise ValueError("currency must be 20 bytes")
        if self.is_native:
            if self.mantissa == 0:
                self.offset = 0
                self.negative = False
                return
            while self.offset < 0:
                self.mantissa //= 10
                self.offset += 1
            while self.offset > 0:
                self.mantissa *= 10
                self.offset -= 1
            if self.mantissa > MAX_NATIVE:
                raise ValueError("native currency amount out of range")
            return
        if self.mantissa == 0:
            self.offset = -100
            self.negative = False
            return
        while self.mantissa < MIN_VALUE and self.offset > MIN_OFFSET:
            self.mantissa *= 10
            self.offset -= 1
        while self.mantissa > MAX_VALUE:
            if self.offset >= MAX_OFFSET:
                raise ValueError("IOU value overflow")
            self.mantissa //= 10
            self.offset += 1
        if self.offset < MIN_OFFSET or self.mantissa < MIN_VALUE:
            # underflow -> canonical zero
            self.mantissa = 0
            self.offset = -100
            self.negative = False

    # -- signed views -----------------------------------------------------

    def drops(self) -> int:
        """Signed native value (reference getSNValue)."""
        if not self.is_native:
            raise ValueError("not a native amount")
        return -self.mantissa if self.negative else self.mantissa

    # -- wire encoding (reference STAmount.cpp:470-489, :530-570) ---------

    def serialize(self, s: Serializer) -> None:
        if self.is_native:
            if self.negative:
                s.add64(self.mantissa)
            else:
                s.add64(self.mantissa | POS_NATIVE)
            return
        if self.mantissa == 0:
            s.add64(NOT_NATIVE)
        else:
            top = self.offset + 512 + 97 + (0 if self.negative else 256)
            s.add64(self.mantissa | (top << 54))
        s.add_bits(self.currency, 20)
        s.add_bits(self.issuer, 20)

    def wire_bytes(self) -> bytes:
        """Memoized wire encoding (8 bytes native / 48 bytes IOU) —
        amounts are value objects, never mutated after construction, so
        the first serialization's bytes serve every later one (the
        native serializer consumes this)."""
        w = getattr(self, "_wire", None)
        if w is None:
            s = Serializer()
            self.serialize(s)
            w = s.data()
            self._wire = w
        return w

    @classmethod
    def deserialize(cls, p: BinaryParser) -> "STAmount":
        value = p.read64()
        if (value & NOT_NATIVE) == 0:
            negative = (value & POS_NATIVE) == 0
            return cls.from_drops(-(value & ~POS_NATIVE) if negative else (value & ~POS_NATIVE))
        currency = p.read(20)
        issuer = p.read(20)
        if currency == CURRENCY_STR:
            raise ValueError("invalid native currency on IOU amount")
        mantissa = value & ((1 << 54) - 1)
        top = value >> 54
        if mantissa == 0:
            if value != NOT_NATIVE:
                raise ValueError("invalid IOU zero encoding")
            return cls.zero_like(currency, issuer)
        offset = (top & 0xFF) - 97
        negative = (top & 0x100) == 0
        if not (MIN_VALUE <= mantissa <= MAX_VALUE and MIN_OFFSET <= offset <= MAX_OFFSET):
            raise ValueError("invalid IOU amount encoding")
        return cls(currency, issuer, mantissa, offset, negative)

    # -- arithmetic (exact reference rounding) ----------------------------

    def __neg__(self) -> "STAmount":
        if self.mantissa == 0:
            return self
        return STAmount(self.currency, self.issuer, self.mantissa, self.offset, not self.negative)

    def _signed(self) -> tuple[int, int]:
        m = -self.mantissa if self.negative else self.mantissa
        return m, self.offset

    def __add__(self, other: "STAmount") -> "STAmount":
        _check_comparable(self, other)
        if self.is_native:
            return STAmount.from_drops(self.drops() + other.drops())
        if other.mantissa == 0:
            return STAmount(self.currency, self.issuer, self.mantissa, self.offset, self.negative)
        if self.mantissa == 0:
            return STAmount(self.currency, self.issuer, other.mantissa, other.offset, other.negative)
        # align to common offset (reference operator+: offsets walked to match)
        m1, o1 = self._signed()
        m2, o2 = other._signed()
        while o1 < o2:
            m1 = _div10_toward_zero(m1)
            o1 += 1
        while o2 < o1:
            m2 = _div10_toward_zero(m2)
            o2 += 1
        total = m1 + m2
        # tiny cancelling sums collapse to zero (reference operator+,
        # STAmount.cpp: |sum| <= 10 -> canonical zero)
        if -10 <= total <= 10:
            return STAmount.zero_like(self.currency, self.issuer)
        return STAmount(self.currency, self.issuer, abs(total), o1, total < 0)

    def __sub__(self, other: "STAmount") -> "STAmount":
        return self + (-other)

    def compare(self, other: "STAmount") -> int:
        _check_comparable(self, other)
        s1, s2 = self.signum(), other.signum()
        if s1 != s2:
            return -1 if s1 < s2 else 1
        if s1 == 0:
            return 0
        mag = self._compare_magnitude(other)
        return mag * (-1 if self.negative else 1)

    def _compare_magnitude(self, other: "STAmount") -> int:
        if self.is_native:
            a, b = self.mantissa, other.mantissa
        else:
            if self.offset != other.offset:
                return -1 if self.offset < other.offset else 1
            a, b = self.mantissa, other.mantissa
        if a == b:
            return 0
        return -1 if a < b else 1

    def __eq__(self, other) -> bool:
        if not isinstance(other, STAmount):
            return NotImplemented
        return (
            self.currency == other.currency
            and self.issuer == other.issuer
            and self.mantissa == other.mantissa
            and self.offset == other.offset
            and self.negative == other.negative
        )

    def __lt__(self, other: "STAmount") -> bool:
        return self.compare(other) < 0

    def __le__(self, other: "STAmount") -> bool:
        return self.compare(other) <= 0

    def __gt__(self, other: "STAmount") -> bool:
        return self.compare(other) > 0

    def __ge__(self, other: "STAmount") -> bool:
        return self.compare(other) >= 0

    def __hash__(self):
        return hash((self.currency, self.issuer, self.mantissa, self.offset, self.negative))

    @staticmethod
    def multiply(v1: "STAmount", v2: "STAmount", currency: bytes, issuer: bytes) -> "STAmount":
        """Reference STAmount::multiply — (m1*m2)/10^14 + 7 rounding."""
        if v1.is_zero() or v2.is_zero():
            return STAmount.zero_like(currency, issuer)
        if v1.is_native and v2.is_native and currency == CURRENCY_STR:
            prod = abs(v1.drops()) * abs(v2.drops())
            if prod > MAX_NATIVE:
                raise ValueError("native value overflow")
            return STAmount.from_drops(prod if v1.negative == v2.negative else -prod)
        m1, o1 = _to_iou_range(v1.mantissa, v1.offset, v1.is_native)
        m2, o2 = _to_iou_range(v2.mantissa, v2.offset, v2.is_native)
        mant = (m1 * m2) // 10**14 + 7
        return STAmount(currency, issuer, mant, o1 + o2 + 14, v1.negative != v2.negative)

    @staticmethod
    def divide(num: "STAmount", den: "STAmount", currency: bytes, issuer: bytes) -> "STAmount":
        """Reference STAmount::divide — (num*10^17)/den + 5 rounding."""
        if den.is_zero():
            raise ZeroDivisionError("amount division by zero")
        if num.is_zero():
            return STAmount.zero_like(currency, issuer)
        m1, o1 = _to_iou_range(num.mantissa, num.offset, num.is_native)
        m2, o2 = _to_iou_range(den.mantissa, den.offset, den.is_native)
        mant = (m1 * 10**17) // m2 + 5
        return STAmount(currency, issuer, mant, o1 - o2 - 17, num.negative != den.negative)

    # -- text / JSON ------------------------------------------------------

    def value_text(self) -> str:
        """Decimal rendering of the magnitude with sign (reference getText)."""
        if self.is_native:
            return str(self.drops())
        if self.mantissa == 0:
            return "0"
        sign = "-" if self.negative else ""
        m, e = self.mantissa, self.offset
        while m % 10 == 0 and m:
            m //= 10
            e += 1
        digits = str(m)
        if e >= 0:
            return sign + digits + "0" * e
        if -e < len(digits):
            ip, fp = digits[:e], digits[e:]
            return f"{sign}{ip}.{fp}"
        return sign + "0." + "0" * (-e - len(digits)) + digits

    def to_json(self):
        if self.is_native:
            return str(self.drops())
        from .keys import encode_account_id

        return {
            "value": self.value_text(),
            "currency": iso_from_currency(self.currency),
            "issuer": encode_account_id(self.issuer),
        }

    def __repr__(self):
        if self.is_native:
            return f"STAmount({self.drops()} drops)"
        return f"STAmount({self.value_text()} {iso_from_currency(self.currency)})"


def _check_comparable(a: STAmount, b: STAmount) -> None:
    if a.is_native != b.is_native:
        raise ValueError("amount comparison across native/IOU")
    if not a.is_native and a.currency != b.currency:
        raise ValueError("amount comparison across currencies")


def _div10_toward_zero(v: int) -> int:
    return -((-v) // 10) if v < 0 else v // 10


def _to_iou_range(mantissa: int, offset: int, is_native: bool) -> tuple[int, int]:
    """Bring a native magnitude into IOU mantissa range (reference
    multiply/divide preamble loops)."""
    if is_native:
        while mantissa < MIN_VALUE:
            mantissa *= 10
            offset -= 1
    return mantissa, offset


def _parse_decimal(text: str) -> tuple[bool, int, int]:
    """Parse sign/mantissa/exponent from a decimal string
    (reference STAmount::setValue regex, STAmount.cpp:276-330)."""
    m = _VALUE_RE.match(text.strip())
    if not m or (not m.group(2) and not m.group(4)):
        raise ValueError(f"cannot parse amount {text!r}")
    negative = m.group(1) == "-"
    int_part = m.group(2) or ""
    frac_part = m.group(4) or ""
    exp = int(m.group(7) or 0) * (-1 if m.group(6) == "-" else 1)
    mantissa = int(int_part + frac_part or "0")
    offset = exp - len(frac_part)
    if mantissa == 0:
        return False, 0, 0
    return negative, mantissa, offset
