"""STObject — self-describing typed object serialization.

The canonical container of the protocol: a mapping of SField -> typed value
that serializes to sorted, tagged binary (reference:
src/ripple_data/protocol/SerializedObject.cpp, SerializedTypes.cpp).

Python value representation per serialized type:
  UINT8/16/32/64    int
  HASH128/160/256   bytes (fixed width)
  AMOUNT            STAmount
  VL                bytes
  ACCOUNT           bytes (20-byte account ID; wire form is VL-encoded)
  OBJECT            STObject
  ARRAY             STArray
  PATHSET           STPathSet
  VECTOR256         list[bytes]
"""

from __future__ import annotations

from dataclasses import dataclass, field as _dcfield
from typing import Any, Iterator

from ..utils.hashes import prefix_hash
from .serializer import BinaryParser, Serializer
from .sfields import (
    K_ACCOUNT,
    K_AMOUNT,
    K_ARRAY,
    K_HASH,
    K_OBJECT,
    K_PATHSET,
    K_UINT8,
    K_UINT64,
    K_VECTOR256,
    K_VL,
    STI,
    SField,
    field_by_code,
    sort_key,
)
from .stamount import STAmount

_OBJECT_END = (int(STI.OBJECT), 1)  # 0xE1 marker
_ARRAY_END = (int(STI.ARRAY), 1)  # 0xF1 marker

# Path-element type bits (reference SerializedTypes.h STPathElement)
PATH_ACCOUNT = 0x01
PATH_CURRENCY = 0x10
PATH_ISSUER = 0x20


@dataclass(frozen=True)
class PathElement:
    account: bytes | None = None
    currency: bytes | None = None
    issuer: bytes | None = None

    @property
    def kind(self) -> int:
        k = 0
        if self.account is not None:
            k |= PATH_ACCOUNT
        if self.currency is not None:
            k |= PATH_CURRENCY
        if self.issuer is not None:
            k |= PATH_ISSUER
        return k


@dataclass
class STPathSet:
    paths: list[list[PathElement]] = _dcfield(default_factory=list)

    def serialize(self, s: Serializer) -> None:
        for i, path in enumerate(self.paths):
            if i:
                s.add8(0xFF)  # path boundary
            for el in path:
                s.add8(el.kind)
                if el.account is not None:
                    s.add_bits(el.account, 20)
                if el.currency is not None:
                    s.add_bits(el.currency, 20)
                if el.issuer is not None:
                    s.add_bits(el.issuer, 20)
        s.add8(0x00)  # end of path set

    @classmethod
    def deserialize(cls, p: BinaryParser) -> "STPathSet":
        paths: list[list[PathElement]] = [[]]
        while True:
            kind = p.read8()
            if kind == 0x00:
                break
            if kind == 0xFF:
                paths.append([])
                continue
            account = p.read(20) if kind & PATH_ACCOUNT else None
            currency = p.read(20) if kind & PATH_CURRENCY else None
            issuer = p.read(20) if kind & PATH_ISSUER else None
            paths[-1].append(PathElement(account, currency, issuer))
        if paths == [[]]:
            paths = []
        return cls(paths)

    def __len__(self) -> int:
        return len(self.paths)

    def to_json(self):
        from .keys import encode_account_id
        from .stamount import iso_from_currency

        out = []
        for path in self.paths:
            jp = []
            for el in path:
                je: dict[str, Any] = {"type": el.kind, "type_hex": f"{el.kind:016X}"}
                if el.account is not None:
                    je["account"] = encode_account_id(el.account)
                if el.currency is not None:
                    je["currency"] = iso_from_currency(el.currency)
                if el.issuer is not None:
                    je["issuer"] = encode_account_id(el.issuer)
                jp.append(je)
            out.append(jp)
        return out


# single-byte end markers: OBJECT(14)<<4|1, ARRAY(15)<<4|1
_OBJECT_END_B = b"\xe1"
_ARRAY_END_B = b"\xf1"


def _serialize_value(s: Serializer, f: SField, v: Any) -> None:
    """Encode one field value (header already written). Dispatch is over
    the precomputed SField.kind int — enum identity tests and per-call
    field-id encoding were measurable at flood rates."""
    k = f.kind
    buf = s._buf
    if 0 <= k <= K_UINT64:  # the four uint kinds, widths precomputed
        if k == K_UINT8:
            buf.append(v & 0xFF)
        else:
            # masked like Serializer.add16/32/64 (silent truncation is
            # the historical add* contract)
            buf += (v & ((1 << (8 * f.width)) - 1)).to_bytes(f.width, "big")
    elif k == K_HASH:
        if len(v) != f.width:
            raise ValueError(f"expected {f.width} bytes, got {len(v)}")
        buf += v
    elif k == K_AMOUNT:
        v.serialize(s)
    elif k == K_VL:
        s.add_vl(v)
    elif k == K_ACCOUNT:
        if len(v) != 20:
            raise ValueError("account field must be 20 bytes")
        buf.append(20)
        buf += v
    elif k == K_OBJECT:
        v.serialize_to(s)
        buf += _OBJECT_END_B
    elif k == K_ARRAY:
        v.serialize_to(s)
        buf += _ARRAY_END_B
    elif k == K_PATHSET:
        v.serialize(s)
    elif k == K_VECTOR256:
        s.add_vl(b"".join(v))
    else:
        raise ValueError(f"cannot serialize field type {f.type_id}")


def _deserialize_value(p: BinaryParser, f: SField) -> Any:
    k = f.kind
    if 0 <= k <= K_UINT64:
        return int.from_bytes(p.read(f.width), "big")
    if k == K_HASH:
        return p.read(f.width)
    if k == K_AMOUNT:
        return STAmount.deserialize(p)
    if k == K_VL:
        return p.read_vl()
    if k == K_ACCOUNT:
        v = p.read_vl()
        if len(v) != 20:
            raise ValueError("account field must be 20 bytes")
        return v
    if k == K_OBJECT:
        return STObject.deserialize(p, inner=True)
    if k == K_ARRAY:
        return STArray.deserialize(p)
    if k == K_PATHSET:
        return STPathSet.deserialize(p)
    if k == K_VECTOR256:
        raw = p.read_vl()
        if len(raw) % 32:
            raise ValueError("bad vector256 length")
        return [raw[i : i + 32] for i in range(0, len(raw), 32)]
    raise ValueError(f"cannot deserialize field type {f.type_id}")


# -- native fast path ------------------------------------------------------
# The _stser CPython extension (native/src/stser.cc) encodes the sorted
# pair list in C; container kinds call back into _container_chunk, which
# recurses through the same machinery per nesting level. Disable with
# STELLARD_NATIVE_STSER=0 (the differential tests pin byte-equality).

_STSER = None
_STSER_TRIED = False


def _container_chunk(f: SField, v: Any) -> bytes:
    s = Serializer()
    _serialize_value(s, f, v)
    return s.data()


def _obj_from_parse(fields: dict, in_order: bool) -> "STObject":
    """Native-parser factory: wrap a C-built fields dict; canonical wire
    order seeds the sort memo exactly like the Python loop."""
    obj = STObject()
    obj._fields = fields
    if in_order:
        obj._sorted_keys = (0, list(fields))
    return obj


def _arr_from_parse(items: list) -> "STArray":
    return STArray(items)


def _amount_from_wire(b: bytes) -> "STAmount":
    # full reference validation lives in STAmount.deserialize — the C
    # parser only slices the 8/48-byte region
    return STAmount.deserialize(BinaryParser(b))


def _pathset_from_wire(b: bytes) -> "STPathSet":
    return STPathSet.deserialize(BinaryParser(b))


def _get_stser():
    global _STSER, _STSER_TRIED
    if not _STSER_TRIED:
        _STSER_TRIED = True
        import os as _os

        if _os.environ.get("STELLARD_NATIVE_STSER", "1") != "0":
            try:
                from ..native import load_stser
                from .sfields import all_fields

                mod = load_stser()
                if mod is not None:
                    mod.register_fields(
                        [(f.cid, f.header, f.kind, f.width,
                          1 if f.signing else 0)
                         for f in all_fields() if f.kind >= 0],
                        _container_chunk,
                    )
                    mod.register_parse(
                        [(f.code, f, f.kind, f.width)
                         for f in all_fields() if f.kind >= 0],
                        _obj_from_parse,
                        _arr_from_parse,
                        _amount_from_wire,
                        _pathset_from_wire,
                    )
                    globals()["_STSER"] = mod
            except Exception:  # noqa: BLE001 — fall back to the Python loop
                pass
    return _STSER


def _copy_value(v: Any) -> Any:
    if isinstance(v, list):
        return [_copy_value(x) for x in v]
    if isinstance(v, STObject):
        return v.copy()
    if isinstance(v, STArray):
        return STArray([(f, o.copy()) for f, o in v])
    return v  # scalars / bytes / STAmount are value-like


class STObject:
    """Ordered-by-canon field map."""

    __slots__ = ("_fields", "_version", "_sorted_keys", "_pairs")

    def __init__(self, fields: dict[SField, Any] | None = None):
        self._fields: dict[SField, Any] = dict(fields or {})
        # bumped on every mutation so holders (SerializedTransaction)
        # can memoize serializations/hashes safely — the reference
        # recomputes getTransactionID per call and its own comment says
        # "perhaps we should cache this" (SerializedTransaction.cpp:169)
        self._version = 0
        # (version, [keys in canonical order]) — every serialization
        # sorts the field set; ledger entries are serialized many times
        # between mutations
        self._sorted_keys: tuple[int, list[SField]] | None = None
        # (version, [(field, value)...]) — fields() is called several
        # times per apply (serialize, meta, invariants); rebuild only
        # after mutation
        self._pairs: tuple[int, list[tuple[SField, Any]]] | None = None

    # -- mapping interface -------------------------------------------------

    def __contains__(self, f: SField) -> bool:
        return f in self._fields

    def __getitem__(self, f: SField) -> Any:
        return self._fields[f]

    def __setitem__(self, f: SField, v: Any) -> None:
        self._fields[f] = v
        self._version += 1

    def __delitem__(self, f: SField) -> None:
        del self._fields[f]
        self._version += 1

    def get(self, f: SField, default: Any = None) -> Any:
        return self._fields.get(f, default)

    def pop(self, f: SField, default: Any = None) -> Any:
        self._version += 1
        return self._fields.pop(f, default)

    def fields(self) -> Iterator[tuple[SField, Any]]:
        return iter(self._pairs_list())

    def _pairs_list(self) -> list[tuple[SField, Any]]:
        """Canonically sorted (field, value) pairs, memoized per version.
        Callers must NOT mutate the list (fields() hands out iterators;
        the native serializer reads it directly)."""
        pairs = self._pairs
        if pairs is not None and pairs[0] == self._version:
            return pairs[1]
        memo = self._sorted_keys
        if memo is None or memo[0] != self._version:
            keys = sorted(self._fields, key=sort_key)
            self._sorted_keys = memo = (self._version, keys)
        fields = self._fields
        # materialized so callers keep snapshot semantics under mutation
        lst = [(k, fields[k]) for k in memo[1]]
        self._pairs = (self._version, lst)
        return lst

    def copy(self) -> "STObject":
        """Copy that detaches container values (lists, nested objects,
        arrays) so mutating the copy never aliases the original."""
        out = STObject()
        out._fields = {f: _copy_value(v) for f, v in self._fields.items()}
        memo = self._sorted_keys
        if memo is not None and memo[0] == self._version:
            # the key list is never mutated in place (fields() replaces
            # the tuple wholesale), so sharing it across copies is safe
            out._sorted_keys = (0, memo[1])
        return out

    def __len__(self) -> int:
        return len(self._fields)

    def __eq__(self, other):
        return isinstance(other, STObject) and self._fields == other._fields

    def __repr__(self):
        inner = ", ".join(f"{f!r}={v!r}" for f, v in self.fields())
        return f"STObject({inner})"

    # -- serialization -----------------------------------------------------

    def serialize_to(self, s: Serializer, *, signing: bool = False) -> None:
        """Canonical serialization: fields sorted by (type, value); when
        ``signing``, non-signing fields (signatures) are omitted
        (reference STObject::getSerializer / getSigningHash,
        SerializedObject.cpp:444)."""
        st = _get_stser()
        if st is not None:
            s._buf += st.serialize(self._pairs_list(), 1 if signing else 0)
            return
        buf = s._buf
        for f, v in self.fields():
            if signing and not f.signing:
                continue
            buf += f.header
            _serialize_value(s, f, v)

    def serialize(self, *, signing: bool = False) -> bytes:
        s = Serializer()
        self.serialize_to(s, signing=signing)
        return s.data()

    def signing_hash(self, prefix: int) -> bytes:
        return prefix_hash(prefix, self.serialize(signing=True))

    def hash(self, prefix: int) -> bytes:
        return prefix_hash(prefix, self.serialize())

    @classmethod
    def deserialize(cls, p: BinaryParser, *, inner: bool = False) -> "STObject":
        st = _get_stser()
        if st is not None and cls is STObject:
            # the native path constructs base STObjects; a future
            # subclass must take the Python loop (obj = cls())
            obj, pos = st.parse(p._data, p._pos, 1 if inner else 0)
            p._pos = pos
            return obj
        obj = cls()
        # canonical input (the overwhelmingly common case: our own
        # serializer always writes sorted) seeds the sort memo so the
        # next serialization skips the sort; non-canonical wire input
        # falls back to sorting in fields()
        in_order = True
        prev_key = None
        while not p.empty():
            type_id, name = p.read_field_id()
            if inner and (type_id, name) == _OBJECT_END:
                if in_order:
                    obj._sorted_keys = (obj._version, list(obj._fields))
                return obj
            f = field_by_code(type_id, name)
            if f is None:
                raise ValueError(f"unknown field ({type_id}, {name})")
            if in_order:
                k = sort_key(f)
                if prev_key is not None and k < prev_key:
                    in_order = False
                prev_key = k
            obj._fields[f] = _deserialize_value(p, f)
        if inner:
            raise ValueError("unterminated inner object")
        if in_order:
            obj._sorted_keys = (obj._version, list(obj._fields))
        return obj

    @classmethod
    def from_bytes(cls, data: bytes) -> "STObject":
        return cls.deserialize(BinaryParser(data))

    # -- JSON --------------------------------------------------------------

    def to_json(self) -> dict:
        from .keys import encode_account_id

        out: dict[str, Any] = {}
        for f, v in self.fields():
            k = f.kind
            if 0 <= k <= K_UINT64:
                # render the type discriminators symbolically, as the
                # reference's STObject::getJson does via KnownFormats
                if f.name == "TransactionType":
                    from .formats import TX_FORMATS, TxType

                    try:
                        fmt = TX_FORMATS.get(TxType(v))
                        out[f.name] = fmt.name if fmt else v
                    except ValueError:
                        out[f.name] = v
                else:
                    out[f.name] = v
            elif k == K_HASH:
                out[f.name] = v.hex().upper()
            elif k == K_AMOUNT:
                out[f.name] = v.to_json()
            elif k == K_VL:
                out[f.name] = v.hex().upper()
            elif k == K_ACCOUNT:
                out[f.name] = encode_account_id(v)
            elif k == K_OBJECT:
                out[f.name] = v.to_json()
            elif k == K_ARRAY:
                out[f.name] = v.to_json()
            elif k == K_PATHSET:
                out[f.name] = v.to_json()
            elif k == K_VECTOR256:
                out[f.name] = [h.hex().upper() for h in v]
        return out


class STArray:
    """Array of named inner objects."""

    __slots__ = ("items",)

    def __init__(self, items: list[tuple[SField, STObject]] | None = None):
        self.items: list[tuple[SField, STObject]] = list(items or [])

    def append(self, f: SField, obj: STObject) -> None:
        self.items.append((f, obj))

    def __len__(self):
        return len(self.items)

    def __iter__(self):
        return iter(self.items)

    def __eq__(self, other):
        return isinstance(other, STArray) and self.items == other.items

    def serialize_to(self, s: Serializer) -> None:
        st = _get_stser()
        if st is not None:
            # item pairs ride the same native loop: K_OBJECT routes
            # through the container callback (header + body + end mark)
            s._buf += st.serialize(self.items, 0)
            return
        for f, obj in self.items:
            s._buf += f.header
            obj.serialize_to(s)
            s._buf += _OBJECT_END_B

    @classmethod
    def deserialize(cls, p: BinaryParser) -> "STArray":
        arr = cls()
        while True:
            type_id, name = p.read_field_id()
            if (type_id, name) == _ARRAY_END:
                return arr
            f = field_by_code(type_id, name)
            if f is None or f.type_id != STI.OBJECT:
                raise ValueError(f"bad array element field ({type_id}, {name})")
            arr.items.append((f, STObject.deserialize(p, inner=True)))

    def to_json(self):
        return [{f.name: obj.to_json()} for f, obj in self.items]
