"""STParsedJSON: client JSON → STObject.

Reference: src/ripple_data/protocol/STParsedJSON.cpp — maps field names
to SFields and parses values according to the field's serialized type;
transaction types and TER tokens may appear as their symbolic names.
"""

from __future__ import annotations

from typing import Any

from .formats import TX_FORMATS_BY_NAME
from .sfields import STI, SField, field_by_name
from .stamount import STAmount
from .stobject import STArray, STObject, STPathSet, PathElement

__all__ = ["parse_tx_json", "parse_st_json"]


class JsonParseError(ValueError):
    pass


def _parse_value(f: SField, v: Any) -> Any:
    t = f.type_id
    if t in (STI.UINT8, STI.UINT16, STI.UINT32, STI.UINT64):
        if isinstance(v, str):
            # symbolic TransactionType ("Payment") per reference
            if f.name == "TransactionType":
                fmt = TX_FORMATS_BY_NAME.get(v)
                if fmt is None:
                    raise JsonParseError(f"unknown TransactionType {v!r}")
                return fmt.type_code
            return int(v, 0)
        if not isinstance(v, int):
            raise JsonParseError(f"{f.name}: expected integer")
        return v
    if t in (STI.HASH128, STI.HASH160, STI.HASH256):
        b = bytes.fromhex(v)
        want = {STI.HASH128: 16, STI.HASH160: 20, STI.HASH256: 32}[t]
        if len(b) != want:
            raise JsonParseError(f"{f.name}: expected {want} bytes")
        return b
    if t == STI.AMOUNT:
        return STAmount.from_json(v)
    if t == STI.VL:
        return bytes.fromhex(v)
    if t == STI.ACCOUNT:
        from .keys import decode_account_id

        if isinstance(v, str) and len(v) == 40:
            try:
                return bytes.fromhex(v)
            except ValueError:
                pass
        return decode_account_id(v)
    if t == STI.OBJECT:
        return parse_st_json(v)
    if t == STI.ARRAY:
        arr = STArray()
        for elem in v:
            if not isinstance(elem, dict) or len(elem) != 1:
                raise JsonParseError(f"{f.name}: array elements are single-key objects")
            (name, body), = elem.items()
            inner_f = field_by_name(name)
            if inner_f is None:
                raise JsonParseError(f"unknown field {name!r}")
            arr.append(inner_f, parse_st_json(body))
        return arr
    if t == STI.PATHSET:
        return _parse_pathset(v)
    if t == STI.VECTOR256:
        return [bytes.fromhex(h) for h in v]
    raise JsonParseError(f"{f.name}: unsupported type {t}")


def _parse_pathset(v: Any) -> STPathSet:
    from .keys import decode_account_id
    from .stamount import currency_from_iso

    paths = []
    for path in v:
        elems = []
        for e in path:
            account = issuer = None
            currency = None
            if e.get("account"):
                account = decode_account_id(e["account"])
            if e.get("issuer"):
                issuer = decode_account_id(e["issuer"])
            if e.get("currency") is not None:
                iso = e["currency"]
                currency = bytes.fromhex(iso) if len(iso) == 40 else currency_from_iso(iso)
            elems.append(PathElement(account=account, currency=currency, issuer=issuer))
        paths.append(elems)
    return STPathSet(paths)


def parse_st_json(j: dict) -> STObject:
    obj = STObject()
    for name, v in j.items():
        if name in ("hash", "metaData"):  # computed, never parsed in
            continue
        f = field_by_name(name)
        if f is None:
            raise JsonParseError(f"unknown field {name!r}")
        obj[f] = _parse_value(f, v)
    return obj


def parse_tx_json(j: dict) -> STObject:
    """Parse a client tx_json (reference: STParsedJSON via
    RPC::transactionSign)."""
    if "TransactionType" not in j:
        raise JsonParseError("missing TransactionType")
    return parse_st_json(j)
