"""SerializedTransaction (STTx): a signed, typed transaction.

Reference: src/ripple_app/misc/SerializedTransaction.{h,cpp} —
getSigningHash (:162-165, HP_TX_SIGN prefix over the no-signature
serialization), sign (:185-190), checkSign (:192-230, the #1 north-star
hot call, memoized), getTransactionID (HP_TXN_ID over the full blob),
passesLocalChecks (:350-369).
"""

from __future__ import annotations

from typing import Optional

from ..utils.hashes import HP_TXN_ID, HP_TX_SIGN, prefix_hash
from .formats import TX_FORMATS, TxType, validate_against
from .keys import KeyPair, verify_signature
from .serializer import BinaryParser
from .sfields import (
    sfAccount,
    sfFee,
    sfFlags,
    sfSequence,
    sfSigningPubKey,
    sfTransactionType,
    sfTxnSignature,
)
from .stamount import STAmount
from .stobject import STObject
from ..utils.hashes import hash160

__all__ = ["SerializedTransaction"]


class SerializedTransaction:
    """Wraps the tx STObject with signing/verification and typed access."""

    def __init__(self, obj: STObject):
        self.obj = obj
        # memoized signature verdict (reference: mSigGood/mSigBad flags,
        # SerializedTransaction.h — the HashRouter SF_SIGGOOD seam)
        self._sig_good: Optional[bool] = None
        # (version, value) memos — txid/blob are recomputed several
        # times per tx along the submit->open-apply->close-apply path;
        # STObject._version keeps the cache safe across mutations
        self._blob_memo: Optional[tuple[int, bytes]] = None
        self._txid_memo: Optional[tuple[int, bytes]] = None
        self._tx_type_memo: Optional[tuple[int, TxType]] = None
        # passes_local_checks is a pure function of the object bytes and
        # runs once per apply — which, with the delta-replay close, is
        # twice per submit (open check pass + speculative close run)
        self._local_memo: Optional[tuple[int, tuple[bool, str]]] = None

    # -- construction -----------------------------------------------------

    @classmethod
    def build(cls, tx_type: TxType, account: bytes, sequence: int,
              fee: int, fields: Optional[dict] = None) -> "SerializedTransaction":
        obj = STObject()
        obj[sfTransactionType] = int(tx_type)
        obj[sfAccount] = account
        obj[sfSequence] = sequence
        obj[sfFee] = STAmount.from_drops(fee)
        for f, v in (fields or {}).items():
            obj[f] = v
        return cls(obj)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "SerializedTransaction":
        tx = cls(STObject.from_bytes(blob))
        # the received bytes ARE the serialization: the reference keeps
        # the raw Serializer and hashes getTransactionID over it, so a
        # parsed tx must never re-serialize (and txid must cover exactly
        # the wire bytes, even for a non-canonical peer encoding)
        tx._blob_memo = (tx.obj._version, blob)
        return tx

    @classmethod
    def from_parser(cls, p: BinaryParser) -> "SerializedTransaction":
        return cls(STObject.deserialize(p))

    # -- typed accessors --------------------------------------------------

    @property
    def tx_type(self) -> TxType:
        # enum construction is measurable at flood rates; version-guarded
        # like _blob_memo/_txid_memo (the public obj is mutable)
        memo = self._tx_type_memo
        ver = self.obj._version
        if memo is not None and memo[0] == ver:
            return memo[1]
        t = TxType(self.obj[sfTransactionType])
        self._tx_type_memo = (ver, t)
        return t

    @property
    def account(self) -> bytes:
        return self.obj[sfAccount]

    @property
    def sequence(self) -> int:
        return self.obj[sfSequence]

    @property
    def fee(self) -> STAmount:
        return self.obj.get(sfFee) or STAmount.from_drops(0)

    @property
    def flags(self) -> int:
        return self.obj.get(sfFlags, 0)

    @property
    def signing_pub_key(self) -> bytes:
        return self.obj.get(sfSigningPubKey, b"")

    @property
    def signature(self) -> bytes:
        return self.obj.get(sfTxnSignature, b"")

    # -- hashing / signing ------------------------------------------------

    def serialize(self) -> bytes:
        memo = self._blob_memo
        if memo is not None and memo[0] == self.obj._version:
            return memo[1]
        blob = self.obj.serialize()
        self._blob_memo = (self.obj._version, blob)
        return blob

    def signing_hash(self) -> bytes:
        """HP_TX_SIGN prefix hash over the signature-less serialization
        (reference: SerializedTransaction.cpp:162-165 via
        STObject::getSigningHash)."""
        return self.obj.signing_hash(HP_TX_SIGN)

    def txid(self) -> bytes:
        """HP_TXN_ID over the full (signed) blob
        (reference: getTransactionID — memoized here, versioned against
        object mutation)."""
        memo = self._txid_memo
        if memo is not None and memo[0] == self.obj._version:
            return memo[1]
        h = prefix_hash(HP_TXN_ID, self.serialize())
        self._txid_memo = (self.obj._version, h)
        return h

    def sign(self, key: KeyPair) -> None:
        """reference: SerializedTransaction::sign (:185-190)"""
        self.obj[sfSigningPubKey] = key.public
        self.obj[sfTxnSignature] = key.sign(self.signing_hash())
        self._sig_good = None

    def check_sign(self) -> bool:
        """Ed25519 verify of TxnSignature by SigningPubKey over the signing
        hash, canonical-S enforced; memoized (reference:
        SerializedTransaction::checkSign :192-230)."""
        if self._sig_good is None:
            self._sig_good = verify_signature(
                self.signing_pub_key, self.signing_hash(), self.signature
            )
        return self._sig_good

    def set_sig_verdict(self, good: bool) -> None:
        """Inject an externally-computed verdict (the batched TPU verifier
        path — same role as HashRouter SF_SIGGOOD memoization)."""
        self._sig_good = good

    # -- validity ---------------------------------------------------------

    def passes_local_checks(self) -> tuple[bool, str]:
        """Cheap structural checks before any state access
        (reference: passesLocalChecks, SerializedTransaction.cpp:350-369);
        memoized, versioned against object mutation."""
        memo = self._local_memo
        if memo is not None and memo[0] == self.obj._version:
            return memo[1]
        verdict = self._local_checks()
        self._local_memo = (self.obj._version, verdict)
        return verdict

    def _local_checks(self) -> tuple[bool, str]:
        fee = self.obj.get(sfFee)
        if fee is None or not fee.is_native or fee.negative:
            return False, "invalid fee"
        if sfAccount not in self.obj:
            return False, "no source account"
        if self.obj[sfAccount] == b"\x00" * 20:
            return False, "bad source account"
        fmt = TX_FORMATS.get(self.tx_type)
        if fmt is None:
            return False, "unknown transaction type"
        problems = validate_against(self.obj, fmt)
        if problems:
            return False, "; ".join(problems)
        return True, ""

    def __repr__(self):
        return (
            f"STTx({self.tx_type.name} acct={self.account.hex()[:8]} "
            f"seq={self.sequence})"
        )
