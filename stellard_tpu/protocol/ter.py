"""Transaction Engine Result codes (reference: src/ripple_data/protocol/TER.h).

Ranges (TER.h:180-186):
  tel  [-399,-300)  local error           — not applied, not forwarded
  tem  [-299,-200)  malformed             — reject, can never succeed
  tef  [-199,-100)  failure (ledger state)— not applied, not forwarded
  ter  [ -99,  -1)  retry                 — hold, retry next ledger
  tes  0            success
  tec  [100, 256)   claimed fee only      — applied, fee burned
"""

from __future__ import annotations

from enum import IntEnum


class TER(IntEnum):
    # -- local errors ------------------------------------------------------
    telLOCAL_ERROR = -399
    telBAD_DOMAIN = -398
    telBAD_PATH_COUNT = -397
    telBAD_PUBLIC_KEY = -396
    telFAILED_PROCESSING = -395
    telINSUF_FEE_P = -394
    telNO_DST_PARTIAL = -393
    telNOT_TIME = -392

    # -- malformed ---------------------------------------------------------
    temMALFORMED = -299
    temBAD_AMOUNT = -298
    temBAD_AUTH_MASTER = -297
    temBAD_CURRENCY = -296
    temBAD_FEE = -295
    temBAD_EXPIRATION = -294
    temBAD_ISSUER = -293
    temBAD_LIMIT = -292
    temBAD_OFFER = -291
    temBAD_PATH = -290
    temBAD_PATH_LOOP = -289
    temBAD_PUBLISH = -288
    temBAD_TRANSFER_RATE = -287
    temBAD_SEND_STR_LIMIT = -286
    temBAD_SEND_STR_MAX = -285
    temBAD_SEND_STR_NO_DIRECT = -284
    temBAD_SEND_STR_PARTIAL = -283
    temBAD_SEND_STR_PATHS = -282
    temBAD_SIGNATURE = -281
    temBAD_SRC_ACCOUNT = -280
    temBAD_SEQUENCE = -279
    temDST_IS_SRC = -278
    temDST_NEEDED = -277
    temINVALID = -276
    temINVALID_FLAG = -275
    temREDUNDANT = -274
    temREDUNDANT_SEND_MAX = -273
    temRIPPLE_EMPTY = -272
    temUNCERTAIN = -271
    temUNKNOWN = -270

    # -- failures ----------------------------------------------------------
    tefFAILURE = -199
    tefALREADY = -198
    tefBAD_ADD_AUTH = -197
    tefBAD_AUTH = -196
    tefBAD_CLAIM_ID = -195
    tefBAD_GEN_AUTH = -194
    tefBAD_LEDGER = -193
    tefCLAIMED = -192
    tefCREATED = -191
    tefDST_TAG_NEEDED = -190
    tefEXCEPTION = -189
    tefGEN_IN_USE = -188
    tefINTERNAL = -187
    tefNO_AUTH_REQUIRED = -186
    tefPAST_SEQ = -185
    tefWRONG_PRIOR = -184
    tefMASTER_DISABLED = -183
    tefMAX_LEDGER = -182

    # -- retry -------------------------------------------------------------
    terRETRY = -99
    terFUNDS_SPENT = -98
    terINSUF_FEE_B = -97
    terNO_ACCOUNT = -96
    terNO_AUTH = -95
    terNO_LINE = -94
    terOWNERS = -93
    terPRE_SEQ = -92
    terLAST = -91
    terNO_RIPPLE = -90
    # admission control (reference: rippled TxQ/FeeEscalation): the tx
    # was valid but paid less than the escalated open-ledger fee; it
    # waits in the fee-priority queue for a later ledger
    terQUEUED = -89

    # -- success -----------------------------------------------------------
    tesSUCCESS = 0

    # -- applied, fee claimed ----------------------------------------------
    tecCLAIM = 100
    tecPATH_PARTIAL = 101
    tecUNFUNDED_ADD = 102
    tecUNFUNDED_OFFER = 103
    tecUNFUNDED_PAYMENT = 104
    tecFAILED_PROCESSING = 105
    tecDIR_FULL = 121
    tecINSUF_RESERVE_LINE = 122
    tecINSUF_RESERVE_OFFER = 123
    tecNO_DST = 124
    tecNO_DST_INSUF_STR = 125
    tecNO_LINE_INSUF_RESERVE = 126
    tecNO_LINE_REDUNDANT = 127
    tecPATH_DRY = 128
    tecUNFUNDED = 129
    tecMASTER_DISABLED = 130
    tecNO_REGULAR_KEY = 131
    tecOWNERS = 132
    tecNO_ISSUER = 133
    tecNO_AUTH = 134
    tecNO_LINE = 135

    # -- class predicates (TER.h:180-186) ---------------------------------

    @property
    def is_tel(self) -> bool:
        return -399 <= self < -299

    @property
    def is_tem(self) -> bool:
        return -299 <= self < -199

    @property
    def is_tef(self) -> bool:
        return -199 <= self < -99

    @property
    def is_ter(self) -> bool:
        return -99 <= self < 0

    @property
    def is_tes(self) -> bool:
        return self == 0

    @property
    def is_tec(self) -> bool:
        return self >= 100

    @property
    def applied(self) -> bool:
        """Whether the result mutates the ledger (tes or tec)."""
        return self.is_tes or self.is_tec

    @property
    def token(self) -> str:
        return self.name

    @property
    def human(self) -> str:
        return _DESCRIPTIONS.get(self, self.name)


_DESCRIPTIONS = {
    TER.tesSUCCESS: "The transaction was applied.",
    TER.tefPAST_SEQ: "This sequence number has already past.",
    TER.terPRE_SEQ: "Missing/inapplicable prior transaction.",
    TER.terQUEUED: "Held until the open ledger fee drops or capacity frees.",
    TER.terNO_ACCOUNT: "The source account does not exist.",
    TER.terINSUF_FEE_B: "Account balance can't pay fee.",
    TER.temBAD_SIGNATURE: "A signature is provided for a non-signing field.",
    TER.temINVALID: "The transaction is ill-formed.",
    TER.tecUNFUNDED_PAYMENT: "Insufficient STR balance to send.",
    TER.tecNO_DST: "Destination does not exist. Send STR to create it.",
    TER.tecNO_DST_INSUF_STR: "Destination does not exist. Too little STR sent to create it.",
    TER.tecPATH_DRY: "Path could not send partial amount.",
    TER.tecPATH_PARTIAL: "Path could not send full amount.",
    TER.tecDIR_FULL: "Can not add entry to full directory.",
    TER.tecUNFUNDED_OFFER: "Offer is unfunded.",
    TER.tecINSUF_RESERVE_LINE: "Insufficient reserve to add trust line.",
    TER.tecINSUF_RESERVE_OFFER: "Insufficient reserve to create offer.",
}
