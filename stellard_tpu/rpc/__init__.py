"""Client API: JSON-RPC + WebSocket handlers and servers.

Reference layer L9 (SURVEY §1): src/ripple_rpc (60 handlers),
src/ripple_app/rpc (dispatch), src/ripple/http, src/ripple_app/websocket.
"""

from .errors import RPCError, rpc_error
from .handlers import dispatch, HANDLERS, Role
from .infosub import InfoSub, SubscriptionManager

__all__ = [
    "RPCError",
    "rpc_error",
    "dispatch",
    "HANDLERS",
    "Role",
    "InfoSub",
    "SubscriptionManager",
]
