"""RPC error taxonomy.

Reference: src/ripple_rpc (ErrorCodes.h) — errors render as
{error, error_code, error_message} inside a "status":"error" response.
"""

from __future__ import annotations

__all__ = ["RPCError", "rpc_error", "ERRORS"]

# (token, code, default message) — subset of reference ErrorCodes.h
ERRORS = {
    "unknownCmd": (26, "Unknown method."),
    "invalidParams": (27, "Invalid parameters."),
    "actNotFound": (15, "Account not found."),
    "actMalformed": (16, "Account malformed."),
    "lgrNotFound": (20, "Ledger not found."),
    "txnNotFound": (24, "Transaction not found."),
    "badSecret": (41, "Bad secret."),
    "badSeed": (42, "Disallowed seed."),
    "noPermission": (6, "You don't have permission for this command."),
    "notStandalone": (7, "Operation valid in standalone mode only."),
    "srcActMissing": (59, "Source account not provided."),
    "srcActMalformed": (60, "Source account malformed."),
    "dstActMissing": (61, "Destination account not provided."),
    "dstActMalformed": (62, "Destination account malformed."),
    "invalidTransaction": (74, "Transaction is invalid."),
    "internal": (71, "Internal error."),
    "notImpl": (72, "Not implemented."),
    "notSupported": (73, "Operation not supported."),
    "notSynced": (55, "Not synced to the network."),
    "lgrIdxInvalid": (57, "Ledger index below the retained history floor."),
    "transactionNotFound": (24, "Transaction not found."),
    "fieldNotFoundTransaction": (63, "Field 'transaction' not found."),
    # resource pricing on the RPC doors (reference rpcSLOW_DOWN): the
    # client's charge balance crossed the drop line — requests refuse
    # until it decays
    "slowDown": (10, "You are placing too much load on the server."),
}


class RPCError(Exception):
    def __init__(self, token: str, message: str | None = None, **extra):
        code, default_msg = ERRORS.get(token, (71, token))
        self.token = token
        self.code = code
        self.message = message or default_msg
        self.extra = extra
        super().__init__(self.message)

    def to_json(self) -> dict:
        out = {
            "error": self.token,
            "error_code": self.code,
            "error_message": self.message,
        }
        out.update(self.extra)
        return out


def rpc_error(token: str, message: str | None = None, **extra) -> dict:
    return RPCError(token, message, **extra).to_json()
