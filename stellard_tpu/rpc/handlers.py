"""RPC method handlers + dispatch.

Reference: src/ripple_rpc/handlers/*.cpp (60 handlers) dispatched by
RPCHandler::doCommand (src/ripple_app/rpc/RPCHandler.cpp) with per-method
role requirements (ADMIN/GUEST). The same handler table serves HTTP
JSON-RPC and WebSocket commands, as in the reference.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any, Callable, Optional

from ..protocol.formats import LedgerEntryType
from ..protocol.keys import (
    KeyPair,
    decode_account_id,
    encode_account_id,
    encode_node_public,
    encode_seed,
)
from ..engine.flags import (
    lsfHighAuth,
    lsfHighNoRipple,
    lsfLowAuth,
    lsfLowNoRipple,
)
from ..protocol.sfields import (
    sfAccount,
    sfBalance,
    sfFlags,
    sfHighLimit,
    sfHighQualityIn,
    sfHighQualityOut,
    sfLedgerEntryType,
    sfLowLimit,
    sfLowQualityIn,
    sfLowQualityOut,
    sfOwnerCount,
    sfRegularKey,
    sfSequence,
    sfTakerGets,
    sfTakerPays,
)
from ..protocol.stamount import STAmount, currency_from_iso, iso_from_currency
from ..protocol.stobject import STObject
from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state import indexes
from ..state.entryset import LedgerEntrySet
from ..state.ledger import Ledger
from ..state.shamap import MissingNodeError
from .errors import RPCError
from .infosub import InfoSub, SubscriptionManager
from .txsign import transaction_sign

__all__ = ["Role", "HANDLERS", "dispatch", "Context"]


class Role(IntEnum):
    GUEST = 0
    ADMIN = 1


@dataclass
class Context:
    node: Any
    params: dict
    role: Role = Role.ADMIN
    infosub: Optional[InfoSub] = None
    subs: Optional[SubscriptionManager] = None
    # set by the result-cache wrapper (rpc/readplane.py): the exact
    # validated ledger this request was keyed against — _select_ledger
    # resolves "validated" to it so the computed result always matches
    # its cache key even if the tip advances mid-request
    pinned_validated: Any = None


HANDLERS: dict[str, tuple[Callable[[Context], dict], Role]] = {}


def handler(name: str, role: Role = Role.GUEST):
    def deco(fn):
        HANDLERS[name] = (fn, role)
        return fn

    return deco


def dispatch(ctx: Context, method: str) -> dict:
    """-> result dict; error results carry {"error": ...} (reference:
    RPCHandler::doCommand wraps into status:error).

    The hot read RPCs route through the validated-seq result cache
    (rpc/readplane.py) when the request targets validated state — a
    cache entry is immutable by construction (a validated ledger never
    changes), invalidated wholesale by the next validated seq."""
    entry = HANDLERS.get(method)
    if entry is None:
        return RPCError("unknownCmd").to_json()
    fn, need_role = entry
    if need_role == Role.ADMIN and ctx.role != Role.ADMIN:
        return RPCError("noPermission").to_json()
    try:
        from .readplane import cached_dispatch

        return cached_dispatch(ctx, method, lambda: fn(ctx))
    except RPCError as exc:
        return exc.to_json()
    except MissingNodeError as exc:
        # a lazily-opened historical ledger faulted a node the store no
        # longer holds (online-deletion sweep retired it mid-cache-life)
        # — that is "this history is gone", not an internal error
        return RPCError(
            "lgrNotFound",
            f"historical state no longer retained ({exc})",
        ).to_json()
    except Exception as exc:  # noqa: BLE001 — handler bug must not kill the door
        import traceback

        traceback.print_exc()
        return RPCError("internal", str(exc)).to_json()


# -- RPC resource pricing (doc/overlay.md charging schedule) ---------------
#
# Every non-admin request charges its client's endpoint with the SAME
# fee schedule the peer overlay uses (overlay/resource.py FEE_*_RPC):
# burden-classed per method, extra on malformed/unknown requests, WARN
# is advisory (rpc_warning attaches `warning: "load"` to responses —
# the reference's load warning), DROP refuses with rpcSLOW_DOWN until
# the balance decays. Admin-allowed IPs are exempt.

def rpc_method_fee(method: Optional[str]):
    from ..overlay.resource import (
        FEE_HIGH_BURDEN_RPC,
        FEE_INVALID_RPC,
        FEE_LOW_BURDEN_RPC,
        FEE_MEDIUM_BURDEN_RPC,
        FEE_PATH_FIND,
        FEE_REFERENCE_RPC,
    )

    if not method or method not in HANDLERS:
        return FEE_INVALID_RPC
    if method in ("server_info", "server_state", "fee", "ping", "random"):
        return FEE_REFERENCE_RPC          # cheap reference data
    if method in ("path_find", "ripple_path_find"):
        return FEE_PATH_FIND              # full candidate search + trials
    if method in ("account_tx", "ledger", "ledger_data", "book_offers",
                  "subscribe"):
        return FEE_MEDIUM_BURDEN_RPC      # history walks / tree dumps
    if method in ("sign", "submit"):
        return FEE_HIGH_BURDEN_RPC if method == "sign" else (
            FEE_LOW_BURDEN_RPC            # submit: verify + apply work
        )
    return FEE_REFERENCE_RPC


def charge_rpc_client(node, client_ip: str, method: Optional[str],
                      role: Role) -> Optional[dict]:
    """Charge one inbound RPC request against its client's balance.
    Returns an error-result dict when the request must be REFUSED
    (balance at/above the drop line), else None. Admin-role requests
    and admin-exempt IPs are never charged."""
    rm = getattr(node, "rpc_resources", None)
    if rm is None or not client_ip or role == Role.ADMIN:
        return None
    from ..overlay.resource import Disposition

    addr = (client_ip, 0)
    if not rm.should_admit(addr):
        rm.note_refused(addr)
        return RPCError("slowDown").to_json()
    if rm.charge(addr, rpc_method_fee(method)) == Disposition.DROP:
        rm.note_disconnect()
        return RPCError("slowDown").to_json()
    return None


def rpc_warning(node, client_ip: str, role: Role) -> Optional[str]:
    """Advisory back-off signal for a served request: "load" while the
    client's balance sits in WARN (the doors attach it to the response
    so a client can slow down BEFORE it gets hard-refused)."""
    rm = getattr(node, "rpc_resources", None)
    if rm is None or not client_ip or role == Role.ADMIN:
        return None
    return "load" if rm.is_throttled((client_ip, 0)) else None


# -- helpers ---------------------------------------------------------------


def _parse_account(params: dict, key: str = "account") -> bytes:
    v = params.get(key)
    if not v:
        raise RPCError("srcActMissing" if key == "account" else "invalidParams")
    try:
        return decode_account_id(v)
    except (ValueError, KeyError) as exc:
        raise RPCError("actMalformed") from exc


def _load_historical(ctx: Context, ledger_hash: bytes) -> Optional[Ledger]:
    """In-memory miss -> rebuild from the NodeStore (the history cache is
    bounded/aged, but persisted ledgers stay queryable forever). The
    rebuilt ledger re-enters the cache so a polling client only pays the
    reconstruction once."""
    try:
        # lazy: an RPC touching one account of a historical ledger must
        # not deserialize the whole tree (out-of-core plane); cold: its
        # faults enter the hot cache one epoch behind, so a deep
        # history scan cannot thrash the serving snapshot's working set
        led = Ledger.load(
            ctx.node.nodestore, ledger_hash, hash_batch=ctx.node.hasher,
            lazy=True, cold=True,
        )
    except (KeyError, ValueError, AttributeError):
        return None
    ctx.node.ledger_master.ledgers_by_hash.put(ledger_hash, led)
    return led


def _select_ledger(ctx: Context) -> Ledger:
    """reference: RPC::lookupLedger (impl/LookupLedger.cpp) — by
    ledger_hash, numeric ledger_index, or current|closed|validated.

    Read RPCs never take the chain lock here (pinned by test): the
    current/closed/validated tips resolve from bare attribute reads —
    the chain swaps whole immutable objects under its own lock, so a
    racing reader sees either tip, both complete — and "validated"
    prefers the read plane's published snapshot (the pointer
    publish_closed_ledger hands the serving side). A follower serves
    the VALIDATED snapshot for selector-less requests (doc/follower.md
    consistency contract)."""
    lm = ctx.node.ledger_master
    p = ctx.params
    if p.get("ledger_hash"):
        h = bytes.fromhex(p["ledger_hash"])
        led = lm.get_ledger_by_hash(h) or _load_historical(ctx, h)
        if led is None:
            raise RPCError("lgrNotFound")
        return led
    idx = p.get("ledger_index")
    if idx is None:
        idx = (
            "validated"
            if getattr(ctx.node, "serve_validated_default", False)
            else "current"
        )
    if isinstance(idx, int) or (isinstance(idx, str) and idx.isdigit()):
        led = lm.get_ledger_by_seq(int(idx))
        if led is None:
            # read-your-writes: a closed-but-not-yet-persisted ledger
            # resolves from its in-flight close-pipeline entry
            pipeline = getattr(ctx.node, "close_pipeline", None)
            if pipeline is not None:
                led = pipeline.get_by_seq(int(idx))
        if led is None:
            hdr = ctx.node.txdb.get_ledger_header(seq=int(idx))
            if hdr is not None:
                led = _load_historical(ctx, hdr["hash"])
        if led is None:
            raise RPCError("lgrNotFound")
        return led
    if idx == "current":
        led = lm.current
        if led is None:
            raise RPCError("lgrNotFound")
        return led
    if idx == "closed":
        led = lm.closed
        if led is None:
            raise RPCError("lgrNotFound")
        return led
    if idx == "validated":
        from .readplane import serving_validated

        led = ctx.pinned_validated
        if led is None:
            led = serving_validated(ctx.node)
        if led is None:
            raise RPCError("lgrNotFound")
        return led
    raise RPCError("invalidParams", f"bad ledger_index {idx!r}")


def _ledger_ident(led: Ledger) -> dict:
    out: dict[str, Any] = {"ledger_index": led.seq}
    if led.closed:
        out["ledger_hash"] = led.hash().hex().upper()
    else:
        out["ledger_current_index"] = led.seq
    return out


def _tx_entries(led: Ledger):
    """Yield (txid, tx, meta_blob) from a ledger's tx map."""
    for txid, blob, meta in led.tx_entries():
        yield txid, SerializedTransaction.from_bytes(blob), meta


# -- basics ----------------------------------------------------------------


@handler("ping")
def do_ping(ctx: Context) -> dict:
    return {}


@handler("random")
def do_random(ctx: Context) -> dict:
    return {"random": os.urandom(32).hex().upper()}


@handler("wallet_propose")
def do_wallet_propose(ctx: Context) -> dict:
    """reference: handlers/WalletPropose.cpp — random or passphrase seed."""
    passphrase = ctx.params.get("passphrase")
    kp = (
        KeyPair.from_passphrase(passphrase) if passphrase else KeyPair.random()
    )
    return {
        "master_seed": kp.human_seed,
        "master_seed_hex": kp.seed.hex().upper(),
        "account_id": kp.human_account_id,
        "public_key": kp.human_account_public,
        "public_key_hex": kp.public.hex().upper(),
    }


@handler("validation_create", Role.ADMIN)
def do_validation_create(ctx: Context) -> dict:
    """reference: handlers/ValidationCreate.cpp"""
    passphrase = ctx.params.get("secret")
    kp = (
        KeyPair.from_passphrase(passphrase) if passphrase else KeyPair.random()
    )
    return {
        "validation_key": passphrase or "",
        "validation_public_key": kp.human_node_public,
        "validation_seed": kp.human_seed,
    }


@handler("validation_seed", Role.ADMIN)
def do_validation_seed(ctx: Context) -> dict:
    node = ctx.node
    if not node.validation_keys:
        return {"message": "not a validator"}
    return {
        "validation_public_key": node.validation_keys.human_node_public,
        "validation_seed": node.validation_keys.human_seed,
    }


# -- server introspection --------------------------------------------------


@handler("server_info")
def do_server_info(ctx: Context) -> dict:
    """reference: handlers/ServerInfo.cpp via NetworkOPs::getServerInfo"""
    node = ctx.node
    lm = node.ledger_master
    lcl = lm.closed_ledger()
    # the validated ledger is the QUORUM-confirmed one — reporting the
    # LCL here would claim agreement the net has not reached (closed
    # chains legitimately diverge until validations land)
    val = lm.validated if lm.validated is not None else lcl
    from ..utils.rfc1751 import word_from_blob

    info = {
        "build_version": "stellard-tpu 0.1.0",
        # one RFC 1751 dictionary word naming this node — the reference
        # derives it from the node address (NetworkOPs.cpp:1696,
        # RFC1751::getWordFromBlob); here from the node identity key
        "hostid": word_from_blob(node.node_keys.public),
        "server_state": node.ops.server_state(),
        "complete_ledgers": _complete_ledgers(node),
        "peers": (
            node.overlay.peer_count()
            if getattr(node, "overlay", None) is not None
            else 0
        ),
        "load_factor": node.fee_track.load_factor / 256.0,
        "load_base": 256,
        "signature_backend": node.config.signature_backend,
        "validation_quorum": node.config.validation_quorum,
        "validated_ledger": {
            "seq": val.seq,
            "hash": val.hash().hex().upper(),
            "close_time": val.close_time,
            "base_fee_str": str(val.base_fee),
            "reserve_base_str": str(val.reserve_base),
            "reserve_inc_str": str(val.reserve_increment),
        },
        "closed_ledger": {
            "seq": lcl.seq,
            "hash": lcl.hash().hex().upper(),
        },
        # node identity vs validator key, as the reference splits them
        # (NetworkOPs.cpp:1721-1726): pubkey_node is the persisted
        # LocalCredentials identity; pubkey_validator is "none" for
        # non-validators
        "pubkey_node": node.node_keys.human_node_public,
        "pubkey_validator": (
            node.validation_keys.human_node_public
            if node.validation_keys
            else "none"
        ),
        "uptime": int(time.monotonic() - node.started_at),
    }
    return {"info": info}


def _complete_ledgers(node) -> str:
    seqs = sorted(node.ledger_master.ledger_history)
    if not seqs:
        return "empty"
    return f"{seqs[0]}-{seqs[-1]}" if len(seqs) > 1 else str(seqs[0])


@handler("server_state")
def do_server_state(ctx: Context) -> dict:
    node = ctx.node
    state = {
        "server_state": node.ops.server_state(),
        "complete_ledgers": _complete_ledgers(node),
        "peers": 0,
        "load_base": 256,
        "load_factor": node.fee_track.load_factor,
    }
    pipeline = getattr(node, "close_pipeline", None)
    if pipeline is not None:
        # per-stage latency histograms + queue-depth gauges for the
        # ledger-close persistence pipeline
        state["close_pipeline"] = pipeline.get_json()
    # storage plane: aggregate counters only (appends, bytes, fsyncs,
    # fetch hit/miss, segments, live ratio, compaction/sweep counts —
    # no filesystem paths on a GUEST-reachable method)
    state["node_store"] = node.nodestore.get_json()
    deleter = getattr(node, "online_deleter", None)
    if deleter is not None:
        state["node_store"]["online_delete"] = deleter.get_json()
    # delta-replay close: spliced/fallback/invalidation counters +
    # close-stage (apply/seal/total) latency percentiles
    state["delta_replay"] = node.ledger_master.delta_replay_json()
    # batched state-tree commit plane: merges, pre-hash drains, seal
    # adoptions (aggregate counters only — no per-tx detail to gate)
    state["tree"] = node.ledger_master.tree_json()
    spec_ex = getattr(node, "spec_executor", None)
    if spec_ex is not None:
        # parallel speculation plane: worker pool + scheduler counters
        # (dispatched/committed/retries/aborts — aggregate only)
        state["spec"] = spec_ex.get_json()
    txq = getattr(node, "txq", None)
    if txq is not None:
        # admission-control plane: queue depth, soft cap, escalated
        # open-ledger fee level (aggregate only — no txids)
        state["txq"] = txq.get_json()
    # read plane: serving snapshot seq + result-cache hit rates
    # (aggregate counters only — no params/keys on a GUEST method)
    cache = getattr(node, "read_cache", None)
    if cache is not None:
        state["read_cache"] = cache.get_json()
    tracer = getattr(node, "tracer", None)
    if tracer is not None:
        # tracing plane status; the consensus/close timeline is ADMIN
        # only — its events carry txids and peer key prefixes, which a
        # GUEST-reachable method must not leak (trace_status/trace_dump
        # serve the full detail behind the ADMIN gate)
        state["trace"] = tracer.status_json(
            timeline=(ctx.role == Role.ADMIN)
        )
    health = getattr(node, "health", None)
    if health is not None:
        # SLO watchdog verdict (node/health.py): status + reason
        # strings are aggregate-only — safe on a GUEST-reachable method
        state["health"] = health.get_json()
    return {"state": state}


@handler("fee")
def do_fee(ctx: Context) -> dict:
    """Admission-control fee oracle (reference: rippled's `fee` method,
    handlers/Fee1.cpp): current open-ledger size vs the adaptive soft
    cap, queue occupancy, and the fee (drops + 1/256 levels) required
    to enter the open ledger right now."""
    node = ctx.node
    led = node.ledger_master.current_ledger()
    txq = getattr(node, "txq", None)
    if txq is None:
        # load-factor-only fallback (no admission plane wired)
        base = led.base_fee
        factor = node.fee_track.load_factor if node.fee_track else 256
        return {
            "drops": {
                "base_fee": str(base),
                "minimum_fee": str(base),
                "open_ledger_fee": str(base * factor // 256),
            },
            "levels": {
                "reference_level": "256",
                "open_ledger_level": str(factor),
            },
            "ledger_current_index": led.seq,
        }
    out = txq.fee_json(led)
    out["enabled"] = txq.enabled
    return out


def _crypto_json(node) -> dict:
    """The get_counts crypto block: devices seen, per-plane mesh
    provenance (requested/effective width, kernel selected, routing
    mode) and cost-model snapshots. jax is only consulted when some
    subsystem already initialized it — a cpu-backend node must not pay
    device discovery for a counters RPC."""
    import sys as _sys

    vp = node.verify_plane.get_json()
    out: dict = {
        "verify": {
            "backend": vp.get("backend"),
            "routing": vp.get("routing"),
            "mesh": vp.get("mesh"),
            "arms": vp.get("arms"),
            "model": vp.get("model"),
            "device_sigs": vp.get("device_sigs"),
            "cpu_sigs": vp.get("cpu_sigs"),
            "transfers": vp.get("transfers"),
        },
    }
    hasher = getattr(node, "hasher", None)
    hj = getattr(hasher, "get_json", None)
    if hj is not None:
        out["hash"] = hj()
    else:
        out["hash"] = {
            "backend": getattr(hasher, "name", None),
            "device_nodes": getattr(hasher, "device_nodes", 0),
            "host_nodes": getattr(hasher, "host_nodes", 0),
        }
    # transfer honesty (ISSUE 16): total host<->device traffic across
    # both planes — per-close deltas of transfers/bytes_moved are the
    # device-residency proof a BENCH reader gates on
    total_t = 0
    total_b = 0
    for block in (vp.get("transfers"), out["hash"].get("transfers")):
        if isinstance(block, dict):
            total_t += int(block.get("transfers", 0))
            total_b += int(block.get("bytes_moved", 0))
    out["transfers"] = total_t
    out["bytes_moved"] = total_b
    jx = _sys.modules.get("jax")
    if jx is not None:
        try:
            out["devices"] = [str(d) for d in jx.devices()]
        except Exception:  # noqa: BLE001 — counters must never fail the RPC
            out["devices"] = "unavailable"
    else:
        out["devices"] = "jax-uninitialized"
    return out


@handler("get_counts", Role.ADMIN)
def do_get_counts(ctx: Context) -> dict:
    """reference: handlers/GetCounts.cpp — object/op counters."""
    node = ctx.node
    hist = node.ledger_master.ledgers_by_hash
    out = {
        "jobq": node.job_queue.get_json(),
        "verify_plane": node.verify_plane.get_json(),
        # crypto-plane routing honesty (ISSUE 15): devices actually
        # seen, mesh width / kernel selected per plane, and the
        # three-arm (host/1-chip/N-chip) cost-model snapshots — the
        # counters BENCH lines and operators read to know what ran
        "crypto": _crypto_json(node),
        "hash_router": node.hash_router.size(),
        "ledgers_cached": len(hist),
        "ledger_cache": {
            "hits": hist.hits,
            "misses": hist.misses,
            "target_size": hist.target_size,
        },
    }
    pipeline = getattr(node, "close_pipeline", None)
    if pipeline is not None:
        out["close_pipeline"] = pipeline.get_json()
        out["persist_backlog"] = pipeline.pending()
    txq = getattr(node, "txq", None)
    if txq is not None:
        # admission-control plane: queue depth/caps + admit/evict/
        # promote counters incl. the queue-aware-speculation split
        out["txq"] = txq.get_json()
    # storage plane: façade cache + backend stats (segstore: segments,
    # live ratio, appends/fsyncs, checkpoint/compaction/sweep counters)
    out["node_store"] = node.nodestore.get_json()
    deleter = getattr(node, "online_deleter", None)
    if deleter is not None:
        out["node_store"]["online_delete"] = deleter.get_json()
    out["held"] = {
        "count": len(node.ledger_master.held),
        **node.ledger_master.held_stats,
    }
    out["delta_replay"] = node.ledger_master.delta_replay_json()
    # batched state-tree commit plane: bulk merges, background pre-hash
    # drains, seal adoptions (node/ledgermaster.py tree_json)
    out["tree"] = node.ledger_master.tree_json()
    spec_ex = getattr(node, "spec_executor", None)
    if spec_ex is not None:
        # parallel speculation plane (engine/specexec.py)
        out["spec"] = spec_ex.get_json()
    # out-of-core state plane: the bounded hot-node cache — hit/miss/
    # fault/evict + resident_bytes evidence for the lazy-faulting tier
    # (state/hotcache.py; [tree] cache_mb)
    from ..state.shamap import inner_node_cache

    out["shamap_inner_cache"] = inner_node_cache().get_json()
    # history-shard tier: sealed ranges + cold-read counters
    shardstore = getattr(node, "shardstore", None)
    if shardstore is not None:
        out["history_shards"] = shardstore.get_json()
    # subscription-fanout plane (`subs.*`): shards, bounded-queue drops,
    # slow-consumer evictions, publish→deliver lag, HTTP-push stats
    subs = getattr(node, "subs", None)
    if subs is not None:
        out["subs"] = subs.get_json()
    # validated-seq result cache + serving snapshot (rpc/readplane.py)
    cache = getattr(node, "read_cache", None)
    if cache is not None:
        out["read_cache"] = cache.get_json()
    plane = getattr(node, "read_plane", None)
    if plane is not None:
        out["read_plane"] = plane.get_json()
    # liquidity plane (`paths.*`): incremental index continuity, per-
    # close re-rank/shed counts, staleness quantiles, evaluator routing
    path_plane = getattr(node, "path_plane", None)
    if path_plane is not None:
        out["paths"] = path_plane.get_json()
    tracer = getattr(node, "tracer", None)
    if tracer is not None:
        out["trace"] = tracer.status_json()  # ADMIN method: timeline ok
    # resource-pricing plane (`resource.*`): per-endpoint charge
    # balances + warn/drop/refuse/throttle evidence for the peer
    # overlay and the RPC doors (doc/overlay.md charging schedule)
    resource: dict = {}
    rpc_rm = getattr(node, "rpc_resources", None)
    if rpc_rm is not None:
        resource["rpc"] = rpc_rm.get_json()
    overlay = getattr(node, "overlay", None)
    if overlay is not None:
        resource["peers"] = overlay.resources.get_json()
        # squelch plane (`squelch.*`): relay fan-out bound evidence +
        # sendq shedding (doc/overlay.md degradation contract)
        out["squelch"] = overlay.squelch_json()
        out["peers"] = overlay.peer_count()
        vn = getattr(overlay, "node", None)
        if vn is not None:
            if getattr(vn, "follower", False):
                # follower ingest plane: ledgers adopted, validation-
                # seen -> adopted latency, live acquisitions, segfetch
                out["follower"] = vn.follower_json()
            sb = getattr(vn, "shard_backfill", None)
            if sb is not None:
                # archive tier (doc/archive.md): backfill session
                # state + the verified floor gating the forever cache
                out["archive"] = {
                    "backfill": sb.get_json(),
                    "verified_floor": (
                        plane.archive_floor if plane is not None else 0
                    ),
                    "txdb": node.txdb.counts(),
                }
            # byzantine-defense counters: hostile inputs recognized and
            # neutralized (bad sigs, equivocation, oversized/forged
            # txsets, malformed frames, garbage segments)
            defense = getattr(vn, "defense", None)
            if defense is not None:
                out["byzantine"] = defense.snapshot()
            # catch-up acquisition plane: live tree acquisitions plus
            # the segment bulk path's timeout/retry/backoff counters
            acq = {
                "inbound_live": len(vn.inbound.live),
            }
            sc = getattr(vn, "segment_catchup", None)
            if sc is not None:
                acq["segfetch"] = sc.get_json()
            out["acquisition"] = acq
    if resource:
        out["resource"] = resource
    # SLO health plane: watchdog verdict + flight-recorder occupancy
    # and the dump paths written this process (node/health.py)
    health = getattr(node, "health", None)
    if health is not None:
        out["health"] = health.get_json()
    flight = getattr(node, "flight", None)
    if flight is not None:
        out["flight"] = flight.get_json()
    return out


@handler("trace_status", Role.ADMIN)
def do_trace_status(ctx: Context) -> dict:
    """Tracing-plane status: [trace] knobs, ring occupancy, span-derived
    per-stage latency quantiles, and the recent consensus/close
    timeline."""
    return {"trace": ctx.node.tracer.status_json()}


@handler("trace_dump", Role.ADMIN)
def do_trace_dump(ctx: Context) -> dict:
    """Dump the span ring as Chrome trace-event JSON — loadable directly
    in Perfetto / chrome://tracing (tools/traceview.py wraps fetch +
    schema validation). Params: {"reset": true} drains atomically —
    snapshot + ring clear under one lock hold — so successive dumps
    window cleanly with no span lost between windows."""
    return ctx.node.tracer.chrome_trace(
        reset=bool(ctx.params.get("reset"))
    )


@handler("metrics_history", Role.ADMIN)
def do_metrics_history(ctx: Context) -> dict:
    """The embedded metric time-series ring ([insight] history_interval/
    history_window, node/metrics.py MetricsHistory): bounded in-process
    snapshots of every instrument, queryable without external scrape
    infrastructure. Params: {"since": <ts>} lower-bounds snapshot wall
    time, {"limit": N} keeps only the newest N rows."""
    try:
        since = float(ctx.params.get("since", 0.0))
        limit = int(ctx.params.get("limit", 0))
    except (TypeError, ValueError):
        return {"error": "invalidParams"}
    return ctx.node.collector.history_json(since=since, limit=limit)


@handler("health", Role.ADMIN)
def do_health(ctx: Context) -> dict:
    """SLO watchdog verdict + flight-recorder state (node/health.py).
    The watchdog block rides NESTED: the RPC envelope owns the top-level
    `status` key and would clobber the health verdict."""
    node = ctx.node
    out: dict = {"enabled": node.health is not None}
    if node.health is not None:
        out["health"] = node.health.get_json()
    flight = getattr(node, "flight", None)
    if flight is not None:
        out["flight"] = flight.get_json()
    return out


@handler("consensus_info", Role.ADMIN)
def do_consensus_info(ctx: Context) -> dict:
    node = ctx.node
    info = {
        "standalone": node.config.standalone,
        "validation_quorum": node.config.validation_quorum,
    }
    overlay = getattr(node, "overlay", None)
    if overlay is not None:
        # live round state (reference: LedgerConsensus::getJson via
        # NetworkOPs::getConsensusInfo), read under the master lock
        with overlay.node.lock:
            info.update(overlay.node.consensus_info())
    return {"info": info}


@handler("peers", Role.ADMIN)
def do_peers(ctx: Context) -> dict:
    overlay = getattr(ctx.node, "overlay", None)
    if overlay is None:
        return {"peers": []}
    return {"peers": overlay.peers_json(), "slots": overlay.slots_json()}


@handler("stop", Role.ADMIN)
def do_stop(ctx: Context) -> dict:
    ctx.node._running.clear()
    return {"message": "stellard server stopping"}


@handler("log_level", Role.ADMIN)
def do_log_level(ctx: Context) -> dict:
    """reference: handlers/LogLevel.cpp — read current levels, or set
    the base severity / one partition's severity. Every logger in this
    tree lives under the "stellard" hierarchy (stellard.device,
    stellard.netops, ...), so the base set covers them all; a
    `partition` narrows to stellard.<partition>. (The handler
    previously set a logger name nothing logs to — no effect at all.)"""
    import logging

    levels = {
        "trace": logging.DEBUG,
        "debug": logging.DEBUG,
        "info": logging.INFO,
        "warn": logging.WARNING,
        "warning": logging.WARNING,
        "error": logging.ERROR,
        "fatal": logging.CRITICAL,
    }
    severity = ctx.params.get("severity")
    if severity:
        if severity not in levels:
            raise RPCError("invalidParams", f"unknown severity {severity!r}")
        partition = ctx.params.get("partition")
        if partition:
            if partition not in _LOG_PARTITIONS:
                # a typo'd name would silently create a phantom logger
                # nothing logs to (and pollute reads forever)
                raise RPCError(
                    "invalidParams", f"unknown partition {partition!r}"
                )
            name = f"stellard.{partition}"
        else:
            name = "stellard"
        logging.getLogger(name).setLevel(levels[severity])
        return {}
    base = logging.getLogger("stellard")
    out = {"base": logging.getLevelName(base.getEffectiveLevel()).lower()}
    # snapshot: lazy first-time getLogger() in another thread mutates
    # loggerDict mid-iteration otherwise
    for name, logger in list(logging.root.manager.loggerDict.items()):
        if name.startswith("stellard.") and isinstance(
            logger, logging.Logger
        ) and logger.level != logging.NOTSET:
            out[name.removeprefix("stellard.")] = logging.getLevelName(
                logger.level
            ).lower()
    return {"levels": out}


# the known log partitions (stellard.<name>) — a static allowlist, not
# an existence check: several of these loggers are created lazily in
# rare error paths, and an operator must be able to raise their
# verbosity BEFORE the event they want to capture
_LOG_PARTITIONS = frozenset({
    "device", "netops", "node", "validator", "unl", "cleaner", "fatal",
})


@handler("feature", Role.ADMIN)
def do_feature(ctx: Context) -> dict:
    return {"features": {}}


# -- ledger inspection -----------------------------------------------------


@handler("ledger_current")
def do_ledger_current(ctx: Context) -> dict:
    return {
        "ledger_current_index": ctx.node.ledger_master.current_ledger().seq
    }


@handler("ledger_closed")
def do_ledger_closed(ctx: Context) -> dict:
    lcl = ctx.node.ledger_master.closed_ledger()
    return {
        "ledger_index": lcl.seq,
        "ledger_hash": lcl.hash().hex().upper(),
    }


def _ledger_header_json(led: Ledger, full_txs: bool = False) -> dict:
    out = {
        "seqNum": str(led.seq),
        "ledger_index": str(led.seq),
        "parent_hash": led.parent_hash.hex().upper(),
        "total_coins": str(led.tot_coins),
        "fee_pool": str(led.fee_pool),
        "inflation_seq": str(led.inflation_seq),
        "close_time": led.close_time,
        "parent_close_time": led.parent_close_time,
        "close_time_resolution": led.close_resolution,
        "close_flags": led.close_flags,
        "closed": led.closed,
        "transaction_hash": led.tx_hash.hex().upper(),
        "account_hash": led.account_hash.hex().upper(),
    }
    if led.closed:
        out["ledger_hash"] = led.hash().hex().upper()
        out["hash"] = out["ledger_hash"]
        out["accepted"] = led.accepted
    return out


@handler("ledger")
def do_ledger(ctx: Context) -> dict:
    led = _select_ledger(ctx)
    out = {"ledger": _ledger_header_json(led)}
    if ctx.params.get("transactions"):
        expand = bool(ctx.params.get("expand"))
        txs = []
        for txid, tx, meta in _tx_entries(led):
            if expand:
                j = tx.obj.to_json()
                j["hash"] = txid.hex().upper()
                if meta:
                    j["metaData"] = STObject.from_bytes(meta).to_json()
                txs.append(j)
            else:
                txs.append(txid.hex().upper())
        out["ledger"]["transactions"] = txs
    if ctx.params.get("accounts"):
        out["ledger"]["accountState"] = [
            STObject.from_bytes(leaf.item.data).to_json()
            for leaf in led.state_map.leaves()
        ]
    return out


@handler("ledger_data")
def do_ledger_data(ctx: Context) -> dict:
    """Paginated full-state dump (reference: handlers/LedgerData.cpp)."""
    led = _select_ledger(ctx)
    limit = min(int(ctx.params.get("limit", 256)), 2048)
    marker = ctx.params.get("marker")
    start = bytes.fromhex(marker) if marker else b"\x00" * 32
    out_state = []
    next_marker = None
    cursor = start if marker else None
    n = 0
    while n < limit:
        item = led.state_map.succ(cursor) if cursor is not None else led.state_map.succ(b"\x00" * 32)
        # succ is strictly-greater; seed the first call one below
        if item is None:
            break
        cursor = item.tag
        out_state.append(
            {
                "index": item.tag.hex().upper(),
                "data": item.data.hex().upper(),
            }
        )
        n += 1
    if n == limit:
        nxt = led.state_map.succ(cursor)
        if nxt is not None:
            next_marker = cursor.hex().upper()
    out = _ledger_ident(led)
    out["state"] = out_state
    if next_marker:
        out["marker"] = next_marker
    return out


@handler("ledger_entry")
def do_ledger_entry(ctx: Context) -> dict:
    """reference: handlers/LedgerEntry.cpp — fetch one SLE by index or by
    typed locator (account_root, offer, ripple_state)."""
    led = _select_ledger(ctx)
    p = ctx.params
    if p.get("index"):
        idx = bytes.fromhex(p["index"])
    elif p.get("account_root"):
        idx = indexes.account_root_index(
            decode_account_id(p["account_root"])
        )
    elif p.get("offer"):
        o = p["offer"]
        idx = indexes.offer_index(decode_account_id(o["account"]), int(o["seq"]))
    elif p.get("ripple_state"):
        rs = p["ripple_state"]
        a = decode_account_id(rs["accounts"][0])
        b = decode_account_id(rs["accounts"][1])
        cur = currency_from_iso(rs["currency"])
        idx = indexes.ripple_state_index(a, b, cur)
    else:
        raise RPCError("invalidParams", "no ledger_entry locator")
    item = led.state_map.get(idx)
    if item is None:
        raise RPCError("lgrNotFound", "entryNotFound")
    out = _ledger_ident(led)
    out["index"] = idx.hex().upper()
    out["node_binary"] = item.data.hex().upper()
    out["node"] = STObject.from_bytes(item.data).to_json()
    return out


@handler("ledger_accept", Role.ADMIN)
def do_ledger_accept(ctx: Context) -> dict:
    """Standalone manual close (reference: handlers/LedgerAccept.cpp —
    rejected unless RUN_STANDALONE)."""
    node = ctx.node
    if not node.config.standalone:
        raise RPCError("notStandalone")
    node.ops.accept_ledger()
    return {
        "ledger_current_index": node.ledger_master.current_ledger().seq
    }


@handler("tx")
def do_tx(ctx: Context) -> dict:
    """reference: handlers/Tx.cpp — by transaction hash, from the SQL
    history DB, with metadata."""
    h = ctx.params.get("transaction")
    if not h:
        raise RPCError("invalidParams", "missing transaction")
    txid = bytes.fromhex(h)
    row = ctx.node.txdb.get_transaction(txid)
    if row is None:
        # read-your-writes: the tx may live in a closed ledger still
        # queued in the close pipeline (persisted momentarily)
        pipeline = getattr(ctx.node, "close_pipeline", None)
        found = pipeline.lookup_tx(txid) if pipeline is not None else None
        if found is None:
            raise RPCError("txnNotFound")
        led, blob, meta, _results = found
        row = {"raw": blob, "meta": meta, "ledger_seq": led.seq}
    tx = SerializedTransaction.from_bytes(row["raw"])
    out = tx.obj.to_json()
    out["hash"] = h.upper()
    out["ledger_index"] = row["ledger_seq"]
    out["validated"] = True
    if row["meta"]:
        out["meta"] = STObject.from_bytes(row["meta"]).to_json()
    return out


@handler("tx_history")
def do_tx_history(ctx: Context) -> dict:
    _await_history(ctx)
    start = int(ctx.params.get("start", 0))
    rows = ctx.node.txdb.tx_history(start=start, limit=20)
    txs = []
    for r in rows:
        tx = SerializedTransaction.from_bytes(r["raw"])
        j = tx.obj.to_json()
        j["hash"] = r["txid"].hex().upper()
        j["ledger_index"] = r["ledger_seq"]
        txs.append(j)
    return {"index": start, "txs": txs}


# -- account inspection ----------------------------------------------------


@handler("account_info")
def do_account_info(ctx: Context) -> dict:
    """reference: handlers/AccountInfo.cpp"""
    led = _select_ledger(ctx)
    account_id = _parse_account(ctx.params)
    root = led.account_root(account_id)
    if root is None:
        raise RPCError("actNotFound", account=ctx.params.get("account"))
    j = root.to_json()
    j["Balance"] = root[sfBalance].to_json()
    j["index"] = indexes.account_root_index(account_id).hex().upper()
    out = _ledger_ident(led)
    out["account_data"] = j
    if ctx.params.get("queue"):
        # admission-queue block (reference: account_info queue_data):
        # this account's queued sequence chain, fee levels, total
        # queued fee spend
        txq = getattr(ctx.node, "txq", None)
        if txq is not None:
            out["queue_data"] = txq.account_json(account_id)
    return out


@handler("account_lines")
def do_account_lines(ctx: Context) -> dict:
    """reference: handlers/AccountLines.cpp — walk the owner directory for
    ltRIPPLE_STATE entries; render from this account's perspective."""
    led = _select_ledger(ctx)
    account_id = _parse_account(ctx.params)
    if led.account_root(account_id) is None:
        raise RPCError("actNotFound")
    peer = None
    if ctx.params.get("peer"):
        peer = decode_account_id(ctx.params["peer"])
    les = LedgerEntrySet(led)
    lines = []
    for entry_idx in les.dir_entries(indexes.owner_dir_index(account_id)):
        sle = les.peek(entry_idx)
        if sle is None or sle.get(sfLedgerEntryType) != int(
            LedgerEntryType.ltRIPPLE_STATE
        ):
            continue
        low = sle[sfLowLimit]
        high = sle[sfHighLimit]
        balance = sle[sfBalance]
        is_low = low.issuer == account_id
        other = high.issuer if is_low else low.issuer
        if peer is not None and other != peer:
            continue
        bal = balance if is_low else -balance
        limit = low if is_low else high
        limit_peer = high if is_low else low
        row = {
            "account": encode_account_id(other),
            "balance": bal.value_text(),
            "currency": iso_from_currency(balance.currency),
            "limit": limit.value_text(),
            "limit_peer": limit_peer.value_text(),
        }
        # optional fields match the reference's presence rules
        # (AccountLines.cpp:102-112: only emitted when set)
        q_in = sle.get(sfLowQualityIn if is_low else sfHighQualityIn, 0)
        q_out = sle.get(sfLowQualityOut if is_low else sfHighQualityOut, 0)
        if q_in:
            row["quality_in"] = q_in
        if q_out:
            row["quality_out"] = q_out
        flags = sle.get(sfFlags, 0)
        my_auth = lsfLowAuth if is_low else lsfHighAuth
        peer_auth = lsfHighAuth if is_low else lsfLowAuth
        my_nr = lsfLowNoRipple if is_low else lsfHighNoRipple
        peer_nr = lsfHighNoRipple if is_low else lsfLowNoRipple
        if flags & my_auth:
            row["authorized"] = True
        if flags & peer_auth:
            row["peer_authorized"] = True
        if flags & my_nr:
            row["no_ripple"] = True
        if flags & peer_nr:
            row["no_ripple_peer"] = True
        lines.append(row)
    out = _ledger_ident(led)
    out["account"] = ctx.params["account"]
    out["lines"] = lines
    return out


@handler("account_offers")
def do_account_offers(ctx: Context) -> dict:
    """reference: handlers/AccountOffers.cpp"""
    led = _select_ledger(ctx)
    account_id = _parse_account(ctx.params)
    if led.account_root(account_id) is None:
        raise RPCError("actNotFound")
    les = LedgerEntrySet(led)
    offers = []
    for entry_idx in les.dir_entries(indexes.owner_dir_index(account_id)):
        sle = les.peek(entry_idx)
        if sle is None or sle.get(sfLedgerEntryType) != int(
            LedgerEntryType.ltOFFER
        ):
            continue
        offers.append(
            {
                "flags": sle.get(sfFlags, 0),
                "seq": sle[sfSequence],
                "taker_gets": sle[sfTakerGets].to_json(),
                "taker_pays": sle[sfTakerPays].to_json(),
            }
        )
    out = _ledger_ident(led)
    out["account"] = ctx.params["account"]
    out["offers"] = offers
    return out


def _await_history(ctx: Context) -> None:
    """Read-your-writes for the SQL-index RPCs: a just-closed ledger may
    still be queued in the close pipeline; wait (bounded) for the CLOSE
    entries pending at call time so history queries never miss a tx
    already reported COMMITTED. Repairs and later-arriving closes are
    excluded — a cleaner backfill must not add latency here — and the
    queue is almost always empty or one deep, so this is microseconds in
    the common case. Pagination/marker semantics stay untouched; on
    timeout (storage stalled) the query proceeds over what is stored."""
    pipeline = getattr(ctx.node, "close_pipeline", None)
    if pipeline is not None:
        pipeline.wait_for_closes(timeout=10)


@handler("account_tx")
def do_account_tx(ctx: Context) -> dict:
    """reference: handlers/AccountTx.cpp over the SQL index."""
    _await_history(ctx)
    account_id = _parse_account(ctx.params)
    p = ctx.params
    min_l = int(p.get("ledger_index_min", -1))
    max_l = int(p.get("ledger_index_max", -1))
    if min_l < 0:
        min_l = 0
    if max_l < 0:
        max_l = 1 << 62
    forward = bool(p.get("forward", False))
    binary = bool(p.get("binary", False))
    limit = max(1, min(int(p.get("limit", 200)), 500))
    after = None
    marker = p.get("marker")
    if marker is not None:
        # a malformed marker must fail loudly, not restart from page one
        # (a well-behaved pager would then loop forever over duplicates)
        try:
            after = (int(marker["ledger"]), int(marker["seq"]))
        except (TypeError, KeyError, ValueError):
            raise RPCError("invalidParams", "malformed marker")
    # sql_trim retention floor: rows strictly below it were deleted by
    # online-deletion rotation. With history shards configured
    # ([node_db] shards=, doc/storage.md) the below-floor portion
    # routes to cold storage instead; WITHOUT them, a marker pointing
    # below the floor (a pager resuming across a trim) and a window
    # lying entirely below it must both fail CLEANLY — a silent empty
    # page would end a well-behaved pagination loop as if history were
    # complete
    floor = getattr(ctx.node.txdb, "retain_floor", 0)
    shardstore = getattr(ctx.node, "shardstore", None)
    req_min = min_l
    # fetch one extra row: its presence means the walk was truncated and
    # a resume marker must be returned (AccountTx.cpp resumeToken)
    want = limit + 1
    # the tier split is planned against one floor reading, but sql_trim
    # runs on other threads: a trim landing between the shard walk
    # (< floor) and the SQL walk (>= floor) deletes rows in
    # [floor, new_floor) that neither tier served. The floor is
    # monotonic, so re-checking it after the walk and re-planning
    # against the new value closes the window; the bound only caps
    # pathological back-to-back trims
    for _ in range(4):
        min_l = req_min
        shard_range = (
            shardstore.range() if shardstore is not None else None
        )
        shards_cover_below = (
            floor > 0 and shard_range is not None and min_l < floor
        )
        if shards_cover_below:
            # the shard tier only covers [shard_lo, floor): history
            # below the FIRST sealed shard (trimmed before shards were
            # enabled) is gone everywhere, and must keep the clean
            # lgrIdxInvalid / clamp-and-echo contract — never a quietly
            # complete-looking page with a hole at the front
            shard_lo = shard_range[0]
            if min_l < shard_lo:
                if after is not None and after[0] < shard_lo:
                    raise RPCError(
                        "lgrIdxInvalid",
                        f"marker ledger {after[0]} is below the oldest "
                        f"sealed history shard ({shard_lo})",
                    )
                if max_l < shard_lo:
                    raise RPCError(
                        "lgrIdxInvalid",
                        f"requested window ends below the oldest sealed "
                        f"history shard ({shard_lo})",
                    )
                min_l = shard_lo  # serve what exists; echo effective min
        if floor > 0 and not shards_cover_below:
            if after is not None and after[0] < floor:
                raise RPCError(
                    "lgrIdxInvalid",
                    f"marker ledger {after[0]} is below the retained "
                    f"history floor {floor}",
                )
            if max_l < floor:
                raise RPCError(
                    "lgrIdxInvalid",
                    f"requested window ends below the retained history "
                    f"floor {floor}",
                )
            if min_l < floor:
                # window straddles the floor: serve what exists and
                # REPORT the effective (clamped) minimum — the
                # reference's effective-range echo — so a pager can see
                # the truncation instead of reading a quietly
                # complete-looking history
                min_l = floor
        if shards_cover_below:
            # two-tier walk, cold shards below the floor + SQL at/above
            # it, in one consistent (ledger_seq, txn_seq) order; the
            # EXCLUSIVE `after` marker filters identically in both
            # tiers, so a pager resumes seamlessly across the boundary
            shard_hi = min(max_l, floor - 1)
            rows = []
            if forward:
                # a resume marker at/above the floor already consumed
                # the whole shard tier (every shard row is < floor and
                # the marker is exclusive) — skip the cold-storage walk
                if after is None or after[0] < floor:
                    rows.extend(shardstore.account_tx(
                        account_id, min_l, shard_hi, want, True,
                        after=after,
                    ))
                if len(rows) < want and max_l >= floor:
                    rows.extend(ctx.node.txdb.account_transactions(
                        account_id, floor, max_l, want - len(rows), True,
                        after=after,
                    ))
            else:
                if max_l >= floor:
                    rows.extend(ctx.node.txdb.account_transactions(
                        account_id, floor, max_l, want, False,
                        after=after,
                    ))
                if len(rows) < want:
                    rows.extend(shardstore.account_tx(
                        account_id, min_l, shard_hi, want - len(rows),
                        False, after=after,
                    ))
        else:
            rows = ctx.node.txdb.account_transactions(
                account_id, min_l, max_l, want, forward, after=after
            )
        new_floor = getattr(ctx.node.txdb, "retain_floor", 0)
        if new_floor == floor:
            break
        floor = new_floor
    more = len(rows) > limit
    rows = rows[:limit]
    served_from_shards = any("shard" in r for r in rows)
    txs = []
    for r in rows:
        if binary:
            entry = {
                "tx_blob": r["raw"].hex().upper(),
                "ledger_index": r["ledger_seq"],
                "validated": True,
            }
            if r["meta"]:
                entry["meta"] = r["meta"].hex().upper()
        else:
            tx = SerializedTransaction.from_bytes(r["raw"])
            j = tx.obj.to_json()
            j["hash"] = r["txid"].hex().upper()
            j["ledger_index"] = r["ledger_seq"]
            entry = {"tx": j, "validated": True}
            if r["meta"]:
                entry["meta"] = STObject.from_bytes(r["meta"]).to_json()
        if "shard" in r:
            # cold-storage provenance: this row came off a sealed
            # history shard, not the live SQL index
            entry["shard"] = r["shard"]
        txs.append(entry)
    out = {
        "account": p["account"],
        "ledger_index_min": min_l,
        "ledger_index_max": max_l if max_l < (1 << 62) else -1,
        "limit": limit,
        "transactions": txs,
    }
    if served_from_shards:
        out["history_shards"] = True
    if more and rows:
        out["marker"] = {
            "ledger": rows[-1]["ledger_seq"],
            "seq": rows[-1]["txn_seq"],
        }
    return out


# -- order books -----------------------------------------------------------


def _parse_book_side(p: dict, key: str) -> tuple[bytes, bytes]:
    side = p.get(key)
    if not isinstance(side, dict) or "currency" not in side:
        raise RPCError("invalidParams", f"missing {key}")
    iso = side["currency"]
    currency = bytes.fromhex(iso) if len(iso) == 40 else currency_from_iso(iso)
    issuer = b"\x00" * 20
    if side.get("issuer"):
        issuer = decode_account_id(side["issuer"])
    return currency, issuer


@handler("book_offers")
def do_book_offers(ctx: Context) -> dict:
    """reference: handlers/BookOffers.cpp — walk the book's quality
    directories in order, rendering resting offers."""
    led = _select_ledger(ctx)
    pays_currency, pays_issuer = _parse_book_side(ctx.params, "taker_pays")
    gets_currency, gets_issuer = _parse_book_side(ctx.params, "taker_gets")
    limit = min(int(ctx.params.get("limit", 256)), 512)

    les = LedgerEntrySet(led)
    base = indexes.book_base(
        pays_currency, pays_issuer, gets_currency, gets_issuer
    )
    end = indexes.quality_next(base)
    offers = []
    cursor = base
    while len(offers) < limit:
        item = led.state_map.succ(cursor)
        if item is None or item.tag >= end:
            break
        cursor = item.tag
        dir_sle = les.peek(item.tag)
        if dir_sle is None:
            continue
        if dir_sle.get(sfLedgerEntryType) != int(LedgerEntryType.ltDIR_NODE):
            continue
        for offer_idx in les.dir_entries(item.tag):
            sle = les.peek(offer_idx)
            if sle is None or sle.get(sfLedgerEntryType) != int(
                LedgerEntryType.ltOFFER
            ):
                continue
            j = sle.to_json()
            j["index"] = offer_idx.hex().upper()
            j["quality"] = str(indexes.get_quality(item.tag))
            offers.append(j)
            if len(offers) >= limit:
                break
    out = _ledger_ident(led)
    out["offers"] = offers
    return out


# -- submission ------------------------------------------------------------


def _engine_result(ter: TER, tx: SerializedTransaction) -> dict:
    return {
        "engine_result": ter.token,
        "engine_result_code": int(ter),
        "engine_result_message": ter.human,
        "tx_blob": tx.serialize().hex().upper(),
        "tx_json": {
            **tx.obj.to_json(),
            "hash": tx.txid().hex().upper(),
        },
    }


@handler("submit")
def do_submit(ctx: Context) -> dict:
    """reference: handlers/Submit.cpp:26-80 — tx_blob path or
    sign-and-submit tx_json path."""
    p = ctx.params
    if "tx_blob" in p:
        try:
            tx = SerializedTransaction.from_bytes(bytes.fromhex(p["tx_blob"]))
        except Exception as exc:  # noqa: BLE001
            raise RPCError("invalidTransaction", str(exc)) from exc
    elif "tx_json" in p:
        if "secret" not in p:
            raise RPCError("invalidParams", "missing secret")
        tx = transaction_sign(
            ctx.node, p["tx_json"], p["secret"],
            build_path=bool(p.get("build_path")),
        )
    else:
        raise RPCError("invalidParams", "need tx_blob or tx_json")
    ter, _applied = ctx.node.ops.process_transaction(
        tx, admin=(ctx.role == Role.ADMIN)
    )
    out = _engine_result(ter, tx)
    if ter == TER.terQUEUED:
        # admission control queued it: tell the caller what entering the
        # open ledger would have cost (and would cost on resubmit)
        txq = getattr(ctx.node, "txq", None)
        if txq is not None:
            led = ctx.node.ledger_master.current_ledger()
            out["queued"] = True
            out["open_ledger_fee"] = str(txq.open_ledger_fee(led))
    return out


@handler("sign")
def do_sign(ctx: Context) -> dict:
    """reference: handlers/Sign.cpp → RPC::transactionSign (no submit)."""
    p = ctx.params
    if "tx_json" not in p or "secret" not in p:
        raise RPCError("invalidParams", "need tx_json and secret")
    tx = transaction_sign(
        ctx.node, p["tx_json"], p["secret"],
        build_path=bool(p.get("build_path")),
    )
    return {
        "tx_blob": tx.serialize().hex().upper(),
        "tx_json": {**tx.obj.to_json(), "hash": tx.txid().hex().upper()},
    }


# -- pub/sub ---------------------------------------------------------------


def _url_sub_target(ctx: Context):
    """Resolve the subscription target for a `url` param (reference:
    Subscribe.cpp:34-80 — HTTP callers subscribe a server-side RPCSub
    pusher instead of a websocket InfoSub; admin only)."""
    p = ctx.params
    if ctx.role != Role.ADMIN:
        raise RPCError("noPermission")
    subs = ctx.subs or getattr(ctx.node, "subs", None)
    if subs is None:
        raise RPCError("notSupported", "node is not serving subscriptions")
    try:
        sub = subs.rpc_sub(
            p["url"],
            p.get("url_username", p.get("username", "")),
            p.get("url_password", p.get("password", "")),
        )
    except ValueError as exc:
        raise RPCError("invalidParams", str(exc)) from exc
    return sub, subs


@handler("subscribe")
def do_subscribe(ctx: Context) -> dict:
    """reference: handlers/Subscribe.cpp:86-112 (websocket InfoSub) and
    :34-80 (HTTP `url` callbacks via RPCSub)."""
    p0 = ctx.params
    # decode-validate BEFORE registering a url sub: a later param error
    # must not leak a phantom rpc_subs entry
    for key in ("accounts", "accounts_proposed", "rt_accounts"):
        for a in p0.get(key) or []:
            try:
                decode_account_id(a)
            except (ValueError, KeyError) as exc:
                raise RPCError("actMalformed") from exc
    if ctx.params.get("url"):
        infosub, subs = _url_sub_target(ctx)
    elif ctx.infosub is None or ctx.subs is None:
        raise RPCError("notSupported",
                       "subscribe requires a websocket or a url")
    else:
        infosub, subs = ctx.infosub, ctx.subs
    ctx = Context(ctx.node, ctx.params, ctx.role, infosub, subs)
    p = ctx.params
    result = {}
    if p.get("streams"):
        result.update(ctx.subs.subscribe_streams(ctx.infosub, p["streams"]))
    if p.get("accounts"):
        accts = [decode_account_id(a) for a in p["accounts"]]
        ctx.subs.subscribe_accounts(ctx.infosub, accts)
    if p.get("accounts_proposed") or p.get("rt_accounts"):
        accts = [
            decode_account_id(a)
            for a in (p.get("accounts_proposed") or p.get("rt_accounts"))
        ]
        ctx.subs.subscribe_accounts(ctx.infosub, accts, proposed=True)
    if "resume" in p:
        # WS-door resume cursor (doc/follower.md reconnect-storm
        # hardening): `resume: N` (or `{"last_seq": N}`) replays every
        # ledgerClosed event after N still inside the bounded replay
        # ring and re-attaches the ledger stream — zero gaps, zero
        # dups. A cursor past the horizon gets the EXPLICIT cold
        # answer ({"cold": true} + the current floor), never a silent
        # re-subscribe.
        r = p["resume"]
        if isinstance(r, dict):
            r = r.get("last_seq")
        if isinstance(r, bool) or not isinstance(r, (int, str)):
            raise RPCError("invalidParams", "malformed resume cursor")
        try:
            last_seq = int(r)
        except (TypeError, ValueError) as exc:
            raise RPCError("invalidParams",
                           "malformed resume cursor") from exc
        if last_seq < 0:
            raise RPCError("invalidParams", "malformed resume cursor")
        result.update(ctx.subs.resume(ctx.infosub, last_seq))
    return result


@handler("unsubscribe")
def do_unsubscribe(ctx: Context) -> dict:
    _prune = None
    if ctx.params.get("url"):
        if ctx.role != Role.ADMIN:
            raise RPCError("noPermission")
        subs = ctx.subs or getattr(ctx.node, "subs", None)
        if subs is None:
            raise RPCError("notSupported", "node is not serving subscriptions")
        # lookup ONLY: unsubscribing a never-subscribed url must error,
        # not find-or-create a phantom subscription
        infosub = subs.rpc_sub_lookup(ctx.params["url"])
        if infosub is None:
            raise RPCError("invalidParams",
                           f"no subscription for url {ctx.params['url']!r}")
        _prune = (subs, infosub)
        ctx = Context(ctx.node, ctx.params, ctx.role, infosub, subs)
    elif ctx.infosub is None or ctx.subs is None:
        raise RPCError("notSupported",
                       "unsubscribe requires a websocket or a url")
    p = ctx.params
    if p.get("streams"):
        ctx.subs.unsubscribe_streams(ctx.infosub, p["streams"])
    if p.get("accounts"):
        ctx.subs.unsubscribe_accounts(
            ctx.infosub, [decode_account_id(a) for a in p["accounts"]]
        )
    if p.get("accounts_proposed"):
        ctx.subs.unsubscribe_accounts(
            ctx.infosub,
            [decode_account_id(a) for a in p["accounts_proposed"]],
            proposed=True,
        )
    if _prune is not None:
        _prune[0].prune_rpc_sub(_prune[1])
    return {}


@handler("ripple_path_find")
def do_ripple_path_find(ctx: Context) -> dict:
    """reference: handlers/RipplePathFind.cpp — one-shot path search:
    source_account, destination_account, destination_amount
    [, send_max] -> ranked alternatives."""
    from ..paths import find_paths
    from ..protocol.stamount import STAmount as _STA
    from ..protocol.stobject import STPathSet

    led = _select_ledger(ctx)
    p = ctx.params
    try:
        src = decode_account_id(p["source_account"])
        dst = decode_account_id(p["destination_account"])
        dst_amount = _STA.from_json(p["destination_amount"])
        send_max = _STA.from_json(p["send_max"]) if "send_max" in p else None
        # search_level bounds which cost-ranked shape-table rows run;
        # 0/absent means "use the default level" (reference: PathRequest
        # treats iLevel 0 as unset, PathRequest.cpp:370-375)
        level = int(p["search_level"]) if "search_level" in p else 0
        if level < 0:
            raise ValueError(f"search_level {level} out of range")
        level = level or None
    except (KeyError, ValueError, TypeError) as e:
        raise RPCError("invalidParams", str(e))
    kwargs = {"send_max": send_max}
    if level is not None:
        kwargs["level"] = level
    # liquidity plane (ISSUE 17): serve off the incrementally-maintained
    # book index when it already reflects the selected ledger (never
    # advance it here — an RPC against a historical ledger must not
    # wreck close-to-close continuity), and let the device plane
    # pre-rank oversized candidate sets
    plane = getattr(ctx.node, "path_plane", None)
    if plane is not None:
        books = plane.books_if_current(led)
        if books is not None:
            kwargs["books"] = books
        pre_rank = plane.make_pre_rank(led)
        if pre_rank is not None:
            kwargs["pre_rank"] = pre_rank
    alts = find_paths(led, src, dst, dst_amount, **kwargs)
    out = _ledger_ident(led)
    out["source_account"] = p["source_account"]
    out["destination_account"] = p["destination_account"]
    out["destination_amount"] = p["destination_amount"]
    out["alternatives"] = [
        {
            "paths_computed": STPathSet(a["paths"]).to_json(),
            "source_amount": a["source_amount"].to_json(),
        }
        for a in alts
    ]
    return out


@handler("path_find")
def do_path_find(ctx: Context) -> dict:
    """reference: handlers/PathFind.cpp — the WebSocket subscription
    form: `create` registers a LIVE path request (re-searched and pushed
    to the subscriber on every ledger close, PathRequests role), `close`
    tears it down, `status` reports it. Over HTTP (no subscriber), a
    create degrades to the one-shot search."""
    sub_cmd = ctx.params.get("subcommand", "create")
    if sub_cmd == "close":
        if ctx.infosub is not None and ctx.subs is not None:
            rid = ctx.params.get("id")
            if rid is not None:
                try:
                    rid = int(rid)
                except (TypeError, ValueError):
                    raise RPCError("invalidParams", "id must be an integer")
            closed = ctx.subs.close_path_request(ctx.infosub, rid)
            return {"closed": closed}
        return {"closed": True}
    if sub_cmd == "status":
        if ctx.infosub is None:
            raise RPCError("notSupported", "status requires a websocket")
        return {
            "requests": [
                {"id": rid, **req.get("echo", {})}
                for rid, req in ctx.infosub.path_requests.items()
            ]
        }
    if sub_cmd != "create":
        raise RPCError("invalidParams", f"unknown subcommand {sub_cmd!r}")
    # the initial answer is the same pure function of the validated
    # snapshot as ripple_path_find — route it through the validated-seq
    # result cache so back-to-back creates share one search (ISSUE 17;
    # dispatch-level wrapping keys on "path_find", which is not
    # cacheable because create/close mutate subscription state)
    from .readplane import cached_dispatch

    out = cached_dispatch(ctx, "ripple_path_find",
                          lambda: do_ripple_path_find(ctx))
    if ctx.infosub is not None and ctx.subs is not None:
        from ..protocol.stamount import STAmount as _STA

        p = ctx.params
        request = {
            "src": decode_account_id(p["source_account"]),
            "dst": decode_account_id(p["destination_account"]),
            "dst_amount": _STA.from_json(p["destination_amount"]),
            "echo": {
                "source_account": p["source_account"],
                "destination_account": p["destination_account"],
                "destination_amount": p["destination_amount"],
            },
        }
        if "send_max" in p:
            request["send_max"] = _STA.from_json(p["send_max"])
        out["id"] = ctx.subs.create_path_request(ctx.infosub, request)
    return out


# --------------------------------------------------------------------------
# round-3 surface completion: the remaining Handlers.cpp table entries


@handler("account_currencies")
def do_account_currencies(ctx: Context) -> dict:
    """reference: handlers/AccountCurrencies.cpp — currencies the account
    can send (positive balance or peer credit) and receive (inbound
    limit)."""
    led = _select_ledger(ctx)
    account_id = _parse_account(ctx.params)
    if led.account_root(account_id) is None:
        raise RPCError("actNotFound")
    les = LedgerEntrySet(led)
    send, receive = set(), set()
    for entry_idx in les.dir_entries(indexes.owner_dir_index(account_id)):
        sle = les.peek(entry_idx)
        if sle is None or sle.get(sfLedgerEntryType) != int(
            LedgerEntryType.ltRIPPLE_STATE
        ):
            continue
        low = sle[sfLowLimit]
        high = sle[sfHighLimit]
        is_low = low.issuer == account_id
        balance = sle[sfBalance] if is_low else -sle[sfBalance]
        our_limit = low if is_low else high
        peer_limit = high if is_low else low
        iso = iso_from_currency(low.currency)
        # sendable = positive balance OR remaining peer credit (a line
        # drawn to its full limit has no capacity left)
        if balance.signum() > 0 or (peer_limit + balance).signum() > 0:
            send.add(iso)
        if our_limit.signum() > 0:
            receive.add(iso)
    out = _ledger_ident(led)
    out["send_currencies"] = sorted(send)
    out["receive_currencies"] = sorted(receive)
    return out


@handler("owner_info")
def do_owner_info(ctx: Context) -> dict:
    """reference: handlers/OwnerInfo.cpp — everything the account owns in
    the current and closed ledgers (offers + trust lines)."""
    account_id = _parse_account(ctx.params)

    def owned(led: Ledger) -> dict:
        if led.account_root(account_id) is None:
            return {}
        les = LedgerEntrySet(led)
        offers, lines = [], []
        for entry_idx in les.dir_entries(indexes.owner_dir_index(account_id)):
            sle = les.peek(entry_idx)
            if sle is None:
                continue
            et = sle.get(sfLedgerEntryType)
            if et == int(LedgerEntryType.ltOFFER):
                offers.append({
                    "seq": sle.get(sfSequence, 0),
                    "taker_pays": sle[sfTakerPays].to_json(),
                    "taker_gets": sle[sfTakerGets].to_json(),
                })
            elif et == int(LedgerEntryType.ltRIPPLE_STATE):
                lines.append({
                    "balance": sle[sfBalance].to_json(),
                    "flags": sle.get(sfFlags, 0),
                })
        return {"offers": offers, "ripple_lines": lines}

    return {
        "accepted": owned(ctx.node.ledger_master.closed_ledger()),
        "current": owned(ctx.node.ledger_master.current_ledger()),
    }


@handler("transaction_entry")
def do_transaction_entry(ctx: Context) -> dict:
    """reference: handlers/TransactionEntry.cpp — a transaction looked up
    INSIDE a specific ledger (by tx_hash + ledger hash/index)."""
    p = ctx.params
    if "tx_hash" not in p:
        raise RPCError("fieldNotFoundTransaction")
    led = _select_ledger(ctx)
    try:
        txid = bytes.fromhex(p["tx_hash"])
    except ValueError:
        raise RPCError("invalidParams", "malformed tx_hash")
    for tid, blob, meta in led.tx_entries():
        if tid == txid:
            tx = SerializedTransaction.from_bytes(blob)
            out = _ledger_ident(led)
            out["tx_json"] = tx.obj.to_json()
            if meta:
                out["metadata"] = STObject.from_bytes(meta).to_json()
            return out
    raise RPCError("transactionNotFound")


@handler("ledger_header")
def do_ledger_header(ctx: Context) -> dict:
    """reference: handlers/LedgerHeader.cpp — header blob + fields."""
    led = _select_ledger(ctx)
    out = _ledger_ident(led)
    out["ledger_data"] = led.header_bytes().hex().upper()
    out["ledger"] = {
        "parent_hash": led.parent_hash.hex().upper(),
        "seqNum": led.seq,
        "close_time": led.close_time,
        "close_time_resolution": led.close_resolution,
        "totalCoins": str(led.tot_coins),
        "transaction_hash": led.tx_hash.hex().upper(),
        "account_hash": led.account_hash.hex().upper(),
    }
    return out


@handler("fetch_info", Role.ADMIN)
def do_fetch_info(ctx: Context) -> dict:
    """reference: handlers/FetchInfo.cpp — live acquisition status."""
    info: dict = {}
    overlay = getattr(ctx.node, "overlay", None)
    inbound = getattr(getattr(overlay, "node", None), "inbound", None)
    if inbound is not None:
        for h, il in list(inbound.live.items()):
            info[h.hex().upper()] = {
                "have_base": il.header is not None,
                "failed": il.failed,
                "complete": il.is_complete(),
            }
    return {"info": info}


@handler("print", Role.ADMIN)
def do_print(ctx: Context) -> dict:
    """reference: handlers/Print.cpp — the PropertyStream walk over live
    subsystems; every plane reports its own introspection JSON."""
    node = ctx.node
    out = {
        "app": {
            "jobq": node.job_queue.get_json(),
            "verify_plane": node.verify_plane.get_json(),
            "load": node.load_manager.get_json(),
            "clf": node.clf.get_json(),
            "unl": {"count": len(node.unl)},
            "nodestore": getattr(node.nodestore, "get_json", dict)(),
        }
    }
    overlay = getattr(node, "overlay", None)
    if overlay is not None:
        out["app"]["peerfinder"] = overlay.peerfinder.get_json()
        out["app"]["resources"] = overlay.resources.get_json()
        out["app"]["squelch"] = overlay.squelch_json()
    rpc_rm = getattr(node, "rpc_resources", None)
    if rpc_rm is not None:
        out["app"]["rpc_resources"] = rpc_rm.get_json()
    return out


@handler("connect", Role.ADMIN)
def do_connect(ctx: Context) -> dict:
    """reference: handlers/Connect.cpp — ask the overlay to dial a peer."""
    overlay = getattr(ctx.node, "overlay", None)
    if overlay is None:
        raise RPCError("notSynced", "no overlay running (standalone)")
    p = ctx.params
    if "ip" not in p:
        raise RPCError("invalidParams", "missing ip")
    addr = (p["ip"], int(p.get("port", 51235)))
    overlay.peerfinder.bootcache.insert(addr)
    overlay._spawn(overlay._dial, addr)
    return {"message": "connecting"}


@handler("log_rotate", Role.ADMIN)
def do_log_rotate(ctx: Context) -> dict:
    """reference: handlers/LogRotate.cpp — reopen the debug log."""
    import logging

    for h in logging.getLogger().handlers:
        if hasattr(h, "doRollover"):
            h.doRollover()
    return {"message": "The log file was closed and reopened."}


@handler("inflate", Role.ADMIN)
def do_inflate(ctx: Context) -> dict:
    """reference: handlers/Inflate.cpp (Stellar-specific) — build, sign
    and submit an Inflation transaction for the given sequence."""
    p = ctx.params
    if "seq" not in p:
        raise RPCError("invalidParams", "missing seq")
    from ..protocol.formats import TxType as _Tx
    from ..protocol.keys import decode_seed, passphrase_to_seed
    from ..protocol.sfields import sfInflateSeq

    node = ctx.node
    secret = p.get("secret")
    if not secret:
        raise RPCError("invalidParams", "missing secret")
    try:
        seed = decode_seed(secret)
    except (ValueError, KeyError):
        seed = passphrase_to_seed(secret)
    kp = KeyPair.from_seed(seed)
    led = node.ledger_master.current_ledger()
    root = led.account_root(kp.account_id)
    if root is None:
        raise RPCError("actNotFound")
    tx = SerializedTransaction.build(
        _Tx.ttINFLATION, kp.account_id, root[sfSequence], 10,
        {sfInflateSeq: int(p["seq"])},
    )
    tx.sign(kp)
    ter, applied = node.ops.process_transaction(tx, admin=True)
    return {"engine_result": ter.token, "applied": applied}


# -- UNL management (reference: handlers/Unl*.cpp) -------------------------


@handler("unl_list", Role.ADMIN)
def do_unl_list(ctx: Context) -> dict:
    return {"unl": ctx.node.unl.get_json()}


@handler("unl_add", Role.ADMIN)
def do_unl_add(ctx: Context) -> dict:
    p = ctx.params
    if "node" not in p:
        raise RPCError("invalidParams", "missing node")
    from ..protocol.keys import decode_node_public

    try:
        pk = decode_node_public(p["node"])
    except (ValueError, KeyError):
        raise RPCError("invalidParams", "malformed node public key")
    ctx.node.unl.add(pk, p.get("comment", ""))
    return {"pubkey_validator": p["node"]}


@handler("unl_delete", Role.ADMIN)
def do_unl_delete(ctx: Context) -> dict:
    p = ctx.params
    if "node" not in p:
        raise RPCError("invalidParams", "missing node")
    from ..protocol.keys import decode_node_public

    try:
        pk = decode_node_public(p["node"])
    except (ValueError, KeyError):
        raise RPCError("invalidParams", "malformed node public key")
    if not ctx.node.unl.remove(pk):
        raise RPCError("invalidParams", "not on the UNL")
    return {"pubkey_validator": p["node"]}


@handler("unl_reset", Role.ADMIN)
def do_unl_reset(ctx: Context) -> dict:
    ctx.node.unl.reset()
    return {"message": "removing nodes"}


@handler("unl_load", Role.ADMIN)
def do_unl_load(ctx: Context) -> dict:
    """Re-seed from the config [validators] section."""
    from ..protocol.keys import decode_node_public

    n = ctx.node.unl.load_from(
        (decode_node_public(v) for v in ctx.node.config.validators), "config"
    )
    return {"message": f"loading (added {n})"}


@handler("unl_network", Role.ADMIN)
def do_unl_network(ctx: Context) -> dict:
    """The reference fetched network UNL sites; this build has no site
    fetcher (zero-egress deployments), so report the static posture."""
    return {"message": "no network sources configured"}


@handler("unl_score", Role.ADMIN)
def do_unl_score(ctx: Context) -> dict:
    """reference: UnlScore.cpp — scoring is deprecated there; here the
    observed-validation bookkeeping doubles as the score report."""
    return {"unl": ctx.node.unl.get_json()}


# -- proof of work (reference: handlers/Proof*.cpp) ------------------------


@handler("proof_create", Role.ADMIN)
def do_proof_create(ctx: Context) -> dict:
    pw = ctx.node.pow_factory.get_proof()
    return {
        "token": pw.token,
        "challenge": pw.challenge.hex().upper(),
        "target": pw.target.hex().upper(),
        "iterations": pw.iterations,
    }


@handler("proof_solve", Role.ADMIN)
def do_proof_solve(ctx: Context) -> dict:
    p = ctx.params
    try:
        challenge = bytes.fromhex(p["challenge"])
        target = bytes.fromhex(p["target"])
        iterations = int(p["iterations"])
    except (KeyError, ValueError):
        raise RPCError("invalidParams", "need challenge/target/iterations")
    from ..utils.pow import ProofOfWork

    pw = ProofOfWork(p.get("token", ""), iterations, challenge, target)
    solution = pw.solve()
    if solution is None:
        raise RPCError("internal", "no solution found")
    return {"solution": solution.hex().upper()}


@handler("proof_verify", Role.ADMIN)
def do_proof_verify(ctx: Context) -> dict:
    p = ctx.params
    try:
        challenge = bytes.fromhex(p["challenge"])
        solution = bytes.fromhex(p["solution"])
        token = p["token"]
    except (KeyError, ValueError):
        raise RPCError("invalidParams", "need token/challenge/solution")
    ok, reason = ctx.node.pow_factory.check_proof(token, challenge, solution)
    return {"valid": ok, "reason": reason}


# -- wallet / misc ---------------------------------------------------------


@handler("wallet_seed", Role.ADMIN)
def do_wallet_seed(ctx: Context) -> dict:
    """reference: handlers/WalletSeed.cpp — seed in its encodings."""
    from ..protocol.keys import decode_seed, passphrase_to_seed

    p = ctx.params
    secret = p.get("secret")
    if secret:
        try:
            seed = decode_seed(secret)
        except (ValueError, KeyError):
            seed = passphrase_to_seed(secret)
    else:
        seed = os.urandom(32)
    kp = KeyPair.from_seed(seed)
    return {
        "seed": kp.human_seed,
        "key": kp.human_seed,
        "deprecated": "use wallet_propose instead",
    }


@handler("wallet_accounts")
def do_wallet_accounts(ctx: Context) -> dict:
    """reference: handlers/WalletAccounts.cpp — accounts reachable from a
    seed (Ed25519 seeds map to exactly one account)."""
    from ..protocol.keys import decode_seed, passphrase_to_seed

    p = ctx.params
    if "seed" not in p and "secret" not in p:
        raise RPCError("invalidParams", "missing seed")
    secret = p.get("seed", p.get("secret"))
    try:
        seed = decode_seed(secret)
    except (ValueError, KeyError):
        seed = passphrase_to_seed(secret)
    kp = KeyPair.from_seed(seed)
    led = _select_ledger(ctx)
    accounts = []
    if led.account_root(kp.account_id) is not None:
        accounts.append({"account": kp.human_account_id})
    return {"accounts": accounts}


@handler("nickname_info")
def do_nickname_info(ctx: Context) -> dict:
    """reference: handlers/NicknameInfo.cpp — nickname entries are
    vestigial (no transactor creates them); faithful 'not found'."""
    raise RPCError("actNotFound", "no nickname entries exist")


@handler("blacklist", Role.ADMIN)
def do_blacklist(ctx: Context) -> dict:
    """reference: handlers/BlackList.cpp — resource-manager balances
    for BOTH charge planes: peer overlay endpoints and RPC clients."""
    overlay = getattr(ctx.node, "overlay", None)
    out = {
        "blacklist": (
            overlay.resources.get_json() if overlay is not None else {}
        ),
    }
    rpc_rm = getattr(ctx.node, "rpc_resources", None)
    if rpc_rm is not None:
        out["rpc"] = rpc_rm.get_json()
    return out


@handler("profile", Role.ADMIN)
def do_profile(ctx: Context) -> dict:
    """Device-plane profiler control (SURVEY §5 tracing). The reference's
    Profile.cpp was a load generator (bench.py is that harness here);
    this build's `profile` instead captures a JAX/XLA profiler trace of
    what the device actually executes — TensorBoard XPlane format.

    params: {"action": "start"|"stop"|"status", "dir": optional path}
    """
    import jax

    p = ctx.params
    node = ctx.node
    action = p.get("action", "status")
    if action == "start":
        if getattr(node, "_trace_dir", None):
            raise RPCError("internal", "trace already running")
        trace_dir = p.get("dir")
        if not trace_dir:
            import tempfile

            trace_dir = tempfile.mkdtemp(prefix="stellard-trace-")
        try:
            jax.profiler.start_trace(trace_dir)
        except Exception as exc:  # noqa: BLE001 — surface, don't crash the door
            raise RPCError("internal", f"profiler start failed: {exc}") from exc
        node._trace_dir = trace_dir
        return {"status": "tracing", "dir": trace_dir}
    if action == "stop":
        trace_dir = getattr(node, "_trace_dir", None)
        if not trace_dir:
            raise RPCError("internal", "no trace running")
        try:
            jax.profiler.stop_trace()
        finally:
            node._trace_dir = None
        return {"status": "stopped", "dir": trace_dir}
    return {
        "status": "tracing" if getattr(node, "_trace_dir", None) else "idle",
        "dir": getattr(node, "_trace_dir", None),
        "verify_latency": node.verify_plane.get_json()["latency_histogram_ms"],
    }


@handler("sms", Role.ADMIN)
def do_sms(ctx: Context) -> dict:
    """reference: handlers/SMS.cpp — posts to a configured SMS gateway;
    zero-egress deployments have none."""
    raise RPCError("notImpl", "no sms gateway configured")


@handler("ledger_cleaner", Role.ADMIN)
def do_ledger_cleaner(ctx: Context) -> dict:
    """reference: handlers/LedgerCleaner.cpp — drive the integrity
    checker."""
    p = ctx.params
    if p.get("stop"):
        return ctx.node.ledger_cleaner.stop()
    if p.get("status") or not (p.get("ledger") or p.get("min_ledger")
                               or p.get("max_ledger") or p.get("full")):
        return ctx.node.ledger_cleaner.get_json()
    if p.get("ledger"):
        lo = hi = int(p["ledger"])
    else:
        lo = int(p["min_ledger"]) if p.get("min_ledger") else None
        hi = int(p["max_ledger"]) if p.get("max_ledger") else None
    return ctx.node.ledger_cleaner.start(lo, hi)


@handler("account_tx_old")
def do_account_tx_old(ctx: Context) -> dict:
    """reference: AccountTxOld.cpp — the legacy parameter shape
    (ledger_min/ledger_max) over the same index."""
    p = dict(ctx.params)
    if "ledger_min" in p:
        p["ledger_index_min"] = p["ledger_min"]
    if "ledger_max" in p:
        p["ledger_index_max"] = p["ledger_max"]
    return do_account_tx(Context(ctx.node, p, ctx.role, ctx.infosub, ctx.subs))


@handler("account_tx_switch")
def do_account_tx_switch(ctx: Context) -> dict:
    """reference: AccountTxSwitch.cpp routes old/new shapes."""
    if "ledger_min" in ctx.params or "ledger_max" in ctx.params:
        return do_account_tx_old(ctx)
    return do_account_tx(ctx)
