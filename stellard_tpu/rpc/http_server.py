"""HTTP JSON-RPC door.

Reference: src/ripple/http (async HTTP server framework) bound to the RPC
handler table by RPCHTTPServer (Application.cpp:325); request format is
JSON-RPC 1.0-style {"method": ..., "params": [{...}]} and responses wrap
the handler result as {"result": {..., "status": "success"|"error"}}
(reference: RPCServerHandler::processRequest).

asyncio protocol implementation — no external HTTP library.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

from .errors import RPCError
from .handlers import Context, Role, dispatch

__all__ = ["HttpRpcServer", "process_http_request"]

_MAX_BODY = 10 * 1024 * 1024


def process_http_request(node, body: bytes, role: Role = Role.ADMIN,
                         client_ip: str = "") -> dict:
    """Decode one JSON-RPC request body → response object. Non-admin
    requests charge the client's resource balance (FEE_*_RPC schedule);
    a client past the drop line gets rpcSLOW_DOWN until it decays."""
    from .handlers import charge_rpc_client

    try:
        req = json.loads(body)
    except ValueError:
        refused = charge_rpc_client(node, client_ip, None, role)  # charged
        err = refused or RPCError("invalidParams", "malformed JSON").to_json()
        return {"result": err | {"status": "error"}}
    method = req.get("method")
    params_list = req.get("params") or [{}]
    params = params_list[0] if isinstance(params_list, list) and params_list else {}
    if not isinstance(params, dict):
        params = {}
    if not isinstance(method, str):
        refused = charge_rpc_client(node, client_ip, None, role)
        err = refused or RPCError("unknownCmd").to_json()
        return {"result": err | {"status": "error"}}
    refused = charge_rpc_client(node, client_ip, method, role)
    if refused is not None:
        result = refused | {"status": "error"}
        out = {"result": result}
        if "id" in req:
            out["id"] = req["id"]
        return out
    result = dispatch(Context(node=node, params=params, role=role), method)
    result["status"] = "error" if "error" in result else "success"
    from .handlers import rpc_warning

    warn = rpc_warning(node, client_ip, role)
    if warn is not None:
        result["warning"] = warn
    out = {"result": result}
    if "id" in req:
        out["id"] = req["id"]
    return out


def _role_for_peer(node, writer) -> Role:
    """ADMIN only for connections from [rpc_admin_allow] source IPs
    (reference: RPCHandler role gating by admin-allowed IP)."""
    peer = writer.get_extra_info("peername")
    ip = peer[0] if peer else ""
    return Role.ADMIN if ip in node.config.admin_ips else Role.GUEST


class HttpRpcServer:
    """Minimal threaded asyncio HTTP/1.1 server for the RPC door."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0,
                 ssl_context=None):
        self._ssl = ssl_context  # reference [rpc_secure] (RPCDoor SSL)
        self.node = node
        self.host = host
        self.port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._server = None

    # -- protocol ---------------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                header = await reader.readuntil(b"\r\n\r\n")
                lines = header.decode("latin-1").split("\r\n")
                request_line = lines[0]
                headers = {}
                for line in lines[1:]:
                    if ":" in line:
                        k, v = line.split(":", 1)
                        headers[k.strip().lower()] = v.strip()
                length = int(headers.get("content-length", 0))
                if length > _MAX_BODY:
                    writer.write(b"HTTP/1.1 413 Payload Too Large\r\n\r\n")
                    await writer.drain()
                    return
                body = await reader.readexactly(length) if length else b""
                status_line = b"HTTP/1.1 200 OK\r\n"
                ctype = b"Content-Type: application/json\r\n"
                if request_line.startswith("GET"):
                    path = (
                        request_line.split(" ", 2)[1]
                        if " " in request_line else "/"
                    )
                    if path.split("?", 1)[0] == "/metrics":
                        # Prometheus exposition door (text format 0.0.4,
                        # node/metrics.py prometheus_text). Resource-
                        # priced like any other RPC: a scraper hammering
                        # the door charges its client balance and gets
                        # 429 until it decays (admin IPs exempt).
                        from .handlers import charge_rpc_client

                        peer = writer.get_extra_info("peername")
                        refused = charge_rpc_client(
                            self.node, peer[0] if peer else "",
                            "metrics", _role_for_peer(self.node, writer),
                        )
                        if refused is not None:
                            status_line = (
                                b"HTTP/1.1 429 Too Many Requests\r\n"
                            )
                            payload = b"slow down\n"
                            ctype = b"Content-Type: text/plain\r\n"
                        else:
                            payload = self._metrics_payload()
                            ctype = (
                                b"Content-Type: text/plain; "
                                b"version=0.0.4; charset=utf-8\r\n"
                            )
                    else:
                        payload = b'{"status": "ok"}'
                else:
                    peer = writer.get_extra_info("peername")
                    payload = json.dumps(
                        process_http_request(
                            self.node, body,
                            _role_for_peer(self.node, writer),
                            client_ip=peer[0] if peer else "",
                        )
                    ).encode()
                writer.write(
                    status_line + ctype
                    + f"Content-Length: {len(payload)}\r\n".encode()
                    + b"Connection: keep-alive\r\n\r\n"
                    + payload
                )
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (asyncio.IncompleteReadError, ConnectionError,
                asyncio.LimitOverrunError):
            pass
        finally:
            writer.close()

    def _metrics_payload(self) -> bytes:
        """One /metrics scrape: every collector instrument plus the
        health verdict as a rank gauge (0=ok 1=warn 2=critical)."""
        extra = {}
        health = getattr(self.node, "health", None)
        if health is not None:
            from ..node.health import _RANK

            extra["health_status"] = _RANK.get(health.status, 0)
        try:
            text = self.node.collector.prometheus_text(extra_gauges=extra)
        except Exception:  # noqa: BLE001 — a scrape must not kill the door
            text = ""
        return text.encode("utf-8")

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "HttpRpcServer":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rpc-http")
        self._thread.start()
        self._started.wait(timeout=10)
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle, self.host, self.port, limit=_MAX_BODY,
                ssl=self._ssl,
            )
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()

        self._loop.run_until_complete(boot())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def stop(self) -> None:
        if self._loop and self._loop.is_running():
            def _shutdown():
                if self._server:
                    self._server.close()
                self._loop.stop()

            self._loop.call_soon_threadsafe(_shutdown)
        if self._thread:
            self._thread.join(timeout=5)
