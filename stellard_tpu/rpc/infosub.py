"""InfoSub: pub/sub subscriber abstraction + subscription manager.

Reference: src/ripple_net/rpc/InfoSub.cpp + NetworkOPsImp's mSub* maps
(NetworkOPsImp.h:372-392) — streams: `ledger`, `server`, `transactions`,
`transactions_proposed` (rt_transactions), per-`accounts` and per-`books`
subscriptions. WS connections implement the InfoSub sink; closes fan out
from the close path.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..protocol.sttx import SerializedTransaction
from ..protocol.ter import TER
from ..state.ledger import Ledger

__all__ = ["InfoSub", "SubscriptionManager"]


class InfoSub:
    """One subscriber (a WS connection or an in-process test sink)."""

    _next_id = 0

    def __init__(self, send: Callable[[dict], None]):
        self.send = send
        InfoSub._next_id += 1
        self.id = InfoSub._next_id
        self.streams: set[str] = set()
        self.accounts: set[bytes] = set()
        self.accounts_proposed: set[bytes] = set()
        # live path-find subscriptions (reference: PathRequest) —
        # request id -> decoded {src, dst, dst_amount, send_max, echo}
        self.path_requests: dict[int, dict] = {}
        self._next_path_id = 0


class SubscriptionManager:
    """Fan-out hub wired into NetworkOPs' close/tx hooks."""

    def __init__(self, ops):
        self.ops = ops
        self._lock = threading.Lock()
        self._subs: dict[int, InfoSub] = {}
        # url -> RpcSub (reference: NetworkOPs mRpcSubMap): HTTP-callback
        # subscriptions outlive any one request; found/created by
        # `subscribe` with a url (admin-only)
        self.rpc_subs: dict[str, InfoSub] = {}
        ops.on_ledger_closed.append(self._pub_ledger)
        ops.on_proposed_tx.append(self._pub_proposed)

    def rpc_sub(self, url: str, username: str = "", password: str = ""):
        """Find-or-create the RPCSub for a url (reference: findRpcSub /
        addRpcSub); fresh credentials update an existing sub."""
        from .rpcsub import RpcSub

        with self._lock:
            sub = self.rpc_subs.get(url)
            if sub is None:
                sub = RpcSub(url, username, password)
                self.rpc_subs[url] = sub
            elif username or password:
                sub.set_credentials(username, password)
            return sub

    def rpc_sub_lookup(self, url: str):
        """Find only (unsubscribe must never create — a typo'd url would
        register a phantom subscription and report success)."""
        with self._lock:
            return self.rpc_subs.get(url)

    def prune_rpc_sub(self, sub) -> None:
        """Drop an RpcSub that no longer subscribes to anything: a url
        entry with no streams/accounts must not live (and get POSTed
        events) forever. Emptiness is re-checked under the registry
        lock so a concurrent re-subscribe (which adds a stream through
        the same lock-guarded find-or-create) is never destroyed."""
        with self._lock:
            if (sub.streams or sub.accounts or sub.accounts_proposed
                    or sub.path_requests):
                return
            self.rpc_subs.pop(getattr(sub, "url", None), None)
            self._subs.pop(sub.id, None)
        close = getattr(sub, "close", None)
        if close is not None:
            close()

    # -- subscribe / unsubscribe (reference: handlers/Subscribe.cpp) ------

    def add(self, sub: InfoSub) -> None:
        with self._lock:
            self._subs[sub.id] = sub

    def remove(self, sub_id: int) -> None:
        with self._lock:
            self._subs.pop(sub_id, None)

    def subscribe_streams(self, sub: InfoSub, streams: list[str]) -> dict:
        """Returns the initial result payload (ledger stream returns the
        current state snapshot, reference Subscribe.cpp:86-112)."""
        result: dict = {}
        for stream in streams:
            if stream not in ("ledger", "server", "transactions",
                              "transactions_proposed", "rt_transactions"):
                continue
            sub.streams.add(stream)
            if stream == "ledger":
                result.update(self._ledger_snapshot())
        self.add(sub)
        return result

    def unsubscribe_streams(self, sub: InfoSub, streams: list[str]) -> None:
        for stream in streams:
            sub.streams.discard(stream)

    def subscribe_accounts(self, sub: InfoSub, accounts: list[bytes],
                           proposed: bool = False) -> None:
        target = sub.accounts_proposed if proposed else sub.accounts
        target.update(accounts)
        self.add(sub)

    # -- path-find subscriptions (reference: PathRequests) ----------------

    def create_path_request(self, sub: InfoSub, request: dict) -> int:
        """Register a live path search; updates push on every close."""
        sub._next_path_id += 1
        rid = sub._next_path_id
        sub.path_requests[rid] = request
        self.add(sub)
        return rid

    def close_path_request(self, sub: InfoSub,
                           rid: Optional[int] = None) -> bool:
        if rid is None:
            had = bool(sub.path_requests)
            sub.path_requests.clear()
            return had
        return sub.path_requests.pop(rid, None) is not None

    def _pub_path_updates(self, ledger: Ledger) -> None:
        from ..paths import find_paths
        from ..paths.pathfinder import PATH_SEARCH_DEFAULT, PATH_SEARCH_FAST

        from ..protocol.stobject import STPathSet

        for sub in self._each():
            for rid, req in list(sub.path_requests.items()):
                # level ramp (reference: PathRequest.cpp:370-379 —
                # answer at PATH_SEARCH_FAST on the first update, then
                # jump to the full PATH_SEARCH level)
                level = (
                    PATH_SEARCH_FAST
                    if req.get("level", 0) < PATH_SEARCH_FAST
                    else PATH_SEARCH_DEFAULT
                )
                req["level"] = level
                try:
                    alts = find_paths(
                        ledger, req["src"], req["dst"], req["dst_amount"],
                        send_max=req.get("send_max"), level=level,
                    )
                except Exception:  # noqa: BLE001 — a bad request must not kill publishing
                    continue
                msg = {
                    "type": "path_find",
                    "id": rid,
                    # only the full-depth search is a definitive answer;
                    # the FAST first pass is marked partial so clients
                    # wait for the deeper updates (reference:
                    # PathRequest's iLastLevel / full_reply contract)
                    "full_reply": level >= PATH_SEARCH_DEFAULT,
                    "ledger_index": ledger.seq,
                    "alternatives": [
                        {
                            "paths_computed": STPathSet(a["paths"]).to_json(),
                            "source_amount": a["source_amount"].to_json(),
                        }
                        for a in alts
                    ],
                    **req.get("echo", {}),
                }
                self._safe_send(sub, msg)

    def unsubscribe_accounts(self, sub: InfoSub, accounts: list[bytes],
                             proposed: bool = False) -> None:
        target = sub.accounts_proposed if proposed else sub.accounts
        target.difference_update(accounts)

    def _ledger_snapshot(self) -> dict:
        lcl = self.ops.lm.closed_ledger()
        return {
            "ledger_index": lcl.seq,
            "ledger_hash": lcl.hash().hex().upper(),
            "ledger_time": lcl.close_time,
            "fee_base": lcl.base_fee,
            "fee_ref": lcl.reference_fee_units,
            "reserve_base": lcl.reserve_base,
            "reserve_inc": lcl.reserve_increment,
        }

    # -- fan-out ----------------------------------------------------------

    def _each(self):
        with self._lock:
            return list(self._subs.values())

    def _pub_ledger(self, ledger: Ledger, results: dict) -> None:
        """reference: NetworkOPs::pubLedger — ledgerClosed stream msg,
        then per-tx accepted messages."""
        msg = {
            "type": "ledgerClosed",
            "ledger_index": ledger.seq,
            "ledger_hash": ledger.hash().hex().upper(),
            "ledger_time": ledger.close_time,
            "fee_base": ledger.base_fee,
            "fee_ref": ledger.reference_fee_units,
            "reserve_base": ledger.reserve_base,
            "reserve_inc": ledger.reserve_increment,
            "txn_count": len(results),
        }
        for sub in self._each():
            if "ledger" in sub.streams:
                self._safe_send(sub, msg)
        # accepted transactions (reference: pubAcceptedTransaction)
        for txid, blob, meta in ledger.tx_entries():
            tx = ledger.parse_tx(txid, blob)
            ter = results.get(txid, TER.tesSUCCESS)
            self._pub_tx(tx, ter, ledger=ledger, validated=True, meta=meta)
        # live path-find subscriptions re-search against the new state on
        # a jtUPDATE_PF job (reference: PathRequests::updateAll) — NOT on
        # this thread, which in networked mode is the ordered persist
        # worker and must not serialize pathfinding into ledger persists
        if any(s.path_requests for s in self._each()):
            from ..node.jobqueue import JobType

            self.ops.jq.add_job(
                JobType.jtUPDATE_PF,
                "pathUpdates",
                lambda: self._pub_path_updates(ledger),
            )

    def pub_server_status(self) -> None:
        """serverStatus event to `server`-stream subscribers (reference:
        NetworkOPs::pubServer on load-factor movement)."""
        from ..node.loadmgr import NORMAL_FEE

        ft = getattr(self.ops, "fee_track", None)
        msg = {
            "type": "serverStatus",
            "server_status": self.ops.server_state(),
            "load_base": NORMAL_FEE,
            "load_factor": ft.load_factor if ft is not None else NORMAL_FEE,
        }
        for sub in self._each():
            if "server" in sub.streams:
                self._safe_send(sub, msg)

    def _pub_proposed(self, tx: SerializedTransaction, ter: TER) -> None:
        self._pub_tx(tx, ter, ledger=None, validated=False)

    def _pub_tx(self, tx: SerializedTransaction, ter: TER,
                ledger: Optional[Ledger], validated: bool,
                meta: bytes = b"") -> None:
        msg = {
            "type": "transaction",
            "transaction": _tx_json_with_hash(tx),
            "status": "closed" if validated else "proposed",
            "engine_result": ter.token,
            "engine_result_code": int(ter),
            "engine_result_message": ter.human,
            "validated": validated,
        }
        if ledger is not None:
            msg["ledger_index"] = ledger.seq
            msg["ledger_hash"] = ledger.hash().hex().upper()
        if meta:
            from ..protocol.stobject import STObject

            msg["meta"] = STObject.from_bytes(meta).to_json()

        # accounts touched: from the metadata when we have it (covers
        # crossed offers, trust-line counterparties, issuers — reference
        # getAffectedAccounts); fall back to Account/Destination for
        # proposed txns that carry no meta yet
        touched = {tx.account}
        from ..protocol.sfields import sfDestination

        dest = tx.obj.get(sfDestination)
        if dest:
            touched.add(dest)
        if meta:
            from ..protocol.meta import affected_accounts

            touched.update(affected_accounts(meta))

        for sub in self._each():
            wants = False
            if validated and "transactions" in sub.streams:
                wants = True
            if not validated and (
                "transactions_proposed" in sub.streams
                or "rt_transactions" in sub.streams
            ):
                wants = True
            if sub.accounts & touched and validated:
                wants = True
            if sub.accounts_proposed & touched:
                wants = True
            if wants:
                self._safe_send(sub, msg)

    def _safe_send(self, sub: InfoSub, msg: dict) -> None:
        try:
            sub.send(msg)
        except Exception:  # noqa: BLE001 — a dead subscriber must not break the pub path
            self.remove(sub.id)


def _tx_json_with_hash(tx: SerializedTransaction) -> dict:
    j = tx.obj.to_json()
    j["hash"] = tx.txid().hex().upper()
    return j
